"""CTC / CRF / NCE / hsigmoid / misc op tests (reference:
tests/unittests/test_warpctc_op.py, test_edit_distance_op.py,
test_linear_chain_crf_op.py, test_crf_decoding_op.py, test_nce.py,
test_hsigmoid_op.py, test_grid_sampler_op.py, test_spectral_norm_op.py)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core
from tests.test_sequence_ops import run_seq_op


def ref_ctc_loss(logp, labels, blank=0):
    """Brute-force CTC -log p(labels) by enumerating alignments."""
    T, C = logp.shape
    import itertools
    total = -np.inf
    for path in itertools.product(range(C), repeat=T):
        # collapse
        collapsed = []
        prev = None
        for s in path:
            if s != prev:
                if s != blank:
                    collapsed.append(s)
            prev = s
        if collapsed == list(labels):
            lp = sum(logp[t, path[t]] for t in range(T))
            total = np.logaddexp(total, lp)
    return -total


def test_warpctc_matches_bruteforce():
    rng = np.random.RandomState(0)
    T, C = 4, 3
    logits = rng.randn(T, C).astype(np.float32)
    labels = np.array([[1], [2]], np.int32)
    (loss,), _ = run_seq_op(
        "warpctc", logits, [[T]], x_slot="Logits",
        extra_inputs=[("Label", labels, [[2]])],
        attrs={"blank": 0}, outputs=("Loss",))
    logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    want = ref_ctc_loss(logp, [1, 2])
    np.testing.assert_allclose(loss[0, 0], want, rtol=1e-4)


def test_warpctc_two_sequences_and_grad():
    rng = np.random.RandomState(1)
    lens = [3, 5]
    C = 4
    logits = rng.randn(sum(lens), C).astype(np.float32)
    labels = np.array([[1], [2], [3]], np.int32)
    (loss,), _ = run_seq_op(
        "warpctc", logits, [lens], x_slot="Logits",
        extra_inputs=[("Label", labels, [[1, 2]])],
        attrs={"blank": 0}, outputs=("Loss",))
    assert loss.shape == (2, 1)
    assert np.isfinite(loss).all()
    logp = logits[:3] - np.log(np.exp(logits[:3]).sum(-1, keepdims=True))
    np.testing.assert_allclose(loss[0, 0], ref_ctc_loss(logp, [1]),
                               rtol=1e-4)


def test_ctc_align_and_edit_distance():
    x = np.array([[0], [1], [1], [0], [2], [2], [0]], np.int32)
    (o,), (olod,) = run_seq_op("ctc_align", x, [[7]], x_slot="Input",
                               attrs={"blank": 0, "merge_repeated": True},
                               outputs=("Output",))
    np.testing.assert_array_equal(o.reshape(-1), [1, 2])

    hyp = np.array([[1], [2], [3]], np.int64)
    ref = np.array([[1], [3]], np.int64)
    (d, n), _ = run_seq_op("edit_distance", hyp, [[3]], x_slot="Hyps",
                           extra_inputs=[("Refs", ref, [[2]])],
                           outputs=("Out", "SequenceNum"))
    assert d[0, 0] == 1.0  # one insertion


def test_linear_chain_crf_single_tag_seq():
    """With one tag, NLL must be 0 (only one path)."""
    em = np.zeros((3, 1), np.float32)
    lab = np.zeros((3, 1), np.int64)
    trans = np.zeros((3, 1), np.float32)
    (nll,), _ = run_seq_op(
        "linear_chain_crf", em, [[3]], x_slot="Emission",
        extra_inputs=[("Transition", trans, None), ("Label", lab, [[3]])],
        outputs=("LogLikelihood",))
    np.testing.assert_allclose(nll[0, 0], 0.0, atol=1e-5)


def test_crf_decoding_matches_argmax_when_no_transitions():
    rng = np.random.RandomState(2)
    K = 4
    em = rng.randn(5, K).astype(np.float32)
    trans = np.zeros((K + 2, K), np.float32)
    (path,), _ = run_seq_op(
        "crf_decoding", em, [[5]], x_slot="Emission",
        extra_inputs=[("Transition", trans, None)],
        outputs=("ViterbiPath",))
    np.testing.assert_array_equal(path.reshape(-1), em.argmax(-1))


def test_nce_and_hsigmoid_and_sampled_softmax_train():
    """All three sampled losses drive a small LM-style model down."""
    V, D, N = 20, 8, 16
    for loss_kind in ("nce", "hsigmoid", "sampled"):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", shape=[D], dtype="float32")
            y = fluid.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, D, act="relu")
            if loss_kind == "nce":
                cost = fluid.layers.nce(h, y, V, num_neg_samples=5, seed=1)
            elif loss_kind == "hsigmoid":
                cost = fluid.layers.hsigmoid(h, y, V)
            else:
                logits = fluid.layers.fc(h, V)
                cost = fluid.layers.sampled_softmax_with_cross_entropy(
                    logits, y, num_samples=5, seed=1)
            loss = fluid.layers.mean(cost)
            fluid.optimizer.Adam(0.05).minimize(loss)
        exe = fluid.Executor()
        scope = core.Scope()
        rng = np.random.RandomState(3)
        X = rng.rand(N, D).astype("float32")
        Y = (np.arange(N) % V).reshape(-1, 1).astype("int64")
        with fluid.scope_guard(scope):
            exe.run(startup)
            losses = []
            for _ in range(15):
                (lv,) = exe.run(main, feed={"x": X, "y": Y},
                                fetch_list=[loss])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
        assert np.isfinite(losses).all(), loss_kind
        assert losses[-1] < losses[0], (loss_kind, losses)


def test_grid_sampler_identity():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    ys, xs = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 4),
                         indexing="ij")
    grid = np.stack([xs, ys], -1)[None].astype(np.float32)
    (o,), _ = run_seq_op("grid_sampler", x, None, x_slot="X",
                         extra_inputs=[("Grid", grid, None)],
                         outputs=("Output",))
    np.testing.assert_allclose(o, x, atol=1e-5)


def test_spectral_norm_unit_sigma():
    rng = np.random.RandomState(4)
    w = rng.randn(4, 6).astype(np.float32)
    u = rng.randn(4).astype(np.float32)
    v = rng.randn(6).astype(np.float32)
    (o,), _ = run_seq_op("spectral_norm", w, None, x_slot="Weight",
                         extra_inputs=[("U", u, None), ("V", v, None)],
                         attrs={"power_iters": 20}, outputs=("Out",))
    s = np.linalg.svd(o, compute_uv=False)
    np.testing.assert_allclose(s[0], 1.0, rtol=1e-3)


def test_center_loss_pulls_to_centers():
    x = np.array([[1.0, 0.0], [0.0, 1.0]], np.float32)
    lab = np.array([[0], [1]], np.int64)
    centers = np.zeros((2, 2), np.float32)
    rate = np.array([0.5], np.float32)
    (loss, diff, new_c), _ = run_seq_op(
        "center_loss", x, None, x_slot="X",
        extra_inputs=[("Label", lab, None), ("Centers", centers, None),
                      ("CenterUpdateRate", rate, None)],
        attrs={"cluster_num": 2, "need_update": True},
        outputs=("Loss", "SampleCenterDiff", "CentersOut"))
    np.testing.assert_allclose(loss.reshape(-1), [0.5, 0.5])
    assert new_c[0, 0] > 0  # moved toward sample
