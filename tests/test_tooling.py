"""Profiler, timeline tool, op bench harness, debugger/net_drawer, and
contrib estimators (reference: platform/profiler.h, tools/timeline.py,
operators/benchmark/op_tester.cc, fluid/debugger.py, contrib/
memory_usage_calc.py, op_frequence.py, extend_optimizer/)."""
import json
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core, profiler


def _mlp_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[8], dtype="float32")
        h = fluid.layers.fc(x, 16, act="relu")
        y = fluid.layers.fc(h, 4, act="softmax")
        loss = fluid.layers.mean(y)
    return main, startup, loss


# ----------------------------------------------------------------- profiler
def test_profiler_collects_and_reports(tmp_path, capsys):
    main, startup, loss = _mlp_program()
    exe = fluid.Executor()
    scope = core.Scope()
    ppath = str(tmp_path / "profile.json")
    with fluid.scope_guard(scope):
        exe.run(startup)
        with profiler.profiler(state="CPU", sorted_key="total",
                               profile_path=ppath):
            for _ in range(3):
                exe.run(main, feed={"x": np.ones((2, 8), "float32")},
                        fetch_list=[loss])
    out = capsys.readouterr().out
    assert "Profiling Report" in out
    assert "compiled_step" in out
    with open(ppath) as _pf:
        trace = json.load(_pf)
    names = {e["name"] for e in trace["traceEvents"]}
    assert "compiled_step" in names
    assert len(trace["traceEvents"]) >= 3


def test_profiler_record_event_nesting(tmp_path):
    profiler.start_profiler(state="CPU")
    with profiler.record_event("outer"):
        with profiler.record_event("inner"):
            pass
    from paddle_tpu.fluid.profiler import _prof
    names = [e.name for e in _prof.events]
    profiler.stop_profiler(profile_path=str(tmp_path / "p.json"))
    assert names == ["inner", "outer"]  # inner closes first


def test_profiler_eager_per_op_spans(tmp_path):
    # stateful op (py print path) forces the eager executor → per-op spans
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, 4)
        arr = fluid.layers.create_array("float32")
        i = fluid.layers.fill_constant([1], "int64", 0)
        fluid.layers.array_write(y, i, arr)
    exe = fluid.Executor()
    scope = core.Scope()
    profiler.start_profiler(state="CPU")
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": np.ones((2, 4), "float32")},
                fetch_list=[y])
    from paddle_tpu.fluid.profiler import _prof
    names = {e.name for e in _prof.events}
    profiler.stop_profiler(profile_path="")
    assert "mul" in names or "elementwise_add" in names


# ----------------------------------------------------------------- timeline
def test_timeline_merge(tmp_path):
    p0 = tmp_path / "p0.json"
    p1 = tmp_path / "p1.json"
    for i, p in enumerate((p0, p1)):
        p.write_text(json.dumps({"traceEvents": [
            {"name": f"op{i}", "ph": "X", "pid": 99, "tid": 1,
             "ts": 0, "dur": 10}]}))
    out = tmp_path / "t.json"
    r = subprocess.run(
        [sys.executable, "tools/timeline.py",
         "--profile_path", f"w0={p0},w1={p1}",
         "--timeline_path", str(out)],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    merged = json.loads(out.read_text())
    pids = {e["pid"] for e in merged["traceEvents"]}
    assert pids == {0, 1}
    names = {e.get("args", {}).get("name") for e in merged["traceEvents"]
             if e.get("ph") == "M"}
    assert names == {"w0", "w1"}


# ----------------------------------------------------------------- op bench
def test_op_bench_harness():
    sys.path.insert(0, "tools")
    try:
        from op_bench import bench_op, parse_inputs, parse_attrs
    finally:
        sys.path.pop(0)
    res = bench_op("softmax", parse_inputs("X:8x32:float32"),
                   parse_attrs(["axis=-1"]), repeat=5, warmup=1)
    assert res["op"] == "softmax"
    assert res["eager_ms"] > 0 and res["jit_ms"] > 0


# ------------------------------------------------------ debugger/net_drawer
def test_debugger_and_net_drawer(tmp_path):
    from paddle_tpu.fluid import debugger, net_drawer
    main, startup, loss = _mlp_program()
    text = debugger.pprint_program_codes(main)
    assert "softmax" in text and "mul" in text
    dot = net_drawer.draw_graph(startup, main,
                                path=str(tmp_path / "g.dot"))
    assert dot.startswith("digraph") and "softmax" in dot
    assert (tmp_path / "g.dot").exists()


# ------------------------------------------------------- contrib estimators
def test_memory_usage_and_op_freq_and_model_stat():
    from paddle_tpu.fluid.contrib import (memory_usage, op_freq_statistic,
                                          summary)
    main, startup, loss = _mlp_program()
    lo, hi = memory_usage(main, batch_size=32)
    assert 0 < lo < hi
    uni, adj = op_freq_statistic(main)
    assert uni["mul"] == 2
    assert any("mul->elementwise_add" == k for k in adj)
    params, flops = summary(main, print_table=False)
    assert params == 8 * 16 + 16 + 16 * 4 + 4
    assert flops > 0


def test_profiler_nested_sessions(tmp_path, capsys):
    """Inner profiler context must not end the outer session."""
    profiler.start_profiler(state="CPU")
    with profiler.record_event("a"):
        pass
    with profiler.profiler(state="CPU",
                           profile_path=str(tmp_path / "inner.json")):
        with profiler.record_event("b"):
            pass
    assert profiler.is_profiling()  # outer still live
    with profiler.record_event("c"):
        pass
    from paddle_tpu.fluid.profiler import _prof
    names = [e.name for e in _prof.events]
    profiler.stop_profiler(profile_path=str(tmp_path / "outer.json"))
    assert names == ["a", "b", "c"]
    assert not (tmp_path / "inner.json").exists()
    assert (tmp_path / "outer.json").exists()


def test_record_event_decorator():
    calls = []

    @profiler.RecordEvent("decorated")
    def fn(x):
        calls.append(x)
        return x + 1

    profiler.start_profiler(state="CPU")
    assert fn(1) == 2
    from paddle_tpu.fluid.profiler import _prof
    names = [e.name for e in _prof.events]
    profiler.stop_profiler(profile_path="")
    assert names == ["decorated"] and calls == [1]


def test_model_stat_excludes_optimizer_state_and_transpose():
    from paddle_tpu.fluid.contrib import summary
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, 4)
        loss = fluid.layers.mean(y)
    p0, _ = summary(main, print_table=False)
    with fluid.program_guard(main, startup):
        fluid.optimizer.Adam(0.01).minimize(loss)
    p1, _ = summary(main, print_table=False)
    assert p0 == p1 == 4 * 4 + 4  # adam moments don't inflate the count
    # transpose_Y matmul flops use the transposed output dim
    m2, s2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(m2, s2):
        a = fluid.data("a", shape=[8, 16], dtype="float32",
                       append_batch_size=False)
        b = fluid.data("b", shape=[32, 16], dtype="float32",
                       append_batch_size=False)
        fluid.layers.matmul(a, b, transpose_y=True)
    _, fl = summary(m2, print_table=False)
    assert fl == 2 * 8 * 16 * 32


def test_decoupled_decay_dygraph_mode():
    import paddle_tpu.fluid.dygraph as dygraph
    from paddle_tpu.fluid.dygraph import to_variable
    from paddle_tpu.fluid.contrib import extend_with_decoupled_weight_decay
    SGDW = extend_with_decoupled_weight_decay(fluid.optimizer.SGD)
    with dygraph.guard():
        net = dygraph.Linear(4, 4)
        opt = SGDW(weight_decay=0.5, learning_rate=0.1,
                   parameter_list=net.parameters())
        before = np.abs(net.weight.numpy()).sum()
        # zero input -> zero grads; only the decoupled decay moves W
        loss = fluid.layers.reduce_mean(
            net(to_variable(np.zeros((2, 4), "float32"))))
        loss.backward()
        opt.minimize(loss)
        after = np.abs(net.weight.numpy()).sum()
    np.testing.assert_allclose(after, before * (1 - 0.1 * 0.5), rtol=1e-5)


def test_extend_with_decoupled_weight_decay():
    from paddle_tpu.fluid.contrib import extend_with_decoupled_weight_decay
    AdamW = extend_with_decoupled_weight_decay(fluid.optimizer.Adam)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, 4)
        loss = fluid.layers.mean(y)
        opt = AdamW(weight_decay=0.5, learning_rate=0.1)
        opt.minimize(loss)
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        wname = [p.name for p in main.all_parameters()
                 if p.shape == (4, 4)][0]
        before = np.asarray(scope.find_var(wname).get_tensor().array).copy()
        exe.run(main, feed={"x": np.zeros((2, 4), "float32")},
                fetch_list=[loss])
        after = np.asarray(scope.find_var(wname).get_tensor().array)
    # zero input -> zero grad for W; decay still shrinks W (decoupled)
    assert np.abs(after).sum() < np.abs(before).sum()
    with pytest.raises(TypeError):
        extend_with_decoupled_weight_decay(object)


def test_multiprocess_dataloader_matches_inline():
    """use_multiprocess=True runs the generator in a child process with
    shared-memory batch transport (reference reader.py:684 multiprocess
    GeneratorLoader over mmap allocations) and must yield identical
    batches."""
    import numpy as np
    import paddle_tpu.fluid as fluid

    def make_reader():
        def reader():
            rng = np.random.RandomState(42)
            for i in range(7):
                yield {"x": rng.rand(4, 3).astype("float32"),
                       "y": np.full((4, 1), i, "int64")}
        return reader

    inline = fluid.DataLoader.from_generator(feed_list=[], capacity=4)
    inline.set_batch_generator(make_reader())
    mp_loader = fluid.DataLoader.from_generator(
        feed_list=[], capacity=4, use_multiprocess=True)
    mp_loader.set_batch_generator(make_reader())

    got_inline = list(inline)
    got_mp = list(mp_loader)
    assert len(got_inline) == len(got_mp) == 7
    for a, b in zip(got_inline, got_mp):
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])


def test_api_signatures_tool():
    """tools/api_signatures.py dumps the public surface without import
    failures (reference print_signatures.py for API-diff checking)."""
    import subprocess, sys, os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "api_signatures.py"),
         "--module", "paddle_tpu.fluid.layers"],
        capture_output=True, text=True, timeout=180,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert res.returncode == 0, res.stderr[-1500:]
    lines = res.stdout.strip().splitlines()
    assert len(lines) > 150
    assert not any("import failed" in l for l in lines)
    assert any(l.startswith("paddle_tpu.fluid.layers.fc(") for l in lines)


def test_mfu_report_xla_cost_analysis():
    """tools/mfu_report.py (perf pre-staging): XLA's own cost analysis of
    the FULL compiled train step — flops, bytes accessed, arithmetic
    intensity — plus measured step time, one JSON-able dict."""
    import json
    from tools.mfu_report import report

    out = report("mnist", steps=2)
    assert out["xla_flops_per_step"] > 1e6
    assert out["step_ms"] > 0
    # bytes-accessed keys are optional per the tool's contract (some
    # jax/backends omit "bytes accessed" from cost_analysis)
    if "xla_bytes_accessed" in out:
        assert out["xla_bytes_accessed"] > 0
        assert out["flops_per_byte"] > 0
    json.dumps(out)
