"""Parameter-server integration tests — multiprocess on localhost
(reference: tests/unittests/test_dist_base.py:506 TestDistBase._run_cluster;
the 1-trainer-vs-2-trainer loss oracle of check_with_place:933)."""
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKLOAD = os.path.join(REPO, "tests", "dist_ps_workload.py")


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def run_cluster(trainers, steps, tmpdir, sparse=False, geo=False,
                timeout=240, n_pservers=1, extra_args=()):
    eps = ",".join(f"127.0.0.1:{free_port()}" for _ in range(n_pservers))
    ep = eps
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep +
               os.environ.get("PYTHONPATH", ""), JAX_PLATFORMS="cpu")
    procs = []
    logs = []

    def spawn(tag, args):
        log = open(os.path.join(tmpdir, tag + ".log"), "wb+")
        logs.append((tag, log))
        p = subprocess.Popen(args, env=env, stdout=log, stderr=log)
        procs.append(p)
        return p

    def log_tail(tag):
        for t, log in logs:
            if t == tag:
                log.flush()
                log.seek(0)
                return log.read().decode(errors="replace")[-3000:]
        return ""

    flags = (["--sparse"] if sparse else []) + \
            (["--geo"] if geo else []) + list(extra_args)
    ps_procs = []
    for pid in range(n_pservers):
        ps_out = os.path.join(tmpdir, f"ps{pid}.ready")
        ps_procs.append((spawn(f"ps{pid}",
                               [sys.executable, WORKLOAD, "pserver", ep,
                                str(pid), str(trainers), str(steps),
                                ps_out] + flags), ps_out))
    deadline = time.time() + 90
    for pid, (psp, ps_out) in enumerate(ps_procs):
        while not os.path.exists(ps_out):
            if psp.poll() is not None:
                raise RuntimeError(f"pserver {pid} died:\n"
                                   + log_tail(f"ps{pid}"))
            if time.time() > deadline:
                psp.kill()
                raise TimeoutError(f"pserver {pid} never became ready:\n"
                                   + log_tail(f"ps{pid}"))
            time.sleep(0.2)
    touts = []
    trainer_procs = []
    for tid in range(trainers):
        out = os.path.join(tmpdir, f"t{tid}.json")
        touts.append(out)
        trainer_procs.append(spawn(
            f"t{tid}", [sys.executable, WORKLOAD, "trainer", ep, str(tid),
                        str(trainers), str(steps), out] + flags))
    try:
        for tid, p in enumerate(trainer_procs):
            p.wait(timeout=timeout)
            if p.returncode != 0:
                raise RuntimeError("trainer failed:\n" + log_tail(f"t{tid}"))
        for psp, _ in ps_procs:
            psp.wait(timeout=30)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for _t, log in logs:
            log.close()
    return [json.load(open(o)) for o in touts]


def test_ps_sync_single_trainer_converges(tmp_path):
    (losses,) = run_cluster(1, 60, str(tmp_path))
    assert losses[-1] < losses[0] * 0.2, losses


def test_ps_sync_two_trainers_match_and_converge(tmp_path):
    l0, l1 = run_cluster(2, 30, str(tmp_path))
    # same data on both trainers → identical sync losses (reference oracle
    # compares 1- vs 2-trainer losses within delta)
    np.testing.assert_allclose(l0, l1, rtol=1e-4, atol=1e-5)
    assert l0[-1] < l0[0] * 0.5, l0


def test_ps_geo_sgd_converges(tmp_path):
    """GEO async mode: local training with periodic delta pushes
    (reference: geo_sgd_transpiler + GeoSgdCommunicator oracle —
    convergence despite async syncs)."""
    (losses,) = run_cluster(1, 60, str(tmp_path), geo=True)
    assert losses[-1] < losses[0] * 0.2, losses


# r19 fleet-PR buyback (~7s): test_ps_geo_sgd_converges +
# test_ps_geo_sgd_sparse_embedding keep geo-SGD per-commit; the
# two-trainer merge contract re-proves in the full tier.
@pytest.mark.slow
def test_ps_geo_sgd_two_trainers(tmp_path):
    l0, l1 = run_cluster(2, 40, str(tmp_path), geo=True)
    assert l0[-1] < l0[0] * 0.5, l0
    assert l1[-1] < l1[0] * 0.5, l1


def test_ps_sparse_distributed_embedding(tmp_path):
    (losses,) = run_cluster(1, 60, str(tmp_path), sparse=True)
    assert losses[-1] < losses[0] * 0.3, losses


# --------------------------------------------------------------------------
# heartbeat monitor (reference: heart_beat_monitor.h:54 — pserver-side
# worker liveness detection; in-process like rpc_server_test.cc)
# --------------------------------------------------------------------------
def test_heartbeat_monitor_detects_dead_worker():
    from paddle_tpu.fluid.ps_rpc import (HeartBeatMonitor, VarClient,
                                         VarServer, WorkerHeartBeat)
    dead = []
    mon = HeartBeatMonitor(2, timeout=0.6, check_interval=0.1,
                           on_dead=dead.append).start_monitor()
    srv = VarServer(f"127.0.0.1:{free_port()}", mon.handlers()).start()
    ep = f"127.0.0.1:{srv.port}"
    try:
        hb0 = WorkerHeartBeat([ep], trainer_id=0, interval=0.1).start()
        hb1 = WorkerHeartBeat([ep], trainer_id=1, interval=0.1).start()
        time.sleep(0.5)
        assert mon.alive_workers() == [0, 1]
        assert mon.dead_workers() == []
        hb1.stop()                       # worker 1 goes silent
        deadline = time.time() + 5.0
        while time.time() < deadline and mon.dead_workers() != [1]:
            time.sleep(0.1)
        assert mon.dead_workers() == [1]
        assert mon.alive_workers() == [0]
        hb1 = WorkerHeartBeat([ep], trainer_id=1, interval=0.1).start()
        time.sleep(0.3)                  # a new beat revives the worker
        assert mon.dead_workers() == []
        assert dead == [1]
        hb0.stop()
        hb1.stop()
    finally:
        mon.stop()
        srv.shutdown()
        VarClient.reset_pool()


def test_async_communicator_merges_sends():
    """A running Communicator batches queued grads: N pushes arrive at the
    server as fewer, summed sends (reference AsyncCommunicator merge
    contract, communicator.h:237)."""
    from paddle_tpu.fluid.communicator import Communicator
    from paddle_tpu.fluid.ps_rpc import VarClient, VarServer

    got = []
    lock = __import__("threading").Lock()

    def h_send_var(name, value, trainer_id=0, rows=None, height=0):
        with lock:
            got.append((name, np.asarray(value)))
        return True

    srv = VarServer(f"127.0.0.1:{free_port()}",
                    {"send_var": h_send_var}).start()
    ep = f"127.0.0.1:{srv.port}"
    try:
        comm = Communicator(envs={"communicator_max_merge_var_num": 50,
                                  "communicator_send_wait_times": 0.05})
        comm.start()
        assert Communicator.global_instance() is comm
        for i in range(20):
            comm.push("w@GRAD", np.full((4,), 1.0, np.float32), ep)
        deadline = time.time() + 10
        while time.time() < deadline:
            with lock:
                total = sum(v.sum() for _, v in got)
            if total >= 20 * 4:
                break
            time.sleep(0.05)
        comm.stop()
        assert Communicator.global_instance() is None
        with lock:
            total = sum(float(v.sum()) for _, v in got)
            n_rpcs = len(got)
        assert total == 20 * 4.0, total          # nothing lost
        assert n_rpcs < 20, n_rpcs               # merging happened
    finally:
        srv.shutdown()
        VarClient.reset_pool()


@pytest.mark.slow  # demoted r13 (suite-time buyback): 18s of step_sleep
# wall time; dead-trainer detection stays tier-1 via the sync-cluster
# WorkerDeadError test in test_fault_tolerance (same monitor, ~9s) —
# this case only adds the GEO-mode survivor flavor
def test_trainer_failure_detection(tmp_path):
    """Kill a trainer mid-run: the pserver's HeartBeatMonitor flags it,
    the server keeps serving, and the surviving trainer completes
    (reference: operators/distributed/heart_beat_monitor.h:54)."""
    ep = f"127.0.0.1:{free_port()}"
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep +
               os.environ.get("PYTHONPATH", ""), JAX_PLATFORMS="cpu",
               PADDLE_PS_HEARTBEAT_TIMEOUT="3")
    logs = {}

    def spawn(tag, args):
        log = open(os.path.join(str(tmp_path), tag + ".log"), "wb+")
        logs[tag] = log
        return subprocess.Popen(args, env=env, stdout=log, stderr=log)

    def tail(tag):
        logs[tag].flush(); logs[tag].seek(0)
        return logs[tag].read().decode(errors="replace")[-3000:]

    ps_out = os.path.join(str(tmp_path), "ps.ready")
    ps = spawn("ps", [sys.executable, WORKLOAD, "pserver", ep, "0", "2",
                      "40", ps_out, "--geo"])
    deadline = time.time() + 90
    while not os.path.exists(ps_out):
        assert ps.poll() is None, "pserver died:\n" + tail("ps")
        assert time.time() < deadline, "pserver not ready:\n" + tail("ps")
        time.sleep(0.2)

    t0_out = os.path.join(str(tmp_path), "t0.json")
    t0 = spawn("t0", [sys.executable, WORKLOAD, "trainer", ep, "0", "2",
                      "40", t0_out, "--geo", "--step-sleep=0.3",
                      "--no-stop"])
    t1 = spawn("t1", [sys.executable, WORKLOAD, "trainer", ep, "1", "2",
                      "40", os.path.join(str(tmp_path), "t1.json"),
                      "--geo", "--step-sleep=0.3", "--die-after=3"])
    try:
        t1.wait(timeout=120)
        assert t1.returncode == 1, tail("t1")  # simulated crash

        from paddle_tpu.fluid.ps_rpc import VarClient
        cli = VarClient.of(ep)
        deadline = time.time() + 45
        dead = []
        while time.time() < deadline:
            dead = list(cli.call("dead_workers"))
            if 1 in dead:
                break
            time.sleep(0.5)
        assert 1 in dead, (dead, tail("ps"))
        assert 0 not in dead, dead  # the live trainer keeps beating

        t0.wait(timeout=240)
        assert t0.returncode == 0, tail("t0")
        losses = json.load(open(t0_out))
        assert losses[-1] < losses[0] * 0.5, losses
        # server survived the whole episode and still serves parameters
        w = np.asarray(cli.call("get_var", name="w"))
        assert w.shape == (4, 1) and np.isfinite(w).all()
        cli.stop()
        ps.wait(timeout=30)
    finally:
        for p in (ps, t0, t1):
            if p.poll() is None:
                p.kill()
        for log in logs.values():
            log.close()


# r19 fleet-PR buyback (~6s scale smoke): lazy-table mechanics stay
# per-commit via test_ps_lazy_table_eviction_bound + the capacity
# suite (test_ps_capacity).
@pytest.mark.slow
def test_ps_billion_param_lazy_sparse_table(tmp_path):
    """Beyond-HBM sparse scale (reference fleet_wrapper.h:86-190): a
    [62.5M, 16] = 1e9-float logical embedding (4GB dense) row-sharded
    over TWO pservers as init-on-touch LazyEmbeddingTable — training
    converges while each pserver materializes only the rows actually
    touched."""
    (res,) = run_cluster(1, 20, str(tmp_path), sparse=True, n_pservers=2,
                         extra_args=["--sparse-dim=62500000",
                                     "--emb-dim=16", "--stats"],
                         timeout=300)
    losses, stats = res["losses"], res["stats"]
    assert losses[-1] < losses[0] * 0.5, losses
    total_logical = sum(s["logical_params"] for s in stats)
    assert total_logical >= 2 * int(1e9)  # each shard spans the table
    touched = sum(s["touched"] for s in stats)
    assert 0 < touched <= 8, stats       # only the 8 distinct ids exist
    assert sum(s["nbytes"] for s in stats) < 1 << 20, stats
    # both shards served ids (the id spread hits both parities)
    assert all(s["touched"] > 0 for s in stats), stats


def test_ps_lazy_table_eviction_bound(tmp_path):
    """The LRU bound caps pserver memory: with max_rows=4 and 8 distinct
    ids, rows are evicted and the resident count never exceeds the bound
    (the reference's shrink()/eviction trade)."""
    (res,) = run_cluster(1, 6, str(tmp_path), sparse=True, n_pservers=1,
                         extra_args=["--sparse-dim=80000000",
                                     "--emb-dim=8", "--max-rows=4",
                                     "--stats"],
                         timeout=300)
    (stats,) = res["stats"]
    assert stats["touched"] <= 4, stats
    assert stats["evictions"] > 0, stats


def test_ps_geo_sgd_sparse_embedding(tmp_path):
    """GEO mode with a sparse embedding: local training, row-wise delta
    pushes every N steps (reference GeoSgdCommunicator
    SendUpdateSparseVars) — converges like the dense GEO case."""
    (losses,) = run_cluster(1, 60, str(tmp_path), sparse=True, geo=True)
    assert losses[-1] < losses[0] * 0.3, losses


# r19 fleet-PR buyback (~7s): same rationale as the dense
# two-trainer variant above.
@pytest.mark.slow
def test_ps_geo_sgd_sparse_two_trainers(tmp_path):
    l0, l1 = run_cluster(2, 40, str(tmp_path), sparse=True, geo=True)
    assert l0[-1] < l0[0] * 0.6, l0
    assert l1[-1] < l1[0] * 0.6, l1


def test_lazy_table_startup_carries_initializer_seed_scale():
    """get_startup_program must derive the lazy table's row-init
    seed/scale from the model-declared initializer (a symmetric
    uniform_random maps exactly), not hardcode seed=0/scale=0
    (ADVICE r2)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.transpiler import (DistributeTranspiler,
                                             DistributeTranspilerConfig)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        tok = fluid.data("tok", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(
            tok, size=[10_000_000, 8], is_distributed=True,
            param_attr=fluid.ParamAttr(
                name="big_emb",
                initializer=fluid.initializer.Uniform(
                    low=-0.01, high=0.01, seed=7)))
        emb = fluid.layers.reshape(emb, [-1, 8])
        pred = fluid.layers.fc(emb, 1)
        loss = fluid.layers.mean(pred)
        fluid.optimizer.SGD(0.1).minimize(loss)

    cfg = DistributeTranspilerConfig()
    t = DistributeTranspiler(cfg)
    with fluid.program_guard(main, startup):
        t.transpile(trainer_id=0, pservers="127.0.0.1:16999", trainers=1,
                    sync_mode=True, program=main, startup_program=startup)
    sprog = t.get_startup_program("127.0.0.1:16999")
    inits = [op for op in sprog.global_block().ops
             if op.type == "lazy_table_init"]
    assert inits, [op.type for op in sprog.global_block().ops]
    attrs = inits[0].attrs
    assert attrs["seed"] == 7, attrs
    assert abs(attrs["scale"] - 0.01) < 1e-12, attrs


def test_distributed_lookup_empty_ids_keeps_embedding_dim(monkeypatch):
    """An empty id batch must return a [0, emb_dim] result, not [0, 1]
    (ADVICE r2) — downstream concat/fc ops reject the wrong dim."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid.executor import ExecContext
    from paddle_tpu.ops import distributed_ops as D
    from paddle_tpu.ops.registry import OPS

    main = fluid.Program()
    with fluid.program_guard(main):
        blk = main.global_block()
        blk.create_var(name="ids", shape=[-1, 1], dtype="int64")
        blk.create_var(name="emb_w", shape=[1000, 16], dtype="float32",
                       persistable=True)
        blk.create_var(name="out", shape=[-1, 16], dtype="float32")
        op = blk.append_op(type="distributed_lookup_table",
                           inputs={"Ids": ["ids"], "W": ["emb_w"]},
                           outputs={"Outputs": ["out"]},
                           attrs={"epmap": ["ep0", "ep1"],
                                  "table_names": ["emb_w"]})

    scope = core.Scope()
    scope.var("ids").set_value(
        core.LoDTensor(np.zeros((0,), np.int32)))

    def no_rpc(ep):
        raise AssertionError("no RPC expected for an empty id batch")

    monkeypatch.setattr(D, "_client", no_rpc)
    ctx = ExecContext(scope, None, op, None, 0)
    outs = OPS.get("distributed_lookup_table").kernel(
        {}, {"epmap": ["ep0", "ep1"], "table_names": ["emb_w"],
             "_ctx": ctx})
    (res,) = outs["Outputs"]
    assert tuple(res.shape) == (0, 16), res.shape


def test_recv_save_writes_reference_format_blob(tmp_path):
    """recv_save (reference recv_save_op.cc): fetch parameter slices
    from pservers and persist the concatenation in the reference
    LoDTensor serialization; the saved blob round-trips through the io
    deserializer."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid.ps_rpc import VarClient, VarServer
    from paddle_tpu.fluid.io import _deserialize_lod_tensor

    w0 = np.arange(6, dtype=np.float32).reshape(3, 2)
    w1 = np.arange(6, 14, dtype=np.float32).reshape(4, 2)
    store = {}
    handlers = {
        "send_var": lambda name, value, trainer_id=0, rows=None,
        height=0: store.__setitem__(name, np.asarray(value)),
        "get_var": lambda name, trainer_id=0: store[name],
    }
    srv = VarServer(f"127.0.0.1:{free_port()}", handlers).start()
    ep = f"127.0.0.1:{srv.port}"
    path = str(tmp_path / "w.blob")
    try:
        cli = VarClient.of(ep)
        cli.send_var("w.block0", w0)
        cli.send_var("w.block1", w1)
        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            prog.global_block().append_op(
                type="recv_save", inputs={}, outputs={},
                attrs={"endpoints": [ep, ep], "file_path": path,
                       "shape": [7, 2],
                       "remote_varnames": ["w.block0", "w.block1"]})
        exe = fluid.Executor()
        with fluid.scope_guard(core.Scope()):
            exe.run(prog, feed={}, fetch_list=[])
        blob = open(path, "rb").read()
        t = _deserialize_lod_tensor(blob)
        np.testing.assert_array_equal(np.asarray(t.array),
                                      np.concatenate([w0, w1]))
    finally:
        srv.shutdown()
        VarClient.reset_pool()


@pytest.mark.slow
# demoted r19 (suite-time buyback, 9s): a 3-trainer × 3-pserver
# multiprocess cluster driver — the class docs/ci.md routes to `slow`
# by convention; sync semantics + lazy sparse tables keep tier-1
# coverage via the 2×2 and single-trainer tests above
def test_ps_three_pservers_three_trainers_lazy_sparse(tmp_path):
    """Beyond the 2×2 cap (VERDICT r2 weak #6): 3 sync trainers × 3
    pservers with a beyond-threshold lazy sparse table — convergence,
    per-trainer loss agreement (sync semantics), and every shard
    touched."""
    res = run_cluster(3, 12, str(tmp_path), sparse=True, n_pservers=3,
                      extra_args=["--sparse-dim=9000000", "--emb-dim=8",
                                  "--stats"],
                      timeout=420)
    assert len(res) == 3
    for r in res:
        losses = r["losses"]
        assert losses[-1] < losses[0] * 0.7, losses
    # sync semantics: all trainers see the same global batch and the
    # same server-side parameters, so their loss curves must AGREE
    np.testing.assert_allclose(res[0]["losses"], res[1]["losses"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(res[0]["losses"], res[2]["losses"],
                               rtol=1e-5, atol=1e-6)
    stats = res[0]["stats"]
    assert len(stats) == 3                       # one entry per pserver
    assert all(s["touched"] > 0 for s in stats), stats
    total_logical = sum(s["logical_params"] for s in stats)
    assert total_logical >= 3 * 9000000 * 8      # each shard full span
