"""Online inference serving plane (ISSUE 7, docs/SERVING.md):
continuous batcher + predictor pool + serving-time embedding fetch.

Acceptance legs covered here:
  * batched-serving correctness — for any interleaving of >= 8
    concurrent predict() clients, per-row outputs are BIT-identical to
    the single-row unbatched oracle (pad rows provably inert);
  * per-bucket jit caching — steady-state traffic compiles nothing new;
  * serving-time sparse path — wide_deep-shaped lookups served through
    LIVE in-process pservers with the embedding cache: a cache-hit
    predict issues ZERO RPCs (server-counter-asserted), TTL expiry
    refetches, results bit-identical to the local-table oracle;
  * a pserver drain mid-serving is transparent to predict()
    (StaleClusterViewError re-route, PR 6);
  * io.save_inference_model -> Predictor round trip incl. wide_deep
    optimizer-slot pruning, bit-identical to Executor.run.
"""
import os
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

pytestmark = pytest.mark.serving


# ======================================================================
# harness
# ======================================================================
@pytest.fixture(scope="module")
def mlp():
    """Tiny forward model + single-row unbatched oracle rows."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[8], dtype="float32")
        h = fluid.layers.fc(x, 16, act="relu")
        out = fluid.layers.fc(h, 4, act="softmax")
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    rng = np.random.RandomState(0)
    X = rng.rand(32, 8).astype(np.float32)
    oracle = []
    with fluid.scope_guard(scope):
        for i in range(len(X)):
            (o,) = exe.run(main, feed={"x": X[i:i + 1]}, fetch_list=[out],
                           scope=scope)
            oracle.append(np.asarray(o))
    return {"main": main, "scope": scope, "out": out.name, "exe": exe,
            "X": X, "oracle": oracle}


def _engine(m, **kw):
    from paddle_tpu.serving import ServingEngine
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_queue_delay_ms", 4.0)
    kw.setdefault("num_workers", 2)
    return ServingEngine(program=m["main"], scope=m["scope"],
                         feed_names=["x"], fetch_names=[m["out"]], **kw)


@pytest.fixture
def _ps_isolation():
    """PS-backed serving tests start from a clean view registry/client
    pool (same shape as tests/test_ps_membership.py's fixture)."""
    from paddle_tpu.fluid import ps_membership, ps_rpc
    from paddle_tpu.fluid.ps_rpc import VarClient
    ps_membership.reset_views()
    prev = ps_rpc.install_row_cache(None)
    yield
    ps_rpc.install_row_cache(prev)
    ps_membership.reset_views()
    VarClient.reset_pool()


# ======================================================================
# batched-serving correctness (acceptance: >= 8 concurrent clients)
# ======================================================================
def test_concurrent_clients_bit_identical_to_single_row_oracle(mlp):
    """8 client threads hammer predict() with interleaved rows; every
    per-row output must equal the single-row Executor.run oracle BIT
    for bit — and batching must actually have happened (the assertion
    is vacuous on a one-row-per-batch run)."""
    eng = _engine(mlp)
    try:
        eng.warm()
        eng.reset_stats()
        X, oracle = mlp["X"], mlp["oracle"]
        errs = []

        def client(wid):
            rng = np.random.RandomState(100 + wid)
            for k in range(12):
                i = int(rng.randint(0, len(X)))
                try:
                    (got,) = eng.predict({"x": X[i]})
                    if got.shape != oracle[i].shape \
                            or not (got == oracle[i]).all():
                        errs.append((wid, k, i, "mismatch"))
                except BaseException as e:
                    errs.append((wid, k, i, repr(e)))
                if k % 5 == wid % 3:  # vary the interleavings
                    time.sleep(0.001)

        ths = [threading.Thread(target=client, args=(w,))
               for w in range(8)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert not errs, errs[:5]
        st = eng.stats()
        assert st["requests"] == 8 * 12
        assert max(st["batch_size_hist"]) > 1, \
            f"no coalescing happened: {st['batch_size_hist']}"
    finally:
        eng.close()


def test_pad_rows_inert_and_pow2_buckets(mlp):
    """A 3-row group pads into the 4-bucket and a 5-row group into the
    8-bucket; the shared row's output is bit-identical in both (and to
    the oracle) — neither pad rows nor batch composition leak into a
    real row."""
    eng = _engine(mlp)
    try:
        X, oracle = mlp["X"], mlp["oracle"]
        (r3,) = eng.predict_many({"x": X[[0, 5, 9]]})
        (r5,) = eng.predict_many({"x": X[[0, 11, 20, 7, 30]]})
        np.testing.assert_array_equal(r3[0:1], oracle[0])
        np.testing.assert_array_equal(r5[0:1], oracle[0])
        for j, i in enumerate((0, 5, 9)):
            np.testing.assert_array_equal(r3[j:j + 1], oracle[i])
        st = eng.stats()
        assert set(st["bucket_hist"]) == {4, 8}
    finally:
        eng.close()


def test_steady_state_traffic_never_recompiles(mlp):
    """After warm(), arbitrary request sizes land in the warmed pow-2
    buckets: the scanned-jit bucket cache must not grow, and no bucket
    retraces (jax's per-jit cache stays at one entry per bucket)."""
    eng = _engine(mlp)
    try:
        eng.warm()
        buckets0 = eng.buckets_compiled()
        assert buckets0 == [1, 2, 4, 8]

        def jit_entries():
            sizes = []
            for f in eng._cb._multi_jit.values():
                cs = getattr(f, "_cache_size", None)
                if cs is not None:
                    sizes.append(cs())
            return sizes

        entries0 = jit_entries()
        rng = np.random.RandomState(3)
        for _ in range(25):
            n = int(rng.randint(1, 9))
            eng.predict_many({"x": mlp["X"][:n]})
        assert eng.buckets_compiled() == buckets0
        assert jit_entries() == entries0, "a warmed bucket retraced"
    finally:
        eng.close()


def test_partial_batch_flushes_on_queue_delay(mlp):
    """max_batch far above the offered load: a lone request must not
    wait for company beyond max_queue_delay_ms."""
    eng = _engine(mlp, max_batch=64, max_queue_delay_ms=10.0)
    try:
        eng.warm((1,))
        eng.reset_stats()
        (got,) = eng.predict({"x": mlp["X"][2]}, timeout=30.0)
        np.testing.assert_array_equal(got, mlp["oracle"][2])
        assert eng.stats()["batch_size_hist"] == {1: 1}
    finally:
        eng.close()


def test_async_submit_future_and_stats_surface(mlp):
    from paddle_tpu.fluid import profiler

    eng = _engine(mlp)
    try:
        eng.warm((1, 2, 4))
        eng.reset_stats()
        profiler.start_profiler(state="CPU")
        try:
            futs = [eng.submit({"x": mlp["X"][i]}) for i in (1, 2, 3)]
            for i, f in zip((1, 2, 3), futs):
                (got,) = f.wait(30.0)
                np.testing.assert_array_equal(got, mlp["oracle"][i])
                assert f.t_done >= f.t_submit
            events = list(profiler._prof.events)
        finally:
            profiler.stop_profiler(profile_path="")
        serve = [e for e in events if e.cat == "serve"]
        names = {e.name.split("[")[0] for e in serve}
        assert {"serve:queue_wait", "serve:exec"} <= names, names
        execs = [e for e in serve if e.name.startswith("serve:exec")]
        assert all(e.args and "bucket" in e.args and "n_valid" in e.args
                   for e in execs)

        st = eng.stats()
        assert st["requests"] == 3 and st["rows"] == 3
        assert st["qps"] > 0
        assert st["latency_ms"]["p50"] <= st["latency_ms"]["p99"]
        assert st["queue_wait_ms"]["p99"] >= 0
        assert sum(st["batch_size_hist"].values()) == st["batches"]
        assert st["mode"] == "scan" and st["workers"] == 2
    finally:
        eng.close()


def test_predict_validates_feeds(mlp):
    eng = _engine(mlp)
    try:
        with pytest.raises(KeyError, match="missing"):
            eng.predict({})
        with pytest.raises(ValueError, match="one sample"):
            eng.predict({"x": np.zeros((2, 8), np.float32)})
        with pytest.raises(ValueError, match="rows must be"):
            eng.predict_many({"x": np.zeros((2, 9), np.float32)})
    finally:
        eng.close()
    with pytest.raises(RuntimeError, match="closed"):
        eng.predict({"x": mlp["X"][0]})


def test_loadgen_closed_and_open_loop_smoke(mlp):
    """tools/serving_loadgen.py as a library: both loop disciplines
    drive the engine and report sane percentiles."""
    from tools import serving_loadgen as LG

    eng = _engine(mlp)
    try:
        eng.warm()
        feeds = [{"x": mlp["X"][i]} for i in range(8)]
        res = LG.run_closed_loop(eng.predict, feeds, clients=4,
                                 duration_s=0.25, warmup_s=0.1)
        assert res["n"] > 0 and res["qps"] > 0
        assert res["p50_ms"] <= res["p99_ms"]
        res2 = LG.run_open_loop(eng.submit, feeds, rate_qps=200.0,
                                duration_s=0.25)
        assert res2["n"] > 0 and res2["p99_ms"] > 0
        assert res2["qps"] == pytest.approx(200.0, rel=0.6)
    finally:
        eng.close()


# ======================================================================
# embedding cache (unit)
# ======================================================================
def test_embedding_cache_ttl_lru_and_counters():
    from paddle_tpu.serving import EmbeddingCache

    table = np.arange(40, dtype=np.float32).reshape(10, 4)
    fetches = []

    def fetch(ids):
        fetches.append(np.asarray(ids))
        return table[np.asarray(ids)]

    c = EmbeddingCache(ttl_s=10.0, max_entries=4)
    clock = [100.0]
    c._clock = lambda: clock[0]

    r = c.lookup("t", [1, 2, 1], fetch)
    np.testing.assert_array_equal(r, table[[1, 2, 1]])
    assert len(fetches) == 1  # duplicate id fetched once
    np.testing.assert_array_equal(fetches[0], [1, 2])
    assert (c.hits, c.misses) == (0, 3)

    r = c.lookup("t", [1, 2], fetch)
    np.testing.assert_array_equal(r, table[[1, 2]])
    assert len(fetches) == 1 and c.hits == 2

    # TTL expiry refetches and counts staleness
    clock[0] += 11.0
    c.lookup("t", [1], fetch)
    assert len(fetches) == 2 and c.expired == 1

    # LRU bound: 4 entries max
    c.lookup("t", [3, 4, 5, 6], fetch)
    assert len(c) == 4 and c.evictions > 0

    # per-table keys don't collide
    c.lookup("u", [1], fetch)
    st = c.stats()
    assert st["entries"] <= 4 and 0.0 <= st["hit_rate"] <= 1.0
    c.invalidate("u")
    c.invalidate()
    assert len(c) == 0

    # invalidate() fences an IN-FLIGHT miss fetch: rows read before the
    # table push must not fill the cache after the flush
    def fetch_racing_invalidate(ids):
        c.invalidate()  # lands while the "RPC" is in flight
        return table[np.asarray(ids)]

    c.lookup("t", [9], fetch_racing_invalidate)
    assert len(c) == 0, "pre-invalidate rows were cached after the flush"


def test_rewrite_sparse_lookups_validation(mlp):
    from paddle_tpu.serving import rewrite_sparse_lookups

    with pytest.raises(ValueError, match="no lookup_table"):
        rewrite_sparse_lookups(mlp["main"], ["127.0.0.1:1"])
    with pytest.raises(ValueError, match="empty endpoint"):
        rewrite_sparse_lookups(mlp["main"], [])


# ======================================================================
# serving-time sparse path against LIVE pservers (in-process harness)
# ======================================================================
def _emb_model(n_slots=2, height=40, dim=4):
    """dense + n_slots distributed embeddings -> fc -> sigmoid."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        dense = fluid.data("dense", shape=[4], dtype="float32")
        slots = [fluid.data("s%d" % i, shape=[1], dtype="int64")
                 for i in range(n_slots)]
        embs = []
        for i, s in enumerate(slots):
            e = fluid.layers.embedding(s, size=[height, dim],
                                       param_attr="emb%d" % i,
                                       is_distributed=True)
            embs.append(fluid.layers.reshape(e, [-1, dim]))
        cat = fluid.layers.concat([dense] + embs, axis=1)
        h = fluid.layers.fc(cat, 8, act="relu")
        out = fluid.layers.sigmoid(fluid.layers.fc(h, 1))
    feed_names = ["dense"] + ["s%d" % i for i in range(n_slots)]
    return main, startup, feed_names, out, ["emb%d" % i
                                            for i in range(n_slots)]


def _feed_rows(n, height, n_slots, seed=7):
    rng = np.random.RandomState(seed)
    feed = {"dense": rng.rand(n, 4).astype(np.float32)}
    for i in range(n_slots):
        feed["s%d" % i] = rng.randint(0, height, (n, 1)).astype(np.int64)
    return feed


def test_wide_deep_ps_serving_cache_zero_rpc_ttl_and_parity(
        _ps_isolation):
    """The serving sparse path end to end: distributed_lookup_table
    over the binary wire against two live pservers, fronted by the
    EmbeddingCache. Asserts (acceptance): bit-parity with the
    local-table oracle, ZERO RPCs on the cache-hit path (pserver
    prefetch_rows counters), and TTL expiry refetching."""
    from paddle_tpu.fluid.ps_rpc import VarClient
    from paddle_tpu.serving import (EmbeddingCache, ServingEngine,
                                    rewrite_sparse_lookups)
    from tools import serving_loadgen as LG

    main, startup, feed_names, out, tables = _emb_model()
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    feed = _feed_rows(4, 40, 2)
    (oracle,) = exe.run(main, feed=feed, fetch_list=[out], scope=scope)
    oracle = np.asarray(oracle)

    eps = [f"127.0.0.1:{LG.free_port()}" for _ in range(2)]
    servers = [LG.start_inproc_pserver(ep) for ep in eps]
    try:
        for t in tables:
            LG.push_table(eps, t,
                          np.asarray(scope.find_var(t).value().array))
        ps_prog, hit = rewrite_sparse_lookups(main, eps)
        assert sorted(hit) == tables

        def prefetch_calls():
            n = 0
            for ep in eps:
                st = VarClient.of(ep).call("stats")
                n += st.get("prefetch_rows", {}).get("calls", 0)
            return n

        cache = EmbeddingCache(ttl_s=30.0, max_entries=1000)
        eng = ServingEngine(program=ps_prog, scope=scope,
                            feed_names=feed_names, fetch_names=[out],
                            max_batch=8, max_queue_delay_ms=2.0,
                            num_workers=2, embedding_cache=cache)
        try:
            assert eng.batch_mode == "fused"  # stateful program
            (got,) = eng.predict_many(feed)
            np.testing.assert_array_equal(got, oracle)  # bit-identical
            n1 = prefetch_calls()
            assert n1 > 0 and cache.misses > 0

            # cache-hit path: SAME rows -> zero new RPCs, same bits
            (got2,) = eng.predict_many(feed)
            np.testing.assert_array_equal(got2, oracle)
            assert prefetch_calls() == n1, \
                "cache-hit predict still issued RPCs"
            assert cache.hits > 0

            # TTL expiry: a stale row refetches (and stays bit-equal —
            # the table is unchanged)
            real_clock = time.monotonic
            cache._clock = lambda: real_clock() + 31.0
            (got3,) = eng.predict_many(feed)
            np.testing.assert_array_equal(got3, oracle)
            assert prefetch_calls() > n1
            assert cache.expired > 0
        finally:
            eng.close()
    finally:
        for ep, (th, _s) in zip(eps, servers):
            LG.stop_inproc_pserver(ep, th)


def test_serving_lookup_transparent_across_pserver_drain(_ps_isolation):
    """Satellite: a DRAINING/just-moved pserver mid-serving. The client
    holds the old view; the typed StaleClusterViewError re-route (PR 6)
    must be invisible to predict() — no error, results bit-identical."""
    from paddle_tpu.fluid.ps_rpc import VarClient
    from paddle_tpu.serving import ServingEngine, rewrite_sparse_lookups
    from tools import serving_loadgen as LG

    main, startup, feed_names, out, tables = _emb_model(n_slots=1)
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    feed = _feed_rows(3, 40, 1, seed=11)
    (oracle,) = exe.run(main, feed=feed, fetch_list=[out], scope=scope)
    oracle = np.asarray(oracle)

    slot = f"127.0.0.1:{LG.free_port()}"
    bind_b = f"127.0.0.1:{LG.free_port()}"
    th_a, _ = LG.start_inproc_pserver(slot)
    th_b, _ = LG.start_inproc_pserver(slot, bind=bind_b, standby=True)
    try:
        for t in tables:
            LG.push_table([slot], t,
                          np.asarray(scope.find_var(t).value().array))
        ps_prog, _hit = rewrite_sparse_lookups(main, [slot])
        # no cache: every predict must actually cross the wire, so the
        # re-route is exercised rather than absorbed by a cache hit
        eng = ServingEngine(program=ps_prog, scope=scope,
                            feed_names=feed_names, fetch_names=[out],
                            max_batch=8, num_workers=2)
        try:
            (before,) = eng.predict_many(feed)
            np.testing.assert_array_equal(before, oracle)

            # live drain: the shard moves A -> B mid-serving
            admin = VarClient(slot, connect_timeout=5.0, resolve=False)
            summary = admin.call("drain", dest=bind_b, _rpc_timeout=60.0)
            assert summary["epoch"] == 1

            # the engine's next pulls hit the DRAINED owner with the old
            # view -> typed stale re-route inside the call, no error
            # surfaces and the rows come back bit-identical
            (after,) = eng.predict_many(feed)
            np.testing.assert_array_equal(after, oracle)
            from paddle_tpu.fluid import ps_membership
            assert ps_membership.current_epoch() == 1
        finally:
            eng.close()
    finally:
        LG.stop_inproc_pserver(bind_b, th_b)
        LG.stop_inproc_pserver(slot, th_a)


# ======================================================================
# io.save_inference_model -> Predictor round trip (satellite)
# ======================================================================
def test_wide_deep_save_load_serve_roundtrip(tmp_path):
    """Train a mini wide_deep (Adam -> slot vars exist), save the
    inference model, and serve it three ways — Executor.run on the
    loaded program, AnalysisPredictor, ServingEngine — all bit-identical
    on the same feed. The saved dir must NOT contain optimizer slot
    files (optimizer-slot pruning: pre-fix, save_inference_model wrote
    the TRAINING program's persistables, moments and all)."""
    from paddle_tpu import inference
    from paddle_tpu.models.wide_deep import wide_deep_net
    from paddle_tpu.serving import ServingEngine

    n_slots, height = 3, 30
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        dense = fluid.data("dense", shape=[4], dtype="float32")
        slots = [fluid.data("slot_%d" % i, shape=[1], dtype="int64")
                 for i in range(n_slots)]
        label = fluid.data("label", shape=[1], dtype="float32")
        prob = wide_deep_net(dense, slots, sparse_dim=height,
                             embedding_dim=4, hidden=(8,))
        loss = fluid.layers.mean(
            fluid.layers.log_loss(prob, label))
        fluid.optimizer.Adam(1e-2).minimize(loss)
    feed_names = (["dense"] + ["slot_%d" % i for i in range(n_slots)])

    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(0)

    def batch(n, seed):
        r = np.random.RandomState(seed)
        f = {"dense": r.rand(n, 4).astype(np.float32),
             "label": r.randint(0, 2, (n, 1)).astype(np.float32)}
        for i in range(n_slots):
            f["slot_%d" % i] = r.randint(0, height, (n, 1)).astype(
                np.int64)
        return f

    d = str(tmp_path / "wd_model")
    with fluid.scope_guard(scope):
        exe.run(startup)
        for s in range(3):
            exe.run(main, feed=batch(16, s), fetch_list=[loss],
                    scope=scope)
        fluid.io.save_inference_model(d, feed_names, [prob], exe, main)

    # optimizer-slot pruning: adam moments/beta pows never reach disk
    files = sorted(os.listdir(d))
    slot_files = [f for f in files
                  if "moment" in f or "beta" in f or "pow_acc" in f]
    assert not slot_files, f"optimizer slots leaked into the saved " \
                           f"inference dir: {slot_files}"
    assert any(f.startswith("deep_emb") for f in files)

    feed = {k: v for k, v in batch(5, 99).items() if k != "label"}
    row0 = {n: feed[n][0] for n in feed_names}

    # 1) classic path: load_inference_model + Executor.run
    exe2 = fluid.Executor()
    scope2 = core.Scope()
    with fluid.scope_guard(scope2):
        prog, feeds_l, fetches = fluid.io.load_inference_model(d, exe2)
        assert feeds_l == feed_names
        (want,) = exe2.run(prog, feed=feed, fetch_list=fetches,
                           scope=scope2)
        (want_row0,) = exe2.run(prog,
                                feed={n: feed[n][:1] for n in feed_names},
                                fetch_list=fetches, scope=scope2)
    want, want_row0 = np.asarray(want), np.asarray(want_row0)

    # 2) AnalysisPredictor on the same dir: bit-identical batch output
    pred = inference.create_predictor(inference.Config(d))
    assert pred.get_input_names() == feed_names
    got = pred.run([feed[n] for n in feed_names])[0]
    np.testing.assert_array_equal(np.asarray(got), want)

    # 3) ServingEngine over the predictor: row-exact scan mode — each
    # row bit-identical to the single-row Executor.run oracle
    eng = ServingEngine(pred, max_batch=4, num_workers=2)
    try:
        (row,) = eng.predict(row0)
        np.testing.assert_array_equal(row, want_row0)
    finally:
        eng.close()


# ======================================================================
# cross-process compile-cache cold start (satellite; multiprocess -> slow)
# ======================================================================
_COLD_START_SCRIPT = r"""
import json, os, sys
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core
from paddle_tpu import inference
from paddle_tpu.serving import ServingEngine

model_dir, cache_dir, make = sys.argv[1], sys.argv[2], sys.argv[3] == "1"
# enable FIRST: anything compiled before the cache is on stays
# process-local (in-memory jit cache) and would surface as "new"
# entries in the next process
inference.enable_compile_cache(cache_dir)
if make:
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[16], dtype="float32")
        h = fluid.layers.fc(x, 16, act="relu")
        out = fluid.layers.fc(h, 4, act="softmax")
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(model_dir, ["x"], [out], exe, main)

cfg = inference.Config(model_dir)
cfg.set_optim_cache_dir(cache_dir)  # enable_compile_cache underneath
pred = inference.create_predictor(cfg)
eng = ServingEngine(pred, max_batch=4, num_workers=1)
try:
    eng.warm((1, 2, 4))
    (y,) = eng.predict({"x": np.linspace(0, 1, 16, dtype="float32")})
finally:
    eng.close()
entries = [f for f in os.listdir(cache_dir) if not f.startswith(".")]
print(json.dumps({"entries": len(entries),
                  "y": np.asarray(y).ravel().tolist()}))
"""


@pytest.mark.slow
def test_serving_cold_start_second_process_adds_zero_cache_entries(
        tmp_path):
    """enable_compile_cache serving cold start (extends the
    tests/test_feed_and_compile_cache.py cross-process smoke): a SECOND
    predictor process warming the same buckets over the same saved
    model must add ZERO new cache entries — every bucket executable
    loads from the persistent XLA cache — and serve identical bits."""
    import json
    import subprocess

    model_dir = str(tmp_path / "model")
    cache_dir = str(tmp_path / "xla_cache")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def run_once(make):
        out = subprocess.run(
            [sys.executable, "-c", _COLD_START_SCRIPT, model_dir,
             cache_dir, "1" if make else "0"],
            capture_output=True, text=True, env=env, timeout=300,
            cwd=root)
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    first = run_once(make=True)
    if first["entries"] == 0:
        pytest.skip("backend does not persist executables on this box")
    second = run_once(make=False)
    assert second["entries"] == first["entries"], \
        "second serving process recompiled (cache entries grew) " \
        "instead of loading bucket executables from the persistent cache"
    np.testing.assert_array_equal(first["y"], second["y"])
