"""StaticRNN unroll tests (reference: tests/unittests/
test_recurrent_op.py / StaticRNN usage in test_rnn_memory_helper_op)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core


def test_static_rnn_cumsum_semantics():
    """mem' = mem + x_t → outputs are the running prefix sums."""
    T, B, D = 4, 2, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[T, B, D], dtype="float32",
                       append_batch_size=False)
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)
            mem = rnn.memory(shape=[-1, D], batch_ref=x_t)
            acc = fluid.layers.elementwise_add(mem, x_t)
            rnn.update_memory(mem, acc)
            rnn.step_output(acc)
        out = rnn()
    exe = fluid.Executor()
    scope = core.Scope()
    X = np.random.RandomState(0).rand(T, B, D).astype("float32")
    with fluid.scope_guard(scope):
        exe.run(startup)
        (o,) = exe.run(main, feed={"x": X}, fetch_list=[out])
    np.testing.assert_allclose(o, np.cumsum(X, axis=0), rtol=1e-5)


def test_static_rnn_with_fc_trains():
    T, B, D, H = 3, 4, 5, 6
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[T, B, D], dtype="float32",
                       append_batch_size=False)
        y = fluid.data("y", shape=[B, 1], dtype="int64",
                       append_batch_size=False)
        w = fluid.ParamAttr(name="rnn_fc_w")
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)
            h_prev = rnn.memory(shape=[-1, H], batch_ref=x_t)
            cat = fluid.layers.concat([x_t, h_prev], axis=1)
            h = fluid.layers.fc(cat, H, act="tanh", param_attr=w,
                                bias_attr=False)
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        seq = rnn()                     # [T, B, H]
        last = fluid.layers.slice(seq, axes=[0], starts=[T - 1], ends=[T])
        last = fluid.layers.squeeze(last, [0])
        pred = fluid.layers.fc(last, 3, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
        fluid.optimizer.Adam(0.05).minimize(loss)
    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(1)
    X = rng.rand(T, B, D).astype("float32")
    Y = rng.randint(0, 3, (B, 1)).astype("int64")
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(20):
            (lv,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.5, losses


def test_cell_weights_shared_across_unrolled_steps():
    """Round-4 fix: the cell's two-input fc used to get a name-dropping
    attr copy for the hidden projection — a FRESH Wh per unrolled step.
    The recurrence must create exactly Wx + Wh (+ bias) however long the
    unroll is, and Wx must not be tied to Wh."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    import paddle_tpu.fluid.layers as layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[7, 5], dtype="float32")  # T=7, D=5
        cell = layers.GRUCell(hidden_size=5)
        out, _ = layers.rnn(cell, x)
    names = sorted(p.name for p in main.all_parameters())
    assert len(names) == 3, names  # Wx, Wh, bias — not 2*T weights
    wx = [n for n in names if n.endswith("_x")]
    wh = [n for n in names if n.endswith("_h")]
    assert len(wx) == 1 and len(wh) == 1 and wx[0] != wh[0], names
