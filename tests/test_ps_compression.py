"""Compressed PS data plane (docs/PS_DATA_PLANE.md "Compression").

Covers the three legs of the compression plane plus its contracts:
  * wire v3 quantized frames — fp16/int8 round-trip error bounds,
    hello negotiation compat BOTH directions (quant peer ↔ pre-quant
    peer always exchanges exact frames), dedup-token replay of a
    quantized frame (retry re-sends the exact quantized bytes), and
    the dequant-on-receive → FLAGS_ps_reject_nonfinite interaction;
  * DGC top-k dense grads — the error-feedback invariant (everything
    sent plus the residual equals the true accumulated gradient), the
    warm-up sparsity ramp, and the dgc_send server apply;
  * replica-chain regression — a quantized/DGC push chain-forwarded to
    a PR 6 warm standby keeps the replica bit-identical to the primary
    (the chain forwards the DECODED apply, never the compressed frame);
  * the geo async WAN lane — delta rounds riding the geo RoundPipeline
    under injected RTT, and the multiprocess 2-region acceptance
    scenario (slow): geo+DGC+int8 ≥5× plain-sync throughput at 50ms
    injected delay, converging to the sync oracle's loss neighborhood.
"""
import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import faultinject as FI

REPO = FI.REPO
WORKLOAD = os.path.join(REPO, "tests", "dist_ps_workload.py")

pytestmark = pytest.mark.wan


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.fixture(autouse=True)
def _compression_isolation():
    """Every test starts with compression off, a fresh client pool, and
    a fresh DGC compressor; flags touched by tests are restored."""
    from paddle_tpu.fluid import communicator, core, ps_membership
    from paddle_tpu.fluid import ps_rpc
    from paddle_tpu.fluid.ps_rpc import VarClient

    saved = {k: core.globals_[k] for k in
             ("FLAGS_ps_wire_quant", "FLAGS_dgc", "FLAGS_dgc_sparsity",
              "FLAGS_dgc_momentum", "FLAGS_dgc_warmup_steps",
              "FLAGS_dgc_min_elements", "FLAGS_ps_reject_nonfinite",
              "FLAGS_ps_replicas", "FLAGS_async_staleness",
              "FLAGS_rpc_retry_times")}
    ps_membership.reset_views()
    yield
    ps_membership.reset_views()
    VarClient.reset_pool()
    communicator.reset_dgc()
    communicator.reset_geo_pipeline()
    ps_rpc.reset_quant_wire_stats()
    for k, v in saved.items():
        core.globals_[k] = v


# ==========================================================================
# quantization codec units
# ==========================================================================
def test_int8_roundtrip_error_bound():
    """Per-row absmax int8: |x - dequant(quant(x))| <= absmax_row/254
    (half a quantization step), zero rows exact, 1-D arrays treated as
    one row."""
    from paddle_tpu.fluid.ps_rpc import _dequant_int8, _quant_int8

    rng = np.random.RandomState(7)
    x = (rng.randn(64, 16) * rng.uniform(0.01, 100, (64, 1))).astype(
        np.float32)
    x[5] = 0.0  # all-zero row must stay exactly zero
    q, scale = _quant_int8(x)
    assert q.dtype == np.int8 and scale.dtype == np.float32
    back = _dequant_int8(q, scale, np.dtype(np.float32))
    bound = np.abs(x).max(axis=1, keepdims=True) / 254.0 + 1e-12
    assert (np.abs(back - x) <= bound).all()
    np.testing.assert_array_equal(back[5], np.zeros(16, np.float32))

    v = rng.randn(33).astype(np.float32)  # 1-D: one row
    qv, sv = _quant_int8(v)
    assert sv.shape == (1,)
    backv = _dequant_int8(qv, sv, np.dtype(np.float32))
    assert (np.abs(backv - v) <= np.abs(v).max() / 254.0 + 1e-12).all()


def test_fp16_quant_wire_roundtrip_error_bound():
    """fp16 frames: relative error <= 2^-11 + eps for values inside the
    fp16 normal range, measured through a real server round trip."""
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid.ps_rpc import VarClient, VarServer

    store = {}
    srv = VarServer(f"127.0.0.1:{free_port()}",
                    {"send_var": lambda name, value, trainer_id=0,
                     rows=None, height=0:
                     store.__setitem__(name, np.asarray(value)) or True
                     }).start()
    try:
        core.set_flag("FLAGS_ps_wire_quant", "fp16")
        cli = VarClient(f"127.0.0.1:{srv.port}", channels=1)
        x = np.random.RandomState(0).randn(32, 8).astype(np.float32)
        cli.send_var("w", x)
        np.testing.assert_allclose(store["w"], x, rtol=2 ** -11 + 1e-4)
        assert store["w"].dtype == np.float32
        cli.close()
    finally:
        srv.shutdown()


def test_int8_wire_end_to_end_counters_and_both_directions():
    """int8 frames through a real server: the pushed value lands within
    the per-row bound, the PULL response is quantized too (server-side
    flag, same connection), and the ps_wire bytes counters record the
    savings."""
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid import ps_rpc
    from paddle_tpu.fluid.ps_rpc import (PROTO_BINARY_Q, VarClient,
                                         VarServer, quant_wire_stats)

    store = {}
    srv = VarServer(f"127.0.0.1:{free_port()}",
                    {"send_var": lambda name, value, trainer_id=0,
                     rows=None, height=0:
                     store.__setitem__(name, np.asarray(value)) or True,
                     "get_var": lambda name, trainer_id=0: store[name]
                     }).start()
    try:
        ps_rpc.reset_quant_wire_stats()
        core.set_flag("FLAGS_ps_wire_quant", "int8")
        cli = VarClient(f"127.0.0.1:{srv.port}", channels=1)
        assert cli._channels[0].proto == PROTO_BINARY_Q
        x = np.random.RandomState(1).randn(128, 16).astype(np.float32)
        cli.send_var("w", x)
        bound = np.abs(x).max(axis=1, keepdims=True) / 254.0 + 1e-12
        assert (np.abs(store["w"] - x) <= bound).all()
        # the pull response quantizes against the SERVER-side stored
        # value — one more half-step of error at most
        back = np.asarray(cli.get_var("w"))
        b2 = np.abs(store["w"]).max(axis=1, keepdims=True) / 254.0
        assert (np.abs(back - store["w"]) <= b2 + 1e-12).all()
        qs = quant_wire_stats()
        assert qs["frames_quantized_total"] >= 2  # push + pull response
        assert 0 < qs["bytes_sent_total"] < qs["bytes_raw_total"]
        # int8 + f32 scale per 16-wide row = (16 + 4)/64 of raw
        assert qs["bytes_raw_total"] / qs["bytes_sent_total"] > 3.0
        cli.close()
    finally:
        srv.shutdown()


def test_int8_nonfinite_payload_ships_raw():
    """A non-finite float32 array must NOT int8-quantize (rint(NaN) is
    undefined in int8) — it ships raw so the receiving guard sees the
    poison exactly."""
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid.ps_rpc import VarClient, VarServer

    store = {}
    srv = VarServer(f"127.0.0.1:{free_port()}",
                    {"send_var": lambda name, value, trainer_id=0,
                     rows=None, height=0:
                     store.__setitem__(name, np.asarray(value)) or True
                     }).start()
    try:
        core.set_flag("FLAGS_ps_wire_quant", "int8")
        cli = VarClient(f"127.0.0.1:{srv.port}", channels=1)
        x = np.arange(64, dtype=np.float32).reshape(8, 8)
        x[3, 4] = np.nan
        x[6, 1] = np.inf
        cli.send_var("w", x)
        np.testing.assert_array_equal(store["w"], x)  # exact, poison too
        cli.close()
    finally:
        srv.shutdown()


# ==========================================================================
# wire-generation compat — quant peer ↔ pre-quant peer, both directions
# ==========================================================================
def test_quant_client_against_v2_and_legacy_servers_stays_exact():
    """A quant-flagged client negotiating with a pre-quant (v2-capped)
    server — and with a legacy v1 server — must deliver EXACT values:
    the hello settles on the lower generation and no quantized spec
    ever crosses the link."""
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid.ps_rpc import (PROTO_BINARY, PROTO_PICKLE,
                                         VarClient, VarServer)

    core.set_flag("FLAGS_ps_wire_quant", "int8")
    x = np.random.RandomState(2).randn(32, 8).astype(np.float32)
    store = {}

    def h(name, value, trainer_id=0, rows=None, height=0):
        store[name] = np.asarray(value)
        return True

    v2 = VarServer(f"127.0.0.1:{free_port()}", {"send_var": h},
                   wire_version=2).start()
    leg = VarServer(f"127.0.0.1:{free_port()}", {"send_var": h},
                    legacy_wire=True).start()
    try:
        c2 = VarClient(f"127.0.0.1:{v2.port}", channels=1)
        assert c2._channels[0].proto == PROTO_BINARY
        c2.send_var("v2", x)
        np.testing.assert_array_equal(store["v2"], x)
        c1 = VarClient(f"127.0.0.1:{leg.port}", channels=1)
        assert c1._channels[0].proto == PROTO_PICKLE
        c1.send_var("v1", x)
        np.testing.assert_array_equal(store["v1"], x)
        c2.close()
        c1.close()
    finally:
        v2.shutdown()
        leg.shutdown()


def test_prequant_client_against_quant_server_stays_exact():
    """The reverse direction: a pre-quant client (v2-capped hello, and
    the full-legacy pickle lane) against a server whose quant flag is
    ON must still receive exact pull responses — response quantization
    is gated on the NEGOTIATED generation, not the flag alone."""
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid.ps_rpc import (PROTO_BINARY, PROTO_PICKLE,
                                         VarClient, VarServer)

    x = np.random.RandomState(3).randn(16, 8).astype(np.float32)
    srv = VarServer(f"127.0.0.1:{free_port()}",
                    {"get_var": lambda name, trainer_id=0: x}).start()
    ep = f"127.0.0.1:{srv.port}"
    try:
        core.set_flag("FLAGS_ps_wire_quant", "int8")
        old_cli = VarClient(ep, channels=1, wire_version=2)
        assert old_cli._channels[0].proto == PROTO_BINARY
        np.testing.assert_array_equal(np.asarray(old_cli.get_var("w")), x)
        old_cli.close()
        os.environ["PADDLE_TPU_PS_PICKLE_WIRE"] = "1"
        try:
            pick_cli = VarClient(ep, channels=1)
            assert pick_cli._channels[0].proto == PROTO_PICKLE
            np.testing.assert_array_equal(
                np.asarray(pick_cli.get_var("w")), x)
            pick_cli.close()
        finally:
            os.environ.pop("PADDLE_TPU_PS_PICKLE_WIRE", None)
        # sanity: a CURRENT client on the same server IS quantized
        new_cli = VarClient(ep, channels=1)
        got = np.asarray(new_cli.get_var("w"))
        assert not np.array_equal(got, x)  # lossy — proves the gate
        assert (np.abs(got - x)
                <= np.abs(x).max(axis=1, keepdims=True) / 254.0
                + 1e-12).all()
        new_cli.close()
    finally:
        srv.shutdown()


def test_quantized_frame_dedup_retry_replays_verbatim():
    """A server death mid-call with quantization ON: the retry re-sends
    the CACHED quantized parts verbatim under the same dedup token —
    applied exactly once, and the applied value equals the local
    dequant(quant(x)) prediction bit-for-bit (no re-quantization on
    the retry path)."""
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid.ps_rpc import (PROTO_BINARY_Q, VarClient,
                                         VarServer, _dequant_int8,
                                         _quant_int8)

    applied = []

    def h_send(name, value, trainer_id=0, rows=None, height=0):
        applied.append(np.asarray(value))
        return True

    core.set_flag("FLAGS_ps_wire_quant", "int8")
    port = free_port()
    ep = f"127.0.0.1:{port}"
    srv = VarServer(ep, {"send_var": h_send}).start()
    cli = VarClient(ep, channels=1)
    assert cli._channels[0].proto == PROTO_BINARY_Q
    srv2 = None
    try:
        # sever the negotiated connection server-side, like a crash —
        # the in-flight/next frame dies mid-stream
        srv.shutdown()
        srv2 = VarServer(ep, {"send_var": h_send}).start()
        big = np.random.RandomState(4).randn(1 << 12, 16).astype(
            np.float32)
        assert cli.send_var("w", big) is True
        assert len(applied) == 1  # exactly once
        q, scale = _quant_int8(big)
        np.testing.assert_array_equal(
            applied[0], _dequant_int8(q, scale, np.dtype(np.float32)))
        assert cli._channels[0].proto == PROTO_BINARY_Q
        assert srv2.stats()["send_var"]["calls"] == 1
        cli.close()
    finally:
        for s in (srv, srv2):
            try:
                if s is not None:
                    s.shutdown()
            except Exception:
                pass


# ==========================================================================
# dequant-on-receive feeds the pserver non-finite guard
# ==========================================================================
def _start_listen_and_serv(sync=False, fanin=1):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core

    main = fluid.Program()
    ep = f"127.0.0.1:{free_port()}"
    with fluid.program_guard(main, fluid.Program()):
        main.global_block().append_op(
            type="listen_and_serv", inputs={}, outputs={},
            attrs={"endpoint": ep, "sync_mode": sync, "Fanin": fanin,
                   "optimize_blocks": [], "grad_to_block_id": []})
    scope = core.Scope()
    exe = fluid.Executor()
    th = threading.Thread(
        target=lambda: exe.run(main, scope=scope, feed={},
                               fetch_list=[]), daemon=True)
    th.start()
    return ep, th, scope


def _stop_listen_and_serv(ep, th):
    from paddle_tpu.fluid.ps_rpc import VarClient
    try:
        c = VarClient(ep, connect_timeout=5.0, channels=1, resolve=False)
        c.stop()
        c.close()
    except Exception:
        pass
    th.join(timeout=10)


def test_fp16_overflow_hits_server_nonfinite_reject():
    """An fp16-quantized value beyond the fp16 range arrives as Inf
    after dequant-on-receive — and the pserver's
    FLAGS_ps_reject_nonfinite=reject guard refuses it TYPED back to the
    sender. Quantization cannot smuggle poison past the guard."""
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid.ps_rpc import VarClient

    core.set_flag("FLAGS_ps_wire_quant", "fp16")
    core.set_flag("FLAGS_ps_reject_nonfinite", "reject")
    ep, th, _scope = _start_listen_and_serv()
    try:
        cli = VarClient(ep, channels=1)
        big = np.full((4, 4), 1e38, np.float32)  # fp16 range: ±65504
        with pytest.raises(core.NumericFaultError):
            cli.send_var("w", big)
        # the server is intact and still serving exact-frame traffic
        core.set_flag("FLAGS_ps_wire_quant", "")
        ok = np.ones((2, 2), np.float32)
        assert cli.send_var("w2", ok) is True
        np.testing.assert_array_equal(
            np.asarray(cli.get_var("w2")), ok)
        cli.close()
    finally:
        core.set_flag("FLAGS_ps_reject_nonfinite", "")
        _stop_listen_and_serv(ep, th)


# ==========================================================================
# DGC — error feedback, warm-up, server apply
# ==========================================================================
def test_dgc_error_feedback_sum_invariant():
    """The DGC contract: after any number of compressed pushes, the
    scatter-sum of everything SENT plus the residual accumulator equals
    the sum of the true gradients (momentum 0 — pure error feedback)."""
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid.communicator import DGCCompressor

    core.set_flag("FLAGS_dgc_min_elements", 1)
    core.set_flag("FLAGS_dgc_momentum", 0.0)
    core.set_flag("FLAGS_dgc_sparsity", 0.9)
    core.set_flag("FLAGS_dgc_warmup_steps", 0)
    comp = DGCCompressor()
    rng = np.random.RandomState(11)
    n = 400
    true_sum = np.zeros(n, np.float64)
    sent_sum = np.zeros(n, np.float64)
    for _ in range(13):
        g = rng.randn(n).astype(np.float32)
        true_sum += g.astype(np.float64)
        idx, vals = comp.compress("w@GRAD", g)
        assert idx.size == max(1, round(n * 0.1))
        np.add.at(sent_sum, idx, vals.astype(np.float64))
    residual = comp.residual("w@GRAD").astype(np.float64)
    np.testing.assert_allclose(sent_sum + residual, true_sum,
                               rtol=1e-5, atol=1e-5)
    st = comp.stats()
    assert st["compression_ratio"] == pytest.approx(10.0, rel=0.05)


def test_dgc_warmup_ramps_sparsity_and_momentum_masks():
    """Warm-up sends MORE early: the per-push selection shrinks toward
    the final sparsity over FLAGS_dgc_warmup_steps; and with momentum
    on, selected entries zero BOTH u and v (factor masking)."""
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid.communicator import DGCCompressor

    core.set_flag("FLAGS_dgc_min_elements", 1)
    core.set_flag("FLAGS_dgc_sparsity", 0.99)
    core.set_flag("FLAGS_dgc_warmup_steps", 4)
    core.set_flag("FLAGS_dgc_momentum", 0.9)
    comp = DGCCompressor()
    rng = np.random.RandomState(5)
    n = 1000
    sizes = []
    for _ in range(6):
        idx, _vals = comp.compress("g", rng.randn(n).astype(np.float32))
        sizes.append(idx.size)
    # monotonically non-increasing toward the final 1% selection
    assert sizes == sorted(sizes, reverse=True)
    assert sizes[0] > sizes[-1]
    assert sizes[-1] == max(1, round(n * 0.01))
    # sub-threshold and non-f32 grads ship dense
    core.set_flag("FLAGS_dgc_min_elements", 512)
    assert comp.compress("tiny", np.ones(4, np.float32)) is None
    assert comp.compress("ints", np.ones(1024, np.int64)) is None


def test_dgc_send_reconstructs_dense_apply_on_server():
    """h_dgc_send against the real listen_and_serv: the (indices,
    values) frame lands as the scattered dense value — identical to
    what a dense send of the scatter would have produced."""
    from paddle_tpu.fluid.ps_rpc import VarClient

    ep, th, _scope = _start_listen_and_serv()
    try:
        cli = VarClient(ep, channels=1)
        shape = [8, 4]
        idx = np.asarray([0, 5, 17, 31], np.int64)
        vals = np.asarray([1.5, -2.0, 3.25, 0.5], np.float32)
        assert cli.call("dgc_send", name="g", values=vals, indices=idx,
                        shape=shape, trainer_id=0) is True
        want = np.zeros(32, np.float32)
        want[idx] = vals
        np.testing.assert_array_equal(
            np.asarray(cli.get_var("g")), want.reshape(8, 4))
        cli.close()
    finally:
        _stop_listen_and_serv(ep, th)


def test_push_dense_batch_compresses_and_falls_back_dense():
    """_push_dense_batch: with FLAGS_dgc on, an eligible grad rides
    dgc_send (server var == top-k scatter, residual holds the rest);
    against a server WITHOUT dgc_send the full accumulated grad ships
    dense — nothing lost, nothing double-sent, miss memoized."""
    from paddle_tpu.fluid import communicator, core
    from paddle_tpu.fluid.ps_rpc import VarClient, VarServer
    from paddle_tpu.ops.distributed_ops import _push_dense_batch

    core.set_flag("FLAGS_dgc", True)
    core.set_flag("FLAGS_dgc_min_elements", 1)
    core.set_flag("FLAGS_dgc_momentum", 0.0)
    core.set_flag("FLAGS_dgc_sparsity", 0.75)
    core.set_flag("FLAGS_dgc_warmup_steps", 0)

    ep, th, _scope = _start_listen_and_serv()
    try:
        g = np.random.RandomState(6).randn(10, 10).astype(np.float32)
        _push_dense_batch(ep, [("g@GRAD", g)], 0)
        comp = communicator.dgc_compressor()
        res = comp.residual("g@GRAD").reshape(10, 10)
        cli = VarClient.of(ep)
        got = np.asarray(cli.get_var("g@GRAD"))
        # sent + residual == g, and the sent part is the top-25%
        np.testing.assert_allclose(got + res, g, rtol=1e-6, atol=1e-7)
        assert (got != 0).sum() == 25
    finally:
        _stop_listen_and_serv(ep, th)

    # old server: no dgc_send handler anywhere in the handler map
    applied = []
    old = VarServer(f"127.0.0.1:{free_port()}",
                    {"send_var": lambda name, value, trainer_id=0,
                     rows=None, height=0:
                     applied.append(np.asarray(value)) or True}).start()
    try:
        from paddle_tpu.fluid import communicator
        comp = communicator.dgc_compressor()
        ep2 = f"127.0.0.1:{old.port}"
        g2 = np.random.RandomState(7).randn(8, 8).astype(np.float32)
        _push_dense_batch(ep2, [("h@GRAD", g2)], 0)
        (dense,) = applied
        # the fallback shipped the FULL accumulated grad, residual zero
        np.testing.assert_allclose(dense, g2, rtol=1e-6, atol=1e-7)
        np.testing.assert_array_equal(
            comp.residual("h@GRAD"), np.zeros(64, np.float32))
        assert "dgc_send" in VarClient.of(ep2)._missing_methods
    finally:
        old.shutdown()


# ==========================================================================
# replica-chain regression: compressed pushes keep the standby
# bit-identical (forward the decoded apply, not the compressed frame)
# ==========================================================================
def _start_pserver_thread(endpoint, bind="", standby=False,
                          replica_map=None, replica_of=""):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core

    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        main.global_block().append_op(
            type="listen_and_serv", inputs={}, outputs={},
            attrs={"endpoint": endpoint, "sync_mode": False, "Fanin": 1,
                   "optimize_blocks": [], "grad_to_block_id": [],
                   "pserver_endpoints": [endpoint],
                   "bind_endpoint": bind, "standby": standby,
                   "replica_of": replica_of})
    scope = core.Scope()
    exe = fluid.Executor()
    th = threading.Thread(
        target=lambda: exe.run(main, scope=scope, feed={},
                               fetch_list=[]), daemon=True)
    th.start()
    return th, scope


def test_replica_chain_stays_bit_identical_under_quant_and_dgc(
        monkeypatch):
    """FLAGS_ps_replicas=2 with int8 wire quant AND DGC pushes: every
    apply the primary runs chain-forwards the DECODED values, so the
    warm standby's state is bit-identical to the primary's — the
    regression that would catch forwarding the compressed frame (a
    re-quantized forward drifts by a quantization step)."""
    from paddle_tpu.fluid import core, ps_membership
    from paddle_tpu.fluid.ps_rpc import VarClient

    slot = f"127.0.0.1:{free_port()}"
    rep = f"127.0.0.1:{free_port()}"
    monkeypatch.setenv("PADDLE_PS_REPLICA_MAP", f"{slot}={rep}")
    core.set_flag("FLAGS_ps_replicas", 2)
    core.set_flag("FLAGS_ps_wire_quant", "int8")
    core.set_flag("FLAGS_dgc", True)
    core.set_flag("FLAGS_dgc_min_elements", 1)
    core.set_flag("FLAGS_dgc_sparsity", 0.5)
    ps_membership.reset_views()

    th_p, scope_p = _start_pserver_thread(slot)
    th_r, scope_r = _start_pserver_thread(slot, bind=rep, standby=True,
                                          replica_of=slot)
    try:
        from paddle_tpu.ops.distributed_ops import _push_dense_batch
        cli = VarClient(slot, connect_timeout=30.0, channels=1)
        rng = np.random.RandomState(8)
        # host the table first (dense send), then a quantized sparse
        # row push applies row-wise SGD onto it on both ends
        cli.send_var("emb", np.ones((12, 6), np.float32))
        rows = np.asarray([1, 3, 9], np.int64)
        vals = rng.randn(3, 6).astype(np.float32) * 3.7
        cli.send_var("emb@GRAD", vals, rows=rows, height=0)
        # quantized dense push + DGC'd dense push
        cli.send_var("dense", rng.randn(5, 5).astype(np.float32))
        _push_dense_batch(slot, [("g@GRAD",
                                  rng.randn(6, 6).astype(np.float32))],
                          0)
        # geo delta (flat + row forms)
        cli.call("geo_delta", name="dense",
                 value=rng.randn(5, 5).astype(np.float32))
        deadline = time.time() + 10
        names = ["emb", "dense", "g@GRAD"]
        while time.time() < deadline:
            if all(scope_r.find_var(n) is not None
                   and scope_r.find_var(n).is_initialized()
                   for n in names):
                break
            time.sleep(0.05)
        for n in names:
            pv = np.asarray(scope_p.find_var(n).value().array)
            rv = np.asarray(scope_r.find_var(n).value().array)
            np.testing.assert_array_equal(pv, rv), n
        cli.close()
    finally:
        for ep, th in ((rep, th_r), (slot, th_p)):
            _stop_listen_and_serv(ep, th)


# ==========================================================================
# geo async WAN lane — in-process unit
# ==========================================================================
def test_geo_async_rounds_converge_under_injected_delay():
    """Single-region in-process unit of the WAN lane: geo training with
    FLAGS_async_staleness=2 + DGC + int8 quant under a 30ms injected
    server delay still converges, the geo RoundPipeline carries the
    delta rounds, and the local steps never block on the full RTT (the
    loop finishes far faster than steps × RTT would allow)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import communicator, core
    from paddle_tpu.fluid.communicator import drain_async_rounds
    from paddle_tpu.fluid.transpiler import (DistributeTranspiler,
                                             DistributeTranspilerConfig)

    # build the linear workload's geo trainer program against one
    # in-process pserver
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        y = fluid.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, 1,
                               param_attr=fluid.ParamAttr(name="w"),
                               bias_attr=fluid.ParamAttr(name="b"))
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    ps_ep = f"127.0.0.1:{free_port()}"
    cfg = DistributeTranspilerConfig()
    cfg.geo_sgd_mode = True
    cfg.geo_sgd_need_push_nums = 4
    t = DistributeTranspiler(cfg)
    with fluid.program_guard(main, startup):
        t.transpile(trainer_id=0, pservers=ps_ep, trainers=1,
                    sync_mode=False, program=main,
                    startup_program=startup)
    pprog = t.get_pserver_program(ps_ep)
    pstart = t.get_startup_program(ps_ep, pprog)

    from paddle_tpu.fluid import core as _core
    ps_scope = _core.Scope()
    ps_exe = fluid.Executor()

    def _serve():
        with fluid.scope_guard(ps_scope):
            ps_exe.run(pstart)
            ps_exe.run(pprog)

    th = threading.Thread(target=_serve, daemon=True)
    th.start()

    core.set_flag("FLAGS_async_staleness", 2)
    core.set_flag("FLAGS_dgc", True)
    core.set_flag("FLAGS_dgc_min_elements", 1)
    core.set_flag("FLAGS_dgc_sparsity", 0.5)
    core.set_flag("FLAGS_ps_wire_quant", "int8")
    rng = np.random.RandomState(7)
    X = rng.rand(8, 4).astype("float32")
    Y = (X @ np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
         + 0.25)
    exe = fluid.Executor()
    scope = core.Scope()
    losses = []
    try:
        with FI.rpc_delay(30, jitter_ms=5):
            with fluid.scope_guard(scope):
                exe.run(startup)
                prog = t.get_trainer_program()
                t0 = time.perf_counter()
                steps = 44
                for _ in range(steps):
                    (lv,) = exe.run(prog, feed={"x": X, "y": Y},
                                    fetch_list=[loss])
                    losses.append(float(np.asarray(lv).reshape(-1)[0]))
                drain_async_rounds()
                dt = time.perf_counter() - t0
        assert losses[-1] < losses[0] * 0.25, losses
        pipe = communicator.active_geo_pipeline()
        assert pipe is not None
        st = pipe.stats()
        assert st["rounds_submitted"] >= 4
        assert st["rounds_submitted"] == st["rounds_acked"]
        # loose sanity bound: the loop must not have serialized every
        # sync point's delayed RPC chain into the steps (CI-safe)
        assert dt < 5.0, dt
        dgc = communicator.active_dgc_stats()
        assert dgc.get("pushes_total", 0) >= 4
    finally:
        core.set_flag("FLAGS_async_staleness", 0)
        _stop_listen_and_serv(ps_ep, th)


# ==========================================================================
# multiprocess 2-region WAN acceptance (slow)
# ==========================================================================
def _run_wan_cluster(tmpdir, tag, steps, env_extra, geo):
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep +
               os.environ.get("PYTHONPATH", ""), JAX_PLATFORMS="cpu",
               **{k: str(v) for k, v in env_extra.items()})
    ep = f"127.0.0.1:{free_port()}"
    # --sparse gives both lanes a real embedding table: geo row-delta
    # pushes are wide enough to clear the int8 profitability floor
    # (the toy dense params are 1-4 floats — correctly shipped raw)
    flags = (["--geo"] if geo else []) + ["--timing", "--sparse",
                                          "--emb-dim=16"]
    procs, outs = [], []
    ps_out = os.path.join(tmpdir, f"{tag}_ps.ready")
    logp = os.path.join(tmpdir, f"{tag}_ps.log")
    ps = subprocess.Popen(
        [sys.executable, WORKLOAD, "pserver", ep, "0", "2", str(steps),
         ps_out] + flags, env=env, stdout=open(logp, "wb"),
        stderr=subprocess.STDOUT)
    procs.append(ps)
    deadline = time.time() + 90
    while not os.path.exists(ps_out):
        assert ps.poll() is None, open(logp).read()[-3000:]
        assert time.time() < deadline, "pserver never became ready"
        time.sleep(0.2)
    for tid in range(2):
        out = os.path.join(tmpdir, f"{tag}_t{tid}.json")
        outs.append(out)
        procs.append(subprocess.Popen(
            [sys.executable, WORKLOAD, "trainer", ep, str(tid), "2",
             str(steps), out] + flags, env=env,
            stdout=open(os.path.join(tmpdir, f"{tag}_t{tid}.log"), "wb"),
            stderr=subprocess.STDOUT))
    try:
        for p in procs[1:]:
            p.wait(timeout=300)
            assert p.returncode == 0, (
                tag, open(os.path.join(
                    tmpdir, f"{tag}_t{procs.index(p) - 1}.log")
                ).read()[-3000:])
        ps.wait(timeout=30)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return [json.load(open(o)) for o in outs]


@pytest.mark.slow
def test_two_region_wan_geo_dgc_quant_5x_sync_throughput(tmp_path):
    """THE acceptance scenario (ISSUE 11): an emulated 2-region cluster
    — two trainer processes, one pserver, 50ms injected RTT with 10ms
    jitter on every data RPC — where geo-delta rounds + DGC top-k +
    int8 quantized frames reach ≥5× the per-step throughput of plain
    sync under the SAME delay, while converging into the sync oracle's
    loss neighborhood (the loss gap is asserted AND reported)."""
    wan = {"PADDLE_TPU_PS_RPC_DELAY_MS": 50,
           "PADDLE_TPU_PS_RPC_DELAY_JITTER_MS": 10}
    steps = 30
    sync_res = _run_wan_cluster(str(tmp_path), "sync", steps, wan,
                                geo=False)
    geo_res = _run_wan_cluster(
        str(tmp_path), "geo", steps,
        dict(wan, FLAGS_async_staleness=2, FLAGS_dgc=1,
             FLAGS_dgc_min_elements=1, FLAGS_ps_wire_quant="int8",
             PADDLE_TPU_GEO_PUSH_NUMS=10),
        geo=True)

    sync_sps = sum(r["steps"] / r["elapsed_s"] for r in sync_res)
    geo_sps = sum(r["steps"] / r["elapsed_s"] for r in geo_res)
    speedup = geo_sps / sync_sps
    sync_last = sync_res[0]["losses"][-1]
    geo_last = geo_res[0]["losses"][-1]
    loss_gap = geo_last - sync_last
    print(f"WAN 2-region: sync {sync_sps:.1f} steps/s, compressed geo "
          f"{geo_sps:.1f} steps/s → {speedup:.1f}x; loss sync={sync_last:.5f} "
          f"geo={geo_last:.5f} gap={loss_gap:+.5f}")
    assert speedup >= 5.0, (sync_sps, geo_sps)
    # both converge, and geo lands in (or below) the sync oracle's
    # loss neighborhood — one-sided: equal step counts favor geo's
    # LOCAL steps over sync's averaged ones, so geo finishing further
    # down is expected; what compression must never do is leave it
    # stranded ABOVE the oracle
    assert geo_last < geo_res[0]["losses"][0] * 0.5
    assert loss_gap <= max(0.05, 0.25 * abs(sync_last)), loss_gap
    # compression evidence crossed the wire: DGC sparsified pushes and
    # quantized frames saved bytes
    dgc = geo_res[0]["dgc"]
    assert dgc.get("pushes_total", 0) > 0
    assert dgc["elements_sent"] < dgc["elements_total"]
    quant = geo_res[0]["quant"]
    assert 0 < quant["bytes_sent_total"] < quant["bytes_raw_total"]


# ==========================================================================
# thin-pipe microbench acceptance: int8 ≥2× effective MB/s at ≥1MB
# ==========================================================================
@pytest.mark.slow
def test_int8_frames_2x_effective_throughput_on_thin_pipe():
    """Wire microbench acceptance on the bandwidth-bound regime the
    compression plane targets: on an emulated 50 MB/s pipe
    (PADDLE_TPU_PS_RPC_BANDWIDTH_MBPS), int8 frames deliver ≥2× the
    raw-frame effective MB/s at ≥1MB payloads. (Raw loopback is
    CPU-bound at GB/s — recorded as the caveat lane in BENCH_LOCAL.)"""
    from tools import rpc_microbench

    rows = rpc_microbench.run_quant(sizes=[1 << 20, 1 << 22],
                                    repeats=2, warmup=1,
                                    bandwidth_mbps=50)
    for r in rows:
        assert r["int8_speedup"] >= 2.0, rows
        assert r["int8_wire_ratio"] > 3.0, rows


@pytest.mark.rpcbench
def test_rpc_quant_microbench_smoke():
    """Tiny quant sweep smoke: all three modes measured, quantized
    modes record a real on-wire compression ratio."""
    from tools import rpc_microbench

    rows = rpc_microbench.run_quant(sizes=[1 << 16], repeats=1,
                                    warmup=1)
    (row,) = rows
    for key in ("raw_mb_s", "fp16_mb_s", "int8_mb_s"):
        assert row[key] > 0
    assert row["fp16_wire_ratio"] > 1.5
    assert row["int8_wire_ratio"] > 3.0
