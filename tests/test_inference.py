"""Inference engine tests: save_inference_model → AnalysisPredictor round
trip (reference: inference/tests/api + tests/unittests/
test_inference_model_io.py)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu import inference
from paddle_tpu.fluid import core


def train_and_save(dirname):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        y = fluid.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, 1, param_attr=fluid.ParamAttr(name="w"))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(0)
    X = rng.rand(16, 4).astype("float32")
    W = np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32)
    Y = X @ W
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(60):
            exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        fluid.io.save_inference_model(dirname, ["x"], [pred], exe, main)
        (out,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[pred])
    return X, out


def test_predictor_matches_training_forward(tmp_path):
    d = str(tmp_path / "model")
    X, want = train_and_save(d)
    config = inference.Config(d)
    predictor = inference.create_predictor(config)
    assert predictor.get_input_names() == ["x"]
    inp = predictor.get_input_handle("x")
    inp.copy_from_cpu(X)
    predictor.run()
    out = predictor.get_output_handle(predictor.get_output_names()[0])
    got = out.copy_to_cpu()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_predictor_run_list_api_and_clone(tmp_path):
    d = str(tmp_path / "model")
    X, want = train_and_save(d)
    predictor = inference.create_predictor(inference.Config(d))
    (got,) = predictor.run([X])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    clone = predictor.clone()
    (got2,) = clone.run([X[:3]])
    np.testing.assert_allclose(got2, want[:3], rtol=1e-5, atol=1e-6)


def test_load_inference_model_executor_path(tmp_path):
    """The classic fluid path: load_inference_model + exe.run (reference
    io.py usage), including pruning of train-only vars."""
    d = str(tmp_path / "model")
    X, want = train_and_save(d)
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        prog, feeds, fetches = fluid.io.load_inference_model(d, exe)
        assert feeds == ["x"]
        (got,) = exe.run(prog, feed={"x": X}, fetch_list=fetches)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
