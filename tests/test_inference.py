"""Inference engine tests: save_inference_model → AnalysisPredictor round
trip (reference: inference/tests/api + tests/unittests/
test_inference_model_io.py)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu import inference
from paddle_tpu.fluid import core


def train_and_save(dirname):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        y = fluid.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, 1, param_attr=fluid.ParamAttr(name="w"))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(0)
    X = rng.rand(16, 4).astype("float32")
    W = np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32)
    Y = X @ W
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(60):
            exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        fluid.io.save_inference_model(dirname, ["x"], [pred], exe, main)
        (out,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[pred])
    return X, out


def test_predictor_matches_training_forward(tmp_path):
    d = str(tmp_path / "model")
    X, want = train_and_save(d)
    config = inference.Config(d)
    predictor = inference.create_predictor(config)
    assert predictor.get_input_names() == ["x"]
    inp = predictor.get_input_handle("x")
    inp.copy_from_cpu(X)
    predictor.run()
    out = predictor.get_output_handle(predictor.get_output_names()[0])
    got = out.copy_to_cpu()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_predictor_run_list_api_and_clone(tmp_path):
    d = str(tmp_path / "model")
    X, want = train_and_save(d)
    predictor = inference.create_predictor(inference.Config(d))
    (got,) = predictor.run([X])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    clone = predictor.clone()
    (got2,) = clone.run([X[:3]])
    np.testing.assert_allclose(got2, want[:3], rtol=1e-5, atol=1e-6)


def test_load_inference_model_executor_path(tmp_path):
    """The classic fluid path: load_inference_model + exe.run (reference
    io.py usage), including pruning of train-only vars."""
    d = str(tmp_path / "model")
    X, want = train_and_save(d)
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        prog, feeds, fetches = fluid.io.load_inference_model(d, exe)
        assert feeds == ["x"]
        (got,) = exe.run(prog, feed={"x": X}, fetch_list=fetches)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_predictor_from_memory_buffers_golden_format():
    """SetModelBuffer path: serve a model whose ProgramDesc + params are
    reference-format byte buffers (the golden fixtures were produced
    independently via protoc over the reference framework.proto)."""
    import os
    from paddle_tpu import inference
    fix = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures")
    prog_bytes = open(os.path.join(fix, "golden_fc.program.pb"),
                      "rb").read()
    params = (open(os.path.join(fix, "golden_fc_b.tensor"), "rb").read()
              + open(os.path.join(fix, "golden_fc_w.tensor"), "rb").read())
    # params stream order = sorted persistable names: fc_b then fc_w
    cfg = inference.Config()
    cfg.set_model_buffer(prog_bytes, params)
    assert cfg.model_from_memory()
    pred = inference.create_predictor(cfg)
    exp = np.load(os.path.join(fix, "golden_expected.npz"))
    x = np.random.RandomState(3).rand(5, 4).astype("float32")
    (out,) = pred.run([x])
    np.testing.assert_allclose(out, x @ exp["w"] + exp["b"],
                               rtol=1e-5, atol=1e-6)


def test_predictor_clone_shares_weights(tmp_path):
    from paddle_tpu import inference
    d = str(tmp_path / "m1")
    train_and_save(d)
    cfg = inference.Config(d)
    p1 = inference.create_predictor(cfg)
    p2 = p1.clone()
    assert p2._scope is p1._scope  # zero weight duplication
    x = np.random.rand(2, 4).astype("float32")
    np.testing.assert_allclose(p1.run([x])[0], p2.run([x])[0], rtol=1e-6)
    pool = inference.PredictorPool(cfg, size=3)
    assert pool.size() == 3
    np.testing.assert_allclose(pool.retrieve(2).run([x])[0],
                               p1.run([x])[0], rtol=1e-6)


def test_pass_builder_customization(tmp_path):
    from paddle_tpu import inference
    d = str(tmp_path / "m2")
    train_and_save(d)
    cfg = inference.Config(d)
    pb = cfg.pass_builder()
    n0 = len(pb.all_passes())
    pb.delete_pass("fc_fuse_pass")
    assert len(pb.all_passes()) == n0 - 1
    pred = inference.create_predictor(cfg)
    # without fc_fuse_pass the mul+elementwise_add stay decomposed
    types = [op.type for op in pred._program.global_block().ops]
    assert "fc" not in types and "mul" in types
    x = np.random.rand(2, 4).astype("float32")
    assert pred.run([x])[0].shape == (2, 1)
    import pytest
    with pytest.raises(ValueError):
        pb.append_pass("not_a_real_pass")


def test_predictor_misc_api(tmp_path):
    from paddle_tpu import inference
    d = str(tmp_path / "m3")
    train_and_save(d)
    cfg = inference.Config(d)
    cfg.enable_bf16()
    assert cfg.bf16_enabled()
    pred = inference.create_predictor(cfg)
    shapes = pred.get_input_tensor_shape()
    assert list(shapes) == pred.get_input_names()
    x = np.random.rand(2, 4).astype("float32")
    y1 = pred.run([x])[0]
    pred.try_shrink_memory()
    y2 = pred.run([x])[0]
    np.testing.assert_allclose(np.asarray(y1, np.float32),
                               np.asarray(y2, np.float32), rtol=1e-2)
    from paddle_tpu.fluid import core
    core.set_flag("FLAGS_use_bf16_matmul", False)  # reset global


def test_predictor_aot_compile_cache_cross_process(tmp_path):
    """set_optim_cache_dir (reference analysis_config.cc SetOptimCacheDir
    / TensorRT engine-cache role): a SECOND process loading the same
    model must hit the persistent XLA executable cache instead of
    recompiling. The child reports jax's own 'compilation cache hit'
    log plus its outputs; outputs must also match across processes."""
    import json
    import subprocess
    import sys

    model_dir = str(tmp_path / "model")
    cache_dir = str(tmp_path / "xla_cache")
    build = """
import json, logging, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core
import paddle_tpu.inference as infer

model_dir, cache_dir, make = MODEL_DIR, CACHE_DIR, MAKE
if make:
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[64], dtype="float32")
        h = x
        for i in range(4):
            h = fluid.layers.fc(h, 64, act="relu")
        out = fluid.layers.fc(h, 8, act="softmax")
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(model_dir, ["x"], [out], exe,
                                      main_program=main)

records = []
h = logging.Handler()
h.emit = lambda r: records.append(r.getMessage())
logging.getLogger("jax._src.compiler").addHandler(h)
logging.getLogger("jax._src.compiler").setLevel(logging.DEBUG)

cfg = infer.Config(model_dir)
cfg.set_optim_cache_dir(cache_dir)
pred = infer.create_predictor(cfg)
X = np.linspace(0, 1, 2 * 64, dtype="float32").reshape(2, 64)
(y,) = pred.run([X])
hit = any("compilation cache hit" in m for m in records)
print(json.dumps({"hit": hit, "y": np.asarray(y).ravel().tolist()}))
"""
    build = build.replace("MODEL_DIR", repr(model_dir)) \
                 .replace("CACHE_DIR", repr(cache_dir))
    env = dict(__import__("os").environ)
    out1 = subprocess.run([sys.executable, "-c",
                           build.replace("MAKE", "True")],
                          capture_output=True, text=True, env=env,
                          timeout=300)
    assert out1.returncode == 0, out1.stderr[-2000:]
    r1 = json.loads(out1.stdout.strip().splitlines()[-1])
    assert __import__("os").listdir(cache_dir), "no cache entries written"
    out2 = subprocess.run([sys.executable, "-c",
                           build.replace("MAKE", "False")],
                          capture_output=True, text=True, env=env,
                          timeout=300)
    assert out2.returncode == 0, out2.stderr[-2000:]
    r2 = json.loads(out2.stdout.strip().splitlines()[-1])
    assert r2["hit"], "second process recompiled instead of cache hit"
    np.testing.assert_allclose(r1["y"], r2["y"], rtol=1e-6)
