"""Segmented compilation tests (VERDICT r5 Weak #1 / Next-round item 2).

The whole-block compiled path is all-or-nothing: one stateful/host op
(auc, print, read, ...) used to route the ENTIRE block to the op-by-op
interpreter. The segmenter (fluid/ir.py analyze_block_segments +
fluid/executor.py _SegmentedBlock) partitions the block into maximal
jitted segments around interpreted islands instead.

Oracle: the pure interpreter (FLAGS_executor_segmentation=False). Every
parity test here runs the same program both ways and compares losses /
metrics step for step.
"""
import contextlib

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core
from paddle_tpu.fluid.executor import _SegmentedBlock
from paddle_tpu.fluid.ir import (analyze_block_segments, get_pass, Graph,
                                 op_island_reason, segment_summary)


@contextlib.contextmanager
def _segmentation(enabled, min_ops=None):
    prev = core.globals_["FLAGS_executor_segmentation"]
    prev_min = core.globals_["FLAGS_executor_seg_min_ops"]
    core.set_flag("FLAGS_executor_segmentation", enabled)
    if min_ops is not None:
        core.set_flag("FLAGS_executor_seg_min_ops", min_ops)
    try:
        yield
    finally:
        core.set_flag("FLAGS_executor_segmentation", prev)
        core.set_flag("FLAGS_executor_seg_min_ops", prev_min)


def _segmented_blocks(exe):
    # tuples are ("interpreted", scope_ref) unprofitable-key markers
    return [v for v in exe._compiled_cache.values()
            if not isinstance(v, tuple) and v.kind == "segmented"]


# --------------------------------------------------------------- analysis
def test_analysis_partitions_maximal_runs():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        h = fluid.layers.scale(x, scale=2.0)
        h = fluid.layers.Print(h, message="dbg")
        h = fluid.layers.scale(h, scale=3.0)
        h = fluid.layers.relu(h)
    ops = [op for op in main.global_block().ops
           if op.type not in ("feed", "fetch")]
    segs = analyze_block_segments(ops)
    assert [s.kind for s in segs] == ["compiled", "island", "compiled"]
    assert [len(s.ops) for s in segs] == [1, 1, 2]
    assert segs[1].island_reasons == ["stateful"]
    # segments tile the op list exactly
    assert [(s.start, s.stop) for s in segs] == [(0, 1), (1, 2), (2, 4)]


def test_island_reasons():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        fluid.layers.relu(x)
    relu_op = [op for op in main.global_block().ops
               if op.type == "relu"][0]
    assert op_island_reason(relu_op) is None

    class FakeOp:
        type = "no_such_op_xyz"
        attrs = {}
    assert op_island_reason(FakeOp()) == "unregistered"


def test_block_segmentation_pass_is_inspectable():
    """The pass stores the partition on the graph and program WITHOUT
    mutating the block."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        h = fluid.layers.fc(x, 8)
        h = fluid.layers.Print(h)
        fluid.layers.relu(h)
    n_ops = len(main.global_block().ops)
    g = Graph(main)
    get_pass("block_segmentation_pass").apply(g)
    assert len(main.global_block().ops) == n_ops  # analysis-only
    segs = g.get("segments")
    assert segs is not None and segs == main._segment_plan
    kinds = [s["kind"] for s in segs]
    assert "island" in kinds and "compiled" in kinds
    isl = [s for s in segs if s["kind"] == "island"][0]
    assert isl["op_types"] == ["print"] \
        and isl["island_reasons"] == ["stateful"]


# ------------------------------------------------------- acceptance: auc
def _build_auc_trainer(num_dense=4, num_slots=3, sparse_dim=50,
                       embedding_dim=4, hidden=(16, 16)):
    """Wide&Deep shape (models/wide_deep.py) scaled down for tests: the
    train program fetches AUC, so the block contains the stateful `auc`
    op among hundreds of pure ops."""
    from paddle_tpu.models import wide_deep
    return wide_deep.build_wide_deep_program(
        num_dense=num_dense, num_slots=num_slots, sparse_dim=sparse_dim,
        embedding_dim=embedding_dim, hidden=hidden, lr=1e-2)


def _run_auc_trainer(segmentation, steps=4, batch=32):
    from paddle_tpu.models import wide_deep
    with _segmentation(segmentation):
        main, startup, feeds, loss, auc = _build_auc_trainer()
        exe = fluid.Executor()
        scope = core.Scope()
        nb = wide_deep.ctr_reader(batch, num_dense=4, num_slots=3,
                                  sparse_dim=50, seed=3)
        feed = nb()
        out = []
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(steps):
                l, a = exe.run(main, feed=feed, fetch_list=[loss, auc])
                out.append((float(np.asarray(l).ravel()[0]),
                            float(np.asarray(a).ravel()[0])))
    return out, exe


@pytest.mark.slow
# demoted r19 (suite-time buyback, 9s): segmented-vs-interpreter
# parity with a host island stays tier-1 via test_print_program_
# trains_as_compiled_segments, and wide_deep convergence via
# test_wide_deep.py; this AUC-island acceptance runs round-end
def test_wide_deep_auc_trains_as_compiled_segments():
    """Acceptance (VERDICT next-round item 2's done-bar): a Wide&Deep
    train program fetching AUC executes fwd+bwd+update as compiled jitted
    segments — only the auc op stays an island — with loss AND metric
    parity vs the pure interpreter."""
    seg, exe = _run_auc_trainer(True)
    assert exe._last_run_mode == "segmented"
    sbs = _segmented_blocks(exe)
    assert len(sbs) == 1
    sb = sbs[0]
    # every island op is the stateful metric; everything else compiled
    island_ops = [o.type for s in sb.segments if s.kind == "island"
                  for o in s.ops]
    assert island_ops == ["auc"]
    compiled_ops = [o.type for s in sb.segments if s.kind == "compiled"
                    for o in s.ops]
    assert "sgd" in compiled_ops or "adam" in compiled_ops
    assert any(t.endswith("_grad") for t in compiled_ops)  # bwd compiled
    # jitted-segment evidence: each compiled segment holds a traced jit
    # cache entry after running
    n_jitted = sum(len(s._cache) for s in sb.segments
                   if s.kind == "compiled")
    assert n_jitted == sum(1 for s in sb.segments if s.kind == "compiled")
    # parity vs the pure interpreter, loss and AUC, step for step
    interp, exe2 = _run_auc_trainer(False)
    assert exe2._last_run_mode == "interpreted"
    np.testing.assert_allclose(np.asarray(seg), np.asarray(interp),
                               rtol=1e-5, atol=1e-6)
    # it actually trains
    assert seg[-1][0] < seg[0][0]


# ----------------------------------------------------- acceptance: print
def _run_print_trainer(segmentation, steps=3):
    with _segmentation(segmentation):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", shape=[8], dtype="float32")
            y = fluid.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, 16, act="relu")
            pred = fluid.layers.fc(h, 4, act="softmax")
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(pred, y))
            fluid.layers.Print(loss, message="loss=", summarize=1)
            fluid.optimizer.Momentum(0.1, momentum=0.9).minimize(loss)
        exe = fluid.Executor()
        scope = core.Scope()
        r = np.random.RandomState(0)
        X = r.rand(32, 8).astype("float32")
        Y = r.randint(0, 4, (32, 1)).astype("int64")
        out = []
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(steps):
                (l,) = exe.run(main, feed={"x": X, "y": Y},
                               fetch_list=[loss])
                out.append(float(np.asarray(l).ravel()[0]))
    return out, exe


def test_print_program_trains_as_compiled_segments(capsys):
    """Acceptance: a train program with a Print debug op keeps
    fwd+bwd+update compiled (print is the only island) with loss parity
    vs the interpreter — and the print side effect still happens every
    step."""
    seg, exe = _run_print_trainer(True)
    assert exe._last_run_mode == "segmented"
    sb = _segmented_blocks(exe)[0]
    island_ops = [o.type for s in sb.segments if s.kind == "island"
                  for o in s.ops]
    assert island_ops == ["print"]
    compiled_ops = [o.type for s in sb.segments if s.kind == "compiled"
                    for o in s.ops]
    assert "momentum" in compiled_ops
    assert any(t.endswith("_grad") for t in compiled_ops)
    printed = capsys.readouterr().out
    assert printed.count("loss=") == 3  # side effect per step
    interp, _ = _run_print_trainer(False)
    np.testing.assert_allclose(seg, interp, rtol=1e-5, atol=1e-6)
    assert seg[-1] < seg[0]


# ------------------------------------------------------------ env handoff
def test_island_output_feeds_compiled_segment_and_back():
    """Handoff contract both directions: compiled segment -> island
    (py_func reads a computed tensor host-side) -> compiled segment
    (consumes the island's output). Values must round-trip exactly."""
    import paddle_tpu.fluid.layers as layers
    with _segmentation(True, min_ops=2):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", shape=[4], dtype="float32")
            a = layers.scale(x, scale=2.0)
            b = layers.elementwise_add(a, a)          # compiled
            c = main.global_block().create_var(name="seg_pyf_out",
                                               dtype="float32")
            layers.py_func(lambda t: t + 1.0, b, c)   # island
            d = layers.scale(c, scale=0.5)            # compiled again
        exe = fluid.Executor()
        scope = core.Scope()
        X = np.arange(8, dtype="float32").reshape(2, 4)
        with fluid.scope_guard(scope):
            exe.run(startup)
            (o,) = exe.run(main, feed={"x": X}, fetch_list=[d])
        assert exe._last_run_mode == "segmented"
        np.testing.assert_allclose(np.asarray(o), (4 * X + 1) * 0.5,
                                   rtol=1e-6)


def test_state_donation_and_writeback_across_steps():
    """Param/optimizer state written by a compiled segment must land back
    in the scope (donated buffers replaced by the new values), and the
    next step must consume the updated state — i.e. repeated same-batch
    steps keep moving the params."""
    with _segmentation(True):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", shape=[4], dtype="float32")
            y = fluid.data("y", shape=[1], dtype="float32")
            p = fluid.layers.fc(x, 1, param_attr=fluid.ParamAttr(
                name="sdw_w"), bias_attr=False)
            loss = fluid.layers.mean(fluid.layers.square(
                fluid.layers.elementwise_sub(p, y)))
            fluid.layers.Print(loss, summarize=1)
            fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        scope = core.Scope()
        r = np.random.RandomState(4)
        X = r.rand(16, 4).astype("float32")
        Y = r.rand(16, 1).astype("float32")
        with fluid.scope_guard(scope):
            exe.run(startup)
            w0 = np.asarray(scope.find_var("sdw_w").get_tensor().array)
            losses = []
            for _ in range(5):
                (l,) = exe.run(main, feed={"x": X, "y": Y},
                               fetch_list=[loss])
                losses.append(float(np.asarray(l).ravel()[0]))
            w1 = np.asarray(scope.find_var("sdw_w").get_tensor().array)
        assert exe._last_run_mode == "segmented"
        assert not np.allclose(w0, w1)          # state written back
        assert losses[-1] < losses[0] * 0.9     # and consumed next step


# ------------------------------------------------------------- fallbacks
def test_all_island_block_stays_interpreted():
    """A block with nothing worth jitting (below the min-ops threshold)
    must quietly take the pure interpreter."""
    with _segmentation(True):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", shape=[4], dtype="float32")
            h = fluid.layers.scale(x, scale=2.0)
            fluid.layers.Print(h)
        exe = fluid.Executor()
        scope = core.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            exe.run(main, feed={"x": np.ones((2, 4), "float32")},
                    fetch_list=[h])
        assert exe._last_run_mode == "interpreted"


def test_flag_off_restores_interpreter():
    with _segmentation(False):
        out, exe = _run_print_trainer(False)
        assert exe._last_run_mode == "interpreted"


def test_exec_strategy_can_pin_interpreter():
    """CompiledProgram + ExecutionStrategy.allow_mixed_compilation=False
    pins a partially-stateful block to the interpreter."""
    from paddle_tpu.fluid.compiler import CompiledProgram, ExecutionStrategy
    with _segmentation(True):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", shape=[8], dtype="float32")
            y = fluid.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, 16, act="relu")
            pred = fluid.layers.fc(h, 4, act="softmax")
            loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
            fluid.layers.Print(loss, summarize=1)
            fluid.optimizer.SGD(0.1).minimize(loss)
        es = ExecutionStrategy()
        es.allow_mixed_compilation = False
        cp = CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, exec_strategy=es, places=[core.CPUPlace()])
        cp._is_data_parallel = False  # exercise the plain delegate path
        exe = fluid.Executor()
        scope = core.Scope()
        r = np.random.RandomState(0)
        with fluid.scope_guard(scope):
            exe.run(startup)
            exe.run(cp, feed={"x": r.rand(8, 8).astype("float32"),
                              "y": r.randint(0, 4, (8, 1)).astype("int64")},
                    fetch_list=[loss])
        assert exe._last_run_mode == "interpreted"
        # and the flag is restored afterwards
        assert core.globals_["FLAGS_executor_segmentation"] is True


def test_unknown_fetch_fails_before_donation():
    """Regression: fetching an unknown var from a segmented block used to
    raise only AFTER compiled segments had run — and donated the param
    buffers — leaving the scope pointing at deleted arrays and poisoning
    every subsequent step. The fetch must fail at build time, and the
    program must keep training afterwards."""
    with _segmentation(True):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", shape=[8], dtype="float32")
            y = fluid.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, 16, act="relu")
            pred = fluid.layers.fc(h, 4, act="softmax")
            loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
            fluid.layers.Print(loss, summarize=1)
            fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        scope = core.Scope()
        r = np.random.RandomState(0)
        feed = {"x": r.rand(8, 8).astype("float32"),
                "y": r.randint(0, 4, (8, 1)).astype("int64")}
        with fluid.scope_guard(scope):
            exe.run(startup)
            exe.run(main, feed=feed, fetch_list=[loss])
            with pytest.raises(KeyError, match="no_such_var"):
                exe.run(main, feed=feed, fetch_list=["no_such_var"])
            # the failed fetch must not have consumed the state buffers
            (l,) = exe.run(main, feed=feed, fetch_list=[loss])
        assert np.isfinite(np.asarray(l)).all()


def test_uninitialized_persistable_raises_like_compiled():
    """A fresh scope without the startup program must raise the compiled
    path's RuntimeError naming the var — not silently fall back to the
    interpreter and crash inside a kernel."""
    with _segmentation(True):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", shape=[8], dtype="float32")
            h = fluid.layers.fc(x, 16, act="relu",
                                param_attr=fluid.ParamAttr(name="up_w"))
            fluid.layers.Print(h, summarize=1)
            for _ in range(6):
                h = fluid.layers.scale(h, scale=1.0)
        exe = fluid.Executor()
        scope = core.Scope()  # startup NOT run
        with fluid.scope_guard(scope):
            with pytest.raises(RuntimeError, match="up_w"):
                exe.run(main, feed={"x": np.ones((2, 8), "float32")},
                        fetch_list=[h])


# ------------------------------------------------------------- profiler
def test_per_segment_profiler_spans():
    """The segmented step surfaces per-segment compile/exec spans and
    island spans (cat='segment') through fluid/profiler.py."""
    from paddle_tpu.fluid import profiler
    with _segmentation(True):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", shape=[8], dtype="float32")
            y = fluid.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, 16, act="relu")
            pred = fluid.layers.fc(h, 4, act="softmax")
            loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
            fluid.layers.Print(loss, summarize=1)
            fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        scope = core.Scope()
        r = np.random.RandomState(0)
        feed = {"x": r.rand(8, 8).astype("float32"),
                "y": r.randint(0, 4, (8, 1)).astype("int64")}
        with fluid.scope_guard(scope):
            exe.run(startup)
            profiler.start_profiler(state="CPU")
            exe.run(main, feed=feed, fetch_list=[loss])  # compile spans
            exe.run(main, feed=feed, fetch_list=[loss])  # exec spans
            events = list(profiler._prof.events)
            profiler.stop_profiler(profile_path="")
        names = [e.name for e in events]
        assert any(n.startswith("segmented_step[") for n in names)
        assert any(":compile" in n and n.startswith("segment[")
                   for n in names)
        assert any(":exec" in n and n.startswith("segment[")
                   for n in names)
        assert any(n.startswith("island[") for n in names)
        seg_events = [e for e in events if e.name.startswith(("segment",
                                                              "island"))]
        assert all(e.cat == "segment" for e in seg_events)


# ------------------------------------------------------ rng determinism
def test_segmented_rng_matches_fused_compiled():
    """A dropout program sliced by an off-path Print must draw the SAME
    rng streams as the fused compiled path (per-op keys fold from global
    op indices), so removing the island does not change the trajectory.
    """
    def run(with_print):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 1234
        with fluid.program_guard(main, startup):
            x = fluid.data("x", shape=[8], dtype="float32")
            h = fluid.layers.dropout(x, dropout_prob=0.5)
            o = fluid.layers.scale(h, scale=1.0)
            for _ in range(4):  # pad past the min-ops threshold
                o = fluid.layers.scale(o, scale=1.0)
            if with_print:
                fluid.layers.Print(o, summarize=1)
        exe = fluid.Executor()
        scope = core.Scope()
        X = np.ones((4, 8), "float32")
        with fluid.scope_guard(scope):
            exe.run(startup)
            (v,) = exe.run(main, feed={"x": X}, fetch_list=[o])
        return np.asarray(v), exe._last_run_mode

    with _segmentation(True, min_ops=4):
        seg, m1 = run(True)
        fused, m2 = run(False)
    assert m1 == "segmented" and m2 == "compiled"
    np.testing.assert_allclose(seg, fused)
