"""Unified telemetry plane tests (docs/OBSERVABILITY.md).

Covers the three legs of ISSUE 10:
  * distributed trace correlation — trace_scope semantics, profiler
    stamping, RPC header propagation (client rpc span ↔ VarServer
    handler span linkage), dedup-retry replays and stale-view
    re-routes keeping the trace id, HTTP X-Trace-Id round trips;
  * metrics registry — primitives, stats-dict views, Prometheus
    exposition, GET /metrics == stats() on a live ingress, the opt-in
    sidecar server;
  * merged cluster timelines — FLAGS_trace_dir shard streaming (ring
    bound + metadata), hello clock-offset capture, tools/timeline.py
    merge clock correction and trace-id filtering.

In-process tests stay tier-1 non-slow; the 2-trainer×2-pserver
wide_deep timeline acceptance also carries `slow`.
"""
import json
import os
import socket
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

pytestmark = pytest.mark.obs

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.fixture(autouse=True)
def _telemetry_isolation():
    """Flags restored; the shard writer and clock offsets reset so one
    test's FLAGS_trace_dir can't leak into the next."""
    from paddle_tpu.fluid import core, telemetry
    from paddle_tpu.fluid.ps_rpc import VarClient

    saved = {k: core.globals_[k] for k in
             ("FLAGS_trace_dir", "FLAGS_trace_shard_max_events",
              "FLAGS_profiler_max_events", "FLAGS_metrics_port")}
    yield
    for k, v in saved.items():
        core.globals_[k] = v
    telemetry.reset_trace_shard()
    telemetry.reset_clock_offsets()
    VarClient.reset_pool()


# ======================================================================
# metrics registry
# ======================================================================
def test_registry_primitives_labels_and_exposition():
    from paddle_tpu.fluid.telemetry import MetricsRegistry

    reg = MetricsRegistry()
    c = reg.counter("req_total", "requests", labelnames=("code",))
    c.labels(code="200").inc()
    c.labels(code="200").inc(2)
    c.labels(code="429").inc()
    g = reg.gauge("depth")
    g.set(7)
    h = reg.histogram("lat_s", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(9.0)

    assert c.value(code="200") == 3
    assert c.value(code="429") == 1
    assert g.value() == 7

    text = reg.exposition()
    assert '# TYPE req_total counter' in text
    assert 'req_total{code="200"} 3' in text
    assert 'req_total{code="429"} 1' in text
    assert "depth 7" in text
    assert 'lat_s_bucket{le="0.1"} 1' in text
    assert 'lat_s_bucket{le="1.0"} 2' in text
    assert 'lat_s_bucket{le="+Inf"} 3' in text
    assert "lat_s_count 3" in text

    # kind/label conflicts are refused, get-or-create is idempotent
    assert reg.counter("req_total", labelnames=("code",)) is c
    with pytest.raises(ValueError):
        reg.gauge("req_total")
    with pytest.raises(ValueError):
        reg.counter("req_total", labelnames=("other",))


def test_registry_view_exposes_stats_dict_numbers_exactly():
    """A registered view's numeric leaves surface as gauges whose
    values equal the dict's EXACTLY (floats repr-round-trip); strings
    and lists are skipped — the dict API stays authoritative."""
    from paddle_tpu.fluid.telemetry import MetricsRegistry

    reg = MetricsRegistry()
    stats = {"shed": 17, "hit_rate": 0.8749999731,
             "nested": {"p99": 12.5}, "mode": "scan",
             "buckets": [1, 2, 4], "flag": True}
    reg.register_view("eng", lambda: stats, labels={"engine": "e0"})
    got = reg.collect()
    assert got["eng_shed"]["samples"] == [({"engine": "e0"}, 17)]
    assert got["eng_hit_rate"]["samples"][0][1] == stats["hit_rate"]
    assert got["eng_nested_p99"]["samples"][0][1] == 12.5
    assert got["eng_flag"]["samples"][0][1] == 1
    assert "eng_mode" not in got and "eng_buckets" not in got
    # text round trip preserves the float bits
    text = reg.exposition()
    line = [ln for ln in text.splitlines()
            if ln.startswith("eng_hit_rate")][0]
    assert float(line.split()[-1]) == stats["hit_rate"]
    # a raising view is skipped, never breaks the scrape
    reg.register_view("bad", lambda: 1 / 0)
    assert "eng_shed" in reg.exposition()


def test_trace_scope_root_child_adopt_and_cross_process_form():
    from paddle_tpu.fluid import telemetry as T

    assert T.current_trace() is None
    with T.trace_scope() as root:
        assert root.parent_id is None
        with T.trace_scope() as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
            assert child.span_id != root.span_id
        # cross-process adoption: same trace id, NEW span id
        with T.trace_scope(trace_id="t123",
                           parent_span_id="s456") as remote:
            assert (remote.trace_id, remote.parent_id) == ("t123",
                                                           "s456")
        # verbatim adoption (fan-out pool threads)
        with T.trace_scope(adopt=root) as same:
            assert same is root
        assert T.current_trace() is root
    assert T.current_trace() is None


def test_profiler_stamps_trace_ids_and_ring_bounds_events():
    from paddle_tpu.fluid import core, profiler, telemetry

    core.globals_["FLAGS_profiler_max_events"] = 4
    profiler.start_profiler("CPU")
    try:
        with telemetry.trace_scope() as ctx:
            profiler.record_instant("traced")
        for i in range(6):
            profiler.record_instant(f"fill{i}")
        evs = profiler.snapshot_events()
        assert len(evs) == 4  # ring bound
        assert profiler.dropped_events() == 3
        assert all(e["trace_id"] is None for e in evs)  # traced dropped
        profiler.reset_profiler()
        with telemetry.trace_scope() as ctx:
            profiler.record_instant("traced2")
        (ev,) = profiler.snapshot_events()
        assert ev["trace_id"] == ctx.trace_id
        assert ev["span_id"] == ctx.span_id
    finally:
        profiler.stop_profiler(profile_path="")


# ======================================================================
# RPC propagation
# ======================================================================
def test_rpc_trace_propagates_to_handler_spans_and_offsets_recorded():
    """The tentpole contract in one process: a traced client call's
    rpc span and the server's handler span share the trace id; the
    handler span is a NEW span parented on the client's rpc span; the
    _hello handshake recorded a clock offset for the endpoint."""
    from paddle_tpu.fluid import profiler, telemetry
    from paddle_tpu.fluid.ps_rpc import VarClient, VarServer

    srv = VarServer("127.0.0.1:0", {"echo": lambda x=0: x + 1}).start()
    ep = f"127.0.0.1:{srv.port}"
    try:
        cli = VarClient(ep)
        assert cli._telemetry_ok
        off = telemetry.clock_offsets()[ep]
        assert abs(off[0]) < 5.0 and 0 < off[1] < 5.0  # same host
        profiler.start_profiler("CPU")
        try:
            with telemetry.trace_scope() as ctx:
                assert cli.call("echo", x=1) == 2
            rpc = [e for e in profiler.snapshot_events()
                   if e["cat"] == "rpc"]
            client_span = next(e for e in rpc
                               if e["name"].startswith("echo"))
            handler = next(e for e in rpc
                           if e["name"] == "rpc_handler:echo")
            assert client_span["trace_id"] == ctx.trace_id
            assert handler["trace_id"] == ctx.trace_id
            assert handler["parent_id"] == client_span["span_id"]
            assert handler["span_id"] != client_span["span_id"]
            assert handler["args"]["ok"] is True
            # untraced calls stamp nothing
            cli.call("echo", x=5)
            handlers = [e for e in profiler.snapshot_events()
                        if e["name"] == "rpc_handler:echo"]
            assert handlers[-1]["trace_id"] is None
        finally:
            profiler.stop_profiler(profile_path="")
    finally:
        srv.shutdown()


def test_legacy_peers_keep_working_without_trace_or_offset():
    """Both compat directions of the hello extension: an old-frame
    server (rejects _hello) never sees _trace and records no offset; a
    legacy-pinned client (PADDLE_TPU_PS_PICKLE_WIRE=1) never probes and
    still interoperates — traced calls succeed in both cases."""
    from paddle_tpu.fluid import telemetry
    from paddle_tpu.fluid.ps_rpc import VarClient, VarServer

    seen = []

    def echo(x=0, **kw):
        seen.append(sorted(kw))
        return x + 1

    srv = VarServer("127.0.0.1:0", {"echo": echo},
                    legacy_wire=True).start()
    ep = f"127.0.0.1:{srv.port}"
    try:
        cli = VarClient(ep)
        assert not cli._telemetry_ok
        assert ep not in telemetry.clock_offsets()
        with telemetry.trace_scope():
            assert cli.call("echo", x=1) == 2
        assert seen == [[]]  # no _trace kwarg leaked into the handler
    finally:
        srv.shutdown()

    os.environ["PADDLE_TPU_PS_PICKLE_WIRE"] = "1"
    try:
        srv2 = VarServer("127.0.0.1:0",
                         {"echo": lambda x=0: x + 1}).start()
        ep2 = f"127.0.0.1:{srv2.port}"
        cli2 = VarClient(ep2)
        assert not cli2._telemetry_ok
        with telemetry.trace_scope():
            assert cli2.call("echo", x=3) == 4
        srv2.shutdown()
    finally:
        os.environ.pop("PADDLE_TPU_PS_PICKLE_WIRE", None)


def test_dedup_retry_replays_same_trace_id_with_new_span_id():
    """A PR 3 retry (same dedup token) executes ONCE; the replay is
    still followable: the server records a replay marker carrying the
    SAME trace id with a fresh server-side span id."""
    from paddle_tpu.fluid import profiler
    from paddle_tpu.fluid import ps_rpc
    from paddle_tpu.fluid.ps_rpc import VarServer, _send_msg, _recv_msg

    calls = []
    srv = VarServer("127.0.0.1:0",
                    {"bump": lambda: calls.append(1) or True}).start()
    profiler.start_profiler("CPU")
    try:
        def raw_call(msg):
            s = socket.create_connection(("127.0.0.1", srv.port), 5.0)
            try:
                _send_msg(s, dict(msg))
                return _recv_msg(s)
            finally:
                s.close()

        msg = {"method": "bump", "_dedup": ("cliX", 0),
               "_trace": ("traceT", "spanS")}
        r1 = raw_call(msg)
        r2 = raw_call(msg)  # the retry: replayed, never re-executed
        assert r1["ok"] and r2["ok"] and r1["result"] == r2["result"]
        assert len(calls) == 1
        handlers = [e for e in profiler.snapshot_events()
                    if e["name"] == "rpc_handler:bump"]
        assert len(handlers) == 2
        execution, replay = handlers
        assert {e["trace_id"] for e in handlers} == {"traceT"}
        assert {e["parent_id"] for e in handlers} == {"spanS"}
        assert execution["span_id"] != replay["span_id"]
        assert replay["args"] == {"dedup_replay": True}
        assert srv.stats()["bump"]["dedup_replays"] == 1
    finally:
        profiler.stop_profiler(profile_path="")
        srv.shutdown()


def test_stale_view_reroute_keeps_trace_id_across_owners():
    """A PR 6 re-route is ONE logical call: the refusing old owner and
    the executing new owner both record handler spans under the SAME
    trace id (new span ids), parented on the one client rpc span."""
    from paddle_tpu.fluid import core, profiler, ps_membership, telemetry
    from paddle_tpu.fluid.ps_rpc import VarClient, VarServer

    ps_membership.reset_views()
    slot = f"127.0.0.1:{free_port()}"
    srv_b = VarServer("127.0.0.1:0",
                      {"get_var": lambda name, trainer_id=0:
                       np.arange(3, dtype=np.float32)}).start()
    bind_b = f"127.0.0.1:{srv_b.port}"
    moved = ps_membership.ClusterView.initial([slot]).moved(
        slot, bind_b, epoch=1)

    def refuse(name, trainer_id=0):
        err = core.StaleClusterViewError(
            f"shard {slot} moved to {bind_b}")
        err.view_dict = moved.to_dict()
        raise err

    srv_a = VarServer(slot, {"get_var": refuse}).start()
    try:
        ps_membership.install_view(ps_membership.ClusterView.initial(
            [slot]))
        profiler.start_profiler("CPU")
        try:
            cli = VarClient(slot)
            with telemetry.trace_scope() as ctx:
                out = cli.call("get_var", name="v")
            np.testing.assert_array_equal(
                np.asarray(out), np.arange(3, dtype=np.float32))
            assert ps_membership.current_epoch() == 1
            evs = profiler.snapshot_events()
            handlers = [e for e in evs
                        if e["name"] == "rpc_handler:get_var"]
            client_spans = [e for e in evs
                            if e["name"].startswith("get_var:")]
            assert len(handlers) == 2  # refusal on A + execution on B
            assert {e["trace_id"] for e in handlers} == {ctx.trace_id}
            assert len({e["span_id"] for e in handlers}) == 2
            # one logical call: every handler parent is the client span
            assert {e["parent_id"] for e in handlers} == \
                {client_spans[0]["span_id"]}
            oks = sorted(e["args"]["ok"] for e in handlers)
            assert oks == [False, True]
        finally:
            profiler.stop_profiler(profile_path="")
    finally:
        srv_a.shutdown()
        srv_b.shutdown()
        ps_membership.reset_views()


# ======================================================================
# serving: X-Trace-Id + /metrics
# ======================================================================
@pytest.fixture(scope="module")
def mlp_engine_parts():
    from tools.serving_loadgen import build_mlp_serving_model
    prog, scope, out_name, feeds = build_mlp_serving_model(n_feeds=4)
    return prog, scope, out_name, feeds


def _mk_engine(parts, **kw):
    from paddle_tpu.serving import ServingEngine
    prog, scope, out_name, _ = parts
    kw.setdefault("num_workers", 2)
    kw.setdefault("max_batch", 8)
    return ServingEngine(program=prog, scope=scope, feed_names=["x"],
                         fetch_names=[out_name], **kw)


def _post(url, body, headers=None):
    req = urllib.request.Request(url, data=json.dumps(body).encode(),
                                 headers=headers or {})
    return urllib.request.urlopen(req, timeout=30)


def test_http_x_trace_id_round_trips_and_spans_carry_it(
        mlp_engine_parts):
    """Satellite: X-Trace-Id in → same id out (on every status);
    minted when absent; the engine's serve spans run under it."""
    from paddle_tpu.fluid import profiler
    from paddle_tpu.serving import ServingIngress

    eng = _mk_engine(mlp_engine_parts, name="traced-mlp")
    ing = ServingIngress({"mlp": eng}).start()
    x = mlp_engine_parts[3][0]["x"].tolist()
    profiler.start_profiler("CPU")
    try:
        r = _post(ing.url + "/predict", {"feed": {"x": x}},
                  {"X-Trace-Id": "req-42"})
        assert r.status == 200
        assert r.headers.get("X-Trace-Id") == "req-42"
        # minted when the client sends none
        r2 = _post(ing.url + "/predict", {"feed": {"x": x}})
        minted = r2.headers.get("X-Trace-Id")
        assert minted and len(minted) == 16 and minted != "req-42"
        # error paths carry the header too (bad feed -> 400)
        try:
            _post(ing.url + "/predict", {"feed": {"wrong": x}},
                  {"X-Trace-Id": "req-43"})
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert e.headers.get("X-Trace-Id") == "req-43"
        serve = [e for e in profiler.snapshot_events()
                 if e["cat"] == "serve"]
        traced = [e for e in serve if e["trace_id"] == "req-42"]
        names = {e["name"].split("[")[0] for e in traced}
        assert "serve:queue_wait" in names
        assert "serve:exec" in names
        exec_span = next(e for e in traced
                         if e["name"].startswith("serve:exec"))
        assert "req-42" in exec_span["args"]["trace_ids"]
    finally:
        profiler.stop_profiler(profile_path="")
        ing.close()


def test_ingress_metrics_endpoint_matches_stats_exactly(
        mlp_engine_parts):
    """Acceptance leg: GET /metrics exposes the shed / deadline /
    degraded / request counters and the cache hit counters with values
    EQUAL to stats() — same underlying objects, no drift possible."""
    import re
    from paddle_tpu.serving import AdmissionController, ServingIngress
    from paddle_tpu.serving.embedding_cache import EmbeddingCache

    cache = EmbeddingCache(ttl_s=60.0, max_entries=64)
    eng = _mk_engine(mlp_engine_parts, name="m0",
                     admission=AdmissionController(max_queue_rows=4),
                     num_workers=1, embedding_cache=cache)
    ing = ServingIngress({"mlp": eng}).start()
    x = mlp_engine_parts[3][0]["x"].tolist()
    try:
        # light concurrent flood so sheds and OKs both happen
        errs = []

        def client(wid):
            for _ in range(12):
                try:
                    _post(ing.url + "/predict", {"feed": {"x": x}})
                except urllib.error.HTTPError as e:
                    if e.code not in (429, 504):
                        errs.append(e.code)
                except Exception as e:  # noqa: BLE001
                    errs.append(repr(e))

        ths = [threading.Thread(target=client, args=(w,))
               for w in range(8)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert not errs, errs[:3]

        text = urllib.request.urlopen(
            ing.url + "/metrics", timeout=30).read().decode()
        st = eng.stats()

        def metric(name, labels='engine="m0"'):
            m = re.search(rf"^{name}{{{labels}}} (\S+)$", text, re.M)
            assert m, f"{name} missing from /metrics"
            return float(m.group(1))

        assert metric("serving_requests_total") == st["requests"]
        assert metric("serving_shed_total") == st["shed"]
        assert metric("serving_deadline_expired_total") == \
            st["deadline_expired"]
        assert metric("serving_degraded_total") == st["degraded"]
        assert metric("serving_cache_hits") == \
            st["embedding_cache"]["hits"]
        assert metric("serving_cache_hit_rate") == \
            st["embedding_cache"]["hit_rate"]
        # ingress's own counters are views over the same dict
        ist = ing.stats()["ingress"]
        m = re.search(r"^serving_ingress_requests (\S+)$", text, re.M)
        # requests moved between the scrape and stats(); allow the gap
        assert m and float(m.group(1)) <= ist["requests"]
        assert "# TYPE serving_requests_total counter" in text
    finally:
        ing.close()


def test_metrics_sidecar_server_and_flag_gate():
    from paddle_tpu.fluid import core, telemetry

    # flag 0 = off
    core.globals_["FLAGS_metrics_port"] = 0
    assert telemetry.maybe_start_metrics_server() is None
    port = telemetry.start_metrics_server(0)
    try:
        assert port and telemetry.metrics_server_port() == port
        telemetry.REGISTRY.counter("sidecar_probe_total").inc(3)
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) \
            .read().decode()
        assert "sidecar_probe_total 3" in text
        ok = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10)
        assert ok.status == 200
        # idempotent: a second start returns the same port
        assert telemetry.start_metrics_server(0) == port
    finally:
        telemetry.stop_metrics_server()


def test_executor_compile_and_retrace_counters():
    """Satellite: compile/retrace cache-miss counters — a repeated
    window K is cached (no growth), a NEW K after warm-up counts as a
    retrace; steady state stays flat."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core, telemetry

    reg = telemetry.REGISTRY
    compiles = reg.counter("executor_compiles_total",
                           labelnames=("kind",))
    retraces = reg.counter("executor_retraces_total",
                           labelnames=("kind",))

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, 2)
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        step0 = compiles.value(kind="step")
        w0 = compiles.value(kind="window")
        rw0 = retraces.value(kind="window")
        exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss])
        assert compiles.value(kind="step") > step0
        feed2 = {"x": np.ones((2, 2, 4), np.float32)}
        exe.run(main, feed=feed2, fetch_list=[loss], n_steps=2)
        assert compiles.value(kind="window") == w0 + 1
        assert retraces.value(kind="window") == rw0
        # same K again: cached, nothing moves (steady state is flat)
        exe.run(main, feed=feed2, fetch_list=[loss], n_steps=2)
        assert compiles.value(kind="window") == w0 + 1
        # a NEW K after warm-up is a retrace
        exe.run(main, feed={"x": np.ones((4, 2, 4), np.float32)},
                fetch_list=[loss], n_steps=4)
        assert compiles.value(kind="window") == w0 + 2
        assert retraces.value(kind="window") == rw0 + 1
        assert reg.counter("jax_backend_compiles_total").value() > 0


# ======================================================================
# trace shards + timeline merge
# ======================================================================
def test_trace_shard_streams_ring_bounded_with_metadata(tmp_path):
    from paddle_tpu.fluid import core, profiler, telemetry

    core.globals_["FLAGS_trace_dir"] = str(tmp_path)
    core.globals_["FLAGS_trace_shard_max_events"] = 1024
    assert profiler.is_profiling()  # shard-only mode records
    with telemetry.trace_scope() as ctx:
        with profiler.RecordEvent("step", cat="segment"):
            pass
    path = telemetry.flush_trace_shard()
    shard = json.load(open(path))
    assert shard["metadata"]["pid"] == os.getpid()
    assert shard["metadata"]["anchor_wall_us"] > 0
    (ev,) = shard["traceEvents"]
    assert ev["name"] == "step" and ev["cat"] == "segment"
    assert ev["args"]["trace_id"] == ctx.trace_id
    # ring: the shard never exceeds the bound, drops are counted
    for i in range(1030):
        profiler.record_instant(f"i{i}")
    telemetry.flush_trace_shard()
    shard = json.load(open(path))
    assert len(shard["traceEvents"]) == 1024
    assert shard["metadata"]["dropped_events"] > 0


def test_timeline_merge_clock_corrects_with_hello_offsets(tmp_path):
    """Synthetic 2-shard merge: the pserver shard's clock is 100 s
    ahead; the trainer's measured hello offset must pull its spans
    back so the rpc→handler nesting is monotone in ONE clock."""
    from tools.timeline import merge_shards

    ep = "127.0.0.1:7001"
    # trainer: rpc span [1.0, 1.4] s on its own clock
    trainer = {
        "traceEvents": [
            {"name": "send:w@" + ep, "ph": "X", "pid": 1, "tid": 1,
             "ts": 1.0e6, "dur": 0.4e6, "cat": "rpc",
             "args": {"trace_id": "T", "span_id": "a"}}],
        "metadata": {"pid": 1, "role": "trainer0", "endpoint": None,
                     "anchor_wall_us": 5e6, "anchor_perf_us": 0.0,
                     "peer_offsets": {
                         ep: {"offset_us": 100.0e6, "rtt_us": 400.0}}},
    }
    # pserver: handler span inside the rpc window, on a clock +100 s
    pserver = {
        "traceEvents": [
            {"name": "rpc_handler:send", "ph": "X", "pid": 2, "tid": 9,
             "ts": 101.1e6, "dur": 0.2e6, "cat": "rpc",
             "args": {"trace_id": "T", "span_id": "b",
                      "parent_id": "a"}}],
        "metadata": {"pid": 2, "role": "pserver0", "endpoint": ep,
                     # wall anchor deliberately WRONG (1h off) to prove
                     # the measured offset wins over the fallback
                     "anchor_wall_us": 3600e6,
                     "anchor_perf_us": 100.0e6,
                     "peer_offsets": {}},
    }
    (tmp_path / "trace-1.json").write_text(json.dumps(trainer))
    (tmp_path / "trace-2.json").write_text(json.dumps(pserver))
    out = str(tmp_path / "timeline.json")
    summary = merge_shards(str(tmp_path), out=out, trace_id="T")
    assert summary["n_shards"] == 2 and summary["n_events"] == 2
    assert summary["processes"]["pserver0"]["source"] == "hello-offset"
    assert summary["processes"]["pserver0"]["delta_us"] == -100.0e6
    merged = json.load(open(out))
    spans = {e["args"]["trace_id"] + ":" + e["args"]["span_id"]: e
             for e in merged["traceEvents"] if e.get("ph") == "X"}
    rpc, handler = spans["T:a"], spans["T:b"]
    # clock-corrected monotone nesting: the handler runs INSIDE the
    # client call's window
    assert rpc["ts"] <= handler["ts"]
    assert handler["ts"] + handler["dur"] <= rpc["ts"] + rpc["dur"]
    # wall fallback kicks in when no offset links the shards
    trainer["metadata"]["peer_offsets"] = {}
    (tmp_path / "trace-1.json").write_text(json.dumps(trainer))
    summary = merge_shards(str(tmp_path), out=None)
    assert summary["processes"]["pserver0"]["source"] == "wall-anchor"


def test_varserver_stats_view_lands_in_registry():
    from paddle_tpu.fluid import telemetry
    from paddle_tpu.fluid.ps_rpc import VarClient, VarServer

    srv = VarServer("127.0.0.1:0", {"echo": lambda x=0: x}).start()
    ep = f"127.0.0.1:{srv.port}"
    try:
        cli = VarClient(ep)
        cli.call("echo", x=1)
        text = telemetry.REGISTRY.exposition()
        assert f'ps_server_echo_calls{{endpoint="{ep}"}}' in text
    finally:
        srv.shutdown()
    # unregistered at shutdown: the next scrape drops the view
    assert f'endpoint="{ep}"' not in telemetry.REGISTRY.exposition()


# ======================================================================
# multiprocess acceptance (slow): 2-trainer × 2-pserver wide_deep
# ======================================================================
@pytest.mark.slow
def test_cluster_timeline_merge_wide_deep_2x2_acceptance(tmp_path):
    """ISSUE 10 acceptance: a 2-trainer×2-pserver wide_deep run with
    FLAGS_trace_dir set produces one shard per process;
    tools/timeline.py merge combines them into a timeline where a
    single training round's trace id links the trainer's rpc spans to
    the owning pserver's handler spans — clock-corrected, with the
    handler inside the client call's span window (monotone ordering)."""
    from tools.chaos_ps import Cluster
    from tools.timeline import merge_shards

    trace_dir = tmp_path / "shards"
    trace_dir.mkdir()
    run = Cluster(str(tmp_path), model="wide_deep", trainers=2,
                  n_pservers=2, steps=5, hb=10.0, step_sleep=0.0,
                  sparse_dim=64, batch=16, tag="obs",
                  env_extra={"FLAGS_trace_dir": str(trace_dir)})
    try:
        run.start_servers()
        run.start_trainers()
        run.join_trainers(timeout=420.0)
        # pserver shards flush on the ~2s background cadence — give the
        # last round's handler spans one beat to land before the kill
        time.sleep(4.0)
    finally:
        run.shutdown()

    out = str(tmp_path / "timeline.json")
    summary = merge_shards(str(trace_dir), out=out, ref="trainer0")
    assert summary["n_shards"] >= 4, summary  # 2 trainers + 2 pservers
    roles = set(summary["processes"])
    assert {"trainer0", "trainer1"} <= roles
    assert sum(1 for r in roles if r.startswith("pserver")) == 2
    # every pserver shard was aligned by a MEASURED hello offset
    for role, info in summary["processes"].items():
        if role.startswith("pserver"):
            assert info["source"] == "hello-offset", summary

    merged = json.load(open(out))
    events = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    pid_role = {e["pid"]: e["args"]["name"]
                for e in merged["traceEvents"] if e.get("ph") == "M"}
    trainer_pids = {p for p, r in pid_role.items()
                    if r.startswith("trainer")}
    pserver_pids = {p for p, r in pid_role.items()
                    if r.startswith("pserver")}

    # pick a training round's trace: a trainer rpc span whose trace id
    # also appears on a pserver handler span
    by_trace = {}
    for e in events:
        tid = (e.get("args") or {}).get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(e)
    linked = 0
    for tid, evs in by_trace.items():
        rpc = [e for e in evs if e["pid"] in trainer_pids
               and e["cat"] == "rpc"
               and not e["name"].startswith("rpc_handler")]
        handlers = [e for e in evs if e["pid"] in pserver_pids
                    and e["name"].startswith("rpc_handler")]
        if not (rpc and handlers):
            continue
        linked += 1
        spans = {e["args"]["span_id"]: e for e in rpc}
        for h in handlers:
            parent = spans.get(h["args"].get("parent_id"))
            if parent is None:
                continue
            # clock-corrected monotone ordering: the handler span nests
            # inside its client rpc span (generous slack for the
            # single-sample offset estimate on a loaded 1-core box)
            slack = 50e3  # 50 ms in us
            assert parent["ts"] - slack <= h["ts"], (tid, parent, h)
            assert h["ts"] + h["dur"] <= \
                parent["ts"] + parent["dur"] + slack, (tid, parent, h)
    # rounds from BOTH trainers must have linked trainer→pserver traces
    assert linked >= 4, (linked, summary)
