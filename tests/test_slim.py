"""contrib.slim: pruning, distillation, post-training quantization, NAS
controller (reference: python/paddle/fluid/contrib/slim/tests/)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core
from paddle_tpu.fluid.contrib.slim.prune import (
    StructurePruner, RatioPruner, PruneStrategy, sensitivity)
from paddle_tpu.fluid.contrib.slim.distillation import (
    L2Distiller, SoftLabelDistiller, FSPDistiller, merge_teacher_program)
from paddle_tpu.fluid.contrib.slim.quantization import (
    PostTrainingQuantization)
from paddle_tpu.fluid.contrib.slim.searcher import SAController
from paddle_tpu.fluid.contrib.slim.nas import (
    LightNASStrategy, SearchSpace, ControllerServer, SearchAgent)
from paddle_tpu.fluid.contrib.slim.core import Compressor, Context


# ------------------------------------------------------------------ pruning
def test_structure_pruner_l1():
    p = np.array([[1.0, 1, 1], [0.1, 0.1, 0.1], [5, 5, 5], [2, 2, 2]],
                 dtype=np.float32)
    pruner = StructurePruner({"*": 0}, {"*": "l1_norm"})
    idx = pruner.cal_pruned_idx("w", p, 0.5)
    assert idx == [0, 1]  # two smallest rows
    masked = pruner.prune_tensor(p, idx, 0, lazy=True)
    assert masked.shape == p.shape
    assert np.all(masked[idx] == 0) and np.all(masked[2] == 5)
    shrunk = pruner.prune_tensor(p, idx, 0, lazy=False)
    assert shrunk.shape == (2, 3)


def test_ratio_pruner_sparsity():
    rng = np.random.RandomState(0)
    p = rng.randn(32, 32).astype(np.float32)
    pruned = RatioPruner().prune(p, 0.75)
    assert abs((pruned == 0).mean() - 0.75) < 0.02


def test_prune_strategy_on_scope():
    scope = core.Scope()
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    w = rng.rand(8, 4).astype("float32") + 0.5
    scope.var("w").set_value(core.LoDTensor(jnp.asarray(w)))
    strat = PruneStrategy(params=["w"], ratios=[0.25])
    ctx = Context(None, scope)
    ctx.epoch_id = 0
    strat.on_epoch_begin(ctx)
    after = np.asarray(scope.find_var("w").get_tensor().array)
    zero_rows = int((np.abs(after).sum(1) == 0).sum())
    assert zero_rows == 2
    # optimizer writes a dense update; mask re-applied at batch end
    scope.var("w").set_value(core.LoDTensor(jnp.asarray(
        np.ones_like(w))))
    strat.on_batch_end(ctx)
    after2 = np.asarray(scope.find_var("w").get_tensor().array)
    assert int((np.abs(after2).sum(1) == 0).sum()) == 2


def test_sensitivity_probe_restores_weights():
    scope = core.Scope()
    import jax.numpy as jnp
    w = np.arange(12, dtype=np.float32).reshape(4, 3) + 1
    scope.var("w").set_value(core.LoDTensor(jnp.asarray(w)))
    calls = []

    def ev():
        calls.append(np.asarray(scope.find_var("w").get_tensor().array))
        return float(calls[-1].sum())

    curves = sensitivity(None, scope, None, ["w"], ev, ratios=(0.25, 0.5))
    assert set(curves["w"]) == {0.25, 0.5}
    assert curves["w"][0.25] < curves["w"][0.5]  # pruning more loses more
    final = np.asarray(scope.find_var("w").get_tensor().array)
    np.testing.assert_array_equal(final, w)


# ------------------------------------------------------------- distillation
def _student_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        h = fluid.layers.fc(x, 8, act="relu", name="student_fc")
        logits = fluid.layers.fc(h, 3, name="student_out")
    return main, startup, x, h, logits


def test_merge_teacher_and_l2_distill():
    t_main, t_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(t_main, t_startup):
        tx = fluid.data("tx", shape=[4], dtype="float32")
        t_logits = fluid.layers.fc(tx, 3, name="teacher_out")
    main, startup, x, h, logits = _student_program()
    rename = merge_teacher_program(t_main, main, {"tx": x.name})
    merged_teacher_out = rename[t_logits.name]
    assert merged_teacher_out.startswith("teacher_")
    with fluid.program_guard(main, startup):
        loss = L2Distiller(logits.name,
                           merged_teacher_out).distiller_loss(main)
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(t_startup)  # teacher params (unprefixed startup)...
        # load teacher weights into prefixed scope names
        import jax.numpy as jnp
        for v in t_main.global_block().vars.values():
            if v.persistable:
                sv = scope.find_var(v.name)
                if sv is not None and sv.is_initialized():
                    scope.var("teacher_" + v.name).set_value(
                        core.LoDTensor(sv.get_tensor().array))
        out = exe.run(main, feed={"x": np.ones((2, 4), "float32")},
                      fetch_list=[loss])
    assert np.asarray(out[0]).shape in ((), (1,))
    assert float(np.asarray(out[0]).ravel()[0]) >= 0


def test_soft_label_distiller_numerics():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        s = fluid.data("s", shape=[3], dtype="float32")
        t = fluid.data("t", shape=[3], dtype="float32")
        loss = SoftLabelDistiller(s.name, t.name, 2.0, 2.0,
                                  1.0).distiller_loss(main)
    exe = fluid.Executor()
    scope = core.Scope()
    sv = np.array([[1.0, 2.0, 3.0]], "float32")
    tv = np.array([[1.0, 2.0, 3.0]], "float32")
    with fluid.scope_guard(scope):
        got = exe.run(main, feed={"s": sv, "t": tv}, fetch_list=[loss])

    def softmax(z):
        e = np.exp(z - z.max())
        return e / e.sum()
    p_s = softmax(sv[0] / 2.0)
    p_t = softmax(tv[0] / 2.0)
    expect = -(p_t * np.log(p_s)).sum()
    np.testing.assert_allclose(float(np.asarray(got[0]).ravel()[0]), expect,
                               rtol=1e-5)


# ------------------------------------------------------- post-training quant
def test_post_training_quantization_abs_max(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, 4, act="relu")
        out = fluid.layers.fc(y, 2)
    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(0)
    X = rng.rand(8, 4).astype("float32")
    with fluid.scope_guard(scope):
        exe.run(startup)
        fp32 = exe.run(main, feed={"x": X}, fetch_list=[out])[0]

        def sample_gen():
            for i in range(4):
                yield {"x": X}

        ptq = PostTrainingQuantization(
            exe, sample_gen, program=main, feed_names=["x"],
            fetch_names=[out.name], scope=scope, algo="abs_max",
            batch_nums=4)
        qprog = ptq.quantize()
        assert ptq.scales, "calibration collected no scales"
        assert any("fake_quantize" in op.type
                   for op in qprog.global_block().ops)
        int8 = exe.run(qprog, feed={"x": X}, fetch_list=[out])[0]
    # int8 sim should stay close to fp32 (few-percent quant noise)
    denom = np.abs(fp32).max() or 1.0
    assert np.abs(int8 - fp32).max() / denom < 0.1


def test_ptq_kl_algo_threshold():
    from paddle_tpu.fluid.contrib.slim.quantization. \
        post_training_quantization import _kl_threshold, _abs_max
    rng = np.random.RandomState(0)
    # heavy-tailed data: KL clip should be well below abs max
    s = [np.concatenate([rng.randn(10000), np.array([50.0])])]
    kl = _kl_threshold(s)
    assert 0 < kl < 50.0
    assert _abs_max(s) == pytest.approx(50.0)


# ----------------------------------------------------------------- NAS / SA
def test_sa_controller_converges_simple():
    ctrl = SAController(seed=0, init_temperature=1.0, reduce_rate=0.7)
    target = [3, 1, 4]
    ctrl.reset([6, 6, 6], [0, 0, 0])
    for _ in range(200):
        tokens = ctrl.next_tokens()
        reward = -sum((a - b) ** 2 for a, b in zip(tokens, target))
        ctrl.update(tokens, reward)
    assert ctrl.max_reward > -3


def test_light_nas_search_loop():
    class Space(SearchSpace):
        def init_tokens(self):
            return [0, 0]

        def range_table(self):
            return [5, 5]

        def create_net(self, tokens=None):
            return (None, tokens, None, None, None)

    def ev(startup, tokens, *rest):
        return -abs(tokens[0] - 3) - abs(tokens[1] - 2)

    strat = LightNASStrategy(controller=SAController(seed=1),
                             search_steps=60)
    best, reward = strat.search(Space(), ev)
    assert reward >= -2


def test_controller_server_agent_roundtrip():
    ctrl = SAController(seed=0)
    ctrl.reset([4, 4], [1, 1])
    server = ControllerServer(ctrl).start()
    try:
        agent = SearchAgent("127.0.0.1", server.port())
        tokens = agent.next_tokens()
        assert len(tokens) == 2
        resp = agent.update(tokens, 1.5)
        assert resp["max_reward"] == 1.5
    finally:
        server.close()


# ------------------------------------------------------------- compressor
def test_compressor_epoch_loop_with_prune():
    import jax.numpy as jnp
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, 2, name="cfc")
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    wname = [p.name for p in main.all_parameters()
             if p.shape == (4, 2)][0]

    def reader():
        yield {"x": np.ones((2, 4), "float32")}

    comp = Compressor(None, scope, main, train_reader=reader,
                      train_fetch_list=[y.name], epoch=1)
    comp.config([PruneStrategy(params=[wname], ratios=[0.5])])
    comp.run()
    w = np.asarray(scope.find_var(wname).get_tensor().array)
    assert int((np.abs(w).sum(axis=1) == 0).sum()) == 2


# ------------------------------------------- end-to-end proofs (VERDICT r2)
def _tiny_regression_setup(seed=0):
    """Build + train a small MLP regression; returns everything needed to
    keep training / evaluating it."""
    rng = np.random.RandomState(seed)
    X = rng.rand(64, 8).astype("float32")
    W_true = rng.randn(8, 1).astype("float32")
    Yd = X @ W_true + 0.1

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[8], dtype="float32")
        y = fluid.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, 16, act="relu",
                            param_attr=fluid.ParamAttr(name="p_fc1_w"),
                            bias_attr=fluid.ParamAttr(name="p_fc1_b"))
        pred = fluid.layers.fc(h, 1,
                               param_attr=fluid.ParamAttr(name="p_fc2_w"),
                               bias_attr=fluid.ParamAttr(name="p_fc2_b"))
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
        fluid.optimizer.Adam(0.01).minimize(loss)
    exe = fluid.Executor()
    scope = core.Scope()

    def train(steps):
        vals = []
        with fluid.scope_guard(scope):
            for _ in range(steps):
                (lv,) = exe.run(main, feed={"x": X, "y": Yd},
                                fetch_list=[loss])
                vals.append(float(np.asarray(lv).ravel()[0]))
        return vals

    with fluid.scope_guard(scope):
        exe.run(startup)
    return main, scope, exe, train, X, Yd


def test_prune_retrain_recovers_accuracy():
    """The reference pruning contract end to end: train → prune 50% of
    fc1 rows (loss jumps) → keep training with masks re-applied every
    batch → loss recovers close to baseline while sparsity holds
    (reference: slim/tests/test_prune_strategy.py role)."""
    main, scope, exe, train, X, Yd = _tiny_regression_setup()
    train(250)
    base = np.mean(train(5))

    strat = PruneStrategy(params=["p_fc1_w"], ratios=[0.5])
    ctx = Context(None, scope)
    ctx.epoch_id = 0
    strat.on_epoch_begin(ctx)   # apply the prune masks
    hurt = np.mean(train(1)[:1])
    assert hurt > base * 1.5 or hurt > base + 1e-3, (base, hurt)

    # retrain WITH the masks enforced after every optimizer step
    masked_losses = []
    for _ in range(150):
        masked_losses.extend(train(1))
        strat.on_batch_end(ctx)
    recovered = np.mean(masked_losses[-5:])
    w = np.asarray(scope.find_var("p_fc1_w").get_tensor().array)
    col_sparsity = (np.abs(w).sum(axis=0) == 0).mean()
    row_sparsity = (np.abs(w).sum(axis=1) == 0).mean()
    assert max(col_sparsity, row_sparsity) >= 0.5 - 1e-6
    # at least 60% of the pruning damage is recovered while masked
    assert recovered < base + 0.4 * (hurt - base), (base, hurt, recovered)


def test_qat_train_quantize_freeze_inference_parity(tmp_path):
    """QAT end to end (reference slim/tests/test_quantization_pass.py
    role): train fp32 → insert QAT fake-quant ops → keep training so the
    moving-average scales settle → freeze → save/load inference model →
    the reloaded frozen program matches the QAT program's outputs within
    8-bit tolerance."""
    from paddle_tpu.fluid.contrib.slim.quantization import (
        QuantizationTransformPass, QuantizationFreezePass)

    rng = np.random.RandomState(1)
    X = rng.rand(32, 8).astype("float32")
    Yd = (X @ rng.randn(8, 1).astype("float32") + 0.1)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[8], dtype="float32")
        y = fluid.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, 16, act="relu")
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
        fluid.optimizer.Adam(0.02).minimize(loss)

    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(40):                       # fp32 pre-training
            exe.run(main, feed={"x": X, "y": Yd}, fetch_list=[loss])

    # insert QAT ops and fine-tune so activation scales settle
    QuantizationTransformPass().apply(main, startup)
    qat_startup = fluid.Program()  # only the new scale vars need init
    with fluid.scope_guard(scope):
        for op in startup.global_block().ops:
            outs = op.output_arg_names
            if any("quant_scale" in n for n in outs):
                qb = qat_startup.global_block()
                for n in outs:
                    if n not in qb.vars:
                        qb.create_var(name=n, persistable=True)
                qb.append_op(type=op.type,
                             inputs={k: list(v)
                                     for k, v in op.inputs.items()},
                             outputs={k: list(v)
                                      for k, v in op.outputs.items()},
                             attrs=dict(op.attrs))
        exe.run(qat_startup)
        losses = []
        for _ in range(40):
            (lv,) = exe.run(main, feed={"x": X, "y": Yd},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
    assert losses[-1] < losses[0] * 2 + 1e-2   # QAT training is stable
    qtypes = [op.type for op in main.global_block().ops]
    assert any(t.startswith("fake_quantize_dequantize") for t in qtypes)

    with fluid.scope_guard(scope):
        (qat_out,) = exe.run(main, feed={"x": X, "y": Yd},
                             fetch_list=[pred.name])
    qat_out = np.asarray(qat_out)

    # freeze + export + reload
    infer = main.clone(for_test=True)
    QuantizationFreezePass().apply(infer)
    model_dir = str(tmp_path / "qat_model")
    with fluid.scope_guard(scope):
        fluid.io.save_inference_model(model_dir, ["x"], [infer.global_block()
                                                         .var(pred.name)],
                                      exe, main_program=infer)
        prog2, feeds2, fetches2 = fluid.io.load_inference_model(model_dir,
                                                                exe)
        (frozen_out,) = exe.run(prog2, feed={feeds2[0]: X},
                                fetch_list=fetches2)
    frozen_out = np.asarray(frozen_out)
    scale = max(1.0, float(np.abs(qat_out).max()))
    assert np.abs(frozen_out - qat_out).max() / scale < 1 / 64.0, (
        np.abs(frozen_out - qat_out).max())
