"""Layer-API tests for the batch-2 vision wrappers (reference:
python/paddle/fluid/layers/nn.py same-named functions) — built into real
Programs and run through Executor, including a backward pass."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core
from paddle_tpu.fluid.framework import Program, program_guard


def _run(prog, startup, feed, fetch):
    scope = core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        return exe.run(prog, feed=feed, fetch_list=fetch)


def test_vision_layer_pipeline_forward():
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.layers.data("img", shape=[3, 16, 16], dtype="float32")
        y = fluid.layers.scale(x, scale=2.0, bias=1.0)
        y = fluid.layers.lrn(y, n=3)
        y = fluid.layers.shuffle_channel(y, group=3)
        up = fluid.layers.resize_trilinear(
            fluid.layers.reshape(y, [-1, 3, 4, 4, 16]),
            out_shape=[6, 6, 18])
        pooled = fluid.layers.adaptive_pool3d(up, pool_size=[3, 3, 6],
                                              pool_type="avg")
        flat = fluid.layers.flatten(pooled)
        sf = fluid.layers.similarity_focus(y, axis=1, indexes=[0])
    X = np.random.RandomState(0).rand(2, 3, 16, 16).astype("float32")
    o_flat, o_sf = _run(main, startup, {"img": X}, [flat, sf])
    assert o_flat.shape == (2, 3 * 3 * 3 * 6)
    assert o_sf.shape == X.shape
    assert set(np.unique(o_sf)).issubset({0.0, 1.0})


def test_deformable_and_transpose_conv_train():
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.layers.data("img", shape=[4, 8, 8], dtype="float32")
        offset = fluid.layers.conv2d(x, num_filters=2 * 9, filter_size=3,
                                     padding=1)
        mask = fluid.layers.conv2d(x, num_filters=9, filter_size=3,
                                   padding=1, act="sigmoid")
        y = fluid.layers.deformable_conv(x, offset, mask, num_filters=6,
                                         filter_size=3, padding=1)
        y5d = fluid.layers.reshape(y, [-1, 6, 2, 8, 4])
        up = fluid.layers.conv3d_transpose(y5d, num_filters=3,
                                           filter_size=2, stride=2)
        loss = fluid.layers.mean(fluid.layers.square(up))
        fluid.optimizer.SGD(0.01).minimize(loss)
    X = np.random.RandomState(1).rand(2, 4, 8, 8).astype("float32")
    scope = core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        l1, = exe.run(main, feed={"img": X}, fetch_list=[loss])
        for _ in range(3):
            l2, = exe.run(main, feed={"img": X}, fetch_list=[loss])
    assert np.isfinite(l1[0]) and float(l2[0]) < float(l1[0])


def test_roi_and_grid_layers():
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.layers.data("feat", shape=[8, 10, 10], dtype="float32")
        rois = fluid.layers.data("rois", shape=[4], dtype="float32",
                                 lod_level=1)
        theta = fluid.layers.data("theta", shape=[2, 3], dtype="float32")
        pp = fluid.layers.psroi_pool(x, rois, output_channels=2,
                                     spatial_scale=1.0, pooled_height=2,
                                     pooled_width=2)
        ra = fluid.layers.roi_align(x, rois, pooled_height=2,
                                    pooled_width=2)
        grid = fluid.layers.affine_grid(theta, out_shape=[1, 8, 5, 5])
    X = np.random.RandomState(2).rand(1, 8, 10, 10).astype("float32")
    R = np.array([[0, 0, 7, 7], [2, 2, 9, 9]], np.float32)
    T = np.array([[[1, 0, 0], [0, 1, 0]]], np.float32)
    scope = core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        rt = core.LoDTensor(R)
        rt.set_recursive_sequence_lengths([[2]])
        o_pp, o_ra, o_g = exe.run(main, feed={"feat": X, "rois": rt,
                                              "theta": T},
                                  fetch_list=[pp, ra, grid])
    assert o_pp.shape == (2, 2, 2, 2)
    assert o_ra.shape == (2, 8, 2, 2)
    assert o_g.shape == (1, 5, 5, 2)
    # identity theta -> grid spans [-1,1]
    np.testing.assert_allclose(o_g[0, 0, 0], [-1, -1], atol=1e-6)
    np.testing.assert_allclose(o_g[0, -1, -1], [1, 1], atol=1e-6)


def test_hash_and_misc_layers():
    main, startup = Program(), Program()
    with program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[1], dtype="int64")
        h = fluid.layers.hash(ids, hash_size=1000, num_hash=3)
        a = fluid.layers.data("a", shape=[6], dtype="float32")
        b = fluid.layers.data("b", shape=[6], dtype="float32")
        cs = fluid.layers.cos_sim(a, b)
    I = np.array([[7], [7], [9]], np.int64)
    A = np.random.RandomState(3).rand(3, 6).astype("float32")
    o_h, o_cs = _run(main, startup, {"ids": I, "a": A, "b": A}, [h, cs])
    assert o_h.shape == (3, 3, 1)
    assert (o_h >= 0).all() and (o_h < 1000).all()
    np.testing.assert_array_equal(o_h[0], o_h[1])   # same id, same buckets
    assert (o_h[0] != o_h[2]).any()                 # different id differs
    np.testing.assert_allclose(o_cs.ravel(), 1.0, rtol=1e-5)  # cos(x,x)=1
