"""2.0-preview namespaces (reference: python/paddle/{nn,tensor,framework,
optimizer,metric,device,distribution,batch}.py thin aliases over fluid)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core


def _run(build_fn, feed):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        outs = build_fn()
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        return exe.run(main, feed=feed, fetch_list=list(outs))


def test_tensor_linalg_ops():
    rng = np.random.RandomState(0)
    A = rng.rand(2, 3, 4).astype("float32")
    B = rng.rand(2, 4, 5).astype("float32")
    v = rng.rand(4).astype("float32")

    def build():
        a = fluid.data("a", shape=[3, 4], dtype="float32")
        b = fluid.data("b", shape=[4, 5], dtype="float32")
        x = fluid.data("x", shape=[4], dtype="float32",
                       append_batch_size=False)
        return (paddle.tensor.bmm(a, b), paddle.tensor.dot(x, x))

    bm, dt = _run(build, {"a": A, "b": B, "x": v})
    np.testing.assert_allclose(bm, A @ B, rtol=1e-5)
    np.testing.assert_allclose(dt, (v * v).sum(), rtol=1e-5)


def test_tensor_trace_flip_kron_full_tile():
    rng = np.random.RandomState(0)
    M = rng.rand(3, 3).astype("float32")

    def build():
        m = fluid.data("m", shape=[3, 3], dtype="float32",
                       append_batch_size=False)
        return (paddle.tensor.trace(m), paddle.tensor.flip(m, axis=0),
                paddle.tensor.kron(m, m),
                paddle.tensor.full([2, 2], 7.0),
                paddle.tensor.logsumexp(m))

    tr, fl, kr, fu, lse = _run(build, {"m": M})
    np.testing.assert_allclose(tr, np.trace(M), rtol=1e-5)
    np.testing.assert_allclose(fl, M[::-1], rtol=1e-6)
    np.testing.assert_allclose(kr, np.kron(M, M), rtol=1e-5)
    np.testing.assert_allclose(fu, np.full((2, 2), 7.0))
    np.testing.assert_allclose(
        np.asarray(lse).ravel()[0],
        np.log(np.exp(M).sum()), rtol=1e-5)


def test_tensor_cholesky_inverse_meshgrid():
    rng = np.random.RandomState(0)
    A = rng.rand(3, 3).astype("float32")
    spd = (A @ A.T + 3 * np.eye(3)).astype("float32")

    def build():
        m = fluid.data("m", shape=[3, 3], dtype="float32",
                       append_batch_size=False)
        xs = fluid.data("xs", shape=[3], dtype="float32",
                        append_batch_size=False)
        ys = fluid.data("ys", shape=[2], dtype="float32",
                        append_batch_size=False)
        g0, g1 = paddle.tensor.meshgrid(xs, ys)
        return (paddle.tensor.cholesky(m), paddle.tensor.inverse(m), g0, g1)

    ch, inv, g0, g1 = _run(build, {"m": spd,
                                   "xs": np.arange(3, dtype="float32"),
                                   "ys": np.arange(2, dtype="float32")})
    np.testing.assert_allclose(ch, np.linalg.cholesky(spd), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(inv, np.linalg.inv(spd), rtol=1e-4,
                               atol=1e-5)
    assert g0.shape == (3, 2) and g1.shape == (3, 2)


def test_nn_functional_and_layers():
    import paddle_tpu.nn.functional as F

    def build():
        x = fluid.data("x", shape=[4], dtype="float32")
        return (F.relu(x), F.softmax(x), F.gelu(x))

    X = np.array([[-1.0, 0.0, 1.0, 2.0]], "float32")
    r, s, g = _run(build, {"x": X})
    np.testing.assert_allclose(r, np.maximum(X, 0), rtol=1e-6)
    np.testing.assert_allclose(s.sum(), 1.0, rtol=1e-5)


def test_optimizer_adamw_namespace():
    def build():
        x = fluid.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, 2)
        loss = fluid.layers.mean(y)
        paddle.optimizer.AdamW(weight_decay=0.01,
                               learning_rate=0.01).minimize(loss)
        return (loss,)

    out = _run(build, {"x": np.ones((2, 4), "float32")})
    assert np.isfinite(np.asarray(out[0])).all()


def test_metric_namespace():
    m = paddle.metric.Accuracy()
    m.update(value=np.array([0.8]), weight=10)
    assert m.eval() == pytest.approx(0.8)


def test_framework_seed_and_dtype():
    paddle.manual_seed(1234)
    assert fluid.default_main_program().random_seed == 1234
    paddle.set_default_dtype("float64")
    assert paddle.get_default_dtype() == "float64"
    paddle.set_default_dtype("float32")
    with pytest.raises(TypeError):
        paddle.set_default_dtype("int32")


def test_batch_reader():
    def reader():
        yield from range(7)

    batches = list(paddle.batch(reader, 3)())
    assert batches == [[0, 1, 2], [3, 4, 5], [6]]
    batches = list(paddle.batch(reader, 3, drop_last=True)())
    assert batches == [[0, 1, 2], [3, 4, 5]]


def test_device_namespace():
    d = paddle.device.get_device()
    assert d.startswith(("cpu", "tpu"))
    assert isinstance(paddle.device.set_device("cpu"), core.CPUPlace)
    with pytest.raises(ValueError):
        paddle.device.set_device("weird")


def test_cross_default_axis_and_losses():
    rng = np.random.RandomState(0)
    A = rng.rand(3, 4).astype("float32")
    B = rng.rand(3, 4).astype("float32")

    def build():
        a = fluid.data("a", shape=[3, 4], dtype="float32",
                       append_batch_size=False)
        b = fluid.data("b", shape=[3, 4], dtype="float32",
                       append_batch_size=False)
        import paddle_tpu.nn.functional as F
        return (paddle.tensor.cross(a, b), F.l1_loss(a, b))

    cr, l1 = _run(build, {"a": A, "b": B})
    np.testing.assert_allclose(cr, np.cross(A, B, axis=0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(l1).ravel()[0],
                               np.abs(A - B).mean(), rtol=1e-5)


def test_nonzero_dygraph_and_as_tuple():
    import paddle_tpu.fluid.dygraph as dygraph
    from paddle_tpu.fluid.dygraph import to_variable
    with dygraph.guard():
        x = to_variable(np.array([[1, 0], [0, 2]], "float32"))
        idx = paddle.tensor.nonzero(x)
        np.testing.assert_array_equal(idx.numpy(),
                                      [[0, 0], [1, 1]])
        rows, cols = paddle.tensor.nonzero(x, as_tuple=True)
        np.testing.assert_array_equal(rows.numpy(), [0, 1])
        np.testing.assert_array_equal(cols.numpy(), [0, 1])


def test_full_honors_default_dtype():
    paddle.set_default_dtype("float64")
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            v = paddle.tensor.full([2], 1.0)
            assert v.dtype == core.VarDesc.VarType.FP64
    finally:
        paddle.set_default_dtype("float32")


def test_device_index_round_trip():
    paddle.device.set_device("cpu")
    assert paddle.device.get_device() == "cpu"
    if paddle.device.is_compiled_with_tpu():
        paddle.device.set_device("tpu:1")
        assert paddle.device.get_device() == "tpu:1"
        paddle.device.set_device("cpu")


def test_model_fit_empty_reader():
    import paddle_tpu.fluid.dygraph as dygraph
    from paddle_tpu.incubate.hapi import Model, CrossEntropy
    with dygraph.guard():
        net = dygraph.Linear(4, 2)

        class M(dygraph.Layer):
            def __init__(self):
                super().__init__()
                self.fc = net

            def forward(self, x):
                return self.fc(x)
        model = Model(M())
        model.prepare(fluid.optimizer.SGD(
            0.1, parameter_list=net.parameters()), CrossEntropy())
        hist = model.fit(lambda: iter([]), epochs=1, verbose=0)
    assert hist[0]["loss"] is None


def test_distribution_namespace():
    import paddle_tpu.fluid.dygraph as dygraph
    with dygraph.guard():
        n = paddle.distribution.Normal(loc=0.0, scale=1.0)
        s = n.sample([100])
        assert np.asarray(s.numpy()).shape[0] == 100


def test_legacy_and_20_shims(capsys):
    """fluid.memory_optimize/require_version/one_hot/embedding + 2.0-style
    paddle.enable_static/disable_static/in_dynamic_mode/summary."""
    import warnings
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        fluid.memory_optimize(None)
        fluid.release_memory(None)
    assert len(w) == 2 and all(issubclass(x.category, DeprecationWarning)
                               for x in w)

    fluid.require_version("1.0.0")
    fluid.require_version("1.0.0", "99.0")
    with pytest.raises(Exception):
        fluid.require_version("99.0.0")
    with pytest.raises(TypeError):
        fluid.require_version(1)
    with pytest.raises(NotImplementedError):
        fluid.load_op_library("libfoo.so")

    # v1.7 unified one_hot/embedding: ids WITHOUT trailing-1 dim
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.data("ids20", shape=[5], dtype="int64")
        oh = fluid.one_hot(ids, depth=7)
        emb = fluid.embedding(ids, size=[7, 3])
    exe = fluid.Executor()
    scope = core.Scope()
    idv = np.array([[0, 2, 6, 1, 3]], "int64")
    with fluid.scope_guard(scope):
        exe.run(startup)
        o, e = exe.run(main, feed={"ids20": idv},
                       fetch_list=[oh.name, emb.name])
    assert np.asarray(o).shape == (1, 5, 7)
    np.testing.assert_allclose(np.asarray(o).sum(-1), np.ones((1, 5)))
    assert np.asarray(e).shape == (1, 5, 3)

    # 2.0 mode toggles
    assert not paddle.in_dynamic_mode()
    paddle.disable_static()
    assert paddle.in_dynamic_mode()
    x = paddle.to_variable(np.ones((2, 2), np.float32))
    assert float(x.numpy().sum()) == 4.0
    paddle.enable_static()
    assert not paddle.in_dynamic_mode()

    # summary over a dygraph layer
    import paddle_tpu.fluid.dygraph as dygraph
    with dygraph.guard():
        net = dygraph.Linear(4, 2)
        info = paddle.summary(net)
    out = capsys.readouterr().out
    assert "Total params" in out
    assert info["total_params"] == 4 * 2 + 2


def test_tensor_20_extras_numeric():
    """paddle.{clamp,full_like,log_softmax,t,var,std,numel,addcmul,
    allclose,rand,randn} (reference 2.0 tensor API tests)."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core

    main, st = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, st):
        x = fluid.data("x20", shape=[3, 4], dtype="float32")
        y2 = fluid.data("y20", shape=[4], dtype="float32")
        outs = dict(
            clamp=paddle.clamp(x, 0.2, 0.8),
            fl=paddle.full_like(x, 7.0),
            ls=paddle.log_softmax(x),
            tt=paddle.t(y2),
            v=paddle.var(x), s=paddle.std(x), n=paddle.numel(x),
            v1=paddle.var(x, axis=1),
            ac=paddle.addcmul(x, x, x, value=0.5),
            alc=paddle.allclose(x, x),
            rn=paddle.randn([2, 2]), rd=paddle.rand([2, 2]))
    exe = fluid.Executor()
    scope = core.Scope()
    xv = np.random.RandomState(0).rand(2, 3, 4).astype("float32")
    yv = np.random.RandomState(1).rand(2, 4).astype("float32")
    with fluid.scope_guard(scope):
        exe.run(st)
        names = {k: v.name for k, v in outs.items()}
        res = exe.run(main, feed={"x20": xv, "y20": yv},
                      fetch_list=list(names.values()))
    res = dict(zip(names, [np.asarray(r) for r in res]))
    np.testing.assert_allclose(res["clamp"], np.clip(xv, 0.2, 0.8),
                               rtol=1e-6)
    np.testing.assert_allclose(res["fl"], np.full_like(xv, 7.0))
    e = np.exp(xv - xv.max(-1, keepdims=True))
    np.testing.assert_allclose(res["ls"], np.log(e / e.sum(-1,
                                                           keepdims=True)),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(res["tt"], yv.T, rtol=1e-6)
    np.testing.assert_allclose(res["v"].ravel()[0], xv.var(ddof=1),
                               rtol=1e-5)
    np.testing.assert_allclose(res["s"].ravel()[0], xv.std(ddof=1),
                               rtol=1e-5)
    np.testing.assert_allclose(res["v1"], xv.var(1, ddof=1), rtol=1e-5)
    assert int(res["n"].ravel()[0]) == xv.size
    np.testing.assert_allclose(res["ac"], xv + 0.5 * xv * xv, rtol=1e-6)
    assert bool(res["alc"].ravel()[0])
    assert res["rn"].shape == (2, 2) and res["rd"].shape == (2, 2)


def test_nn_loss_and_activation_classes():
    """paddle.nn class wrappers (reference paddle/nn layer classes)."""
    import numpy as np
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.fluid.dygraph as dygraph

    rng = np.random.RandomState(0)
    with dygraph.guard():
        x = dygraph.to_variable(rng.randn(4, 5).astype("float32"))
        lab = dygraph.to_variable(
            rng.randint(0, 5, (4, 1)).astype("int64"))
        ce = nn.CrossEntropyLoss()(x, lab)
        e = np.exp(np.asarray(x.numpy())
                   - np.asarray(x.numpy()).max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        want = -np.log(sm[np.arange(4),
                          np.asarray(lab.numpy()).ravel()]).mean()
        np.testing.assert_allclose(
            np.asarray(ce.numpy()).ravel()[0], want, rtol=1e-5)

        r = nn.ReLU()(x)
        assert float(np.asarray(r.numpy()).min()) >= 0.0
        s = nn.Softmax()(x)
        np.testing.assert_allclose(np.asarray(s.numpy()).sum(-1),
                                   np.ones(4), rtol=1e-5)
        mse = nn.MSELoss()(x, x)
        assert abs(float(np.asarray(mse.numpy()).ravel()[0])) < 1e-7
        ls = F.log_softmax(x)
        np.testing.assert_allclose(np.asarray(ls.numpy()), np.log(sm),
                                   rtol=1e-4, atol=1e-5)
        probs = dygraph.to_variable(
            rng.rand(4, 1).astype("float32") * 0.8 + 0.1)
        tgt = dygraph.to_variable(
            rng.randint(0, 2, (4, 1)).astype("float32"))
        bce = nn.BCELoss()(probs, tgt)
        p = np.asarray(probs.numpy())
        t = np.asarray(tgt.numpy())
        want_bce = -(t * np.log(p) + (1 - t) * np.log(1 - p)).mean()
        np.testing.assert_allclose(np.asarray(bce.numpy()).ravel()[0],
                                   want_bce, rtol=1e-4)
