"""Combined-topology worker (VERDICT r2 #5): launcher-driven DP trainer
processes x pservers hosting beyond-threshold LAZY sparse tables — the
BASELINE.md Wide&Deep shape (reference: test_dist_base.py:506 run_trainer
+ fleet_wrapper.h:86-190 DownpourSparseTable).

Trainer role (spawned by paddle_tpu.distributed.launch): brings up
jax.distributed from the PADDLE_* env (the multi-process bring-up the
launcher provides), transpiles a wide&deep-lite model against the PS
plane (sync mode — the trainers are data-parallel THROUGH the pserver
grad averaging, the reference's sync-DP semantics), trains on its half
of a deterministic global batch, and writes per-step losses + a
throughput row from rank 0.

Pserver role: hosts its shard; the sparse table exceeds
FLAGS_lazy_sparse_table_threshold, so it materializes as an
init-on-touch LazyEmbeddingTable.
"""
import json
import os
import sys
import time

os.environ["FLAGS_lazy_sparse_table_threshold"] = "1000000"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu.fluid as fluid  # noqa: E402
from paddle_tpu.fluid import core  # noqa: E402

STEPS = 5
GLOBAL_BATCH = 16
SPARSE_DIM = int(2.5e6)   # > threshold → lazy tables on the pservers
EMB_DIM = 8


def build():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        tok = fluid.data("tok", shape=[1], dtype="int64")
        y = fluid.data("y", shape=[1], dtype="float32")
        emb = fluid.layers.embedding(
            tok, size=[SPARSE_DIM, EMB_DIM], is_distributed=True,
            param_attr=fluid.ParamAttr(name="wd_emb"))
        emb = fluid.layers.reshape(emb, [-1, EMB_DIM])
        feat = fluid.layers.concat([x, emb], axis=1)
        h = fluid.layers.fc(feat, 16, act="relu")
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def transpile(main, startup, eps, trainer_id, trainers):
    from paddle_tpu.fluid.transpiler import DistributeTranspiler
    t = DistributeTranspiler()
    with fluid.program_guard(main, startup):
        t.transpile(trainer_id=trainer_id, pservers=eps, trainers=trainers,
                    sync_mode=True, program=main, startup_program=startup)
    return t


def global_batch():
    rng = np.random.RandomState(3)
    X = rng.rand(GLOBAL_BATCH, 4).astype("float32")
    # ids spread over the whole [0, SPARSE_DIM) range: proves
    # init-on-touch at beyond-RAM logical size, and hits both shards
    toks = ((np.arange(GLOBAL_BATCH) * 104729 + 11) % SPARSE_DIM
            ).astype("int64").reshape(-1, 1)
    Y = (X.sum(1, keepdims=True) * 0.5).astype("float32")
    return X, toks, Y


def run_trainer(eps, out_path):
    from paddle_tpu.parallel import env as penv
    from paddle_tpu.fluid.ps_rpc import WorkerHeartBeat

    trainers = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    tid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if trainers > 1:
        penv.init_distributed()   # jax.distributed over the launcher env
        assert penv.world_size() == trainers, (
            penv.world_size(), trainers)

    main, startup, loss = build()
    t = transpile(main, startup, eps, tid, trainers)
    prog = t.get_trainer_program()

    X, toks, Y = global_batch()
    per = GLOBAL_BATCH // trainers
    lo, hi = tid * per, (tid + 1) * per

    beat = WorkerHeartBeat(eps.split(","), tid, interval=0.5).start()
    exe = fluid.Executor()
    scope = core.Scope()
    losses = []
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            t0 = time.perf_counter()
            for _ in range(STEPS):
                (lv,) = exe.run(prog,
                                feed={"x": X[lo:hi], "tok": toks[lo:hi],
                                      "y": Y[lo:hi]},
                                fetch_list=[loss])
                losses.append(float(np.asarray(lv).ravel()[0]))
            dt = time.perf_counter() - t0
    finally:
        beat.stop()
    # every rank reports: each trainer's loss is over ITS half of the
    # global batch, so the cross-rank MEAN is the full-batch loss the
    # single-process oracle computes
    with open(f"{out_path}.r{tid}", "w") as f:
        json.dump({"losses": losses,
                   "samples_per_sec": per * trainers * STEPS / dt,
                   "trainers": trainers}, f)


def run_pserver(eps, idx, trainers):
    main, startup, loss = build()
    t = transpile(main, startup, eps, 0, trainers)
    ep = eps.split(",")[idx]
    pprog = t.get_pserver_program(ep)
    pstart = t.get_startup_program(ep, pprog)
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(pstart)
        tbl = scope.find_var("wd_emb")
        lazy = tbl is not None and isinstance(tbl.value(),
                                              core.LazyEmbeddingTable)
        print(f"PSERVER_READY lazy={lazy}", flush=True)
        exe.run(pprog)  # blocks until stop rpc


def main():
    role = sys.argv[1]
    if role == "pserver":
        run_pserver(sys.argv[2], int(sys.argv[3]), int(sys.argv[4]))
    elif role == "trainer":
        run_trainer(sys.argv[2], sys.argv[3])
    else:
        raise SystemExit(f"unknown role {role!r}")


if __name__ == "__main__":
    main()
