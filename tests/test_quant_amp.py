"""QAT + AMP + collective-transpiler + sync-BN tests (reference:
tests/unittests/test_quantization_pass.py, test_fake_quantize_op.py,
contrib/tests/test_image_classification_fp16.py,
test_sync_batch_norm_op.py)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core
from tests.test_sequence_ops import run_seq_op


def test_fake_quantize_abs_max_levels():
    x = np.array([[0.5, -1.0, 0.25]], np.float32)
    (q, s), _ = run_seq_op("fake_quantize_abs_max", x, None,
                           attrs={"bit_length": 8},
                           outputs=("Out", "OutScale"))
    assert s[0] == 1.0
    np.testing.assert_allclose(q, np.round(x * 127), atol=0)


def test_fake_quant_dequant_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    x = rng.randn(32, 32).astype(np.float32)
    (qdq, s), _ = run_seq_op("fake_quantize_dequantize_abs_max", x, None,
                             attrs={"bit_length": 8},
                             outputs=("Out", "OutScale"))
    # quantization error bounded by scale/127/2 per element
    assert np.abs(qdq - x).max() <= s[0] / 127.0 * 0.5 + 1e-6


def test_qat_program_trains():
    from paddle_tpu.fluid.contrib.slim.quantization import (
        QuantizationTransformPass)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[8], dtype="float32")
        y = fluid.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, 16, act="relu")
        pred = fluid.layers.fc(h, 4, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
    # quantize BEFORE building the backward, like the reference QAT flow
    with fluid.program_guard(main, startup):
        QuantizationTransformPass().apply(main, startup)
        fluid.optimizer.Adam(0.05).minimize(loss)
    qtypes = [op.type for op in main.global_block().ops]
    assert "fake_quantize_dequantize_abs_max" in qtypes
    assert "fake_quantize_dequantize_moving_average_abs_max" in qtypes
    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(0)
    X = rng.rand(16, 8).astype("float32")
    Y = rng.randint(0, 4, (16, 1)).astype("int64")
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(20):
            (lv,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_amp_decorate_trains_bf16():
    from paddle_tpu.fluid.contrib.mixed_precision import decorate
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[8], dtype="float32")
        y = fluid.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, 16, act="relu")
        pred = fluid.layers.fc(h, 4, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
        opt = decorate(fluid.optimizer.Adam(0.05))
        opt.minimize(loss)
    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(1)
    X = rng.rand(16, 8).astype("float32")
    Y = rng.randint(0, 4, (16, 1)).astype("int64")
    with fluid.scope_guard(scope):
        exe.run(startup)
        first = last = None
        for _ in range(15):
            (lv,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
            v = float(np.asarray(lv).reshape(-1)[0])
            first = first if first is not None else v
            last = v
    assert last < first


def test_sync_batch_norm_same_as_batch_norm_single_chip():
    x = np.random.RandomState(2).rand(4, 3, 2, 2).astype(np.float32)
    args = dict(
        extra_inputs=[("Scale", np.ones(3, np.float32), None),
                      ("Bias", np.zeros(3, np.float32), None),
                      ("Mean", np.zeros(3, np.float32), None),
                      ("Variance", np.ones(3, np.float32), None)],
        attrs={"is_test": False, "epsilon": 1e-5},
        outputs=("Y",))
    (a,), _ = run_seq_op("batch_norm", x, None, x_slot="X", **args)
    (b,), _ = run_seq_op("sync_batch_norm", x, None, x_slot="X", **args)
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_collective_transpiler_grad_allreduce():
    from paddle_tpu.fluid.transpiler.collective import GradAllReduce
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        y = fluid.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    GradAllReduce().transpile(startup, main, rank=0,
                              endpoints="127.0.0.1:1,127.0.0.1:2",
                              current_endpoint="127.0.0.1:1")
    types = [op.type for op in main.global_block().ops]
    assert "c_allreduce_sum" in types
    assert "scale" in types
    assert "c_comm_init_all" in [op.type for op in
                                 startup.global_block().ops]


def test_bf16_matmul_flag_conv_training():
    """FLAGS_use_bf16_matmul must keep conv/matmul grads working (the
    mixed-dtype conv transpose has no vjp rule, so the kernel computes in
    bf16 end-to-end and casts back)."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core

    core.set_flag("FLAGS_use_bf16_matmul", True)
    try:
        main, st = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, st), fluid.unique_name.guard():
            img = fluid.data("img", shape=[3, 8, 8], dtype="float32")
            lab = fluid.data("lab", shape=[1], dtype="int64")
            c = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                    act="relu")
            p = fluid.layers.fc(c, 10, act="softmax")
            loss = fluid.layers.mean(fluid.layers.cross_entropy(p, lab))
            fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        sc = core.Scope()
        rng = np.random.RandomState(0)
        losses = []
        with fluid.scope_guard(sc):
            exe.run(st)
            for _ in range(10):
                x = rng.rand(8, 3, 8, 8).astype("float32")
                y = (x.mean((1, 2, 3)) * 10).astype("int64").reshape(-1, 1) % 10
                (lv,) = exe.run(main, feed={"img": x, "lab": y},
                                fetch_list=[loss.name])
                losses.append(float(np.asarray(lv).ravel()[0]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses
    finally:
        core.set_flag("FLAGS_use_bf16_matmul", False)
