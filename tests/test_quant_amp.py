"""QAT + AMP + collective-transpiler + sync-BN tests (reference:
tests/unittests/test_quantization_pass.py, test_fake_quantize_op.py,
contrib/tests/test_image_classification_fp16.py,
test_sync_batch_norm_op.py)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core
from tests.test_sequence_ops import run_seq_op


def test_fake_quantize_abs_max_levels():
    x = np.array([[0.5, -1.0, 0.25]], np.float32)
    (q, s), _ = run_seq_op("fake_quantize_abs_max", x, None,
                           attrs={"bit_length": 8},
                           outputs=("Out", "OutScale"))
    assert s[0] == 1.0
    np.testing.assert_allclose(q, np.round(x * 127), atol=0)


def test_fake_quant_dequant_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    x = rng.randn(32, 32).astype(np.float32)
    (qdq, s), _ = run_seq_op("fake_quantize_dequantize_abs_max", x, None,
                             attrs={"bit_length": 8},
                             outputs=("Out", "OutScale"))
    # quantization error bounded by scale/127/2 per element
    assert np.abs(qdq - x).max() <= s[0] / 127.0 * 0.5 + 1e-6


def test_qat_program_trains():
    from paddle_tpu.fluid.contrib.slim.quantization import (
        QuantizationTransformPass)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[8], dtype="float32")
        y = fluid.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, 16, act="relu")
        pred = fluid.layers.fc(h, 4, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
    # quantize BEFORE building the backward, like the reference QAT flow
    with fluid.program_guard(main, startup):
        QuantizationTransformPass().apply(main, startup)
        fluid.optimizer.Adam(0.05).minimize(loss)
    qtypes = [op.type for op in main.global_block().ops]
    assert "fake_quantize_dequantize_abs_max" in qtypes
    assert "fake_quantize_dequantize_moving_average_abs_max" in qtypes
    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(0)
    X = rng.rand(16, 8).astype("float32")
    Y = rng.randint(0, 4, (16, 1)).astype("int64")
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(20):
            (lv,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_amp_decorate_trains_bf16():
    from paddle_tpu.fluid.contrib.mixed_precision import decorate
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[8], dtype="float32")
        y = fluid.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, 16, act="relu")
        pred = fluid.layers.fc(h, 4, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
        opt = decorate(fluid.optimizer.Adam(0.05))
        opt.minimize(loss)
    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(1)
    X = rng.rand(16, 8).astype("float32")
    Y = rng.randint(0, 4, (16, 1)).astype("int64")
    with fluid.scope_guard(scope):
        exe.run(startup)
        first = last = None
        for _ in range(15):
            (lv,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
            v = float(np.asarray(lv).reshape(-1)[0])
            first = first if first is not None else v
            last = v
    assert last < first


def _amp_dyn_program(seed=3, incr_every=2, decr_every=1, white_list=None):
    from paddle_tpu.fluid.contrib.mixed_precision import (
        AutoMixedPrecisionLists, decorate)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.data("x", shape=[8], dtype="float32")
        y = fluid.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, 16, act="relu")
        pred = fluid.layers.fc(h, 4, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
        lists = AutoMixedPrecisionLists()
        if white_list is not None:
            lists.white_list = set(white_list)
        opt = decorate(fluid.optimizer.SGD(0.1), amp_lists=lists,
                       init_loss_scaling=8.0,
                       incr_every_n_steps=incr_every,
                       decr_every_n_nan_or_inf=decr_every,
                       incr_ratio=2.0, decr_ratio=0.5, use_fp16=True)
        opt.minimize(loss)
    return main, startup, loss, opt


def _amp_dyn_run(mode, inject_at=(2,), steps=6, **build_kw):
    """(losses, scales) over ``steps`` with overflow injected at the
    given step indices, executed under FLAGS_executor_mode=``mode``."""
    saved = core.globals_["FLAGS_executor_mode"]
    core.set_flag("FLAGS_executor_mode", mode)
    try:
        main, startup, loss, opt = _amp_dyn_program(**build_kw)
        exe = fluid.Executor()
        scope = core.Scope()
        rng = np.random.RandomState(0)
        X = rng.rand(16, 8).astype("float32")
        Y = rng.randint(0, 4, (16, 1)).astype("int64")
        Xbad = X.copy()
        Xbad[0, 0] = np.inf
        losses, scales = [], []
        with fluid.scope_guard(scope):
            exe.run(startup)
            for i in range(steps):
                (lv,) = exe.run(
                    main, feed={"x": Xbad if i in inject_at else X,
                                "y": Y}, fetch_list=[loss])
                losses.append(np.asarray(lv).item())
                scales.append(np.asarray(scope.find_var(
                    opt._loss_scaling_var.name).get_tensor().array
                    ).item())
        return losses, scales, exe._last_run_mode
    finally:
        core.set_flag("FLAGS_executor_mode", saved)


def test_amp_dynamic_scaling_compiled_halves_and_regrows():
    """Closes the test_quant_amp gap: REAL dynamic loss scaling on the
    fully compiled path — an injected overflow halves the scale
    (decr_every_n_nan_or_inf=1, decr_ratio=0.5), incr_every_n_steps=2
    clean steps regrow it (incr_ratio=2.0), and the overflowed step is
    discarded whole (params revert via the fused guard select, which
    the scaler shares its health scalar with)."""
    losses, scales, mode = _amp_dyn_run("compiled")
    assert mode == "compiled"
    # steps:   0      1     2(bad)  3     4      5
    # scale:  8->8  8->16  16->8   8->8  8->16  16->16
    assert scales == [8.0, 16.0, 8.0, 8.0, 16.0, 16.0]
    assert np.isnan(losses[2])
    clean = losses[:2] + losses[3:]
    assert np.isfinite(clean).all()


def test_amp_dynamic_scaling_bit_identical_to_interpreter_oracle():
    """The scale/counter transition and the step trajectory must be
    BIT-identical between the compiled path and the interpreter oracle
    — both consume the same fused health scalar and run the same
    _amp_scale_update arithmetic. The white list is emptied so the
    comparison isolates the scaler (bf16 cast folding differs across
    XLA fusion boundaries by design and has its own parity test)."""
    lc, sc, _ = _amp_dyn_run("compiled", white_list=())
    li, si, _ = _amp_dyn_run("interpreted", white_list=())
    assert sc == si
    assert np.array_equal(np.asarray(lc), np.asarray(li), equal_nan=True)


def test_amp_raise_replay_sees_pre_step_scale():
    """raise-mode regression: the interpreter replay must run from the
    EXACT pre-step loss scale. Here the overflow is caused by the scale
    magnitude itself (grad = scale*x overflows fp32 at scale 4 but is
    finite at the decayed scale 2), so if the tripped step's AMP decay
    landed before the replay, the replay would run CLEAN at scale 2,
    mis-report "the fault did not replay", and its phantom optimizer
    update would corrupt the pre-step state the select kept."""
    from paddle_tpu.fluid.contrib.mixed_precision import (
        AutoMixedPrecisionLists, decorate)
    saved = {k: core.globals_[k] for k in
             ("FLAGS_check_nan_inf", "FLAGS_nan_inf_action")}
    core.set_flag("FLAGS_check_nan_inf", True)
    core.set_flag("FLAGS_nan_inf_action", "raise")
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup), fluid.unique_name.guard():
            x = fluid.data("x", shape=[1], dtype="float32")
            h = fluid.layers.fc(
                x, 1, bias_attr=False,
                param_attr=fluid.ParamAttr(
                    name="amp_raise_w",
                    initializer=fluid.initializer.Constant(0.1)))
            loss = fluid.layers.mean(h)
            lists = AutoMixedPrecisionLists()
            lists.white_list = set()  # keep everything fp32
            opt = decorate(fluid.optimizer.SGD(1e-4), amp_lists=lists,
                           init_loss_scaling=4.0, incr_every_n_steps=1000,
                           decr_every_n_nan_or_inf=1, incr_ratio=2.0,
                           decr_ratio=0.5, use_fp16=True)
            opt.minimize(loss)
        exe = fluid.Executor()
        scope = core.Scope()
        # scaled grad_w = scale * x: 4e38 overflows fp32, 2e38 does not
        X = np.full((1, 1), 1e38, np.float32)
        with fluid.scope_guard(scope):
            exe.run(startup)
            w0 = np.asarray(
                scope.find_var("amp_raise_w").get_tensor().array).copy()
            with pytest.raises(FloatingPointError) as ei:
                exe.run(main, feed={"x": X}, fetch_list=[loss])
            # op-level localization, not the non-reproduction fallback
            assert "op #" in str(ei.value), ei.value
            assert "did not replay" not in str(ei.value)
            scale = np.asarray(scope.find_var(
                opt._loss_scaling_var.name).get_tensor().array).item()
            assert scale == 4.0  # pre-step scale preserved for the replay
            w1 = np.asarray(
                scope.find_var("amp_raise_w").get_tensor().array)
            assert np.array_equal(w0, w1)  # no phantom-replay update
    finally:
        for k, v in saved.items():
            core.set_flag(k, v)


def test_amp_scale_floors_at_one_under_persistent_overflow():
    """The decayed scale clamps at 1.0 (reference update_loss_scaling):
    without the floor a persistent fault would underflow the fp32 scale
    to exactly 0, where it sticks (0*incr==0) and the zeroed scaled
    loss reads as healthy — a silent training freeze."""
    losses, scales, _ = _amp_dyn_run(
        "compiled", inject_at=set(range(8)), steps=8)
    # 8 -> 4 -> 2 -> 1 -> 1 -> ... (decr_every=1, decr_ratio=0.5)
    assert scales == [4.0, 2.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0]


def test_amp_static_scaling_when_dynamic_disabled():
    """decorate(use_fp16=True, use_dynamic_loss_scaling=False) must
    apply STATIC scaling (loss*const, grads/const) — not silently drop
    the requested init_loss_scaling. Scaling by a power of two is exact
    in fp32, so the trajectory is bit-identical to an undecorated run."""
    from paddle_tpu.fluid.contrib.mixed_precision import (
        AutoMixedPrecisionLists, decorate)

    def build(static_amp):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 9
        with fluid.program_guard(main, startup), fluid.unique_name.guard():
            x = fluid.data("x", shape=[8], dtype="float32")
            y = fluid.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, 16, act="relu")
            pred = fluid.layers.fc(h, 4, act="softmax")
            loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
            opt = fluid.optimizer.SGD(0.1)
            if static_amp:
                lists = AutoMixedPrecisionLists()
                lists.white_list = set()  # isolate the scaling machinery
                opt = decorate(opt, amp_lists=lists,
                               init_loss_scaling=1024.0,
                               use_dynamic_loss_scaling=False,
                               use_fp16=True)
            opt.minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(0)
    X = rng.rand(16, 8).astype("float32")
    Y = rng.randint(0, 4, (16, 1)).astype("int64")
    out = {}
    for static_amp in (False, True):
        main, startup, loss = build(static_amp)
        if static_amp:  # the static scaled-loss op made it into the graph
            assert "scale" in [op.type for op in main.global_block().ops]
        exe = fluid.Executor()
        scope = core.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            out[static_amp] = [
                np.asarray(exe.run(main, feed={"x": X, "y": Y},
                                   fetch_list=[loss])[0]).item()
                for _ in range(6)]
    assert out[True] == out[False], (out[True], out[False])


def test_amp_split_backward_apply_optimize_unscales():
    """The reference split API (backward() then apply_optimize()) must
    route through the wrapper's unscale — the inner optimizer's
    apply_optimize would apply the still-scaled grads raw (a 2**15x
    update that diverges on step 1 with every grad finite)."""
    from paddle_tpu.fluid.contrib.mixed_precision import decorate
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 6
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.data("x", shape=[8], dtype="float32")
        y = fluid.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, 16, act="relu")
        pred = fluid.layers.fc(h, 4, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
        opt = decorate(fluid.optimizer.SGD(0.1),
                       init_loss_scaling=2.0 ** 15, use_fp16=True)
        pg = opt.backward(loss)
        opt.apply_optimize(loss, None, pg)
    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(0)
    X = rng.rand(16, 8).astype("float32")
    Y = rng.randint(0, 4, (16, 1)).astype("int64")
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = [np.asarray(exe.run(main, feed={"x": X, "y": Y},
                                     fetch_list=[loss])[0]).item()
                  for _ in range(5)]
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses  # scaled-raw grads would blow up


def test_amp_epilogue_inert_on_forward_only_pruned_program():
    """A clone/prune that slices the scaled-loss machinery away (eval
    pruned to a forward fetch) must NOT keep running the scale
    epilogue: eval steps would silently inflate the shared training
    scale and good/bad counters."""
    main, startup, loss, opt = _amp_dyn_program(incr_every=1)
    # forward-only eval program: prune to the softmax, whose slice
    # contains no grad/scale ops
    pred_name = [op for op in main.global_block().ops
                 if op.type == "softmax"][0].output_arg_names[0]
    eval_prog = main._prune([pred_name])
    types = [op.type for op in eval_prog.global_block().ops]
    assert "elementwise_mul" not in types  # scaled-loss op sliced away
    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(0)
    X = rng.rand(16, 8).astype("float32")
    with fluid.scope_guard(scope):
        exe.run(startup)
        scale_name = opt._loss_scaling_var.name
        before = np.asarray(
            scope.find_var(scale_name).get_tensor().array).item()
        for _ in range(3):  # incr_every=1: any epilogue run would x2
            exe.run(eval_prog, feed={"x": X}, fetch_list=[pred_name])
        after = np.asarray(
            scope.find_var(scale_name).get_tensor().array).item()
    assert after == before, (before, after)


def test_amp_dynamic_state_survives_program_clone():
    """Program.clone() must carry _amp_dynamic (CompiledProgram
    build-strategy re-apply, transpiled trainer programs): the clone
    keeps the scaled-loss and unscale ops, so losing the state dict
    would silently freeze the scale and stop discarding overflowed
    steps. A CLONED full training program must halve/regrow exactly
    like the original; a backward slice that drops the scale-consuming
    ops instead deactivates the epilogue (see the forward-only test
    below)."""
    main, startup, loss, opt = _amp_dyn_program()
    cloned = main.clone()
    assert getattr(cloned, "_amp_dynamic", None) == main._amp_dynamic
    assert getattr(main._prune([loss.name]), "_amp_dynamic", None) \
        == main._amp_dynamic  # the dict rides every clone; activation
    #                           is decided per-block by who reads scale
    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(0)
    X = rng.rand(16, 8).astype("float32")
    Y = rng.randint(0, 4, (16, 1)).astype("int64")
    Xbad = X.copy()
    Xbad[0, 0] = np.inf
    scales = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for i in range(4):
            exe.run(cloned, feed={"x": Xbad if i == 2 else X, "y": Y},
                    fetch_list=[loss.name])
            scales.append(np.asarray(scope.find_var(
                opt._loss_scaling_var.name).get_tensor().array).item())
    assert scales == [8.0, 16.0, 8.0, 8.0], scales


def test_sync_batch_norm_same_as_batch_norm_single_chip():
    x = np.random.RandomState(2).rand(4, 3, 2, 2).astype(np.float32)
    args = dict(
        extra_inputs=[("Scale", np.ones(3, np.float32), None),
                      ("Bias", np.zeros(3, np.float32), None),
                      ("Mean", np.zeros(3, np.float32), None),
                      ("Variance", np.ones(3, np.float32), None)],
        attrs={"is_test": False, "epsilon": 1e-5},
        outputs=("Y",))
    (a,), _ = run_seq_op("batch_norm", x, None, x_slot="X", **args)
    (b,), _ = run_seq_op("sync_batch_norm", x, None, x_slot="X", **args)
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_collective_transpiler_grad_allreduce():
    from paddle_tpu.fluid.transpiler.collective import GradAllReduce
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        y = fluid.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    GradAllReduce().transpile(startup, main, rank=0,
                              endpoints="127.0.0.1:1,127.0.0.1:2",
                              current_endpoint="127.0.0.1:1")
    types = [op.type for op in main.global_block().ops]
    assert "c_allreduce_sum" in types
    assert "scale" in types
    assert "c_comm_init_all" in [op.type for op in
                                 startup.global_block().ops]


def test_bf16_matmul_flag_conv_training():
    """FLAGS_use_bf16_matmul must keep conv/matmul grads working (the
    mixed-dtype conv transpose has no vjp rule, so the kernel computes in
    bf16 end-to-end and casts back)."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core

    core.set_flag("FLAGS_use_bf16_matmul", True)
    try:
        main, st = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, st), fluid.unique_name.guard():
            img = fluid.data("img", shape=[3, 8, 8], dtype="float32")
            lab = fluid.data("lab", shape=[1], dtype="int64")
            c = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                    act="relu")
            p = fluid.layers.fc(c, 10, act="softmax")
            loss = fluid.layers.mean(fluid.layers.cross_entropy(p, lab))
            fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        sc = core.Scope()
        rng = np.random.RandomState(0)
        losses = []
        with fluid.scope_guard(sc):
            exe.run(st)
            for _ in range(10):
                x = rng.rand(8, 3, 8, 8).astype("float32")
                y = (x.mean((1, 2, 3)) * 10).astype("int64").reshape(-1, 1) % 10
                (lv,) = exe.run(main, feed={"img": x, "lab": y},
                                fetch_list=[loss.name])
                losses.append(float(np.asarray(lv).ravel()[0]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses
    finally:
        core.set_flag("FLAGS_use_bf16_matmul", False)
