"""Model-family smoke tests on tiny shapes (reference tier-3 strategy:
tests/book/ + test_imperative_resnet/transformer — build, train a few
steps, assert loss decreases / stays finite)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core
from paddle_tpu.models import resnet, bert


# Root-caused r20 (was the STANDING KNOWN-FAIL since PR 15): at
# lr=0.05 / momentum=0.9 on one repeated 4-sample batch the first
# ~6 steps are a ringing transient (loss overshoots to 11.9-15.6 at
# step 3) that exponentially amplifies ULP-level reduction-order
# differences — under the suite's --xla_force_host_platform_device_count=8
# the step-5 loss lands at 3.55 (> initial 2.66) where the 1-device
# run lands at 1.97 (<). Both converge to ~0 by step 7. The old
# 5-step losses[-1] < losses[0] assertion sat inside the transient;
# assert past it instead (PR 13 Adagrad-ringing precedent). Stays
# `slow` as a ~20s heavyweight per the docs/ci.md convention.
@pytest.mark.slow
def test_resnet18_tiny_trains():
    np.random.seed(0)
    main, startup, feeds, fetches = resnet.build_resnet_train_program(
        depth=18, class_dim=4, image_size=16, lr=0.05)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = core.Scope()
    rng = np.random.RandomState(0)
    feed = {"image": rng.rand(4, 3, 16, 16).astype("float32"),
            "label": rng.randint(0, 4, (4, 1)).astype("int64")}
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(12):
            lv, _ = exe.run(main, feed=feed, fetch_list=fetches)
            losses.append(float(lv[0]))
    assert np.isfinite(losses).all()
    assert min(losses[6:]) < 0.5 * losses[0]


def test_resnet50_builds():
    main, startup, feeds, fetches = resnet.build_resnet_train_program(
        depth=50, class_dim=10, image_size=32)
    types = {op.type for op in main.global_block().ops}
    assert "conv2d" in types and "batch_norm" in types
    # 53 convs in resnet50 (49 + shortcuts... just sanity-count)
    n_conv = sum(1 for op in main.global_block().ops if op.type == "conv2d")
    assert n_conv == 53


# r19 fleet-PR buyback (~15s compile-dominated convergence smoke):
# bert coverage stays per-commit via test_book_models bert feed +
# the recompute path in test_backward_executor (PR 13 precedent:
# vgg/transformer convergence twins live in the full tier).
@pytest.mark.slow
def test_bert_tiny_trains():
    cfg = dict(bert.bert_base_config())
    cfg.update(vocab_size=100, hidden=32, layers=2, heads=2, ffn=64,
               max_len=16)
    main, startup, feeds, fetches = bert.build_bert_pretrain_program(
        cfg, seq_len=16, lr=1e-3)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = core.Scope()
    rng = np.random.RandomState(0)
    B, S, M = 2, 16, 4
    feed = {
        "src_ids": rng.randint(0, 100, (B, S)).astype("int64"),
        "pos_ids": np.tile(np.arange(S), (B, 1)).astype("int64"),
        "sent_ids": np.zeros((B, S), "int64"),
        "mask_pos": rng.randint(0, B * S, (M, 1)).astype("int64"),
        "mask_label": rng.randint(0, 100, (M, 1)).astype("int64"),
    }
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(8):
            lv, = exe.run(main, feed=feed, fetch_list=fetches)
            losses.append(float(lv[0]))
    assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0]


def test_flash_attention_matches_reference():
    """Pallas/jax flash_attention vs naive softmax attention."""
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.flash_attention import (flash_attention,
                                                       _ref_attention)
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.rand(2, 3, 16, 8).astype("float32"))
    k = jnp.asarray(rng.rand(2, 3, 16, 8).astype("float32"))
    v = jnp.asarray(rng.rand(2, 3, 16, 8).astype("float32"))
    o1 = flash_attention(q, k, v, 0.35)
    o2 = _ref_attention(q, k, v, 0.35)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)
    # causal
    o3 = flash_attention(q, k, v, 0.35, True)
    o4 = _ref_attention(q, k, v, 0.35, True)
    np.testing.assert_allclose(np.asarray(o3), np.asarray(o4), atol=1e-5)


def test_fused_attention_op_grad():
    """fused_attention_qkv backward via custom vjp is finite & correct
    direction (analytic vs numeric on a tiny case)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.registry import OPS
    info = OPS.get("fused_attention_qkv")
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.rand(1, 4, 8).astype("float32"))
    k = jnp.asarray(rng.rand(1, 4, 8).astype("float32"))
    v = jnp.asarray(rng.rand(1, 4, 8).astype("float32"))

    def f(q):
        o = info.kernel({"Q": [q], "K": [k], "V": [v]},
                        {"num_heads": 2})["Out"][0]
        return jnp.sum(o)

    g = jax.grad(f)(q)
    eps = 1e-3
    q2 = q.at[0, 1, 2].add(eps)
    num = (f(q2) - f(q)) / eps
    assert abs(float(g[0, 1, 2]) - float(num)) < 1e-2


@pytest.mark.slow  # 28s: BERT-scale remat parity is full-tier; the
# per-commit remat coverage is test_backward_executor's recompute test
# (PR 13 suite-time buyback, PR 8 precedent)
def test_bert_recompute_checkpoints_engage_and_match():
    """build_bert_pretrain_program(recompute=True): per-layer remat
    engages (no fallback warning, plan present) and per-step losses
    match the plain build exactly."""
    import warnings as _w
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core
    from paddle_tpu.models import bert

    cfg = bert.bert_base_config()
    cfg.update(layers=3, hidden=64, heads=4, ffn=128)
    rng = np.random.RandomState(0)
    feed = {
        "src_ids": rng.randint(0, cfg["vocab_size"],
                               (2, 16)).astype("int64"),
        "pos_ids": np.tile(np.arange(16), (2, 1)).astype("int64"),
        "sent_ids": np.zeros((2, 16), "int64"),
        "mask_pos": rng.randint(0, 32, (4, 1)).astype("int64"),
        "mask_label": rng.randint(0, cfg["vocab_size"],
                                  (4, 1)).astype("int64"),
    }
    out = {}
    for recompute in (False, True):
        main, startup, feeds, fetches = bert.build_bert_pretrain_program(
            cfg, seq_len=16, dropout=0.0, lr=1e-3, recompute=recompute)
        main.random_seed = startup.random_seed = 3
        exe = fluid.Executor()
        scope = core.Scope()
        ctx = _w.catch_warnings()
        with ctx:
            if recompute:
                _w.simplefilter("error")  # fallback warning = failure
            with fluid.scope_guard(scope):
                exe.run(startup)
                ls = []
                for _ in range(3):
                    (l,) = exe.run(main, feed=feed, fetch_list=fetches)
                    ls.append(float(np.asarray(l).ravel()[0]))
        if recompute:
            cb = list(exe._compiled_cache.values())[-1]
            assert cb._remat_plan is not None
        out[recompute] = ls
    np.testing.assert_allclose(out[True], out[False], rtol=2e-5)
