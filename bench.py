#!/usr/bin/env python
"""Benchmark entry point — prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline config (BASELINE.md, the default): BERT-base MLM train step,
samples/sec/chip, through the full fluid front end (Program → jitted XLA
step with donation, Pallas flash attention). MFU is reported against v5e
bf16 peak. Other modes:

    python bench.py mnist       MLP smoke bench
    python bench.py resnet      ResNet-50 train step (BASELINE row 1)
    python bench.py allreduce   Fleet DP step time, transformer-big WMT
"""
import json
import os
import subprocess
import sys
import time
import traceback


def _pin_host_threads(n=8):
    """Fix BLAS/OMP pools so CPU trend rows are comparable across
    sessions (round-3 drift 5.19 -> 4.61 samples/s had no in-repo
    explanation; ambient thread-pool sizing was the suspect). MUST run
    before numpy loads OpenBLAS/MKL — the pools size themselves at
    library load. Explicit env set by the caller wins."""
    for var in ("OMP_NUM_THREADS", "OPENBLAS_NUM_THREADS",
                "MKL_NUM_THREADS", "NUMEXPR_NUM_THREADS"):
        os.environ.setdefault(var, str(n))


_pin_host_threads()

import numpy as np  # noqa: E402  (after the thread pinning, by design)

V5E_PEAK_FLOPS = 197e12  # bf16 peak per chip


PROBE_ERROR = None  # diagnostic from the last failed backend probe


def _ensure_backend(probe_timeouts=(80, 80, 150), spacing=10):
    """Bounded-time backend probe, run in a subprocess so a hung TPU
    tunnel (the sitecustomize-pinned 'axon' plugin blocks forever inside
    jax.devices()) cannot hang the bench itself. The tunnel is known to
    have transient live windows, so the probe retries `attempts` times
    with `spacing` seconds between tries before degrading. On failure,
    force the CPU backend in this process before jax initializes, so
    every bench mode still produces its JSON line; the reason is kept in
    PROBE_ERROR and emitted as `probe_error` in the JSON."""
    global PROBE_ERROR
    code = ("import jax; d = jax.devices()[0]; "
            "jax.numpy.ones(4).sum().block_until_ready(); "
            "print('PLATFORM=' + d.platform)")
    errs = []
    for i, probe_timeout in enumerate(probe_timeouts):
        if i:
            time.sleep(spacing)
        try:
            out = subprocess.run([sys.executable, "-c", code],
                                 capture_output=True, text=True,
                                 timeout=probe_timeout,
                                 env=os.environ.copy())
            for line in out.stdout.splitlines():
                if line.startswith("PLATFORM="):
                    PROBE_ERROR = None
                    return line.split("=", 1)[1]
            errs.append(f"attempt {i + 1}: rc={out.returncode} "
                        + out.stderr.strip()[-200:])
        except subprocess.TimeoutExpired:
            errs.append(f"attempt {i + 1}: probe timeout {probe_timeout}s "
                        "(tunnel hang)")
        except OSError as e:
            errs.append(f"attempt {i + 1}: {e!r}")
    PROBE_ERROR = "; ".join(errs)[:500]
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return "cpu_fallback"


LAST_COMPILE_S = None  # wall time of the last harness compile+warm call
# (first_contact banks it per stage: a SECOND invocation loading the
# persisted executable shows compile_s collapsing — the on-disk
# cache-reload proof for the fluid entrypoint, VERDICT r04 item 2)


def _timed_steps(exe, main, feed, fetch_list, steps, warmup, mesh=None):
    """Shared timing harness: `steps` optimizer steps execute as ONE
    dispatched lax.scan (exe.run n_steps) — per-dispatch host and
    TPU-tunnel overhead (~10 ms RTT measured round 4) amortizes to a
    single dispatch per window, so the clock sees device time. The
    warmup call uses the same n_steps so the scanned executable is
    compiled exactly once. Feeds are immutable here, so the device-side
    feed cache skips the per-step device_put."""
    global LAST_COMPILE_S
    from paddle_tpu.fluid import core as _core
    _core.set_flag("FLAGS_feed_device_cache", True)
    if os.environ.get("PADDLE_TPU_BENCH_LOOP"):
        # per-dispatch comparison mode (measures host+wire overhead too)
        return _timed_steps_loop(exe, main, feed, fetch_list, steps,
                                 warmup, mesh=mesh)
    del warmup  # the compile run below IS the warmup
    tc = time.perf_counter()
    exe.run(main, feed=feed, fetch_list=fetch_list, mesh=mesh,
            return_numpy=False, n_steps=steps)  # compile + warm
    LAST_COMPILE_S = round(time.perf_counter() - tc, 2)
    t0 = time.perf_counter()
    out = exe.run(main, feed=feed, fetch_list=fetch_list, mesh=mesh,
                  return_numpy=False, n_steps=steps)
    _ = float(np.asarray(out[0].array).ravel()[-1])  # sync
    return time.perf_counter() - t0


LAST_FETCHES = None  # final-step fetch values of the last timed loop


def _timed_steps_loop(exe, main, feed, fetch_list, steps, warmup,
                      mesh=None):
    """Per-step dispatch variant for MULTI-PROCESS benches whose sync
    plane barriers every step (the PS plane lock-steps subprocess
    trainers by run count — a scanned window would change trainer 0's
    barrier count and deadlock the plane)."""
    global LAST_COMPILE_S, LAST_FETCHES
    from paddle_tpu.fluid import core as _core
    _core.set_flag("FLAGS_feed_device_cache", True)
    for i in range(warmup):
        tc = time.perf_counter()
        exe.run(main, feed=feed, fetch_list=fetch_list, mesh=mesh,
                return_numpy=False)
        if i == 0:  # first warmup call is the compile
            LAST_COMPILE_S = round(time.perf_counter() - tc, 2)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = exe.run(main, feed=feed, fetch_list=fetch_list, mesh=mesh,
                      return_numpy=False)
    _ = float(np.asarray(out[0].array).ravel()[0])  # sync
    LAST_FETCHES = out
    return time.perf_counter() - t0


def bench_mnist_mlp(batch=256, steps=60, warmup=10):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data("img", shape=[784], dtype="float32")
        label = fluid.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(img, 1024, act="relu")
        h = fluid.layers.fc(h, 1024, act="relu")
        pred = fluid.layers.fc(h, 10, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.Momentum(0.01, momentum=0.9).minimize(loss)
    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(0)
    X = rng.rand(batch, 784).astype("float32")
    Y = rng.randint(0, 10, (batch, 1)).astype("int64")
    with fluid.scope_guard(scope):
        exe.run(startup)
        dt = _timed_steps(exe, main, {"img": X, "label": Y}, [loss],
                          steps, warmup)
    return {"metric": "mnist_mlp_samples_per_sec",
            "value": round(batch * steps / dt, 1), "unit": "samples/s",
            "vs_baseline": 1.0}


def _realdata_pair(build_fn, batches, k, warmup=2):
    """Real-data step windows (ISSUE 2): time one full pass over
    ``batches`` (all DISTINCT) two ways —

      loop           one exe.run dispatch per batch (per-step host,
                     dispatch and upload costs paid N times)
      scan_realdata  DataLoader.window(k) stacks K batches + device-
                     prefetches the next window while this one computes;
                     exe.run(n_steps=k) scans the K slices in ONE
                     dispatch per window

    Both lanes pull from the same loader protocol and run the same
    batch sequence from a fresh program/scope. Returns a dict with both
    throughput numbers plus a window-of-K vs K-sequential-steps loss
    parity check (fresh programs, same seed — the contract the fast
    tier enforces in tests/test_window_executor.py)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core as _core
    from paddle_tpu.fluid.reader import DataLoader

    n = len(batches)

    def loader_of():
        dl = DataLoader.from_generator(capacity=4)
        dl.set_batch_generator(lambda: iter(batches))
        return dl

    # ---- loop lane: one dispatch per distinct batch
    main, startup, fetch_list = build_fn()
    exe = fluid.Executor()
    scope = _core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        # warm through the SAME production path the timed loop uses:
        # the first call compiles against uncommitted startup state, the
        # second against the committed step outputs — both signatures
        # must be warm or a recompile lands inside the clock
        for b, _ in zip(loader_of(), range(max(1, warmup))):
            exe.run(main, feed=b, fetch_list=fetch_list,
                    return_numpy=False)
        t0 = time.perf_counter()
        for b in loader_of():
            out = exe.run(main, feed=b, fetch_list=fetch_list,
                          return_numpy=False)
        _ = float(np.asarray(out[0].array).ravel()[-1])  # sync
        loop_dt = time.perf_counter() - t0
    loop_mode = exe._last_run_mode

    # ---- scan lane: one dispatch per K-batch window
    main, startup, fetch_list = build_fn()
    exe = fluid.Executor()
    scope = _core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for w, _ in zip(loader_of().window(k, drop_last=True),
                        range(max(1, warmup))):
            exe.run(main, feed=w, fetch_list=fetch_list,
                    return_numpy=False, n_steps=k)
        t0 = time.perf_counter()
        for w in loader_of().window(k, drop_last=True):
            out = exe.run(main, feed=w, fetch_list=fetch_list,
                          return_numpy=False, n_steps=k)
        _ = float(np.asarray(out[0].array).ravel()[-1])  # sync
        scan_dt = time.perf_counter() - t0
    scan_mode = exe._last_run_mode
    wfeed = {name: np.stack([np.asarray(b[name]) for b in batches[:k]])
             for name in batches[0]}

    # ---- parity: window-of-K losses == K sequential steps
    def first_losses(windowed):
        main, startup, fetch_list = build_fn()
        exe = fluid.Executor()
        scope = _core.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            if windowed:
                (l,) = exe.run(main, feed=wfeed,
                               fetch_list=fetch_list[:1], n_steps=k)
                return np.asarray(l).ravel()
            return np.asarray([
                float(np.asarray(exe.run(main, feed=b,
                                         fetch_list=fetch_list[:1])[0]
                                 ).ravel()[0])
                for b in batches[:k]])

    diff = float(np.max(np.abs(first_losses(True) - first_losses(False))))
    return {"loop_dt": loop_dt, "scan_dt": scan_dt,
            "loop_steps": n, "scan_steps": (n // k) * k,
            "loop_mode": loop_mode, "scan_mode": scan_mode,
            "parity_max_diff": diff, "parity_ok": diff < 1e-4}


def bench_mnist_realdata(batch=64, hidden=256, n_batches=64, k=8):
    """MNIST-shaped MLP trained on DISTINCT batches: the honest
    training-loop number (the headline mnist lane reuses ONE batch, so
    its scan window measures dispatch amortization with an asterisk).
    Model is sized so per-step compute doesn't drown the per-dispatch
    overhead this lane exists to measure."""
    import paddle_tpu.fluid as fluid

    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 5
        with fluid.program_guard(main, startup):
            img = fluid.data("img", shape=[784], dtype="float32")
            label = fluid.data("label", shape=[1], dtype="int64")
            h = fluid.layers.fc(img, hidden, act="relu")
            h = fluid.layers.fc(h, hidden, act="relu")
            pred = fluid.layers.fc(h, 10, act="softmax")
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(pred, label))
            fluid.optimizer.Momentum(0.01, momentum=0.9).minimize(loss)
        return main, startup, [loss]

    rng = np.random.RandomState(0)
    batches = [{"img": rng.rand(batch, 784).astype("float32"),
                "label": rng.randint(0, 10, (batch, 1)).astype("int64")}
               for _ in range(n_batches)]
    r = _realdata_pair(build, batches, k)
    return {"metric": "mnist_mlp_realdata_samples_per_sec",
            "value": round(batch * r["scan_steps"] / r["scan_dt"], 1),
            "unit": "samples/s", "vs_baseline": 1.0,
            "mode": "scan_realdata", "window": k, "batch": batch,
            "hidden": hidden, "distinct_batches": n_batches,
            "loop_samples_per_sec":
                round(batch * r["loop_steps"] / r["loop_dt"], 1),
            "speedup_vs_loop":
                round((batch * r["scan_steps"] / r["scan_dt"])
                      / (batch * r["loop_steps"] / r["loop_dt"]), 3),
            "executor_mode": r["scan_mode"],
            "parity_ok": r["parity_ok"],
            "parity_max_diff": r["parity_max_diff"]}


def bench_mnist_realdata_guard(batch=64, hidden=256, n_batches=64, k=8,
                               repeats=3):
    """Paired guard-off vs guard-on lanes for the windowed
    mnist_realdata shape (ISSUE 5 acceptance: fused-guard overhead ≤ 2%
    with action=skip). Both lanes run the IDENTICAL scan window path
    (DataLoader.window(k) → one dispatch per window); the guard-on lane
    sets FLAGS_check_nan_inf=1, FLAGS_nan_inf_action=skip — the per-step
    health reduction + bad-step select fused into the scan. Best-of-
    ``repeats`` per lane (this 1-core box jitters ±10-15%); a first-
    window loss parity check confirms the guard changes nothing on
    clean data."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core as _core
    from paddle_tpu.fluid.reader import DataLoader

    if n_batches < k:
        raise ValueError(
            f"mnist_guard needs n_batches >= window k "
            f"({n_batches} < {k}): drop_last windows would yield "
            f"nothing to time")

    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 5
        with fluid.program_guard(main, startup):
            img = fluid.data("img", shape=[784], dtype="float32")
            label = fluid.data("label", shape=[1], dtype="int64")
            h = fluid.layers.fc(img, hidden, act="relu")
            h = fluid.layers.fc(h, hidden, act="relu")
            pred = fluid.layers.fc(h, 10, act="softmax")
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(pred, label))
            fluid.optimizer.Momentum(0.01, momentum=0.9).minimize(loss)
        return main, startup, [loss]

    rng = np.random.RandomState(0)
    batches = [{"img": rng.rand(batch, 784).astype("float32"),
                "label": rng.randint(0, 10, (batch, 1)).astype("int64")}
               for _ in range(n_batches)]

    def loader_of():
        dl = DataLoader.from_generator(capacity=4)
        dl.set_batch_generator(lambda: iter(batches))
        return dl

    def scan_pass():
        """One timed full pass over the windowed loader (fresh program/
        scope; both warmup signatures warmed). Returns (dt, first-window
        losses)."""
        main, startup, fetch_list = build()
        exe = fluid.Executor()
        scope = _core.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            for w, _ in zip(loader_of().window(k, drop_last=True),
                            range(2)):
                first = exe.run(main, feed=w, fetch_list=fetch_list,
                                return_numpy=False, n_steps=k)
            first_losses = np.asarray(first[0].array).ravel().copy()
            t0 = time.perf_counter()
            for w in loader_of().window(k, drop_last=True):
                out = exe.run(main, feed=w, fetch_list=fetch_list,
                              return_numpy=False, n_steps=k)
            _ = float(np.asarray(out[0].array).ravel()[-1])  # sync
            return time.perf_counter() - t0, first_losses

    def lane():
        best_dt, losses = min((scan_pass() for _ in range(repeats)),
                              key=lambda r: r[0])
        return best_dt, losses

    saved = (_core.globals_["FLAGS_check_nan_inf"],
             _core.globals_["FLAGS_nan_inf_action"])
    try:
        _core.set_flag("FLAGS_check_nan_inf", False)
        off_dt, off_losses = lane()
        _core.set_flag("FLAGS_check_nan_inf", True)
        _core.set_flag("FLAGS_nan_inf_action", "skip")
        on_dt, on_losses = lane()
    finally:
        _core.set_flag("FLAGS_check_nan_inf", saved[0])
        _core.set_flag("FLAGS_nan_inf_action", saved[1])
    steps = (n_batches // k) * k
    off_sps = batch * steps / off_dt
    on_sps = batch * steps / on_dt
    return {"metric": "mnist_realdata_guard_samples_per_sec",
            "value": round(on_sps, 1), "unit": "samples/s",
            "vs_baseline": 1.0, "mode": "scan_realdata", "window": k,
            "batch": batch, "hidden": hidden,
            "guard": "skip", "guard_off_samples_per_sec": round(off_sps, 1),
            "guard_overhead_pct": round((off_sps / on_sps - 1.0) * 100, 2),
            "best_of": repeats,
            "parity_ok": bool(np.array_equal(off_losses, on_losses))}


def bench_wide_deep_realdata(batch=256, n_batches=32, k=8):
    """Wide&Deep CTR on distinct batches. ``with_auc=False`` keeps the
    block fully compiled so the window collapses to one dispatch (the
    with-AUC block is segmented — its islands force the documented
    per-step fallback, which the headline wide_deep lane already
    times)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import wide_deep

    def build():
        main, startup, feeds, loss, _ = wide_deep.build_wide_deep_program(
            num_dense=13, num_slots=26, sparse_dim=int(1e5),
            embedding_dim=16, hidden=(64, 64), lr=1e-3, with_auc=False)
        main.random_seed = startup.random_seed = 5
        return main, startup, [loss]

    nb = wide_deep.ctr_reader(batch, num_dense=13, num_slots=26,
                              sparse_dim=int(1e5), seed=0)
    batches = [nb() for _ in range(n_batches)]
    r = _realdata_pair(build, batches, k)
    return {"metric": "wide_deep_realdata_samples_per_sec",
            "value": round(batch * r["scan_steps"] / r["scan_dt"], 1),
            "unit": "samples/s", "vs_baseline": 1.0,
            "mode": "scan_realdata", "window": k, "batch": batch,
            "distinct_batches": n_batches, "with_auc": False,
            "loop_samples_per_sec":
                round(batch * r["loop_steps"] / r["loop_dt"], 1),
            "speedup_vs_loop":
                round((batch * r["scan_steps"] / r["scan_dt"])
                      / (batch * r["loop_steps"] / r["loop_dt"]), 3),
            "executor_mode": r["scan_mode"],
            "parity_ok": r["parity_ok"],
            "parity_max_diff": r["parity_max_diff"]}


def _is_oom(e) -> bool:
    s = repr(e)
    return "RESOURCE_EXHAUSTED" in s or "Out of memory" in s \
        or "out of memory" in s


def _run_with_oom_ladder(name, batches, run_once):
    """First contact must land a number, not an OOM: try each batch in
    ``batches`` (descending); ``run_once(b) -> dt`` raises on OOM.
    Returns (chosen_batch, dt)."""
    last_err = None
    for i, b in enumerate(batches):
        if b < 1:
            break
        try:
            return b, run_once(b)
        except Exception as e:  # noqa: BLE001 — OOM shapes vary by backend
            if not _is_oom(e):
                raise
            last_err = e
            if i + 1 < len(batches):
                print(f"{name}: batch {b} OOM, retrying at "
                      f"{batches[i + 1]}", file=sys.stderr)
    raise last_err


def bench_bert_base(batch=256, seq_len=128, steps=20, warmup=5):
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core
    from paddle_tpu.models import bert

    core.set_flag("FLAGS_use_bf16_matmul", True)  # MXU-native math
    cfg = bert.bert_base_config()
    smoke = jax.devices()[0].platform == "cpu"
    if smoke:  # CPU fallback: prove the path, not the number
        cfg.update(layers=2, hidden=256, heads=4, ffn=1024)
        batch, seq_len, steps, warmup = 8, 64, 3, 1
    # in-window iteration knobs (first_contact's bert_b512 stage, manual
    # MFU ladder work): override the measured config without edits —
    # the OOM ladder still walks DOWN from the override. Ignored in CPU
    # smoke (a tunnel dying between stages must not produce a batch-512
    # row over the shrunken smoke config)
    if not smoke:
        batch = int(os.environ.get("PADDLE_TPU_BENCH_BATCH", batch))
        seq_len = int(os.environ.get("PADDLE_TPU_BENCH_SEQ", seq_len))
    # PADDLE_TPU_BENCH_RECOMPUTE=1: per-layer activation remat — if the
    # default batch OOMs, this usually buys it back for ~1/3 extra FLOPs
    # (often a better MFU trade than halving the batch)
    recompute = os.environ.get("PADDLE_TPU_BENCH_RECOMPUTE") == "1"
    main, startup, feeds, fetches = bert.build_bert_pretrain_program(
        cfg, seq_len=seq_len, dropout=0.0, lr=1e-4, recompute=recompute)
    rng = np.random.RandomState(0)

    def feed_of(b):
        n_mask = max(1, int(b * seq_len * 0.15))
        return {
            "src_ids": rng.randint(0, cfg["vocab_size"],
                                   (b, seq_len)).astype("int64"),
            "pos_ids": np.tile(np.arange(seq_len), (b, 1)).astype("int64"),
            "sent_ids": np.zeros((b, seq_len), "int64"),
            "mask_pos": rng.randint(0, b * seq_len,
                                    (n_mask, 1)).astype("int64"),
            "mask_label": rng.randint(0, cfg["vocab_size"],
                                      (n_mask, 1)).astype("int64"),
        }

    def run_once(b):
        exe = fluid.Executor()
        scope = core.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            return _timed_steps(exe, main, feed_of(b), fetches, steps,
                                warmup)

    batch, dt = _run_with_oom_ladder(
        "bert", (batch, batch // 2, batch // 4, batch // 8), run_once)
    sps = batch * steps / dt
    # 6·N·tokens FLOPs estimate (fwd+bwd), N = transformer params (no embed)
    h, L, f = cfg["hidden"], cfg["layers"], cfg["ffn"]
    n_params = L * (4 * h * h + 2 * h * f)
    flops_per_sample = 6 * n_params * seq_len \
        + 12 * L * seq_len * seq_len * h  # attention scores fwd+bwd
    mfu = sps * flops_per_sample / V5E_PEAK_FLOPS
    out = {"metric": "bert_base_samples_per_sec_per_chip",
           "value": round(sps, 2), "unit": "samples/s",
           "vs_baseline": 1.0, "mfu_vs_v5e_bf16_peak": round(mfu, 4),
           "batch": batch, "seq_len": seq_len}
    if smoke:
        out["cpu_smoke"] = True
    return out


def bench_resnet50(batch=64, image_size=224, steps=10, warmup=3):
    """ResNet-50 ImageNet train step (BASELINE.md row 1)."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core
    from paddle_tpu.models.resnet import build_resnet_train_program

    if jax.devices()[0].platform == "cpu":  # CPU smoke: keep tractable
        batch, image_size, steps = 8, 64, 3
    core.set_flag("FLAGS_use_bf16_matmul", True)  # MXU-native convs
    main, startup, feeds, fetches = build_resnet_train_program(
        depth=50, class_dim=1000, image_size=image_size)
    loss = fetches[0]
    rng = np.random.RandomState(0)

    def run_once(b):
        exe = fluid.Executor()
        scope = core.Scope()
        img = rng.rand(b, 3, image_size, image_size).astype("float32")
        lbl = rng.randint(0, 1000, (b, 1)).astype("int64")
        with fluid.scope_guard(scope):
            exe.run(startup)
            return _timed_steps(exe, main, {"image": img, "label": lbl},
                                [loss], steps, warmup)

    batch, dt = _run_with_oom_ladder(
        "resnet", (batch, batch // 2, batch // 4), run_once)
    sps = batch * steps / dt
    # ~3.8 GFLOPs fwd per 224x224 sample (scales ~quadratically with
    # resolution); x3 for fwd+bwd
    flops_fwd = 3.8e9 * (image_size / 224.0) ** 2
    mfu = sps * flops_fwd * 3 / V5E_PEAK_FLOPS
    return {"metric": "resnet50_samples_per_sec_per_chip",
            "value": round(sps, 2), "unit": "samples/s",
            "vs_baseline": 1.0, "mfu_vs_v5e_bf16_peak": round(mfu, 4),
            "batch": batch}


def bench_allreduce_dp(steps=10, warmup=3):
    """Fleet-collective data-parallel step time over the available mesh
    (BASELINE.md: allreduce step-time, Transformer-big WMT config scaled
    to fit). XLA inserts the grad all-reduce over ICI inside the one
    jitted step; this measures the whole DP step including it."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core
    from paddle_tpu.parallel.mesh import build_mesh
    from paddle_tpu.models.transformer import (build_wmt_train_program,
                                               transformer_big_config)

    n_dev = len(jax.devices())
    on_tpu = jax.devices()[0].platform not in ("cpu",)
    cfg = transformer_big_config()
    cfg.update(src_vocab=4096, trg_vocab=4096, enc_layers=2, dec_layers=2,
               dropout=0.0)
    if not on_tpu:  # CPU smoke: shrink to keep compile+run tractable
        cfg.update(d_model=128, d_inner=256, heads=4)
    B, S = (8 if on_tpu else 2) * max(1, n_dev), 64 if on_tpu else 16
    main, startup, feeds, loss = build_wmt_train_program(
        cfg, src_len=S, trg_len=S, lr=1e-4)
    mesh = build_mesh(n_dev) if n_dev > 1 else None
    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(0)
    sv, tv = cfg["src_vocab"], cfg["trg_vocab"]
    feed = {
        "src_ids": rng.randint(0, sv, (B, S)).astype("int64"),
        "src_mask": np.ones((B, S), "float32"),
        "trg_ids": rng.randint(0, tv, (B, S)).astype("int64"),
        "trg_mask": np.ones((B, S), "float32"),
        "labels": rng.randint(0, tv, (B, S, 1)).astype("int64"),
    }
    with fluid.scope_guard(scope):
        exe.run(startup)
        dt = _timed_steps(exe, main, feed, [loss], steps, warmup,
                          mesh=mesh)
    return {"metric": "fleet_dp_step_ms_transformer_big",
            "value": round(dt / steps * 1e3, 2), "unit": "ms/step",
            "vs_baseline": 1.0, "devices": n_dev, "batch": B}


def bench_wide_deep(batch=4096, steps=20, warmup=5):
    """Wide&Deep CTR train step, samples/sec (BASELINE.md sparse-scale row
    scaled to one chip: dense embeddings + MLP compile into the jitted
    step; the beyond-HBM table path is exercised by the PS tests).

    The AUC metric op stays IN the train program: the segmented executor
    compiles fwd+bwd+update as jitted segments around the stateful auc
    island, instead of de-compiling the whole block (the pre-r6
    interpreter cliff). The row carries compiled_metric: true when that
    path actually served the run."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core
    from paddle_tpu.models import wide_deep

    on_tpu = jax.devices()[0].platform not in ("cpu",)
    if not on_tpu:
        batch, steps = 256, 5
    main, startup, feeds, loss, auc = wide_deep.build_wide_deep_program(
        num_dense=13, num_slots=26, sparse_dim=int(1e6), embedding_dim=16,
        hidden=(400, 400, 400), lr=1e-3)
    exe = fluid.Executor()
    scope = core.Scope()
    nb = wide_deep.ctr_reader(batch, num_dense=13, num_slots=26,
                              sparse_dim=int(1e6), seed=0)
    feed = nb()
    with fluid.scope_guard(scope):
        exe.run(startup)
        # per-step dispatch: the segmented step runs its islands host-side
        # each step, so the scanned window doesn't apply
        dt = _timed_steps_loop(exe, main, feed, [loss, auc], steps, warmup)
        # streaming AUC after the timed window's final step (no extra
        # training step just to read the metric)
        auc_val = float(np.asarray(LAST_FETCHES[1].array).ravel()[0])
    return {"metric": "wide_deep_ctr_samples_per_sec_per_chip",
            "value": round(batch * steps / dt, 1), "unit": "samples/s",
            "vs_baseline": 1.0, "batch": batch,
            "embedding_params": int(26 * 1e6 * 16 + 26 * 1e6),
            "compiled_metric": exe._last_run_mode == "segmented",
            "executor_mode": exe._last_run_mode,
            "auc": round(auc_val, 4)}


def bench_wide_deep_1b(batch=512, steps=10, warmup=2, n_pservers=2,
                       sparse_dim=int(2.5e6), n_trainers=2,
                       async_staleness=0, window_k=1, metric=None):
    """Wide&Deep CTR with ≥1e9 embedding parameters over the distributed
    PS plane (BASELINE.md sparse-scale row): 26 deep [2.5M, 16] + 26 wide
    [2.5M, 1] per-slot tables, row-sharded across pserver subprocesses as
    init-on-touch lazy tables (fleet_wrapper.h DownpourSparseTable role).
    ``n_trainers`` data-parallel trainers train in lock step through the
    sync plane (trainer 0 in-process, the rest as subprocesses); the row
    reports the SUMMED samples/sec. Includes the RPC pulls.

    Paired data-plane lanes (docs/PS_DATA_PLANE.md): the default lane
    rides the overhauled plane (binary framing, channel pool, parallel
    shard fan-out, lookup dedup); PADDLE_TPU_PS_PICKLE_WIRE=1 restores
    the full LEGACY plane for every client (subprocess trainers inherit
    the env). Same model, same feeds, and every legacy-gated difference
    is numerics-exact, so the two rows' final losses must agree
    bit-for-bit (the recorded parity flag).

    Async-overlap lanes (docs/PS_DATA_PLANE.md "Async overlap"):
    ``async_staleness=k`` pipelines every trainer's comm tail behind
    its next step (FLAGS_async_staleness rides into the subprocess
    trainers via env) and ``window_k`` feeds [K, ...] stacks so the
    window fallback stages sparse prefetch for slice i+1 while slice i
    computes. The async row additionally records overlap EVIDENCE from
    a short profiled epilogue — cat="comm" span seconds concurrent
    with cat="segment" step spans — plus the trainer-side prefetch hit
    rate and the pservers' prefetch-tagged pull counters, because on
    this 1-core box the summed samples/s is scheduler-bound, not
    wire-bound (the PR 4 lesson)."""
    import socket
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    os.environ["FLAGS_lazy_sparse_table_threshold"] = "1000000"
    os.environ["FLAGS_async_staleness"] = str(int(async_staleness))
    wire = ("pickle" if os.environ.get("PADDLE_TPU_PS_PICKLE_WIRE") == "1"
            else "binary")
    from tools import wide_deep_ps_worker as W

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    eps = ",".join(f"127.0.0.1:{free_port()}" for _ in range(n_pservers))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.abspath(__file__)))
    # CPU-pinned workers must not pay the axon register() startup stall
    # (~100s per process with a half-open tunnel)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    workers = []
    trainer_procs = []
    try:
        import tempfile
        logfiles = []
        for i in range(n_pservers):
            # log to a FILE, not a pipe: an undrained pipe would block a
            # chatty pserver once the 64KB buffer fills mid-bench
            lf = tempfile.NamedTemporaryFile("wb+", prefix=f"ps{i}_",
                                             suffix=".log", delete=False)
            logfiles.append(lf)
            workers.append(subprocess.Popen(
                [sys.executable, "-m", "tools.wide_deep_ps_worker",
                 "pserver", eps, str(i), str(sparse_dim),
                 str(n_trainers)],
                env=env, stdout=lf, stderr=subprocess.STDOUT))
        deadline = time.time() + 180
        for w, lf in zip(workers, logfiles):
            while True:
                lf.flush()
                if b"PSERVER_READY" in open(lf.name, "rb").read():
                    break
                if w.poll() is not None:
                    raise RuntimeError(
                        f"pserver exited rc={w.returncode}: "
                        + open(lf.name, "rb").read()[-1500:].decode(
                            errors="replace"))
                if time.time() > deadline:
                    raise TimeoutError("pserver never became ready: "
                                       + lf.name)
                time.sleep(0.3)

        # trainers 1..N-1 as subprocesses, lock-stepped with trainer 0
        # through the sync barriers (same warmup+steps count)
        trainer_outs, trainer_logs = [], []
        for tid in range(1, n_trainers):
            tf = tempfile.NamedTemporaryFile("r", prefix=f"tr{tid}_",
                                             suffix=".json", delete=False)
            trainer_outs.append(tf.name)
            tl = tempfile.NamedTemporaryFile("wb+", prefix=f"tr{tid}_",
                                             suffix=".log", delete=False)
            trainer_logs.append(tl)
            trainer_procs.append(subprocess.Popen(
                [sys.executable, "-m", "tools.wide_deep_ps_worker",
                 "trainer", eps, str(tid), str(n_trainers),
                 str(sparse_dim), str(batch), str(steps), str(warmup),
                 tf.name, str(window_k)],
                env=env, stdout=tl, stderr=subprocess.STDOUT))
        # startup grace: a trainer that dies before its first barrier
        # would hang trainer 0 in the sync plane (the pserver-side
        # dead-trainer barrier check needs one heartbeat first)
        time.sleep(2.0)
        for p, tl in zip(trainer_procs, trainer_logs):
            if p.poll() is not None:
                raise RuntimeError(
                    f"trainer subprocess died rc={p.returncode}: "
                    + open(tl.name, "rb").read()[-1500:].decode(
                        errors="replace"))

        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import async_overlap, core, profiler
        from paddle_tpu.fluid.communicator import drain_async_rounds
        from paddle_tpu.models import wide_deep
        core.set_flag("FLAGS_async_staleness", int(async_staleness))
        main_p, startup, feeds, loss, auc = W.build(sparse_dim)
        t = W.transpile(main_p, startup, eps, trainer_id=0,
                        trainers=n_trainers)
        prog = t.get_trainer_program()
        exe = fluid.Executor()
        scope = core.Scope()
        nb = wide_deep.ctr_reader(batch, num_dense=13, num_slots=26,
                                  sparse_dim=sparse_dim, seed=0)
        evidence = {}
        from paddle_tpu.fluid.ps_rpc import WorkerHeartBeat
        beat = WorkerHeartBeat(eps.split(","), 0, interval=1.0).start()
        try:
            with fluid.scope_guard(scope):
                exe.run(startup)
                if window_k <= 1:
                    feed = nb()
                    dt = _timed_steps_loop(exe, prog, feed, [loss],
                                           steps, warmup)
                else:
                    # [K, ...] stacks of K DISTINCT batches — the
                    # window-fallback shape that staggers sparse
                    # prefetch across the slices
                    assert steps % window_k == 0 \
                        and warmup % window_k == 0
                    batches = [nb() for _ in range(window_k)]
                    feed = {n: np.stack([b[n] for b in batches])
                            for n in batches[0]}
                    global LAST_FETCHES
                    n_warm = warmup // window_k
                    for w in range(n_warm):
                        if w == n_warm - 1:
                            # evidence window: profile the LAST WARMUP
                            # window (it runs the identical production
                            # path) so the timed loop below stays free
                            # of profiling overhead — cat="comm" spans
                            # from the round pipeline / prefetch
                            # threads concurrent with cat="segment"
                            # step spans prove the wire ran behind the
                            # step
                            profiler.start_profiler("CPU")
                        out = exe.run(prog, feed=feed,
                                      fetch_list=[loss],
                                      n_steps=window_k,
                                      return_numpy=False)
                    ev = profiler.snapshot_events()
                    profiler.stop_profiler(profile_path="")
                    t0 = time.perf_counter()
                    for _ in range(steps // window_k):
                        out = exe.run(prog, feed=feed,
                                      fetch_list=[loss],
                                      n_steps=window_k,
                                      return_numpy=False)
                    # in-flight rounds are part of the measured work
                    drain_async_rounds()
                    dt = time.perf_counter() - t0
                    comm_s = sum(e["end"] - e["start"] for e in ev
                                 if e["cat"] == "comm")
                    overlap_s = profiler.concurrent_seconds(
                        "comm", "segment", events=ev)
                    evidence = {
                        "comm_span_s": round(comm_s, 4),
                        "comm_overlap_s": round(overlap_s, 4),
                        "comm_overlap_frac": round(
                            overlap_s / comm_s, 4) if comm_s else 0.0,
                    }
                    plane = async_overlap.active_plane()
                    if plane is not None:
                        s = plane.stats()
                        evidence["prefetch_hit_rate"] = round(
                            s["hit_rate"], 4)
                        evidence["prefetch_stages"] = s["stages"]
                    LAST_FETCHES = out
        finally:
            beat.stop()
        total_sps = batch * steps / dt
        for p, out_path, tl in zip(trainer_procs, trainer_outs,
                                   trainer_logs):
            p.wait(timeout=120)
            if p.returncode != 0:
                raise RuntimeError(
                    f"trainer subprocess rc={p.returncode}: "
                    + open(tl.name, "rb").read()[-1500:].decode(
                        errors="replace"))
            total_sps += json.load(open(out_path))["samples_per_sec"]
        emb_params = 26 * sparse_dim * 16 + 26 * sparse_dim
        final_loss = float(np.asarray(LAST_FETCHES[0].array).ravel()[-1])
        if int(async_staleness) > 0:
            # server-side view of the prefetch traffic (stats RPC)
            try:
                from paddle_tpu.fluid.ps_rpc import VarClient
                pf = [VarClient.of(ep).call("stats").get("prefetch", {})
                      for ep in eps.split(",")]
                evidence["server_prefetch_calls"] = sum(
                    int(p.get("calls", 0)) for p in pf)
                evidence["server_prefetch_rows"] = sum(
                    int(p.get("rows", 0)) for p in pf)
            except Exception:
                pass
        # capacity-tier gauges (docs/PS_DATA_PLANE.md "Capacity tier"):
        # when the pservers run a spill tier, record the aggregated
        # slab stats as the lane's evidence surface before teardown
        try:
            from paddle_tpu.fluid import slab_spill
            from paddle_tpu.fluid.ps_rpc import VarClient
            slabs = [VarClient.of(ep).call("stats").get("slab") or {}
                     for ep in eps.split(",")]
            agg = slab_spill.merge_tier_stats(slabs)
            if agg:
                evidence["slab"] = {
                    k: agg.get(k, 0) for k in (
                        "resident_rows", "spilled_rows",
                        "resident_bytes", "spilled_bytes", "hit_rate",
                        "density_x", "promoted_rows",
                        "clean_evictions", "store_reads")}
        except Exception:
            pass
        return {"metric": metric or "wide_deep_1b_ps_samples_per_sec",
                "value": round(total_sps, 1), "unit": "samples/s",
                "vs_baseline": 1.0, "batch": batch,
                "embedding_params": int(emb_params),
                "pservers": n_pservers, "trainers": n_trainers,
                # wire lane + trainer-0 final loss: the paired
                # binary-vs-pickle rows must agree on this bit-for-bit
                # (framing must never change the numerics; the
                # staleness>0 lane is NOT bit-comparable — bounded-
                # staleness reads are the point)
                "wire": wire, "final_loss": final_loss,
                "async_staleness": int(async_staleness),
                "window_k": int(window_k),
                **evidence,
                # the AUC op rides in-graph: fwd+bwd+update run as
                # compiled jitted segments around the stateful islands
                # (auc + RPC ops) instead of the whole-block interpreter
                "compiled_metric": exe._last_run_mode == "segmented",
                "executor_mode": exe._last_run_mode}
    finally:
        try:
            from paddle_tpu.fluid.ps_rpc import VarClient
            for ep in eps.split(","):
                VarClient.of(ep).stop()
        except Exception:
            pass
        for w in workers + trainer_procs:
            if w.poll() is None:
                w.terminate()
            try:
                w.wait(timeout=10)
            except Exception:
                w.kill()
        # never leak the overlap plane into a later lane of the same
        # bench invocation
        os.environ.pop("FLAGS_async_staleness", None)
        try:
            from paddle_tpu.fluid import async_overlap as _ao
            from paddle_tpu.fluid import communicator as _comm
            from paddle_tpu.fluid import core as _core
            _core.set_flag("FLAGS_async_staleness", 0)
            _ao.reset_plane()
            _comm.reset_round_pipeline()
        except Exception:
            pass


def bench_wide_deep_1b_syncw(batch=512, steps=16, warmup=16,
                             n_pservers=2, sparse_dim=int(2.5e6),
                             n_trainers=2):
    """Windowed SYNC baseline of the async-overlap pair: same [K=8]
    window stacks, same cluster shape, FLAGS_async_staleness=0 (the
    plain send/barrier/recv/fetch tail). Pairs with wide_deep_1b_async
    and wide_deep_1b_ceiling (docs/PS_DATA_PLANE.md "Async overlap")."""
    return bench_wide_deep_1b(
        batch=batch, steps=steps, warmup=warmup, n_pservers=n_pservers,
        sparse_dim=sparse_dim, n_trainers=n_trainers, async_staleness=0,
        window_k=8, metric="wide_deep_1b_ps_syncw_samples_per_sec")


def bench_wide_deep_1b_async(batch=512, steps=16, warmup=16,
                             n_pservers=2, sparse_dim=int(2.5e6),
                             n_trainers=2, staleness=2):
    """Async-overlap lane: FLAGS_async_staleness=2 pipelines every
    trainer's round (push/barrier/pull) behind its next step and the
    window fallback prefetches slice i+1's embedding rows while slice
    i computes. Row carries overlap evidence (comm∩segment span
    seconds from the profiled last window, prefetch hit rate, server
    prefetch counters) because summed samples/s on the 1-core box is
    scheduler-bound (docs/PS_DATA_PLANE.md "Async overlap")."""
    return bench_wide_deep_1b(
        batch=batch, steps=steps, warmup=warmup, n_pservers=n_pservers,
        sparse_dim=sparse_dim, n_trainers=n_trainers,
        async_staleness=staleness, window_k=8,
        metric="wide_deep_1b_ps_async_samples_per_sec")


def bench_wide_deep_geo(batch=256, steps=64, warmup=8, n_pservers=2,
                        sparse_dim=20000, n_trainers=2):
    """Compressed geo WAN lane (docs/PS_DATA_PLANE.md "Compression"):
    the same wide_deep cluster as wide_deep_1b but geo-SGD transpiled
    (local optimizer + delta pushes every 8 steps), under an emulated
    WAN — 50ms injected server-side delay with 10ms jitter on every
    data RPC — with the whole compression stack on: geo deltas ride
    the async RoundPipeline (staleness 2), DGC top-k sparsifies them
    (error feedback in @GEO_OLD), and the wire runs int8 quantized
    frames. Non-lazy tables (geo keeps the optimizer local), so
    sparse_dim stays small. Pairs with wide_deep_geo_sync: plain sync
    mode under the SAME delay — the ratio is the WAN-survivability
    claim. The row carries the dgc/quant compression ratios from the
    in-process trainer."""
    from paddle_tpu.fluid import communicator as _comm
    from paddle_tpu.fluid import ps_rpc as _ps_rpc
    saved = {k: os.environ.get(k) for k in
             ("PADDLE_TPU_PS_RPC_DELAY_MS",
              "PADDLE_TPU_PS_RPC_DELAY_JITTER_MS", "PADDLE_TPU_WD_GEO",
              "FLAGS_dgc", "FLAGS_ps_wire_quant",
              "FLAGS_lazy_sparse_table_threshold")}
    os.environ.update({
        "PADDLE_TPU_PS_RPC_DELAY_MS": "50",
        "PADDLE_TPU_PS_RPC_DELAY_JITTER_MS": "10",
        "PADDLE_TPU_WD_GEO": "1",
        "FLAGS_dgc": "1", "FLAGS_ps_wire_quant": "int8",
        # geo refuses lazy tables; keep the small tables dense-hosted
        "FLAGS_lazy_sparse_table_threshold": str(1 << 26)})
    from paddle_tpu.fluid import core as _core
    _core.set_flag("FLAGS_dgc", True)
    _core.set_flag("FLAGS_ps_wire_quant", "int8")
    _core.set_flag("FLAGS_lazy_sparse_table_threshold", 1 << 26)
    _comm.reset_dgc()
    _ps_rpc.reset_quant_wire_stats()
    try:
        row = bench_wide_deep_1b(
            batch=batch, steps=steps, warmup=warmup,
            n_pservers=n_pservers, sparse_dim=sparse_dim,
            n_trainers=n_trainers, async_staleness=2, window_k=1,
            metric="wide_deep_geo_wan_samples_per_sec")
        dgc = _comm.active_dgc_stats()
        quant = _ps_rpc.quant_wire_stats()
        row.update({
            "mode": "geo+dgc+int8", "rpc_delay_ms": 50,
            "dgc_compression_ratio": dgc.get("compression_ratio"),
            "wire_bytes_raw": quant.get("bytes_raw_total"),
            "wire_bytes_sent": quant.get("bytes_sent_total"),
            "wire_ratio": round(
                quant.get("bytes_raw_total", 0)
                / max(1, quant.get("bytes_sent_total", 1)), 2)})
        return row
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        _core.set_flag("FLAGS_dgc", False)
        _core.set_flag("FLAGS_ps_wire_quant", "")
        _core.set_flag("FLAGS_lazy_sparse_table_threshold", 1 << 26)


def bench_wide_deep_geo_sync(batch=256, steps=8, warmup=2, n_pservers=2,
                             sparse_dim=20000, n_trainers=2):
    """Plain-sync counterpart of wide_deep_geo under the SAME 50ms+
    jitter WAN emulation: every step pays the full send/barrier/recv
    tail plus one delayed row pull per sparse table — which is exactly
    why the step count is small (each step costs seconds). Same model,
    same cluster shape, compression off."""
    saved = {k: os.environ.get(k) for k in
             ("PADDLE_TPU_PS_RPC_DELAY_MS",
              "PADDLE_TPU_PS_RPC_DELAY_JITTER_MS",
              "FLAGS_lazy_sparse_table_threshold")}
    os.environ.update({
        "PADDLE_TPU_PS_RPC_DELAY_MS": "50",
        "PADDLE_TPU_PS_RPC_DELAY_JITTER_MS": "10",
        "FLAGS_lazy_sparse_table_threshold": str(1 << 26)})
    from paddle_tpu.fluid import core as _core
    _core.set_flag("FLAGS_lazy_sparse_table_threshold", 1 << 26)
    try:
        row = bench_wide_deep_1b(
            batch=batch, steps=steps, warmup=warmup,
            n_pservers=n_pservers, sparse_dim=sparse_dim,
            n_trainers=n_trainers, async_staleness=0, window_k=1,
            metric="wide_deep_geo_sync_wan_samples_per_sec")
        row.update({"mode": "sync", "rpc_delay_ms": 50})
        return row
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def bench_wide_deep_spill(batch=256, steps=12, warmup=4, n_pservers=2,
                          sparse_dim=int(2.5e6), n_trainers=2,
                          resident_frac=0.10):
    """Capacity-tier paired lanes (docs/PS_DATA_PLANE.md "Capacity
    tier", ROADMAP item 2): the SAME wide_deep cluster and
    deterministic feed three ways — (a) all-in-RAM oracle, (b) spill
    tier with each table's hot set capped at ~10% of its per-step
    working set (raw rows at rest), (c) the same cap with int8 rows at
    rest. The tier flags reach the pserver subprocesses via env
    (lazy_table_init reads them at startup). Acceptance: (b) trains at
    >50% of (a)'s rate with the final loss BIT-IDENTICAL (raw
    write-back is exact — promotion/eviction churn must not change a
    single bit); (c) stays within the documented int8 at-rest error
    envelope (absmax_row/254 per element per first quantization) and
    holds >=3.5x at-rest row density at dim 16+scale — the slab gauges
    are scraped from the pservers' stats RPC before teardown.

    The repeated-batch feed makes this the LRU worst case: every step
    cycles the whole working set through a hot set 10x smaller, so the
    spill lane pays promotion+write-back for ~90% of its rows every
    step (hit_rate evidence ~= resident fraction). Real CTR traffic is
    zipfian and does strictly better; the clean-backing write elision
    (unmodified promotes evict for free) is what keeps even this
    pathological lane inside the bar."""
    import tempfile

    # per-table working set of the repeated batch ~= `batch` distinct
    # ids (uniform draw over 2.5e6); the hot cap is ~10% of that
    hot_rows = max(16, int(batch * resident_frac))
    lanes = {}
    saved = {k: os.environ.get(k) for k in
             ("FLAGS_ps_slab_spill_dir", "FLAGS_ps_slab_hot_rows",
              "FLAGS_ps_at_rest_quant", "FLAGS_ps_slab_seg_rows")}

    def _restore():
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    try:
        # the ORACLE lane must run tier-off even if the caller's env
        # has the spill flags exported — otherwise the "RAM" baseline
        # also spills and every comparison self-compares
        for k in saved:
            os.environ.pop(k, None)
        lanes["ram"] = bench_wide_deep_1b(
            batch=batch, steps=steps, warmup=warmup,
            n_pservers=n_pservers, sparse_dim=sparse_dim,
            n_trainers=n_trainers,
            metric="wide_deep_spill_ram_samples_per_sec")
        for key, quant in (("spill", ""), ("spill_int8", "int8")):
            spill_dir = tempfile.mkdtemp(prefix=f"pt-wdspill-{key}-")
            os.environ["FLAGS_ps_slab_spill_dir"] = spill_dir
            os.environ["FLAGS_ps_slab_hot_rows"] = str(hot_rows)
            os.environ["FLAGS_ps_at_rest_quant"] = quant
            os.environ["FLAGS_ps_slab_seg_rows"] = str(max(64, batch))
            try:
                lanes[key] = bench_wide_deep_1b(
                    batch=batch, steps=steps, warmup=warmup,
                    n_pservers=n_pservers, sparse_dim=sparse_dim,
                    n_trainers=n_trainers,
                    metric=f"wide_deep_{key}_samples_per_sec")
            finally:
                _restore()
                import shutil
                shutil.rmtree(spill_dir, ignore_errors=True)
    finally:
        _restore()

    ram, spill, spill8 = lanes["ram"], lanes["spill"], lanes["spill_int8"]
    ratio = spill["value"] / max(ram["value"], 1e-9)
    ratio8 = spill8["value"] / max(ram["value"], 1e-9)
    return {
        "metric": "wide_deep_spill_samples_per_sec",
        "value": spill["value"], "unit": "samples/s",
        "vs_baseline": 1.0, "batch": batch,
        "embedding_params": ram.get("embedding_params"),
        "pservers": n_pservers, "trainers": n_trainers,
        "resident_frac_target": resident_frac, "hot_rows": hot_rows,
        "ram_samples_per_sec": ram["value"],
        "rate_vs_ram": round(ratio, 3),
        "rate_bar_0p5_met": ratio > 0.5,
        # raw-at-rest loss parity is the bit-exactness contract
        "final_loss": spill["final_loss"],
        "loss_ram": ram["final_loss"],
        "loss_bit_identical": spill["final_loss"] == ram["final_loss"],
        "slab": spill.get("slab", {}),
        # int8-at-rest companion: rate + loss envelope + density gauge
        "int8_samples_per_sec": spill8["value"],
        "int8_rate_vs_ram": round(ratio8, 3),
        "loss_int8": spill8["final_loss"],
        "int8_loss_delta": round(
            abs(spill8["final_loss"] - ram["final_loss"]), 6),
        "int8_slab": spill8.get("slab", {}),
        # density is a row-WIDTH property (dim/(dim/4+4)): this model's
        # dim-16 deep tables cap at 3.2x and its dim-1 wide tables are
        # expansion-gated to raw, so the aggregate lands ~2.8x; the
        # >=3.5x acceptance gauge is evidenced at dim>=32 by
        # tests/test_ps_capacity.py and rpc_microbench --spill (3.76x
        # at dim 64)
        "int8_density_x": spill8.get("slab", {}).get("density_x", 0.0),
    }


def bench_wide_deep_1b_ceiling(batch=512, steps=16, warmup=8,
                               sparse_dim=20000, window_k=8):
    """No-PS compiled ceiling PROXY for the wide_deep_1b pair: the same
    arch/batch/window shape with LOCAL embedding tables at a reduced
    sparse_dim — the true 2.5M-row×26-slot tables are ~4.3 GB dense and
    exactly why the PS plane exists, so the ceiling is what the
    compiled step could do if the wire were free. Single process, no
    pservers; with_auc keeps the segmented execution shape of the PS
    lanes."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core
    from paddle_tpu.models import wide_deep

    main, startup, feeds, loss, auc = wide_deep.build_wide_deep_program(
        num_dense=13, num_slots=26, sparse_dim=sparse_dim,
        embedding_dim=16, hidden=(64, 64), lr=1e-3,
        optimizer=fluid.optimizer.SGD(1e-3))
    exe = fluid.Executor()
    scope = core.Scope()
    nb = wide_deep.ctr_reader(batch, num_dense=13, num_slots=26,
                              sparse_dim=sparse_dim, seed=0)
    batches = [nb() for _ in range(window_k)]
    feed = {n: np.stack([b[n] for b in batches]) for n in batches[0]}
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(max(1, warmup // window_k)):
            exe.run(main, feed=feed, fetch_list=[loss],
                    n_steps=window_k, return_numpy=False)
        t0 = time.perf_counter()
        for _ in range(max(1, steps // window_k)):
            out = exe.run(main, feed=feed, fetch_list=[loss],
                          n_steps=window_k, return_numpy=False)
        _ = float(np.asarray(out[0].array).ravel()[-1])
        dt = time.perf_counter() - t0
    return {"metric": "wide_deep_1b_nops_ceiling_samples_per_sec",
            "value": round(batch * steps / dt, 1), "unit": "samples/s",
            "vs_baseline": 1.0, "batch": batch,
            "sparse_dim_proxy": int(sparse_dim), "window_k": window_k,
            "executor_mode": exe._last_run_mode,
            "note": "no-PS ceiling proxy at reduced local table size"}


def bench_serving_mnist(clients=16, duration=2.5, warmup_s=0.5):
    """Online-serving lanes (docs/SERVING.md "Bench methodology"):
    closed-loop QPS + p50/p99 at ``clients`` concurrent single-row
    clients over the mnist MLP, three lanes on one model/scope:

      * naive   — the PRE-serving-plane path: reference PredictorPool /
                  Clone() semantics, one ``Executor.run`` dispatch per
                  request on a per-client executor. One-request-one-
                  dispatch, zero batching.
      * nobatch — the ServingEngine with max_batch=1: the batching
                  ablation (same queue/futures plumbing, batching off).
      * batched — continuous batching, max_batch=``clients``: the
                  serving plane's default row-exact scan mode.

    The acceptance bar (ISSUE 7) compares batched vs naive; the nobatch
    ablation is reported because on this 1-core box the client threads'
    GIL wakeups bound it — see the SERVING.md caveat."""
    import threading
    import paddle_tpu.fluid as fluid
    from paddle_tpu.serving import ServingEngine
    from tools import serving_loadgen as LG

    main, scope, out_name, feeds = LG.build_mlp_serving_model()
    feeds_b = [{"x": f["x"][None]} for f in feeds]  # [1, 784] for exe.run

    # --- naive lane: per-client executor, one dispatch per request ----
    exes = [fluid.Executor() for _ in range(clients)]
    for e in exes:  # warm TWICE through the production path (memory:
        for _ in range(2):  # arg-sharding recompile on call 2)
            e.run(main, feed=feeds_b[0], fetch_list=[out_name],
                  scope=scope)
    tl = threading.local()
    nxt = iter(range(clients))
    lk = threading.Lock()

    def naive_predict(feed):
        e = getattr(tl, "exe", None)
        if e is None:
            with lk:
                tl.exe = e = exes[next(nxt)]
        return e.run(main, feed=feed, fetch_list=[out_name],
                     scope=scope)

    naive = LG.run_closed_loop(naive_predict, feeds_b, clients=clients,
                               duration_s=duration, warmup_s=warmup_s)

    def engine_lane(max_batch):
        eng = ServingEngine(program=main, scope=scope, feed_names=["x"],
                            fetch_names=[out_name], max_batch=max_batch,
                            max_queue_delay_ms=2.0, num_workers=2)
        try:
            eng.warm()
            eng.reset_stats()
            res = LG.run_closed_loop(eng.predict, feeds, clients=clients,
                                     duration_s=duration,
                                     warmup_s=warmup_s)
            st = eng.stats()
        finally:
            eng.close()
        return res, st

    nobatch, _ = engine_lane(1)
    batched, bst = engine_lane(clients)
    return {"metric": "serving_mnist_qps", "value": round(batched["qps"], 1),
            "unit": "req/s", "vs_baseline": round(
                batched["qps"] / max(naive["qps"], 1e-9), 2),
            "clients": clients,
            "naive_qps": round(naive["qps"], 1),
            "engine_nobatch_qps": round(nobatch["qps"], 1),
            "speedup_vs_naive": round(
                batched["qps"] / max(naive["qps"], 1e-9), 2),
            "speedup_vs_nobatch": round(
                batched["qps"] / max(nobatch["qps"], 1e-9), 2),
            "p50_ms": round(batched["p50_ms"], 2),
            "p99_ms": round(batched["p99_ms"], 2),
            "naive_p50_ms": round(naive["p50_ms"], 2),
            "naive_p99_ms": round(naive["p99_ms"], 2),
            "batch_mode": bst["mode"],
            "avg_batch": round(bst["avg_batch"], 1),
            "buckets_compiled": bst["buckets_compiled"]}


def bench_serving_wide_deep(clients=8, duration=2.0, warmup_s=0.5,
                            sparse_dim=20000, num_slots=26):
    """Wide&Deep CTR serving lanes: the same forward program served
    (a) from local embedding tables (compiled row-exact scan mode) and
    (b) through LIVE pservers — ``rewrite_sparse_lookups`` points the 52
    per-slot tables at 2 in-process listen_and_serv shards and the
    engine's EmbeddingCache fronts the ``distributed_lookup_table``
    pulls (PR 4 binary wire underneath). Reports both lanes' QPS +
    p50/p99, the cache hit rate, and a bit-parity flag: the PS lane's
    predictions must equal the local-table oracle bit-for-bit on the
    same padded bucket (the table is unchanged during the bench)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid.ps_rpc import VarClient
    from paddle_tpu.models.wide_deep import wide_deep_net, ctr_reader
    from paddle_tpu.serving import (EmbeddingCache, ServingEngine,
                                    rewrite_sparse_lookups)
    from tools import serving_loadgen as LG

    num_dense = 13
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        dense = fluid.data("dense", shape=[num_dense], dtype="float32")
        slots = [fluid.data("slot_%d" % i, shape=[1], dtype="int64")
                 for i in range(num_slots)]
        prob = wide_deep_net(dense, slots, sparse_dim=sparse_dim,
                             embedding_dim=16, hidden=(128, 64),
                             is_distributed=True)
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    feed_names = ["dense"] + ["slot_%d" % i for i in range(num_slots)]
    nb = ctr_reader(64, num_dense=num_dense, num_slots=num_slots,
                    sparse_dim=sparse_dim, seed=0)
    raw = [nb() for _ in range(16)]
    feeds = []
    for b in raw:
        for i in range(8):  # single-row serving requests
            feeds.append({n: b[n][i] for n in feed_names})

    probe = {n: np.stack([feeds[k][n] for k in range(4)])
             for n in feed_names}

    def lane(program, cache=None, mode=None, loadgen=True):
        eng = ServingEngine(program=program, scope=scope,
                            feed_names=feed_names,
                            fetch_names=[prob.name], max_batch=clients,
                            max_queue_delay_ms=2.0, num_workers=2,
                            batch_mode=mode, embedding_cache=cache)
        res, st = None, None
        try:
            eng.warm((1, 2, 4, clients))
            if loadgen:
                eng.reset_stats()
                res = LG.run_closed_loop(eng.predict, feeds,
                                         clients=clients,
                                         duration_s=duration,
                                         warmup_s=warmup_s)
                st = eng.stats()
            # parity probe: one deterministic padded bucket through THIS
            # engine (oracle comparison happens outside the timed loop)
            (pred,) = eng.predict_many(probe)
        finally:
            eng.close()
        return res, st, pred

    local_res, local_st, local_pred = lane(main)

    eps = [f"127.0.0.1:{LG.free_port()}" for _ in range(2)]
    servers = [LG.start_inproc_pserver(ep) for ep in eps]
    try:
        tables = (["wide_emb_%d" % i for i in range(num_slots)]
                  + ["deep_emb_%d" % i for i in range(num_slots)])
        with fluid.scope_guard(scope):
            for t in tables:
                LG.push_table(
                    eps, t, np.asarray(scope.find_var(t).value().array))
        ps_prog, _hit = rewrite_sparse_lookups(main, eps, tables=tables)
        cache = EmbeddingCache(ttl_s=300.0, max_entries=2_000_000)
        ps_res, ps_st, ps_pred = lane(ps_prog, cache=cache, mode="fused")
        cache_stats = ps_st.get("embedding_cache") or {}
        # no-cache PS lane for the RPC-elision delta
        ps_nc_res, _st, _p = lane(ps_prog, cache=None, mode="fused")
        # local-table oracle for the SAME padded probe bucket (fused
        # mode at the same bucket size -> bit-comparable)
        _r, _s, oracle_pred = lane(main, mode="fused", loadgen=False)
        parity_ok = bool((ps_pred == oracle_pred).all())
    finally:
        for ep, (th, _scope) in zip(eps, servers):
            LG.stop_inproc_pserver(ep, th)
        VarClient.reset_pool()
    return {"metric": "serving_wide_deep_qps",
            "value": round(ps_res["qps"], 1), "unit": "req/s",
            "vs_baseline": 1.0, "clients": clients,
            "sparse_dim": sparse_dim, "num_slots": num_slots,
            "local_qps": round(local_res["qps"], 1),
            "ps_qps_cached": round(ps_res["qps"], 1),
            "ps_qps_nocache": round(ps_nc_res["qps"], 1),
            "cache_hit_rate": round(cache_stats.get("hit_rate", 0.0), 4),
            "p50_ms": round(ps_res["p50_ms"], 2),
            "p99_ms": round(ps_res["p99_ms"], 2),
            "local_p50_ms": round(local_res["p50_ms"], 2),
            "local_p99_ms": round(local_res["p99_ms"], 2),
            "parity_ok": parity_ok,
            "pservers": len(eps)}


def bench_serve_http_overload(clients=16, duration=2.5, warmup_s=0.5,
                              overload_factor=4.0):
    """HTTP ingress overload lane (docs/SERVING.md "Ingress &
    overload"): the full serving stack on the wire — ThreadingHTTP
    ingress → admission queue → continuous batcher → scan-mode engine —
    measured closed-loop at capacity (1× load), then open-loop at 1×
    and 4× the measured capacity with 16 HTTP clients. Reports the
    accepted-request p99 at 1× and 4×, the shed rate (typed 429s; any
    untyped 5xx/transport failure fails the lane), and the engine's
    shed/deadline counters. The robustness claim is the RATIO: under
    4× offered load the accepted p99 stays bounded (admission bound +
    CoDel head-drop) and every refused request is answered typed.
    1-core caveat: clients, ingress handlers, and engine workers
    time-slice one core, so absolute QPS is trend-only (PR 7 serving
    caveat) — ratio and typed-refusal figures are the robust
    numbers."""
    from tools.serving_loadgen import run_overload_scenario

    res = run_overload_scenario(clients=clients, duration_s=duration,
                                warmup_s=warmup_s,
                                overload_factor=overload_factor)
    return {
        "metric": "serve_http_overload_p99_ratio",
        "value": res["p99_ratio"],
        "unit": "x (accepted p99 at 4x / 1x)",
        "vs_baseline": res["p99_ratio"],
        "clients": clients,
        "capacity_qps_1x": res["capacity_qps_1x"],
        "accepted_p99_ms_1x": round(res["accepted_p99_ms_1x"], 2),
        "accepted_p99_ms_1x_open": round(
            res["accepted_p99_ms_1x_open"], 2),
        "accepted_p99_ms_overload": round(
            res["accepted_p99_ms_overload"], 2),
        "p99_ratio_vs_open_1x": res["p99_ratio_vs_open_1x"],
        "shed_rate_overload": res["shed_rate_overload"],
        "overload_statuses": res["open_overload"]["statuses"],
        "untyped_failures": res["untyped_failures"],
        "all_refusals_typed": res["all_refusals_typed"],
        "engine_shed": res["engine"]["shed"],
        "engine_deadline_expired": res["engine"]["deadline_expired"],
        # the bound/deadline the scenario actually resolved and ran
        # with — re-deriving its defaults here would silently drift
        "max_queue_rows": res["max_queue_rows"],
        "deadline_ms": res["deadline_ms"],
    }


def bench_serve_fleet(members=4, clients=8, duration=3.0, warmup_s=0.5,
                      n_rows=256, dim=8):
    """Serving-fleet scale lane (docs/SERVING.md "Fleet"): ``members``
    REAL engine subprocesses (tools/chaos_ps.py serving-member — each
    its own interpreter, ingress, EmbeddingCache and invalidation
    subscriber) behind a FleetDirectory, driven closed-loop through
    the FleetRouter, vs the SAME load against one member. Also probes
    the fleet contracts outside the timed loops: per-member response
    parity (every member must answer a probe id identically — they
    serve one table), and the trainer-push freshness window (publish →
    new value in a remote HTTP response, wall-clock measured).

    1-core caveat: all member processes time-slice one core, so the
    fleet/single QPS ratio is trend-only there — the acceptance
    evidence arm is parity + freshness + the per-endpoint spread
    showing genuine multi-process overlap (PR 7 serving caveat; the
    ≥3× scale claim needs ≥``members`` cores)."""
    import tempfile
    import threading

    from tools.chaos_ps import (_spawn, _wait_file, free_port)
    from tools.serving_loadgen import (HttpClient,
                                       run_http_fleet_closed_loop)
    from paddle_tpu.fluid.ps_rpc import VarClient, VarServer
    from paddle_tpu.serving import FleetDirectory, InvalidationPublisher

    rng = np.random.RandomState(7)
    table = rng.rand(n_rows, dim).astype(np.float32)
    tlock = threading.Lock()

    def serve_table(name, rows, prefetch=False, trainer_id=0):
        with tlock:
            return table[np.asarray(rows, np.int64)].copy()

    workdir = tempfile.mkdtemp(prefix="bench_fleet_")
    table_ep = f"127.0.0.1:{free_port()}"
    pub_ep = f"127.0.0.1:{free_port()}"
    dir_ep = f"127.0.0.1:{free_port()}"
    srv = VarServer(table_ep, {"prefetch_rows": serve_table}).start()
    pub = InvalidationPublisher(pub_ep).start()
    directory = FleetDirectory(dir_ep, heartbeat_timeout_s=2.0).start()
    chaos_py = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tools", "chaos_ps.py")
    procs = []
    try:
        waits = []
        for i in range(members):
            ready = os.path.join(workdir, f"m{i}.ready")
            p, tail = _spawn(
                [chaos_py, "serving-member", f"m{i}", table_ep, pub_ep,
                 dir_ep, ready, f"--rows={n_rows}", f"--dim={dim}",
                 "--hb=2.0"],
                os.path.join(workdir, f"m{i}.log"))
            procs.append(p)
            waits.append((ready, p, tail))
        ports = []
        for ready, p, tail in waits:
            _wait_file(ready, 180, [(p, tail)], desc=ready)
            ports.append(int(open(ready).read().strip()))

        feeds = [{"ids": np.array([[i % n_rows]], np.int64)}
                 for i in range(64)]
        # per-member parity probe: one table, identical answers
        probe_id = 13
        answers = []
        for port in ports:
            cli = HttpClient("127.0.0.1", port)
            try:
                status, obj = cli.predict({"ids": [[probe_id]]},
                                          model="fleet")
            finally:
                cli.close()
            assert status == 200, (status, obj)
            answers.append(float(np.asarray(obj["outputs"][0])
                                 .reshape(-1)[0]))
        parity_ok = all(a == answers[0] for a in answers)

        single = run_http_fleet_closed_loop(
            [f"127.0.0.1:{ports[0]}"], feeds, clients=clients,
            duration_s=duration, warmup_s=warmup_s, model="fleet")
        fleet = run_http_fleet_closed_loop(
            [], feeds, clients=clients, duration_s=duration,
            warmup_s=warmup_s, model="fleet", directory_ep=dir_ep)

        # freshness: a trainer push must reach a REMOTE response fast
        with tlock:
            table[probe_id] += 1.0
            expect = float(table[probe_id].sum())
        t_push = time.time()
        pub.publish("emb_fleet", [probe_id])
        window = None
        cli = HttpClient("127.0.0.1", ports[-1])
        try:
            while time.time() - t_push < 10.0:
                status, obj = cli.predict({"ids": [[probe_id]]},
                                          model="fleet")
                if status == 200 and abs(
                        float(np.asarray(obj["outputs"][0])
                              .reshape(-1)[0]) - expect) < 1e-3:
                    window = time.time() - t_push
                    break
                time.sleep(0.01)
        finally:
            cli.close()

        ratio = (fleet["qps"] / single["qps"]) if single["qps"] else 0.0
        return {
            "metric": "serve_fleet_scale",
            "value": round(ratio, 3),
            "unit": f"x ({members}-member fleet QPS / 1-member QPS; "
                    "trend-only on 1 core)",
            "vs_baseline": round(ratio, 3),
            "members": members, "clients": clients,
            "fleet_qps": round(fleet["qps"], 1),
            "single_qps": round(single["qps"], 1),
            "fleet_p99_ms": round(fleet["p99_ms"], 2),
            "single_p99_ms": round(single["p99_ms"], 2),
            "by_endpoint_ok": {
                ep: d.get("ok", 0)
                for ep, d in fleet["by_endpoint"].items()},
            "parity_ok": bool(parity_ok),
            "freshness_window_s": (round(window, 4)
                                   if window is not None else None),
            "cores": os.cpu_count(),
        }
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=15)
            except Exception:
                p.kill()
        directory.close()
        pub.close()
        srv.shutdown()
        VarClient.reset_pool()


def bench_stream_ctr(steps=30, batch=8, step_sleep=0.12):
    """Streaming online-learning CTR lane (docs/FAULT_TOLERANCE.md
    "Streaming online learning"): runs the full chaos acceptance
    scenario — sync-oracle leg, then the fully-async train+serve
    cluster with its mid-run pserver SIGKILL — and reports async vs
    sync-oracle trainer samples/s plus the event→served freshness p99
    scraped off the serving member's /metrics histogram. Appends one
    BENCH_LOCAL row per leg (the ISSUE 20 evidence contract).

    1-core evidence-arm caveat (same as serve_fleet /
    wide_deep_1b_async): every cluster process shares one core, so
    samples/s is scheduler-bound evidence — the robustness checks
    (zero typed-error leaks across the SIGKILL, loss in the oracle's
    neighborhood) are the lane's primary product. The async trainer is
    paced by ``step_sleep`` (it models event arrival; the oracle leg
    is unpaced), so the row records the pacing and a pacing-adjusted
    rate alongside the raw one. Faster pacing starves the co-located
    serving member on one core (accepted p99 blows the bar at 0.05s),
    so the default keeps the scenario's 0.12s event cadence."""
    import tempfile
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tools.chaos_ps import run_streaming_scenario

    wd = tempfile.mkdtemp(prefix="bench_stream_ctr_")
    res = run_streaming_scenario(wd, steps=steps, batch=batch,
                                 step_sleep=step_sleep,
                                 kill_at=max(5, steps // 3))
    n_async = int(res.get("async_steps_run") or steps)
    wall_a = float(res.get("async_train_wall_s") or 0) or None
    wall_o = float(res.get("oracle_train_wall_s") or 0) or None
    sps_async = round(n_async * batch / wall_a, 2) if wall_a else None
    sps_oracle = round(steps * batch / wall_o, 2) if wall_o else None
    paced_out = n_async * step_sleep
    sps_async_adj = (round(n_async * batch / (wall_a - paced_out), 2)
                     if wall_a and wall_a > paced_out else None)
    note = ("1-core box: all cluster processes share one core — "
            "samples/s is scheduler-bound evidence; robustness checks "
            "(zero typed leaks across SIGKILL, oracle-neighborhood "
            "loss) are the lane's product")
    rows = [
        {"metric": "stream_ctr_async_samples_per_sec",
         "value": sps_async, "unit": "samples/s",
         "vs_baseline": (round(sps_async / sps_oracle, 3)
                         if sps_async and sps_oracle else None),
         "steps": n_async, "batch": batch, "step_sleep_s": step_sleep,
         "pacing_adjusted_samples_per_sec": sps_async_adj,
         "freshness_p99_s": res.get("freshness_p99_s"),
         "freshness_samples": res.get("freshness_samples"),
         "serving_p99_ms": (res.get("load") or {}).get("p99_ms"),
         "shrink_runs": res.get("shrink_runs"),
         "async_tail_mean": res.get("async_tail_mean"),
         "ok": res.get("ok"), "note": note},
        {"metric": "stream_ctr_sync_oracle_samples_per_sec",
         "value": sps_oracle, "unit": "samples/s", "vs_baseline": 1.0,
         "steps": steps, "batch": batch, "step_sleep_s": 0.0,
         "oracle_tail_mean": res.get("oracle_tail_mean"),
         "note": note},
    ]
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_LOCAL.json")
    try:
        bl = json.load(open(path))
    except (OSError, ValueError):
        bl = {"note": "", "rows": []}
    bl.setdefault("rows", []).extend(rows)
    json.dump(bl, open(path, "w"), indent=1)
    return rows[0]


def bench_longctx(iters=8):
    """Long-context attention lane (SURVEY §5: long-context is
    first-class here — ring/Ulysses SP + flash kernels — where the
    reference's v1.7 answer was LoD ragged batching). Two shapes:

    TPU (one chip): causal Pallas flash attention fwd+bwd at S=8192,
    bf16 — the single-chip long-sequence path, scan-timed so the tunnel
    RTT stays out of the number.
    CPU (virtual mesh): 8-device ring attention fwd+bwd, the
    sequence-parallel path whose K/V blocks rotate over ppermute.

    Reports tokens/s and attention-only achieved TFLOPs (causal fwd
    2·B·H·S²·D multiply-adds ≈ 4·B·H·S²·D FLOPs halved for causality,
    ×3.5 for fwd+bwd)."""
    import jax
    import jax.numpy as jnp
    from tools.flash_smoke import _timed_scan

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # the CPU lane measures the 8-device ring — force the virtual
        # mesh BEFORE the backend initializes (ambient XLA_FLAGS must
        # not be a prerequisite; a 1-device "ring" never exercises the
        # ppermute rotation this lane exists for)
        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except Exception:
            pass  # backend already initialized (e.g. env-forced count)
    on_tpu = jax.devices()[0].platform not in ("cpu",)
    rng = np.random.RandomState(0)
    if on_tpu:
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        B, H, S, D = 1, 12, 8192, 64
        dt_ = jnp.bfloat16
        q, k, v = (jnp.asarray(rng.randn(B, H, S, D) * 0.3, dt_)
                   for _ in range(3))
        sm = 1.0 / float(np.sqrt(D))

        def fwdbwd(q_, k_, v_):
            def loss(q2, k2, v2):
                return jnp.sum(
                    flash_attention(q2, k2, v2, sm, causal=True)
                    .astype(jnp.float32) ** 2)
            l, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(
                q_, k_, v_)
            return l + sum(jnp.sum(g.astype(jnp.float32) ** 2)
                           for g in grads)
        ms = _timed_scan(fwdbwd, q, k, v, iters)
        mode = "flash_causal_1chip"
        n_dev = 1
    else:
        from paddle_tpu.parallel.ring_attention import (ring_attention,
                                                        sequence_mesh)
        n_dev = len(jax.devices())
        if n_dev == 1:
            # the jax_num_cpu_devices update silently no-ops once the
            # backend is initialized; a 1-device "ring" never exercises
            # the ppermute rotation this lane exists to measure — emit an
            # explicit degraded row instead of a normal-looking number
            # (r5 advisor finding)
            return {"metric": "longctx_attention_tokens_per_sec",
                    "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
                    "ok": False, "mode": "ring_sp1_degenerate",
                    "devices": 1,
                    "error": "CPU ring lane requires a multi-device "
                             "virtual mesh; backend initialized before "
                             "jax_num_cpu_devices could take effect"}
        mesh = sequence_mesh(n_dev)
        B, H, D = 1, 4, 64
        S = 512 * max(1, n_dev)
        q, k, v = (jnp.asarray(rng.randn(B, H, S, D) * 0.3, jnp.float32)
                   for _ in range(3))
        sm = 1.0 / float(np.sqrt(D))

        def fwdbwd(q_, k_, v_):
            def loss(q2, k2, v2):
                return jnp.sum(ring_attention(q2, k2, v2, sm, causal=True,
                                              mesh=mesh) ** 2)
            l, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(
                q_, k_, v_)
            return l + sum(jnp.sum(g) for g in grads)
        ms = _timed_scan(fwdbwd, q, k, v, iters)
        mode = f"ring_sp{n_dev}_virtual"
    flops = 4.0 * B * H * S * S * D / 2.0 * 3.5  # causal fwd+bwd
    return {"metric": "longctx_attention_tokens_per_sec",
            "value": round(B * S / (ms / 1e3), 1), "unit": "tokens/s",
            "vs_baseline": 1.0, "seq_len": S, "heads": H, "head_dim": D,
            "mode": mode, "devices": n_dev, "step_ms": round(ms, 3),
            "attn_tflops": round(flops / (ms / 1e3) / 1e12, 3)}


def bench_lm3d(k=8, rounds=3, parity_steps=4):
    """Composed 3D-parallel LM lane (ROADMAP item 4): a GPT-style
    decoder trained at dp2×pp2×sp2 (+ a 4-expert MoE expert-parallel
    variant over "dp") on the 8-device virtual mesh —
    parallel/lm3d.py. Reports tokens/s and achieved model TFLOPs
    (6·N·tokens, the longctx-lane methodology; attention quadratic term
    alongside), per-step loss parity vs the single-device oracle,
    counted MoE token drops, zero-retrace steady-state evidence
    (jit cache size + jax backend-compile counter over the timed
    region, scraped as executor_retraces_total{kind=lm3d}), and a PR 10
    merged cluster-timeline artifact (tools/lm3d_timeline.json) whose
    cat="window" spans are the dispatch-level overlap evidence. On this
    1-core box the 8 mesh "devices" time-slice one CPU, so tokens/s is
    a composition-correctness trend number, not a speedup claim
    (docs/PERF.md caveats)."""
    import tempfile
    import jax
    import jax.numpy as jnp

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except Exception:
            pass  # backend already initialized
    n_dev = len(jax.devices())
    if n_dev < 8:
        # PR 1 longctx precedent: an un-virtualizable mesh must emit an
        # explicit degraded row, never a normal-looking number
        return {"metric": "lm3d_tokens_per_sec", "value": 0.0,
                "unit": "tokens/s", "vs_baseline": 0.0, "ok": False,
                "mode": "lm3d_degenerate", "devices": n_dev,
                "error": "composed dp2×pp2×sp2 lane needs an 8-device "
                         "(virtual) mesh; backend initialized before "
                         "jax_num_cpu_devices could take effect"}

    from paddle_tpu.fluid import core as _core, telemetry, profiler
    from paddle_tpu.parallel import lm3d

    telemetry.install_jax_compile_listener()
    trace_dir = tempfile.mkdtemp(prefix="lm3d_trace_")
    _core.set_flag("FLAGS_trace_dir", trace_dir)
    telemetry.set_process_role("lm3d")

    def backend_compiles():
        fam = telemetry.REGISTRY.get("jax_backend_compiles_total")
        return sum(c.value() for c in fam.children()) if fam else 0.0

    def run_variant(tag, cfg):
        global LAST_COMPILE_S
        mesh = cfg.mesh()
        params = lm3d.place_params(cfg, mesh, lm3d.init_params(cfg))
        amp = lm3d.init_amp_state(cfg, mesh)
        win = jax.jit(lm3d.make_window_step(cfg, mesh))
        key = jax.random.PRNGKey(cfg.seed)
        telemetry.count_compile(f"lm3d_{tag}")
        t0 = time.perf_counter()
        with profiler.RecordEvent(f"compile:lm3d_{tag}[{k}]",
                                  cat="compile"):
            w = lm3d.place_window(cfg, mesh,
                                  lm3d.sample_window(cfg, 0, k))
            p, a, outs = win(params, amp, w, key, jnp.int32(0))
            jax.block_until_ready(outs[0])
        compile_s = round(time.perf_counter() - t0, 2)
        LAST_COMPILE_S = compile_s
        loss0 = float(outs[0][0])
        # timed steady state: the jitted window must never retrace
        c0 = backend_compiles()
        idx = k
        t0 = time.perf_counter()
        for _ in range(rounds):
            wz = lm3d.place_window(cfg, mesh,
                                   lm3d.sample_window(cfg, idx, k))
            with profiler.RecordEvent(f"lm3d_{tag}:window[{k}]",
                                      cat="window",
                                      args={"steps": k}):
                p, a, outs = win(p, a, wz, key, jnp.int32(idx))
                jax.block_until_ready(outs[0])
            idx += k
        dt = time.perf_counter() - t0
        retraces = win._cache_size() - 1
        if retraces > 0:
            telemetry.count_compile(f"lm3d_{tag}", retrace=True)
        fl = lm3d.flops_per_step(cfg, lm3d.param_count(
            lm3d.init_params(cfg)))
        steps = rounds * k
        tokens = fl["tokens"] * steps
        # oracle parity: fresh params, same feeds/folds, one device
        ostep = jax.jit(lm3d.make_oracle_step(cfg))
        po = lm3d.init_params(cfg)
        ao = lm3d.init_amp_state(cfg)
        pc = lm3d.init_params(cfg)
        pc = lm3d.place_params(cfg, mesh, pc)
        ac = lm3d.init_amp_state(cfg, mesh)
        step = jax.jit(lm3d.make_train_step(cfg, mesh))
        wp = lm3d.sample_window(cfg, 0, parity_steps)
        rel = 0.0
        for i in range(parity_steps):
            xb = jnp.asarray(wp[i, ..., :-1])
            yb = jnp.asarray(wp[i, ..., 1:])
            kk = jax.random.fold_in(key, i)
            pc, ac, (lc, _, _, dc) = step(pc, ac, xb, yb, kk)
            po, ao, (lo, _, _, do) = ostep(po, ao, xb, yb, kk)
            lo_f = float(lo)
            rel = max(rel, abs(float(lc) - lo_f) / max(abs(lo_f),
                                                       1e-9))
        return {
            "tokens_per_sec": round(tokens / dt, 1),
            "model_tflops": round(fl["model_flops"] * steps / dt
                                  / 1e12, 5),
            "attn_tflops": round(fl["attn_flops"] * steps / dt / 1e12,
                                 5),
            "n_params": int(fl["n_params"]),
            "n_active_params": int(fl["n_active_params"]),
            "step_ms": round(dt / steps * 1e3, 2),
            "compile_s": compile_s, "loss_first": round(loss0, 4),
            "loss_last": round(float(outs[0][-1]), 4),
            "loss_rel_vs_oracle_max": rel,
            "retraces_steady": int(retraces),
            "moe_dropped_tokens": int(outs[3][-1]),
        }

    base = dict(vocab=256, d_model=128, n_heads=4, seq_len=256,
                layers_per_stage=1, dp=2, pp=2, sp=2, n_micro=4,
                batch=16, lr=0.05, seed=1)
    dense = run_variant("dense", lm3d.LMConfig(**base))
    moe = run_variant("moe", lm3d.LMConfig(
        **base, n_experts=4, capacity_factor=8.0))
    # counted-drops probe: a deliberately tight per-expert capacity
    # must DROP (Switch semantics) and the schedule-total count it
    cfg_drop = lm3d.LMConfig(**base, n_experts=4, capacity_factor=0.25)
    mesh = cfg_drop.mesh()
    stepd = jax.jit(lm3d.make_train_step(cfg_drop, mesh))
    pd = lm3d.place_params(cfg_drop, mesh, lm3d.init_params(cfg_drop))
    wd = lm3d.sample_window(cfg_drop, 0, 1)
    _, _, (_, _, _, dropped) = stepd(
        pd, {}, jnp.asarray(wd[0, ..., :-1]),
        jnp.asarray(wd[0, ..., 1:]), jax.random.PRNGKey(0))
    drops_probe = int(dropped)

    # merged PR 10 cluster timeline artifact (window/compile spans)
    _core.set_flag("FLAGS_trace_dir", "")  # retire + final-flush
    telemetry._shard()
    timeline_out = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools",
        "lm3d_timeline.json")
    try:
        from tools.timeline import merge_shards
        tl = merge_shards(trace_dir, out=timeline_out)
        timeline = {"out": timeline_out, "n_events": tl["n_events"],
                    "n_shards": tl["n_shards"]}
    except Exception as e:  # evidence artifact, never a lane failure
        timeline = {"error": repr(e)[:200]}

    retr = telemetry.REGISTRY.get("executor_retraces_total")
    retraces_total = sum(c.value() for c in retr.children()) \
        if retr else 0.0
    n_micro, pp = base["n_micro"], base["pp"]
    ok = (dense["loss_rel_vs_oracle_max"] < 2e-5
          and moe["loss_rel_vs_oracle_max"] < 2e-5
          and dense["retraces_steady"] == 0
          and moe["retraces_steady"] == 0
          and drops_probe > 0
          and dense["loss_last"] < dense["loss_first"])
    return {"metric": "lm3d_tokens_per_sec",
            "value": dense["tokens_per_sec"], "unit": "tokens/s",
            "vs_baseline": 1.0, "ok": ok, "devices": n_dev,
            "mode": "dp2_pp2_sp2_virtual", "window": k,
            "bubble_frac_analytic": round((pp - 1)
                                          / (n_micro + pp - 1), 4),
            "dense": dense, "moe": moe,
            "moe_drops_probe_tokens": drops_probe,
            "executor_retraces_total": retraces_total,
            "timeline": timeline}


def bench_flash():
    """Pallas flash-attention Mosaic bring-up: compile (no interpret),
    parity vs einsum, block-size sweep. Per-config JSON rows go to
    stderr AND are banked in tools/flash_rows.jsonl — a tunnel window
    can close mid-sweep, and the next run resumes from the banked ok
    rows instead of restarting. The contract line (summary over all
    banked rows) is the return value."""
    import jax
    from tools import flash_smoke
    backend = jax.devices()[0].platform
    on_tpu = backend not in ("cpu",)
    bank = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "flash_rows.jsonl")
    prior, done = [], set()
    if on_tpu and os.path.exists(bank):
        kfp = flash_smoke.kernel_fingerprint()
        for line in open(bank):
            try:
                r = json.loads(line)
            except ValueError:
                continue
            # rows banked under an OLDER kernel neither satisfy nor
            # pollute a resumed sweep — re-measure them
            if r.get("status") == "ok" and r.get("kfp") == kfp:
                prior.append(r)
                done.add(flash_smoke.config_key(r))

    def emit(s):
        print(s, file=sys.stderr)
        if on_tpu:
            with open(bank, "a") as f:
                f.write(s + "\n")

    rows = flash_smoke.sweep(on_tpu=on_tpu, emit=emit, done=done)
    if on_tpu:
        # bank the measured-best blocks so later kernel calls use them
        flash_smoke.write_tuning(
            prior + rows,
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tools", "flash_blocks.json"))
    return flash_smoke.summarize(prior + rows, backend)


CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".xla_cache")


def _cache_entries():
    try:
        return len([f for f in os.listdir(CACHE_DIR)
                    if not f.startswith(".")])
    except OSError:
        return 0


def _enable_compile_cache():
    """Persist XLA executables across bench invocations (the driver runs
    bench.py as a fresh process per round; a cached bert step turns the
    20-40s first compile into a disk load — more of a short tunnel
    window spent measuring). PADDLE_TPU_NO_COMPILE_CACHE=1 disables."""
    if os.environ.get("PADDLE_TPU_NO_COMPILE_CACHE") == "1":
        return
    try:
        from paddle_tpu.inference import enable_compile_cache
        enable_compile_cache(CACHE_DIR)
    except Exception as e:  # cache is an optimization, never a failure
        print(f"compile cache unavailable: {e!r}", file=sys.stderr)


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "bert"
    benches = {"bert": bench_bert_base, "mnist": bench_mnist_mlp,
               "resnet": bench_resnet50, "allreduce": bench_allreduce_dp,
               "wide_deep": bench_wide_deep,
               "wide_deep_1b": bench_wide_deep_1b,
               "wide_deep_1b_syncw": bench_wide_deep_1b_syncw,
               "wide_deep_1b_async": bench_wide_deep_1b_async,
               "wide_deep_1b_ceiling": bench_wide_deep_1b_ceiling,
               "wide_deep_geo": bench_wide_deep_geo,
               "wide_deep_geo_sync": bench_wide_deep_geo_sync,
               "wide_deep_spill": bench_wide_deep_spill,
               "mnist_realdata": bench_mnist_realdata,
               "mnist_guard": bench_mnist_realdata_guard,
               "wide_deep_realdata": bench_wide_deep_realdata,
               "serve_mnist": bench_serving_mnist,
               "serve_wide_deep": bench_serving_wide_deep,
               "serve_http_overload": bench_serve_http_overload,
               "serve_fleet": bench_serve_fleet,
               "stream_ctr": bench_stream_ctr,
               "flash": bench_flash, "longctx": bench_longctx,
               "lm3d": bench_lm3d}
    if which not in benches:
        raise SystemExit(f"unknown bench '{which}'; one of "
                         f"{sorted(benches)}")
    backend = _ensure_backend()
    if which in ("longctx", "lm3d") \
            and (backend in ("cpu", "cpu_fallback")
                 or os.environ.get("JAX_PLATFORMS",
                                   "").startswith("cpu")):
        # the CPU ring lane needs the 8-device virtual mesh BEFORE any
        # backend init in this process (enable_compile_cache below
        # initializes it; after that jax_num_cpu_devices silently no-ops
        # and the lane degrades to ring_sp1_degenerate). Checked AFTER
        # _ensure_backend so the probe-failure path — which sets
        # JAX_PLATFORMS=cpu itself — is covered too; XLA_FLAGS is read at
        # backend init, so setting it here is still in time.
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")
    _enable_compile_cache()
    entries_before = _cache_entries()
    try:
        res = benches[which]()
    except Exception as e:  # the contract is ONE JSON line, always
        traceback.print_exc(file=sys.stderr)
        res = {"metric": f"{which}_error", "value": 0.0, "unit": "error",
               "vs_baseline": 0.0, "error": repr(e)[:500]}
    res.setdefault("backend", backend)
    if PROBE_ERROR:
        res.setdefault("probe_error", PROBE_ERROR)
    # executable-cache reload evidence: a warm second invocation shows
    # entries_before > 0 and compile_s collapsing vs the cold run
    if LAST_COMPILE_S is not None:
        res.setdefault("compile_s", LAST_COMPILE_S)
        res.setdefault("xla_cache_entries_before", entries_before)
        res.setdefault("xla_cache_entries_after", _cache_entries())
    print(json.dumps(res))


if __name__ == "__main__":
    main()
