#!/usr/bin/env python
"""Benchmark entry point — prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline config (BASELINE.md): BERT-base MLM train step, samples/sec/chip,
through the full fluid front end (Program → jitted XLA step with donation,
Pallas flash attention). ``python bench.py mnist`` runs the MLP smoke bench
instead. MFU is reported in the JSON payload against v5e bf16 peak.
"""
import json
import sys
import time

import numpy as np

V5E_PEAK_FLOPS = 197e12  # bf16 peak per chip


def bench_mnist_mlp(batch=256, steps=60, warmup=10):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data("img", shape=[784], dtype="float32")
        label = fluid.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(img, 1024, act="relu")
        h = fluid.layers.fc(h, 1024, act="relu")
        pred = fluid.layers.fc(h, 10, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.Momentum(0.01, momentum=0.9).minimize(loss)
    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(0)
    X = rng.rand(batch, 784).astype("float32")
    Y = rng.randint(0, 10, (batch, 1)).astype("int64")
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(warmup):
            exe.run(main, feed={"img": X, "label": Y}, fetch_list=[loss])
        t0 = time.perf_counter()
        for _ in range(steps):
            out = exe.run(main, feed={"img": X, "label": Y},
                          fetch_list=[loss])
        _ = float(out[0][0])
        dt = time.perf_counter() - t0
    return {"metric": "mnist_mlp_samples_per_sec",
            "value": round(batch * steps / dt, 1), "unit": "samples/s",
            "vs_baseline": 1.0}


def bench_bert_base(batch=256, seq_len=128, steps=20, warmup=5):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core
    from paddle_tpu.models import bert

    core.set_flag("FLAGS_use_bf16_matmul", True)  # MXU-native math
    cfg = bert.bert_base_config()
    main, startup, feeds, fetches = bert.build_bert_pretrain_program(
        cfg, seq_len=seq_len, dropout=0.0, lr=1e-4)
    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(0)
    n_mask = max(1, int(batch * seq_len * 0.15))
    feed = {
        "src_ids": rng.randint(0, cfg["vocab_size"],
                               (batch, seq_len)).astype("int64"),
        "pos_ids": np.tile(np.arange(seq_len), (batch, 1)).astype("int64"),
        "sent_ids": np.zeros((batch, seq_len), "int64"),
        "mask_pos": rng.randint(0, batch * seq_len,
                                (n_mask, 1)).astype("int64"),
        "mask_label": rng.randint(0, cfg["vocab_size"],
                                  (n_mask, 1)).astype("int64"),
    }
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(warmup):
            exe.run(main, feed=feed, fetch_list=fetches)
        t0 = time.perf_counter()
        for _ in range(steps):
            out = exe.run(main, feed=feed, fetch_list=fetches)
        _ = float(out[0][0])
        dt = time.perf_counter() - t0
    sps = batch * steps / dt
    # 6·N·tokens FLOPs estimate (fwd+bwd), N = transformer params (no embed)
    h, L, f = cfg["hidden"], cfg["layers"], cfg["ffn"]
    n_params = L * (4 * h * h + 2 * h * f)
    flops_per_sample = 6 * n_params * seq_len \
        + 12 * L * seq_len * seq_len * h  # attention scores fwd+bwd
    mfu = sps * flops_per_sample / V5E_PEAK_FLOPS
    return {"metric": "bert_base_samples_per_sec_per_chip",
            "value": round(sps, 2), "unit": "samples/s",
            "vs_baseline": 1.0, "mfu_vs_v5e_bf16_peak": round(mfu, 4),
            "batch": batch, "seq_len": seq_len}


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "bert"
    if which == "mnist":
        res = bench_mnist_mlp()
    else:
        res = bench_bert_base()
    print(json.dumps(res))


if __name__ == "__main__":
    main()
