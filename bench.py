#!/usr/bin/env python
"""Benchmark entry point — prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Current flagship bench: MNIST-MLP train-step throughput through the full
fluid front end (Program → traced+jitted XLA step with donation) on the
available accelerator. Upgraded as model families land (BERT-base next —
see BASELINE.md targets).
"""
import json
import sys
import time

import numpy as np


def bench_mnist_mlp(batch=256, steps=60, warmup=10):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data("img", shape=[784], dtype="float32")
        label = fluid.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(img, 1024, act="relu")
        h = fluid.layers.fc(h, 1024, act="relu")
        pred = fluid.layers.fc(h, 10, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.Momentum(0.01, momentum=0.9).minimize(loss)

    exe = fluid.Executor()
    scope = core.Scope()
    rng = np.random.RandomState(0)
    X = rng.rand(batch, 784).astype("float32")
    Y = rng.randint(0, 10, (batch, 1)).astype("int64")
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(warmup):
            exe.run(main, feed={"img": X, "label": Y}, fetch_list=[loss])
        t0 = time.perf_counter()
        for _ in range(steps):
            out = exe.run(main, feed={"img": X, "label": Y},
                          fetch_list=[loss])
        # fetch forces sync
        _ = float(out[0][0])
        dt = time.perf_counter() - t0
    return batch * steps / dt


def main():
    sps = bench_mnist_mlp()
    print(json.dumps({
        "metric": "mnist_mlp_samples_per_sec",
        "value": round(sps, 1),
        "unit": "samples/s",
        "vs_baseline": 1.0,  # reference publishes no numbers (BASELINE.md)
    }))


if __name__ == "__main__":
    main()
