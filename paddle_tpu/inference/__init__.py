"""Inference engine (reference: paddle/fluid/inference/ —
AnalysisPredictor analysis_predictor.cc:288, AnalysisConfig
api/analysis_config.cc, ZeroCopyTensor, C API capi/).

TPU inversion of the reference pipeline: the reference loads a
ProgramDesc, runs ~30 IR fusion passes, optionally captures TensorRT/Lite
subgraphs, then interprets with NaiveExecutor (analysis_predictor.cc:497,
:235). Here the load step jits the whole pruned program once — operator
fusion, layout and memory planning are XLA's; the "TensorRT engine"
becomes the XLA executable itself, and warmup/compile caching replaces
subgraph capture.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["Config", "AnalysisConfig", "Predictor", "AnalysisPredictor",
           "create_predictor", "create_paddle_predictor", "PredictTensor"]


class AnalysisConfig:
    """reference: api/paddle_analysis_config.h. GPU/MKLDNN/TensorRT knobs
    are accepted and recorded; on TPU they map to one compiled executable,
    so they only gate diagnostics."""

    def __init__(self, model_dir: Optional[str] = None,
                 params_file: Optional[str] = None):
        self._model_dir = model_dir
        self._prog_file = None
        self._params_file = params_file
        self._ir_optim = True
        self._use_feed_fetch_ops = False
        self._enable_memory_optim = True
        self._tensorrt = False
        self._device = "tpu"

    # --- model location ---------------------------------------------------
    def set_model(self, model_dir, params_file=None):
        self._model_dir = model_dir
        self._params_file = params_file

    def set_prog_file(self, f):
        self._prog_file = f

    def set_params_file(self, f):
        self._params_file = f

    def model_dir(self):
        return self._model_dir

    # --- toggles (parity surface) ----------------------------------------
    def switch_ir_optim(self, flag=True):
        self._ir_optim = bool(flag)

    def switch_use_feed_fetch_ops(self, flag=True):
        self._use_feed_fetch_ops = bool(flag)

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = bool(flag)

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"  # single accelerator backend on this build

    def disable_gpu(self):
        self._device = "cpu"

    def enable_tensorrt_engine(self, **kwargs):
        """TensorRT subgraphs ≈ the jitted XLA executable; recorded only."""
        self._tensorrt = True

    def tensorrt_engine_enabled(self):
        return self._tensorrt

    def switch_specify_input_names(self, flag=True):
        pass

    def specify_input_name(self):
        return True


Config = AnalysisConfig


class PredictTensor:
    """Zero-copy style handle (reference: ZeroCopyTensor
    inference/api/details/zero_copy_tensor.cc)."""

    def __init__(self, predictor: "AnalysisPredictor", name: str,
                 is_input: bool):
        self._p = predictor
        self.name = name
        self._is_input = is_input

    def copy_from_cpu(self, arr: np.ndarray):
        if not self._is_input:
            raise RuntimeError(f"'{self.name}' is an output tensor")
        self._p._inputs[self.name] = np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        if self._is_input:
            raise RuntimeError(f"'{self.name}' is an input tensor")
        return np.asarray(self._p._outputs[self.name])

    def reshape(self, shape):
        pass  # shapes flow from copy_from_cpu

    @property
    def lod(self):
        return self._p._output_lods.get(self.name, [])


class AnalysisPredictor:
    """reference: analysis_predictor.cc:288 Run / :235 PrepareExecutor."""

    def __init__(self, config: AnalysisConfig):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import core
        self.config = config
        self._exe = fluid.Executor()
        self._scope = core.Scope()
        with fluid.scope_guard(self._scope):
            (self._program, self._feed_names,
             self._fetch_targets) = fluid.io.load_inference_model(
                 config.model_dir(), self._exe,
                 model_filename=config._prog_file,
                 params_filename=config._params_file)
        self._fetch_names = [v.name for v in self._fetch_targets]
        if config._ir_optim:
            # reference AnalysisPredictor::OptimizeInferenceProgram
            # (analysis_predictor.cc:497): canonicalise + fuse with the
            # param scope so conv+bn folding can rewrite weights; the
            # model's fetch targets are protected from fusion.
            from paddle_tpu.fluid.ir import INFERENCE_PASSES, PassManager
            pm = PassManager(INFERENCE_PASSES, scope=self._scope)
            self._program = pm.apply(self._program, for_test=True,
                                     protected=self._fetch_names)
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs: Dict[str, np.ndarray] = {}
        self._output_lods: Dict[str, list] = {}

    # --- interface --------------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names)

    def get_input_handle(self, name) -> PredictTensor:
        if name not in self._feed_names:
            raise KeyError(f"unknown input '{name}'")
        return PredictTensor(self, name, True)

    def get_output_handle(self, name) -> PredictTensor:
        if name not in self._fetch_names:
            raise KeyError(f"unknown output '{name}'")
        return PredictTensor(self, name, False)

    # reference AnalysisPredictor::Run — one call, feeds set beforehand
    def run(self, inputs: Optional[List[np.ndarray]] = None):
        import paddle_tpu.fluid as fluid
        if inputs is not None:
            for name, arr in zip(self._feed_names, inputs):
                self._inputs[name] = np.asarray(arr)
        missing = [n for n in self._feed_names if n not in self._inputs]
        if missing:
            raise KeyError(f"inputs not set: {missing}")
        with fluid.scope_guard(self._scope):
            fetched = self._exe.run(self._program, feed=dict(self._inputs),
                                    fetch_list=self._fetch_names,
                                    return_numpy=False)
        self._outputs = {}
        self._output_lods = {}
        for n, t in zip(self._fetch_names, fetched):
            self._outputs[n] = np.asarray(t.array)
            self._output_lods[n] = t.lod()
        return [self._outputs[n] for n in self._fetch_names]

    def clone(self) -> "AnalysisPredictor":
        return AnalysisPredictor(self.config)


Predictor = AnalysisPredictor


def create_predictor(config: AnalysisConfig) -> AnalysisPredictor:
    return AnalysisPredictor(config)


create_paddle_predictor = create_predictor
