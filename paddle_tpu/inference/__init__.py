"""Inference engine (reference: paddle/fluid/inference/ —
AnalysisPredictor analysis_predictor.cc:288, AnalysisConfig
api/analysis_config.cc, ZeroCopyTensor, C API capi/).

TPU inversion of the reference pipeline: the reference loads a
ProgramDesc, runs ~30 IR fusion passes, optionally captures TensorRT/Lite
subgraphs, then interprets with NaiveExecutor (analysis_predictor.cc:497,
:235). Here the load step jits the whole pruned program once — operator
fusion, layout and memory planning are XLA's; the "TensorRT engine"
becomes the XLA executable itself, and warmup/compile caching replaces
subgraph capture.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["Config", "AnalysisConfig", "Predictor", "AnalysisPredictor",
           "create_predictor", "create_paddle_predictor", "PredictTensor",
           "PassStrategy", "PredictorPool", "enable_compile_cache"]


_COMPILE_CACHE_DIR = None


def enable_compile_cache(cache_dir: str):
    """Point XLA's persistent compilation cache at ``cache_dir`` — the
    TPU-native role of the reference's serialized TensorRT engine cache
    (analysis_config.cc SetOptimCacheDir + tensorrt/ engine
    serialization): a SECOND process loading the same model skips the
    XLA compile entirely (the executable is loaded from disk, keyed by
    HLO hash). Process-global; idempotent per dir. Every compile in the
    process benefits (training steps included), which matches how the
    engine cache removes the reference's cold-start."""
    global _COMPILE_CACHE_DIR
    import os
    import jax
    cache_dir = os.path.abspath(cache_dir)
    if _COMPILE_CACHE_DIR == cache_dir:
        return
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache every executable: the defaults skip small/fast compiles,
    # which is exactly the cold-start this exists to remove
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    # LRU-bound the directory: programs change every commit and orphaned
    # HLO-keyed entries would otherwise accumulate forever
    try:
        jax.config.update("jax_compilation_cache_max_size",
                          4 * 1024 * 1024 * 1024)
    except Exception:
        pass  # older jax: no eviction knob
    # env too, so SUBPROCESS workers (multi-process benches/predictor
    # pools, the backend probe) inherit the cache
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
    os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "-1"
    # jax initializes the cache module LAZILY at the first compile and
    # never re-reads the config after that — enabling the cache in a
    # process that already compiled anything (a predictor created after
    # model-building ran, the serving cold-start shape) was a silent
    # no-op: zero entries ever written. Force a re-init so the NEXT
    # compile picks the directory up.
    try:
        from jax._src import compilation_cache as _cc
        if getattr(_cc, "is_initialized", None) and _cc.is_initialized():
            _cc.reset_cache()
    except Exception:
        pass  # older/newer jax: first-compile init reads the config
    _COMPILE_CACHE_DIR = cache_dir


class AnalysisConfig:
    """reference: api/paddle_analysis_config.h. GPU/MKLDNN/TensorRT knobs
    are accepted and recorded; on TPU they map to one compiled executable,
    so they only gate diagnostics."""

    def __init__(self, model_dir: Optional[str] = None,
                 params_file: Optional[str] = None):
        self._model_dir = model_dir
        self._prog_file = None
        self._params_file = params_file
        self._prog_bytes = None
        self._params_bytes = None
        self._ir_optim = True
        self._use_feed_fetch_ops = False
        self._enable_memory_optim = True
        self._tensorrt = False
        self._device = "tpu"
        self._bf16 = False
        self._profile = False
        self._pass_builder = None
        self._optim_cache_dir = None

    # --- model location ---------------------------------------------------
    def set_model(self, model_dir, params_file=None):
        self._model_dir = model_dir
        self._params_file = params_file

    def set_model_buffer(self, prog_bytes: bytes, params_bytes: bytes):
        """Serve a model from in-memory byte buffers — the reference's
        SetModelBuffer path (analysis_config.cc SetModelBuffer), used by
        services that ship models over the wire. The bytes are the
        standard serialized ProgramDesc + save_combine stream."""
        self._prog_bytes = bytes(prog_bytes)
        self._params_bytes = bytes(params_bytes)

    def model_from_memory(self) -> bool:
        return self._prog_bytes is not None

    def set_optim_cache_dir(self, cache_dir: str):
        """reference analysis_config.cc SetOptimCacheDir — on TPU this
        activates the persistent XLA executable cache (see
        enable_compile_cache): later processes loading this model skip
        the compile."""
        self._optim_cache_dir = cache_dir

    def set_prog_file(self, f):
        self._prog_file = f

    def set_params_file(self, f):
        self._params_file = f

    def model_dir(self):
        return self._model_dir

    # --- toggles (parity surface) ----------------------------------------
    def switch_ir_optim(self, flag=True):
        self._ir_optim = bool(flag)

    def switch_use_feed_fetch_ops(self, flag=True):
        self._use_feed_fetch_ops = bool(flag)

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = bool(flag)

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"  # single accelerator backend on this build

    def disable_gpu(self):
        self._device = "cpu"

    def enable_tensorrt_engine(self, **kwargs):
        """TensorRT subgraphs ≈ the jitted XLA executable; recorded only."""
        self._tensorrt = True

    def tensorrt_engine_enabled(self):
        return self._tensorrt

    def switch_specify_input_names(self, flag=True):
        pass

    def specify_input_name(self):
        return True

    def enable_bf16(self):
        """bf16 inference (the reference's enable_mkldnn_bfloat16 /
        TRT-fp16 role): matmuls/convs run MXU-native bf16."""
        self._bf16 = True

    def bf16_enabled(self):
        return self._bf16

    def enable_profile(self):
        self._profile = True

    def pass_builder(self) -> "PassStrategy":
        """Customizable IR pass pipeline (reference: PaddlePassBuilder,
        api/paddle_pass_builder.h) — mutations here change which passes
        the predictor applies at load."""
        if self._pass_builder is None:
            from paddle_tpu.fluid.ir import INFERENCE_PASSES
            self._pass_builder = PassStrategy(list(INFERENCE_PASSES))
        return self._pass_builder


class PassStrategy:
    """reference: paddle_pass_builder.h PaddlePassBuilder."""

    def __init__(self, passes: List[str]):
        self._passes = list(passes)

    def all_passes(self) -> List[str]:
        return list(self._passes)

    def append_pass(self, name: str):
        from paddle_tpu.fluid.ir import get_pass
        get_pass(name)  # validate it exists
        self._passes.append(name)

    def insert_pass(self, idx: int, name: str):
        from paddle_tpu.fluid.ir import get_pass
        get_pass(name)
        self._passes.insert(idx, name)

    def delete_pass(self, name: str):
        self._passes = [p for p in self._passes if p != name]


Config = AnalysisConfig


class PredictTensor:
    """Zero-copy style handle (reference: ZeroCopyTensor
    inference/api/details/zero_copy_tensor.cc)."""

    def __init__(self, predictor: "AnalysisPredictor", name: str,
                 is_input: bool):
        self._p = predictor
        self.name = name
        self._is_input = is_input

    def copy_from_cpu(self, arr: np.ndarray):
        if not self._is_input:
            raise RuntimeError(f"'{self.name}' is an output tensor")
        self._p._inputs[self.name] = np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        if self._is_input:
            raise RuntimeError(f"'{self.name}' is an input tensor")
        return np.asarray(self._p._outputs[self.name])

    def reshape(self, shape):
        pass  # shapes flow from copy_from_cpu

    @property
    def lod(self):
        return self._p._output_lods.get(self.name, [])


class AnalysisPredictor:
    """reference: analysis_predictor.cc:288 Run / :235 PrepareExecutor."""

    def __init__(self, config: AnalysisConfig, _shared=None):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import core
        self.config = config
        if config._optim_cache_dir:
            enable_compile_cache(config._optim_cache_dir)
        self._exe = fluid.Executor()
        if _shared is not None:
            # weight-sharing clone (reference AnalysisPredictor::Clone
            # shares the params scope across predictors serving threads)
            (self._scope, self._program, self._feed_names,
             self._fetch_names) = _shared
        elif config.model_from_memory():
            self._scope = core.Scope()
            self._program, self._feed_names, self._fetch_names = \
                self._load_from_memory(config)
            self._optimize(config)
        else:
            self._scope = core.Scope()
            with fluid.scope_guard(self._scope):
                (self._program, self._feed_names,
                 fetch_targets) = fluid.io.load_inference_model(
                     config.model_dir(), self._exe,
                     model_filename=config._prog_file,
                     params_filename=config._params_file)
            self._fetch_names = [v.name for v in fetch_targets]
            self._optimize(config)
        if config._bf16:
            core.set_flag("FLAGS_use_bf16_matmul", True)
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs: Dict[str, np.ndarray] = {}
        self._output_lods: Dict[str, list] = {}

    def _load_from_memory(self, config):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import core
        from paddle_tpu.fluid.framework import Program
        from paddle_tpu.fluid.io import _deserialize_lod_tensor_stream
        prog = Program.parse_from_string(config._prog_bytes)
        block = prog.global_block()
        persistables = sorted(
            v.name for v in block.vars.values()
            if v.persistable and v.name not in ("feed", "fetch"))
        tensors = _deserialize_lod_tensor_stream(config._params_bytes,
                                                 len(persistables))
        for name, t in zip(persistables, tensors):
            self._scope.var(name).set_value(t)
        feed_names = [v.name for v in block.vars.values()
                      if getattr(v, "need_check_feed", False)
                      or getattr(v, "is_data", False)]
        written, written_order = set(), []
        for op in block.ops:
            for n in op.output_arg_names:
                if n not in written:
                    written.add(n)
                    written_order.append(n)
        consumed = set()
        for op in block.ops:
            consumed.update(op.input_arg_names)
        # program order, not set order: output position must be stable
        # across processes (clients index Predictor.run results)
        fetch_names = [n for n in written_order
                       if n not in consumed
                       and block.vars.get(n) is not None
                       and not block.vars[n].persistable]
        return prog, feed_names, fetch_names

    def _optimize(self, config):
        if not config._ir_optim:
            return
        # reference AnalysisPredictor::OptimizeInferenceProgram
        # (analysis_predictor.cc:497): canonicalise + fuse with the
        # param scope so conv+bn folding can rewrite weights; the
        # model's fetch targets are protected from fusion. A customized
        # config.pass_builder() overrides the canonical pipeline.
        from paddle_tpu.fluid.ir import INFERENCE_PASSES, PassManager
        names = (config._pass_builder.all_passes()
                 if config._pass_builder is not None
                 else INFERENCE_PASSES)
        pm = PassManager(names, scope=self._scope)
        self._program = pm.apply(self._program, for_test=True,
                                 protected=self._fetch_names)

    # --- interface --------------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names)

    def get_input_handle(self, name) -> PredictTensor:
        if name not in self._feed_names:
            raise KeyError(f"unknown input '{name}'")
        return PredictTensor(self, name, True)

    def get_output_handle(self, name) -> PredictTensor:
        if name not in self._fetch_names:
            raise KeyError(f"unknown output '{name}'")
        return PredictTensor(self, name, False)

    # reference AnalysisPredictor::Run — one call, feeds set beforehand
    def run(self, inputs: Optional[List[np.ndarray]] = None):
        import paddle_tpu.fluid as fluid
        if inputs is not None:
            for name, arr in zip(self._feed_names, inputs):
                self._inputs[name] = np.asarray(arr)
        missing = [n for n in self._feed_names if n not in self._inputs]
        if missing:
            raise KeyError(f"inputs not set: {missing}")
        with fluid.scope_guard(self._scope):
            fetched = self._exe.run(self._program, feed=dict(self._inputs),
                                    fetch_list=self._fetch_names,
                                    return_numpy=False)
        self._outputs = {}
        self._output_lods = {}
        for n, t in zip(self._fetch_names, fetched):
            self._outputs[n] = np.asarray(t.array)
            self._output_lods[n] = t.lod()
        return [self._outputs[n] for n in self._fetch_names]

    def get_input_tensor_shape(self) -> Dict[str, List[int]]:
        block = self._program.global_block()
        return {n: list(getattr(block.vars.get(n), "shape", ()) or ())
                for n in self._feed_names}

    def try_shrink_memory(self):
        """Drop cached executables/feed copies (reference
        TryShrinkMemory); the next run re-jits."""
        self._exe._compiled_cache.clear()
        if hasattr(self._exe, "_feed_cache"):
            self._exe._feed_cache.clear()

    def clone(self, share_weights: bool = True) -> "AnalysisPredictor":
        """Reference Clone(): the clone serves from the SAME params scope
        (zero weight duplication) with its own feed/fetch state."""
        if share_weights:
            return AnalysisPredictor(
                self.config, _shared=(self._scope, self._program,
                                      list(self._feed_names),
                                      list(self._fetch_names)))
        return AnalysisPredictor(self.config)


Predictor = AnalysisPredictor


class PredictorPool:
    """reference: api/paddle_inference_api.h PredictorPool — one loaded
    predictor cloned per serving slot, weights shared."""

    def __init__(self, config: AnalysisConfig, size: int = 1):
        first = AnalysisPredictor(config)
        self._preds = [first] + [first.clone() for _ in range(size - 1)]

    def retrieve(self, idx: int) -> AnalysisPredictor:
        return self._preds[idx]

    def size(self) -> int:
        return len(self._preds)


def create_predictor(config: AnalysisConfig) -> AnalysisPredictor:
    return AnalysisPredictor(config)


create_paddle_predictor = create_predictor
