"""paddle.distribution 2.0-preview (reference: python/paddle/
distribution.py — Uniform/Normal/Categorical over the fluid
distributions)."""
from __future__ import annotations

from .fluid.layers.distributions import (  # noqa: F401
    Distribution, Uniform, Normal, Categorical, MultivariateNormalDiag)

__all__ = ["Distribution", "Uniform", "Normal", "Categorical",
           "MultivariateNormalDiag"]
