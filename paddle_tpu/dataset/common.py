"""Shared dataset plumbing (reference: python/paddle/dataset/common.py —
DATA_HOME, download with md5 check, cluster file splitting)."""
from __future__ import annotations

import glob
import hashlib
import os
import pickle

__all__ = ["DATA_HOME", "download", "md5file", "split", "cluster_files_reader"]

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def _ensure_dir(path):
    os.makedirs(path, exist_ok=True)
    return path


def md5file(fname: str) -> str:
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url: str, module_name: str, md5sum: str | None = None,
             save_name: str | None = None) -> str:
    """Resolve a dataset file path under DATA_HOME. This build runs with no
    network egress: if the file was pre-placed (or cached by an earlier
    environment) it is used — and md5-verified when a sum is given;
    otherwise FileNotFoundError tells the caller to fall back to the
    synthetic reader."""
    dirname = _ensure_dir(os.path.join(DATA_HOME, module_name))
    filename = os.path.join(
        dirname, save_name if save_name else url.split("/")[-1])
    if os.path.exists(filename):
        if md5sum and md5file(filename) != md5sum:
            raise IOError(f"{filename} exists but fails its md5 check")
        return filename
    raise FileNotFoundError(
        f"dataset file {filename} not present and downloads are disabled "
        f"(no egress); place the file there or use the synthetic reader")


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    """Split a reader's samples into pickled chunk files (reference
    common.py split)."""
    dumper = dumper or (lambda obj, f: pickle.dump(obj, f))
    lines = []
    idx = 0
    for sample in reader():
        lines.append(sample)
        if len(lines) == line_count:
            with open(suffix % idx, "wb") as f:
                dumper(lines, f)
            lines = []
            idx += 1
    if lines:
        with open(suffix % idx, "wb") as f:
            dumper(lines, f)


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """Read this trainer's shard of chunk files (reference common.py
    cluster_files_reader)."""
    loader = loader or (lambda f: pickle.load(f))

    def reader():
        flist = sorted(glob.glob(files_pattern))
        for i, fn in enumerate(flist):
            if i % trainer_count == trainer_id:
                with open(fn, "rb") as f:
                    for sample in loader(f):
                        yield sample
    return reader
