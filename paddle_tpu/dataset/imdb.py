"""IMDB sentiment readers (reference: python/paddle/dataset/imdb.py —
word_dict() vocabulary, train/test readers of (word_id_list, 0/1 label))."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["word_dict", "train", "test", "SYNTHETIC"]

SYNTHETIC = True

_VOCAB = 5147  # synthetic vocab size (real imdb cutoff-150 dict is ~5147)

_POS = list(range(10, 60))      # "positive" token ids in the synthetic set
_NEG = list(range(60, 110))


def word_dict():
    """token -> id map. Synthetic fallback: ids name themselves."""
    try:
        path = common.download("", "imdb", save_name="aclImdb_v1.tar.gz")
    except FileNotFoundError:
        return {("w%d" % i): i for i in range(_VOCAB)}
    raise NotImplementedError(
        "real aclImdb parsing requires the tarball layout; this build ships "
        "the synthetic reader")


def _synthetic(n, seed):
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            label = int(r.randint(0, 2))
            length = int(r.randint(8, 120))
            base = r.randint(0, _VOCAB, size=length)
            marker = r.choice(_POS if label == 0 else _NEG,
                              size=max(2, length // 6))
            ids = np.concatenate([base, marker])
            r.shuffle(ids)
            yield (list(map(int, ids)), label)
    return reader


def train(word_idx=None):
    return _synthetic(2000, seed=0)


def test(word_idx=None):
    return _synthetic(400, seed=1)
