"""NLTK movie-reviews sentiment readers (reference:
python/paddle/dataset/sentiment.py — get_word_dict(), train/test readers of
(word_id_list, 0/1)). Shares the synthetic corpus shape with imdb but a
smaller vocabulary, like the original."""
from __future__ import annotations

import numpy as np

__all__ = ["get_word_dict", "train", "test", "SYNTHETIC"]

SYNTHETIC = True

_VOCAB = 2000
_POS = list(range(5, 45))
_NEG = list(range(45, 85))


def get_word_dict():
    return {("w%d" % i): i for i in range(_VOCAB)}


def _synthetic(n, seed):
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            label = int(r.randint(0, 2))
            length = int(r.randint(5, 60))
            base = r.randint(0, _VOCAB, size=length)
            marker = r.choice(_POS if label == 0 else _NEG,
                              size=max(2, length // 5))
            ids = np.concatenate([base, marker])
            r.shuffle(ids)
            yield (list(map(int, ids)), label)
    return reader


def train():
    return _synthetic(1600, seed=0)


def test():
    return _synthetic(400, seed=1)
