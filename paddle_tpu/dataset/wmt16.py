"""WMT16 en-de readers (reference: python/paddle/dataset/wmt16.py — BPE
vocab, samples (src_ids, trg_ids_next, trg_ids) with <s>/<e>/<unk>)."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "validation", "get_dict", "SYNTHETIC"]

SYNTHETIC = True

_SRC_VOCAB = 2000
_TRG_VOCAB = 2000
_BOS, _EOS, _UNK = 0, 1, 2


def get_dict(lang, dict_size, reverse=False):
    size = _SRC_VOCAB if lang == "en" else _TRG_VOCAB
    size = min(size, dict_size)
    d = {"<s>": _BOS, "<e>": _EOS, "<unk>": _UNK}
    d.update({("%s_tok%d" % (lang, i)): i for i in range(3, size)})
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def _synthetic(n, seed, src_dict_size, trg_dict_size):
    sv = min(_SRC_VOCAB, src_dict_size)
    tv = min(_TRG_VOCAB, trg_dict_size)

    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            length = int(r.randint(3, 30))
            src = r.randint(3, sv, size=length)
            # the synthetic "translation": a deterministic token map with
            # occasional reordering — learnable structure for seq2seq
            trg = (src * 7 + 3) % (tv - 3) + 3
            if length > 4:
                trg = np.concatenate([trg[1:3], trg[:1], trg[3:]])
            src_ids = list(map(int, src))
            trg_full = [_BOS] + list(map(int, trg)) + [_EOS]
            yield (src_ids, trg_full[1:], trg_full[:-1])
    return reader


def train(src_dict_size=2000, trg_dict_size=2000, src_lang="en"):
    return _synthetic(4000, 0, src_dict_size, trg_dict_size)


def test(src_dict_size=2000, trg_dict_size=2000, src_lang="en"):
    return _synthetic(400, 1, src_dict_size, trg_dict_size)


def validation(src_dict_size=2000, trg_dict_size=2000, src_lang="en"):
    return _synthetic(400, 2, src_dict_size, trg_dict_size)
