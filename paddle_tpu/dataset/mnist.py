"""MNIST readers (reference: python/paddle/dataset/mnist.py — idx-format
parsing, samples (img[784] float32 in [-1,1], label int)). Falls back to a
deterministic synthetic set when the idx files aren't cached locally."""
from __future__ import annotations

import gzip
import struct

import numpy as np

from . import common

__all__ = ["train", "test", "SYNTHETIC"]

SYNTHETIC = True  # flipped off when real idx files are found

_TRAIN_IMAGES = "train-images-idx3-ubyte.gz"
_TRAIN_LABELS = "train-labels-idx1-ubyte.gz"
_TEST_IMAGES = "t10k-images-idx3-ubyte.gz"
_TEST_LABELS = "t10k-labels-idx1-ubyte.gz"


def _parse_idx(img_path, label_path, buffer_size=100):
    with gzip.open(img_path, "rb") as fi, gzip.open(label_path, "rb") as fl:
        magic, n, rows, cols = struct.unpack(">IIII", fi.read(16))
        lmagic, ln = struct.unpack(">II", fl.read(8))
        for _ in range(n):
            img = np.frombuffer(fi.read(rows * cols), np.uint8)
            label = struct.unpack("B", fl.read(1))[0]
            yield (img.astype("float32") / 127.5 - 1.0, int(label))


def _synthetic(n, seed):
    """Digits drawn as coarse template patterns + noise — learnable by the
    book models, deterministic across runs."""
    trng = np.random.RandomState(1234)  # templates shared by train/test
    tmpl = trng.rand(10, 784).astype("float32")

    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            y = int(r.randint(0, 10))
            x = tmpl[y] + 0.35 * r.randn(784).astype("float32")
            yield (np.clip(x, 0, 1).astype("float32") * 2.0 - 1.0, y)
    return reader


def _reader(images, labels, n_synth, seed):
    global SYNTHETIC
    try:
        img = common.download("", "mnist", save_name=images)
        lab = common.download("", "mnist", save_name=labels)
        SYNTHETIC = False

        def reader():
            yield from _parse_idx(img, lab)
        return reader
    except FileNotFoundError:
        return _synthetic(n_synth, seed)


def train():
    return _reader(_TRAIN_IMAGES, _TRAIN_LABELS, n_synth=8192, seed=0)


def test():
    return _reader(_TEST_IMAGES, _TEST_LABELS, n_synth=1024, seed=1)
