"""UCI housing readers (reference: python/paddle/dataset/uci_housing.py —
13 normalized features + price; the book's fit_a_line dataset)."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "feature_names", "SYNTHETIC"]

SYNTHETIC = True

feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS",
                 "RAD", "TAX", "PTRATIO", "B", "LSTAT"]


def _load_real():
    path = common.download("", "uci_housing", save_name="housing.data")
    data = np.loadtxt(path)
    feats = data[:, :13]
    feats = (feats - feats.mean(0)) / (feats.std(0) + 1e-8)
    return np.concatenate([feats, data[:, 13:14]], axis=1)


def _load_synthetic():
    """y = w·x + noise over normalized features — same shapes, learnable."""
    rng = np.random.RandomState(7)
    n = 506
    x = rng.randn(n, 13).astype("float32")
    w = rng.randn(13).astype("float32") * 2.0
    y = (x @ w + 22.5 + rng.randn(n).astype("float32")).reshape(-1, 1)
    return np.concatenate([x, y], axis=1)


def _data():
    global SYNTHETIC
    try:
        d = _load_real()
        SYNTHETIC = False
        return d
    except FileNotFoundError:
        return _load_synthetic()


def train():
    def reader():
        d = _data()
        split = int(len(d) * 0.8)
        for row in d[:split]:
            yield (row[:13].astype("float32"),
                   row[13:14].astype("float32"))
    return reader


def test():
    def reader():
        d = _data()
        split = int(len(d) * 0.8)
        for row in d[split:]:
            yield (row[:13].astype("float32"),
                   row[13:14].astype("float32"))
    return reader
