"""CoNLL-2005 SRL readers (reference: python/paddle/dataset/conll05.py —
get_dict() returning (word, verb, label) dicts and a test() reader of
8-slot samples: word_ids, ctx_n2/n1/0/p1/p2 ids, mark_ids, label_ids)."""
from __future__ import annotations

import numpy as np

__all__ = ["get_dict", "get_embedding", "test", "SYNTHETIC"]

SYNTHETIC = True

_WORDS = 1200
_VERBS = 60
_LABELS = 30  # BIO-style tag inventory size


def get_dict():
    word_dict = {("w%d" % i): i for i in range(_WORDS)}
    verb_dict = {("v%d" % i): i for i in range(_VERBS)}
    label_dict = {("L%d" % i): i for i in range(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Deterministic stand-in for the pretrained emb32 table."""
    return np.random.RandomState(77).rand(_WORDS, 32).astype("float32")


def _synthetic(n, seed):
    def reader2():
        r = np.random.RandomState(seed)
        for _ in range(n):
            L = int(r.randint(4, 20))
            words = r.randint(0, _WORDS, L)
            verb_pos = int(r.randint(0, L))
            mark = np.zeros(L, np.int64)
            mark[verb_pos] = 1
            labels = (words + np.abs(np.arange(L) - verb_pos)) % _LABELS
            ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2 = (
                np.roll(words, 2), np.roll(words, 1), words,
                np.roll(words, -1), np.roll(words, -2))
            yield (list(map(int, words)), list(map(int, ctx_n2)),
                   list(map(int, ctx_n1)), list(map(int, ctx_0)),
                   list(map(int, ctx_p1)), list(map(int, ctx_p2)),
                   list(map(int, mark)), list(map(int, labels)))
    return reader2


def test():
    return _synthetic(400, seed=0)
