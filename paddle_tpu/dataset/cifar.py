"""CIFAR readers (reference: python/paddle/dataset/cifar.py — samples
(img[3072] float32 in [0,1], label int); cifar-10 and cifar-100)."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train10", "test10", "train100", "test100", "SYNTHETIC"]

SYNTHETIC = True


def _synthetic(n, classes, seed):
    trng = np.random.RandomState(4321)
    tmpl = trng.rand(classes, 3072).astype("float32")

    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            y = int(r.randint(0, classes))
            x = np.clip(tmpl[y] + 0.25 * r.randn(3072), 0, 1)
            yield (x.astype("float32"), y)
    return reader


def _reader(tarname, keys, classes, n_synth, seed):
    global SYNTHETIC
    try:
        import pickle
        import tarfile
        path = common.download("", "cifar", save_name=tarname)
        SYNTHETIC = False

        def reader():
            with tarfile.open(path) as tf:
                for m in tf.getmembers():
                    if any(k in m.name for k in keys):
                        batch = pickle.load(tf.extractfile(m),
                                            encoding="latin1")
                        labels = batch.get("labels") or \
                            batch.get("fine_labels")
                        for img, lab in zip(batch["data"], labels):
                            yield (img.astype("float32") / 255.0, int(lab))
        return reader
    except FileNotFoundError:
        return _synthetic(n_synth, classes, seed)


def train10():
    return _reader("cifar-10-python.tar.gz", ["data_batch"], 10, 4096, 0)


def test10():
    return _reader("cifar-10-python.tar.gz", ["test_batch"], 10, 512, 1)


def train100():
    return _reader("cifar-100-python.tar.gz", ["train"], 100, 4096, 2)


def test100():
    return _reader("cifar-100-python.tar.gz", ["test"], 100, 512, 3)
