"""paddle.dataset — dataset readers (reference: python/paddle/dataset/ —
mnist, cifar, uci_housing, imdb, movielens, wmt16, flowers, common).

This environment has no network egress, so each module first looks for the
real data in ``common.DATA_HOME`` and otherwise serves a DETERMINISTIC
SYNTHETIC stand-in with the exact sample shapes/dtypes/vocab contracts of
the original (clearly marked via ``<module>.SYNTHETIC``). The reader
protocol — zero-arg callables yielding samples — matches the reference, so
book models and tests run unchanged either way."""
from . import common  # noqa: F401
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import uci_housing  # noqa: F401
from . import imdb  # noqa: F401
from . import movielens  # noqa: F401
from . import wmt16  # noqa: F401
from . import flowers  # noqa: F401
from . import conll05  # noqa: F401
from . import sentiment  # noqa: F401
from . import wmt14  # noqa: F401
from . import voc2012  # noqa: F401

__all__ = ["common", "mnist", "cifar", "uci_housing", "imdb", "movielens",
           "wmt16", "flowers", "conll05", "sentiment", "wmt14", "voc2012"]
