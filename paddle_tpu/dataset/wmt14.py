"""WMT14 fr-en readers (reference: python/paddle/dataset/wmt14.py — samples
(src_ids, trg_ids, trg_ids_next) with <s>=0 <e>=1 <unk>=2). Same synthetic
mapping machinery as wmt16 with the wmt14 sample ordering."""
from __future__ import annotations

from . import wmt16 as _w16

__all__ = ["train", "test", "SYNTHETIC"]

SYNTHETIC = True


def _reorder(reader):
    # wmt16 yields (src, trg_next, trg_in); wmt14's contract is
    # (src, trg, trg_next) where trg includes <s> and trg_next shifts
    def r():
        for src, trg_next, trg_in in reader():
            yield (src, trg_in, trg_next)
    return r


def train(dict_size=2000):
    return _reorder(_w16.train(dict_size, dict_size))


def test(dict_size=2000):
    return _reorder(_w16.test(dict_size, dict_size))
