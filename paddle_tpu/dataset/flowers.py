"""Oxford-102 flowers readers (reference: python/paddle/dataset/flowers.py
— samples (img[3,224,224] float32, label int in [0,102)))."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "valid", "SYNTHETIC"]

SYNTHETIC = True

_CLASSES = 102
_SIZE = 224


def _synthetic(n, seed):
    trng = np.random.RandomState(555)
    # coarse 8x8 color templates upsampled — cheap but class-separable
    tmpl = trng.rand(_CLASSES, 3, 8, 8).astype("float32")

    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            y = int(r.randint(0, _CLASSES))
            coarse = tmpl[y] + 0.2 * r.randn(3, 8, 8).astype("float32")
            img = np.kron(coarse, np.ones((1, _SIZE // 8, _SIZE // 8),
                                          "float32"))
            yield (np.clip(img, 0, 1).reshape(3, _SIZE, _SIZE), y)
    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _synthetic(512, seed=0)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _synthetic(128, seed=1)


def valid(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _synthetic(128, seed=2)
