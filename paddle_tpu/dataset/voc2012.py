"""PASCAL VOC2012 segmentation readers (reference:
python/paddle/dataset/voc2012.py — samples (img[3,H,W] float32, seg
label[H,W] int32 with 21 classes))."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "val", "SYNTHETIC"]

SYNTHETIC = True

_CLASSES = 21
_SIZE = 64  # synthetic stand-in keeps test memory small


def _synthetic(n, seed):
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            # blocky class regions + color correlated with class
            coarse = r.randint(0, _CLASSES, (4, 4))
            seg = np.kron(coarse, np.ones((_SIZE // 4, _SIZE // 4),
                                          np.int32))
            img = np.stack([(seg * 37 % 255), (seg * 91 % 255),
                            (seg * 53 % 255)]).astype("float32") / 255.0
            img = img + 0.05 * r.randn(3, _SIZE, _SIZE).astype("float32")
            yield (np.clip(img, 0, 1), seg.astype("int32"))
    return reader


def train():
    return _synthetic(256, seed=0)


def test():
    return _synthetic(64, seed=1)


def val():
    return _synthetic(64, seed=2)
