"""MovieLens-1M readers (reference: python/paddle/dataset/movielens.py —
samples [user_id, gender, age, job, movie_id, category_ids, title_ids,
rating]; the book's recommender dataset)."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "max_user_id", "max_movie_id", "max_job_id",
           "age_table", "movie_categories", "SYNTHETIC"]

SYNTHETIC = True

_N_USERS = 600
_N_MOVIES = 400
_N_JOBS = 21
_N_CATES = 18
_TITLE_VOCAB = 1000
age_table = [1, 18, 25, 35, 45, 50, 56]


def max_user_id():
    return _N_USERS


def max_movie_id():
    return _N_MOVIES


def max_job_id():
    return _N_JOBS - 1


def movie_categories():
    return {("cat%d" % i): i for i in range(_N_CATES)}


def _synthetic(n, seed):
    trng = np.random.RandomState(99)
    user_bias = trng.randn(_N_USERS + 1) * 0.5
    movie_bias = trng.randn(_N_MOVIES + 1) * 0.8

    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            uid = int(r.randint(1, _N_USERS + 1))
            mid = int(r.randint(1, _N_MOVIES + 1))
            gender = int(r.randint(0, 2))
            age = int(r.randint(0, len(age_table)))
            job = int(r.randint(0, _N_JOBS))
            cats = list(map(int, r.randint(0, _N_CATES,
                                           size=r.randint(1, 4))))
            title = list(map(int, r.randint(0, _TITLE_VOCAB,
                                            size=r.randint(1, 6))))
            score = 3.0 + user_bias[uid] + movie_bias[mid] + 0.3 * r.randn()
            rating = float(min(5.0, max(1.0, round(score))))
            yield [uid, gender, age, job, mid, cats, title, rating]
    return reader


def train():
    return _synthetic(6000, seed=0)


def test():
    return _synthetic(1200, seed=1)
