"""paddle.reader — functional reader decorators (reference:
python/paddle/reader/decorator.py — map_readers, buffered, compose, chain,
shuffle, firstn, xmap_readers, cache, multiprocess_reader). A "reader" is a
zero-arg callable returning an iterable of samples; decorators wrap readers
into new readers. These run on the host feeding the device step, so plain
Python + threads is the right tool."""
from .decorator import (buffered, cache, chain, compose, firstn, map_readers,
                        multiprocess_reader, shuffle, xmap_readers)

__all__ = ["buffered", "cache", "chain", "compose", "firstn", "map_readers",
           "multiprocess_reader", "shuffle", "xmap_readers"]
