"""Reader decorators (reference: python/paddle/reader/decorator.py)."""
from __future__ import annotations

import itertools
import queue
import random
import threading

__all__ = ["map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "xmap_readers", "cache", "multiprocess_reader"]


def map_readers(func, *readers):
    """Yield func applied across samples of several readers in lockstep
    (reference decorator.py map_readers)."""
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)
    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer (reference decorator.py shuffle)."""
    def shuffled(reader_inner=reader, buf_size_inner=buf_size):
        buf = []
        for e in reader_inner():
            buf.append(e)
            if len(buf) >= buf_size_inner:
                random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            random.shuffle(buf)
            yield from buf
    return shuffled


def chain(*readers):
    """Concatenate readers back to back (reference decorator.py chain)."""
    def reader():
        for r in readers:
            yield from r()
    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """Zip several readers into flat tuples: (a, b1, b2) from readers
    yielding a and (b1, b2) (reference decorator.py compose)."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(map(make_tuple, outputs), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum(map(make_tuple, outputs), ())
    return reader


def buffered(reader, size):
    """Prefetch up to ``size`` samples in a background thread (reference
    decorator.py buffered)."""
    class _End:
        pass

    def data_reader():
        r = reader()
        q: "queue.Queue" = queue.Queue(maxsize=size)

        def feed():
            try:
                for d in r:
                    q.put(d)
            finally:
                q.put(_End)
        t = threading.Thread(target=feed, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e
    return data_reader


def firstn(reader, n):
    """Keep only the first n samples (reference decorator.py firstn)."""
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item
    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over samples with worker threads (reference
    decorator.py xmap_readers; thread-based — mappers are IO/numpy-bound
    on the host)."""
    END = object()

    def data_reader():
        in_q: "queue.Queue" = queue.Queue(buffer_size)
        out_q: "queue.Queue" = queue.Queue(buffer_size)

        def feeder():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(END)

        def worker():
            while True:
                item = in_q.get()
                if item is END:
                    out_q.put(END)
                    return
                i, sample = item
                out_q.put((i, mapper(sample)))

        threading.Thread(target=feeder, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=worker, daemon=True).start()

        finished = 0
        if not order:
            while finished < process_num:
                item = out_q.get()
                if item is END:
                    finished += 1
                else:
                    yield item[1]
        else:
            next_idx = 0
            held = {}
            while finished < process_num or held:
                if next_idx in held:
                    yield held.pop(next_idx)
                    next_idx += 1
                    continue
                if finished == process_num:
                    # drain remaining in order
                    for k in sorted(held):
                        yield held.pop(k)
                    break
                item = out_q.get()
                if item is END:
                    finished += 1
                else:
                    held[item[0]] = item[1]
    return data_reader


def cache(reader):
    """Materialise the reader once, replay from memory after (reference
    decorator.py cache)."""
    all_data = []
    filled = [False]

    def cache_reader():
        if not filled[0]:
            for sample in reader():
                all_data.append(sample)
                yield sample
            filled[0] = True
        else:
            yield from all_data
    return cache_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave several readers, each in its own process (reference
    decorator.py multiprocess_reader over pipes)."""
    import multiprocessing as mp
    import pickle

    def data_reader():
        ctx = mp.get_context("fork")
        q = ctx.Queue(queue_size)

        def worker(r):
            try:
                for sample in r():
                    q.put(pickle.dumps(sample))
            finally:
                q.put(None)

        procs = [ctx.Process(target=worker, args=(r,), daemon=True)
                 for r in readers]
        from ..fluid.core import start_forked_quietly
        start_forked_quietly(procs)
        finished = 0
        try:
            while finished < len(readers):
                item = q.get()
                if item is None:
                    finished += 1
                else:
                    yield pickle.loads(item)
        finally:
            for p in procs:
                p.terminate()
                p.join(timeout=5.0)
    return data_reader
