"""Version info (reference: generated python/paddle/version.py)."""
full_version = "1.7.0+tpu"
major = "1"
minor = "7"
patch = "0"
rc = "0"
istaged = True
commit = "tpu-native-build"
with_mkl = "OFF"


def show():
    print("paddle-tpu", full_version, "commit:", commit)
