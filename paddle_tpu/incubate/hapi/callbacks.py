"""hapi callbacks (reference: incubate/hapi/callbacks.py — Callback:112,
CallbackList:55, ProgBarLogger:283, ModelCheckpoint:425)."""
from __future__ import annotations

import os
from typing import List, Optional

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *args: self._call(name, *args)
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Step/epoch logging (reference :283 — without the terminal bar,
    which doesn't survive log files)."""

    def __init__(self, log_freq: int = 10, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            msg = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                             else f"{k}: {v}"
                             for k, v in (logs or {}).items())
            print(f"epoch {self.epoch} step {step}: {msg}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            msg = " - ".join(f"{k}: {v}" for k, v in (logs or {}).items())
            print(f"eval: {msg}")


class ModelCheckpoint(Callback):
    """Periodic save (reference :425)."""

    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is None or not self.save_dir:
            return
        if epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, f"{epoch}")
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model is not None and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=10, verbose=2, save_freq=1, save_dir=None,
                     metrics=None):
    """reference callbacks.py:22 — normalize the list and add defaults."""
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks):
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"epochs": epochs, "steps": steps,
                    "verbose": verbose, "metrics": metrics or []})
    return lst
