"""hapi — the high-level Model/fit API (reference:
python/paddle/incubate/hapi/)."""
from .model import Model, Input
from .callbacks import Callback, CallbackList, ProgBarLogger, ModelCheckpoint
from .loss import Loss, CrossEntropy, SoftmaxWithCrossEntropy
from .metrics import Metric, Accuracy

__all__ = ["Model", "Input", "Callback", "CallbackList", "ProgBarLogger",
           "ModelCheckpoint", "Loss", "CrossEntropy",
           "SoftmaxWithCrossEntropy", "Metric", "Accuracy"]
