"""hapi Model — Keras-style train/eval/predict driver over a dygraph Layer
(reference: incubate/hapi/model.py — Model with prepare/fit/evaluate/
predict/save/load; the reference runs either a static or dygraph adapter,
here the dygraph path IS the compiled path via the framework's tracing).
"""
from __future__ import annotations

import os
import pickle
from typing import List, Optional, Sequence

import numpy as np

from ...fluid import core
from ...fluid import dygraph
from ...fluid.dygraph.base import to_variable
from .callbacks import config_callbacks
from .loss import Loss
from .metrics import Metric

__all__ = ["Model", "Input"]


class Input:
    """Input spec (reference hapi/model.py Input): name/shape/dtype."""

    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = shape
        self.dtype = dtype
        self.name = name


class Model:
    """Wraps a dygraph Layer with a training loop (reference Model)."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []

    # ------------------------------------------------------------ prepare
    def prepare(self, optimizer=None, loss_function=None, metrics=None):
        self._optimizer = optimizer
        self._loss = loss_function
        metrics = metrics or []
        self._metrics = metrics if isinstance(metrics, (list, tuple)) \
            else [metrics]
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} is not a hapi Metric")
        return self

    # ------------------------------------------------------- step helpers
    def _to_vars(self, data):
        if isinstance(data, (list, tuple)):
            return [to_variable(np.asarray(d)) for d in data]
        return [to_variable(np.asarray(data))]

    def train_batch(self, inputs, labels=None):
        self.network.train()
        ins = self._to_vars(inputs)
        lbs = self._to_vars(labels) if labels is not None else []
        outs = self.network(*ins)
        losses = self._loss(outs, lbs) if isinstance(self._loss, Loss) \
            else [self._loss(outs, *lbs)]
        total = losses[0]
        for l in losses[1:]:
            from ...fluid import layers
            total = layers.elementwise_add(total, l)
        total.backward()
        self._optimizer.minimize(total)
        self.network.clear_gradients()
        return [float(np.asarray(l.numpy()).ravel()[0]) for l in losses]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        ins = self._to_vars(inputs)
        lbs = self._to_vars(labels) if labels is not None else []
        outs = self.network(*ins)
        losses = self._loss(outs, lbs) if self._loss else []
        metrics = []
        for m in self._metrics:
            o = outs[0] if isinstance(outs, (list, tuple)) else outs
            pred, lab = m.add_metric_op(o.numpy(), lbs[0].numpy()
                                        if lbs else None)
            metrics.append(m.update(pred, lab))
        self.network.train()
        return ([float(np.asarray(l.numpy()).ravel()[0]) for l in losses],
                metrics)

    def predict_batch(self, inputs):
        self.network.eval()
        outs = self.network(*self._to_vars(inputs))
        self.network.train()
        if isinstance(outs, (list, tuple)):
            return [np.asarray(o.numpy()) for o in outs]
        return np.asarray(outs.numpy())

    # ------------------------------------------------------------ fitting
    def fit(self, train_data=None, eval_data=None, epochs=1,
            log_freq=10, save_dir=None, save_freq=1, verbose=2,
            callbacks=None):
        """train_data: callable -> iterable of (inputs, labels) batches
        (a paddle.batch reader or any generator factory)."""
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                log_freq=log_freq, verbose=verbose,
                                save_freq=save_freq, save_dir=save_dir,
                                metrics=[n for m in self._metrics
                                         for n in m.name()])
        cbks.on_train_begin({})
        history = []
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch, {})
            losses = []
            for step, batch in enumerate(train_data()):
                inputs, labels = batch
                cbks.on_train_batch_begin(step, {})
                losses = self.train_batch(inputs, labels)
                cbks.on_train_batch_end(step, {"loss": losses[0]})
            logs = {"loss": losses[0] if losses else None}
            if eval_data is not None:
                logs.update(self.evaluate(eval_data, verbose=0))
            history.append(logs)
            cbks.on_epoch_end(epoch, logs)
        cbks.on_train_end({})
        return history

    def evaluate(self, eval_data, log_freq=10, verbose=2, callbacks=None):
        from .callbacks import CallbackList
        cbks = CallbackList(callbacks or [])
        cbks.set_model(self)
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin({})
        losses_all = []
        for step, batch in enumerate(eval_data()):
            inputs, labels = batch
            cbks.on_eval_batch_begin(step, {})
            losses, _ = self.eval_batch(inputs, labels)
            losses_all.extend(losses)
            cbks.on_eval_batch_end(
                step, {"loss": losses[0] if losses else None})
        res = {}
        if losses_all:
            res["loss"] = float(np.mean(losses_all))
        for m in self._metrics:
            names = m.name()
            vals = m.accumulate()
            vals = vals if isinstance(vals, (list, tuple)) else [vals]
            res.update(dict(zip(names, vals)))
        cbks.on_eval_end(res)
        if verbose:
            print("eval: " + " - ".join(f"{k}: {v}" for k, v in
                                        res.items()))
        return res

    def predict(self, test_data):
        return [self.predict_batch(inputs) for inputs in test_data()]

    # --------------------------------------------------------- save/load
    def save(self, path: str):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        state = {name: np.asarray(p.numpy())
                 for name, p in self.network.state_dict().items()}
        with open(path + ".pdparams", "wb") as f:
            pickle.dump(state, f)

    def load(self, path: str):
        with open(path + ".pdparams", "rb") as f:
            state = pickle.load(f)
        self.network.load_dict(state)

    def parameters(self):
        return self.network.parameters()
