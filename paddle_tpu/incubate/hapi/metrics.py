"""hapi metrics (reference: incubate/hapi/metrics.py — Metric base with
add_metric_op/update/accumulate/reset; Accuracy with top-k)."""
from __future__ import annotations

import numpy as np

__all__ = ["Metric", "Accuracy"]


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def add_metric_op(self, pred, label):
        """Post-process forward outputs into the tensors update() eats."""
        return pred, label


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def add_metric_op(self, pred, label):
        return pred, label

    def update(self, pred, label):
        pred = np.asarray(pred)
        label = np.asarray(label).reshape(-1)
        topk_idx = np.argsort(-pred, axis=-1)[:, :self.maxk]
        corrects = topk_idx == label[:, None]
        res = []
        for i, k in enumerate(self.topk):
            acc = corrects[:, :k].any(axis=1).mean()
            self.total[i] += float(acc) * len(label)
            self.count[i] += len(label)
            res.append(float(acc))
        return res if len(res) > 1 else res[0]

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res if len(res) > 1 else res[0]

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]
