"""hapi losses (reference: incubate/hapi/loss.py — Loss base,
CrossEntropy, SoftmaxWithCrossEntropy)."""
from __future__ import annotations

from ...fluid import layers

__all__ = ["Loss", "CrossEntropy", "SoftmaxWithCrossEntropy"]


class Loss:
    def __init__(self, average=True):
        self.average = average

    def forward(self, outputs, labels):
        raise NotImplementedError

    def __call__(self, outputs, labels):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        outputs = outputs if isinstance(outputs, (list, tuple)) \
            else [outputs]
        losses = self.forward(outputs, labels)
        if not isinstance(losses, (list, tuple)):
            losses = [losses]
        if self.average:
            losses = [layers.reduce_mean(l) for l in losses]
        return losses


class CrossEntropy(Loss):
    """softmax outputs vs integer labels (reference loss.py CrossEntropy)."""

    def forward(self, outputs, labels):
        return [layers.cross_entropy(o, l)
                for o, l in zip(outputs, labels)]


class SoftmaxWithCrossEntropy(Loss):
    """raw logits vs integer labels (reference loss.py
    SoftmaxWithCrossEntropy)."""

    def forward(self, outputs, labels):
        return [layers.softmax_with_cross_entropy(o, l,
                                                  return_softmax=False)
                for o, l in zip(outputs, labels)]
