"""paddle.incubate 2.0-preview (reference: python/paddle/incubate/ — the
hapi high-level Model API and complex-tensor helpers)."""
from . import hapi  # noqa: F401
from . import complex  # noqa: F401
