"""Complex-tensor helpers (reference: python/paddle/incubate/complex/ —
a ComplexVariable carrying separate real/imag tensors plus elementwise /
matmul ops over them; pre-dates native complex dtype support)."""
from .tensor_op import (ComplexVariable, elementwise_add, elementwise_sub,
                        elementwise_mul, elementwise_div, matmul, kron)

__all__ = ["ComplexVariable", "elementwise_add", "elementwise_sub",
           "elementwise_mul", "elementwise_div", "matmul", "kron"]
