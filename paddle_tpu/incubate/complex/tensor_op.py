"""Complex arithmetic over (real, imag) tensor pairs (reference:
incubate/complex/tensor/math.py + linalg.py — the v1.7-era complex support
kept real and imaginary parts as two fluid Variables)."""
from __future__ import annotations

from ...fluid import layers as L

__all__ = ["ComplexVariable", "elementwise_add", "elementwise_sub",
           "elementwise_mul", "elementwise_div", "matmul", "kron"]


class ComplexVariable:
    """A (real, imag) pair of Variables/VarBases."""

    def __init__(self, real, imag):
        self.real = real
        self.imag = imag

    @property
    def shape(self):
        return self.real.shape

    def numpy(self):
        import numpy as np
        return np.asarray(self.real.numpy()) + 1j * np.asarray(
            self.imag.numpy())

    __add__ = lambda s, o: elementwise_add(s, o)
    __sub__ = lambda s, o: elementwise_sub(s, o)
    __mul__ = lambda s, o: elementwise_mul(s, o)
    __truediv__ = lambda s, o: elementwise_div(s, o)


def _as_complex(x):
    if isinstance(x, ComplexVariable):
        return x
    return ComplexVariable(x, L.zeros_like(x))


def elementwise_add(x, y, axis=-1, name=None):
    x, y = _as_complex(x), _as_complex(y)
    return ComplexVariable(L.elementwise_add(x.real, y.real, axis=axis),
                           L.elementwise_add(x.imag, y.imag, axis=axis))


def elementwise_sub(x, y, axis=-1, name=None):
    x, y = _as_complex(x), _as_complex(y)
    return ComplexVariable(L.elementwise_sub(x.real, y.real, axis=axis),
                           L.elementwise_sub(x.imag, y.imag, axis=axis))


def elementwise_mul(x, y, axis=-1, name=None):
    x, y = _as_complex(x), _as_complex(y)
    rr = L.elementwise_mul(x.real, y.real, axis=axis)
    ii = L.elementwise_mul(x.imag, y.imag, axis=axis)
    ri = L.elementwise_mul(x.real, y.imag, axis=axis)
    ir = L.elementwise_mul(x.imag, y.real, axis=axis)
    return ComplexVariable(L.elementwise_sub(rr, ii),
                           L.elementwise_add(ri, ir))


def elementwise_div(x, y, axis=-1, name=None):
    x, y = _as_complex(x), _as_complex(y)
    denom = L.elementwise_add(
        L.elementwise_mul(y.real, y.real, axis=axis),
        L.elementwise_mul(y.imag, y.imag, axis=axis))
    num = elementwise_mul(x, ComplexVariable(
        y.real, L.scale(y.imag, scale=-1.0)))
    return ComplexVariable(L.elementwise_div(num.real, denom),
                           L.elementwise_div(num.imag, denom))


def matmul(x, y, name=None):
    x, y = _as_complex(x), _as_complex(y)
    rr = L.matmul(x.real, y.real)
    ii = L.matmul(x.imag, y.imag)
    ri = L.matmul(x.real, y.imag)
    ir = L.matmul(x.imag, y.real)
    return ComplexVariable(L.elementwise_sub(rr, ii),
                           L.elementwise_add(ri, ir))


def kron(x, y, name=None):
    from ...tensor import kron as _kron
    x, y = _as_complex(x), _as_complex(y)
    rr = _kron(x.real, y.real)
    ii = _kron(x.imag, y.imag)
    ri = _kron(x.real, y.imag)
    ir = _kron(x.imag, y.real)
    return ComplexVariable(L.elementwise_sub(rr, ii),
                           L.elementwise_add(ri, ir))
