// C++ training demo (reference: paddle/fluid/train/ — a pure-C++ binary
// that loads a saved ProgramDesc and trains without any Python script;
// test_train_recognize_digits.cc). Here the C++ main embeds the CPython
// runtime and drives the framework's Executor directly — the compute
// still runs as ONE jitted XLA computation per step.
//
// Usage: train_demo <model_dir> <steps>
//   model_dir must hold __main__ and __startup__ (serialized ProgramDesc
//   of the train/startup programs), plus feeds.json describing the feed
//   vars: {"feeds": [{"name":..., "shape":[...], "dtype":"float32"|
//   "int64", "max": V}], "fetch": "loss_var_name"}.
// Prints one line per step: "step N loss L"; exit 0 on success with the
// final loss finite and lower than the first.
#include <Python.h>

#include <cstdio>
#include <string>

static PyObject* run_string(const char* code, PyObject* globals) {
  PyObject* r = PyRun_String(code, Py_file_input, globals, globals);
  if (!r) {
    PyErr_Print();
  }
  return r;
}

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s <model_dir> <steps>\n", argv[0]);
    return 2;
  }
  Py_Initialize();
  PyObject* globals = PyDict_New();
  PyDict_SetItemString(globals, "__builtins__", PyEval_GetBuiltins());
  PyDict_SetItemString(globals, "MODEL_DIR",
                       PyUnicode_FromString(argv[1]));
  PyDict_SetItemString(globals, "STEPS",
                       PyLong_FromLong(std::atol(argv[2])));

  // The training loop, driven from C++: load programs, startup, step.
  // (The reference's C++ demo calls framework::Executor the same way —
  // the executor here lives behind the Python API.)
  const char* code = R"PY(
import json, os
if os.environ.get("PADDLE_TPU_FORCE_CPU"):
    # some deployments pin the accelerator platform in sitecustomize;
    # in-process config is the only override that lands early enough
    import jax
    jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core

with open(os.path.join(MODEL_DIR, "__main__"), "rb") as f:
    main = fluid.Program.parse_from_string(f.read())
with open(os.path.join(MODEL_DIR, "__startup__"), "rb") as f:
    startup = fluid.Program.parse_from_string(f.read())
with open(os.path.join(MODEL_DIR, "feeds.json")) as f:
    spec = json.load(f)

exe = fluid.Executor()
scope = core.Scope()
rng = np.random.RandomState(0)
losses = []
with fluid.scope_guard(scope):
    exe.run(startup)
    for step in range(STEPS):
        feed = {}
        for fs in spec["feeds"]:
            shape = fs["shape"]
            if fs["dtype"] == "int64":
                feed[fs["name"]] = rng.randint(
                    0, fs.get("max", 2), shape).astype("int64")
            else:
                feed[fs["name"]] = rng.rand(*shape).astype("float32")
        out = exe.run(main, feed=feed, fetch_list=[spec["fetch"]])
        loss = float(np.asarray(out[0]).ravel()[0])
        losses.append(loss)
        print(f"step {step} loss {loss:.6f}", flush=True)
OK = bool(np.isfinite(losses[-1]) and (len(losses) < 2
                                       or losses[-1] <= losses[0]))
)PY";

  PyObject* r = run_string(code, globals);
  int rc = 1;
  if (r) {
    Py_DECREF(r);
    PyObject* ok = PyDict_GetItemString(globals, "OK");
    rc = (ok && PyObject_IsTrue(ok)) ? 0 : 1;
  }
  Py_DECREF(globals);
  Py_Finalize();
  return rc;
}
