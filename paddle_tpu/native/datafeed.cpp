// Native data-feed engine — multi-threaded slot-format ingestion,
// in-memory shuffle, batch packing with LoD offsets.
//
// TPU-native rebuild of the reference's C++ dataset stack (reference:
// paddle/fluid/framework/data_feed.h:106 MultiSlotDataFeed parsing,
// data_set.h:159 DatasetImpl in-memory shuffle, channel.h blocking
// channels, data_feed.cc slot-format grammar). The host side stays native
// C++ exactly like the reference's: N parser threads stream text files
// into pinned record storage, the trainer thread drains packed batches
// (contiguous value buffer + LoD offsets per slot) that Python hands to
// the jitted TPU step as device feeds.
//
// Slot-format line grammar (reference data_feed.cc CheckFile):
//   line := (slot_field)*           one group per registered slot, in order
//   slot_field := <n> <v1> ... <vn>
// float slots parse with strtof, id (uint64) slots with strtoll.
//
// C ABI (consumed via ctypes from ../fluid/dataset.py):
//   df_create(slot_spec) -> handle        spec: "name:f|i:dim,..."
//   df_set_filelist / df_set_batch / df_set_threads
//   df_load_into_memory(h)                parse all files (threaded)
//   df_local_shuffle(h, seed)
//   df_epoch_begin(h)                     reset batch cursor
//   df_next_batch(h) -> n_instances (0 = epoch end)
//   df_slot_total(h, s) -> values in current batch for slot s
//   df_slot_copy(h, s, values_out, lod_out)  fills value+offset buffers
//   df_memory_size(h) / df_release(h)
#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <mutex>
#include <random>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Slot {
  std::string name;
  bool is_float;
  int dim;
};

// One parsed instance: per-slot ragged values (reference
// data_feed.h MultiSlotType).
struct Record {
  std::vector<std::vector<float>> fvals;
  std::vector<std::vector<int64_t>> ivals;
};

class DataFeed {
 public:
  explicit DataFeed(const std::string& spec) {
    std::stringstream ss(spec);
    std::string item;
    while (std::getline(ss, item, ',')) {
      if (item.empty()) continue;
      size_t a = item.find(':');
      size_t b = item.find(':', a + 1);
      Slot s;
      s.name = item.substr(0, a);
      s.is_float = item.substr(a + 1, b - a - 1) == "f";
      s.dim = std::atoi(item.c_str() + b + 1);
      slots_.push_back(s);
    }
  }

  void SetFileList(const char** files, int n) {
    files_.assign(files, files + n);
  }
  void SetBatch(int b) { batch_ = b; }
  void SetThreads(int t) { threads_ = t < 1 ? 1 : t; }

  // reference data_set.cc LoadIntoMemory: one thread per file shard.
  void LoadIntoMemory() {
    records_.clear();
    std::vector<std::thread> ths;
    std::vector<std::vector<Record>> parts(threads_);
    std::atomic<size_t> next_file{0};
    for (int t = 0; t < threads_; ++t) {
      ths.emplace_back([this, t, &parts, &next_file]() {
        for (;;) {
          size_t i = next_file.fetch_add(1);
          if (i >= files_.size()) return;
          ParseFile(files_[i], &parts[t]);
        }
      });
    }
    for (auto& th : ths) th.join();
    size_t total = 0;
    for (auto& p : parts) total += p.size();
    records_.reserve(total);
    for (auto& p : parts)
      for (auto& r : p) records_.push_back(std::move(r));
    cursor_ = 0;
  }

  void LocalShuffle(uint64_t seed) {
    std::mt19937_64 rng(seed);
    std::shuffle(records_.begin(), records_.end(), rng);
  }

  void EpochBegin() { cursor_ = 0; }

  // Packs the next batch; returns #instances (0 at epoch end).
  int NextBatch() {
    size_t n = std::min<size_t>(batch_, records_.size() - cursor_);
    cur_batch_.assign(records_.begin() + cursor_,
                      records_.begin() + cursor_ + n);
    cursor_ += n;
    return static_cast<int>(n);
  }

  int64_t SlotTotal(int s) const {
    int64_t total = 0;
    for (const auto& r : cur_batch_)
      total += slots_[s].is_float ? r.fvals[FloatIdx(s)].size()
                                  : r.ivals[IntIdx(s)].size();
    return total;
  }

  // values_out: float* or int64*; lod_out: int64[n_instances + 1] offsets.
  void SlotCopy(int s, void* values_out, int64_t* lod_out) const {
    int64_t off = 0;
    lod_out[0] = 0;
    for (size_t i = 0; i < cur_batch_.size(); ++i) {
      const Record& r = cur_batch_[i];
      if (slots_[s].is_float) {
        const auto& v = r.fvals[FloatIdx(s)];
        std::memcpy(static_cast<float*>(values_out) + off, v.data(),
                    v.size() * sizeof(float));
        off += v.size();
      } else {
        const auto& v = r.ivals[IntIdx(s)];
        std::memcpy(static_cast<int64_t*>(values_out) + off, v.data(),
                    v.size() * sizeof(int64_t));
        off += v.size();
      }
      lod_out[i + 1] = off;
    }
  }

  int64_t MemorySize() const { return static_cast<int64_t>(records_.size()); }
  int NumSlots() const { return static_cast<int>(slots_.size()); }

 private:
  int FloatIdx(int s) const {
    int k = 0;
    for (int i = 0; i < s; ++i)
      if (slots_[i].is_float) ++k;
    return k;
  }
  int IntIdx(int s) const {
    int k = 0;
    for (int i = 0; i < s; ++i)
      if (!slots_[i].is_float) ++k;
    return k;
  }

  void ParseFile(const std::string& path, std::vector<Record>* out) {
    std::ifstream in(path);
    std::string line;
    while (std::getline(in, line)) {
      const char* p = line.c_str();
      char* end = nullptr;
      Record rec;
      bool ok = true;
      for (const Slot& s : slots_) {
        long n = std::strtol(p, &end, 10);
        if (end == p || n < 0) { ok = false; break; }
        p = end;
        if (s.is_float) {
          std::vector<float> v;
          v.reserve(n);
          for (long i = 0; i < n; ++i) {
            v.push_back(std::strtof(p, &end));
            if (end == p) { ok = false; break; }
            p = end;
          }
          if (!ok) break;
          rec.fvals.push_back(std::move(v));
        } else {
          std::vector<int64_t> v;
          v.reserve(n);
          for (long i = 0; i < n; ++i) {
            v.push_back(std::strtoll(p, &end, 10));
            if (end == p) { ok = false; break; }
            p = end;
          }
          if (!ok) break;
          rec.ivals.push_back(std::move(v));
        }
      }
      if (ok) out->push_back(std::move(rec));
    }
  }

  std::vector<Slot> slots_;
  std::vector<std::string> files_;
  std::vector<Record> records_;
  std::vector<Record> cur_batch_;
  size_t cursor_ = 0;
  int batch_ = 1;
  int threads_ = 1;
};

}  // namespace

extern "C" {

void* df_create(const char* slot_spec) { return new DataFeed(slot_spec); }

void df_set_filelist(void* h, const char** files, int n) {
  static_cast<DataFeed*>(h)->SetFileList(files, n);
}
void df_set_batch(void* h, int b) { static_cast<DataFeed*>(h)->SetBatch(b); }
void df_set_threads(void* h, int t) {
  static_cast<DataFeed*>(h)->SetThreads(t);
}
void df_load_into_memory(void* h) {
  static_cast<DataFeed*>(h)->LoadIntoMemory();
}
void df_local_shuffle(void* h, uint64_t seed) {
  static_cast<DataFeed*>(h)->LocalShuffle(seed);
}
void df_epoch_begin(void* h) { static_cast<DataFeed*>(h)->EpochBegin(); }
int df_next_batch(void* h) { return static_cast<DataFeed*>(h)->NextBatch(); }
int64_t df_slot_total(void* h, int s) {
  return static_cast<DataFeed*>(h)->SlotTotal(s);
}
void df_slot_copy(void* h, int s, void* values, int64_t* lod) {
  static_cast<DataFeed*>(h)->SlotCopy(s, values, lod);
}
int64_t df_memory_size(void* h) {
  return static_cast<DataFeed*>(h)->MemorySize();
}
void df_release(void* h) { delete static_cast<DataFeed*>(h); }

}  // extern "C"
