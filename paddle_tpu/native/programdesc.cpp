// Native ProgramDesc loader/validator — the fast path for deserialized
// programs (reference: the C++ ProgramDesc/OpDesc/VarDesc layer,
// framework/program_desc.cc + framework.proto:25-216; here a hand-rolled
// protobuf wire-format walk, so no generated code or libprotobuf
// dependency).
//
// What it does: parse the serialized ProgramDesc, build the block/op/var
// index, and validate structure BEFORE Python touches it — wire integrity,
// block-tree sanity, duplicate var defs, and op arguments that resolve to
// no var in the block chain. Returns a JSON summary (counts + op-type
// histogram + errors) through a C ABI consumed via ctypes.
//
// Field numbers (matching python/paddle_tpu/fluid/proto/framework_pb2.py):
//   ProgramDesc.blocks = 1
//   BlockDesc.idx = 1, .parent_idx = 2, .vars = 3, .ops = 4
//   VarDesc.name = 1, .persistable = 3
//   OpDesc.inputs = 1, .outputs = 2, .type = 3, .attrs = 4
//   OpDesc.Var.parameter = 1, .arguments = 2
//   OpDesc.Attr.name = 1, .type = 2, .block_idx = 12
#include <cstdint>
#include <cstring>
#include <map>
#include <set>
#include <string>
#include <vector>

namespace {

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= uint64_t(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    fail = true;
    return 0;
  }

  // returns (field_number, wire_type); field 0 on exhaustion/error
  std::pair<uint32_t, uint32_t> tag() {
    if (p >= end) return {0, 0};
    uint64_t t = varint();
    if (fail) return {0, 0};
    return {uint32_t(t >> 3), uint32_t(t & 7)};
  }

  Reader sub() {  // length-delimited payload
    uint64_t n = varint();
    if (fail || p + n > end) {
      fail = true;
      return {end, end};
    }
    Reader r{p, p + n};
    p += n;
    return r;
  }

  std::string str() {
    Reader r = sub();
    return fail ? std::string()
                : std::string(reinterpret_cast<const char*>(r.p),
                              r.end - r.p);
  }

  void skip(uint32_t wire) {
    switch (wire) {
      case 0: varint(); break;
      case 1: p += 8; break;
      case 2: sub(); break;
      case 5: p += 4; break;
      default: fail = true;
    }
    if (p > end) fail = true;
  }
};

struct OpInfo {
  std::string type;
  std::vector<std::string> args;      // all input+output var names
  std::vector<int64_t> sub_blocks;    // block_idx attrs
};

struct BlockInfo {
  int64_t idx = -1;
  int64_t parent = -1;
  std::set<std::string> vars;
  std::vector<OpInfo> ops;
  std::vector<std::string> dup_vars;
};

struct Parsed {
  std::vector<BlockInfo> blocks;
  std::vector<std::string> errors;
  std::string json;
  bool ok = false;
};

void parse_opvar(Reader r, OpInfo* op) {
  while (true) {
    auto [f, w] = r.tag();
    if (!f) break;
    if (f == 2 && w == 2) {
      op->args.push_back(r.str());
    } else {
      r.skip(w);
    }
    if (r.fail) return;
  }
}

void parse_attr(Reader r, OpInfo* op) {
  while (true) {
    auto [f, w] = r.tag();
    if (!f) break;
    if (f == 12 && w == 0) {           // block_idx
      op->sub_blocks.push_back(int64_t(r.varint()));
    } else if (f == 14 && w == 0) {    // blocks_idx (repeated varint)
      op->sub_blocks.push_back(int64_t(r.varint()));
    } else {
      r.skip(w);
    }
    if (r.fail) return;
  }
}

void parse_op(Reader r, BlockInfo* blk, Parsed* out) {
  OpInfo op;
  while (true) {
    auto [f, w] = r.tag();
    if (!f) break;
    if (f == 3 && w == 2) {
      op.type = r.str();
    } else if ((f == 1 || f == 2) && w == 2) {
      parse_opvar(r.sub(), &op);
    } else if (f == 4 && w == 2) {
      parse_attr(r.sub(), &op);
    } else {
      r.skip(w);
    }
    if (r.fail) {
      out->errors.push_back("wire error inside OpDesc");
      return;
    }
  }
  if (op.type.empty())
    out->errors.push_back("op with empty type in block " +
                          std::to_string(blk->idx));
  blk->ops.push_back(std::move(op));
}

void parse_var(Reader r, BlockInfo* blk) {
  while (true) {
    auto [f, w] = r.tag();
    if (!f) break;
    if (f == 1 && w == 2) {
      std::string name = r.str();
      if (!blk->vars.insert(name).second) blk->dup_vars.push_back(name);
    } else {
      r.skip(w);
    }
    if (r.fail) return;
  }
}

void parse_block(Reader r, Parsed* out) {
  BlockInfo blk;
  while (true) {
    auto [f, w] = r.tag();
    if (!f) break;
    switch (f) {
      case 1: blk.idx = int64_t(r.varint()); break;
      case 2: blk.parent = int64_t(r.varint()); break;
      case 3: parse_var(r.sub(), &blk); break;
      case 4: parse_op(r.sub(), &blk, out); break;
      default: r.skip(w);
    }
    if (r.fail) {
      out->errors.push_back("wire error inside BlockDesc");
      return;
    }
  }
  out->blocks.push_back(std::move(blk));
}

bool resolves(const Parsed& p, size_t bi, const OpInfo& op,
              const std::string& name) {
  // walk the block chain like Block::_var_recursive...
  int64_t cur = int64_t(bi);
  std::set<int64_t> seen;
  while (cur >= 0 && size_t(cur) < p.blocks.size() &&
         seen.insert(cur).second) {
    if (p.blocks[cur].vars.count(name)) return true;
    cur = p.blocks[cur].parent;
  }
  // ...and control-flow structures reference vars living in descendant
  // blocks (while/conditional_block Out lists, select_input reading
  // branch-produced vars via step scopes — reference while_op.cc /
  // conditional_block_op.cc runtime scope semantics)
  for (size_t d = 0; d < p.blocks.size(); d++) {
    if (d == bi || !p.blocks[d].vars.count(name)) continue;
    int64_t cur = p.blocks[d].parent;  // is bi an ancestor of d?
    std::set<int64_t> seen2;
    while (cur >= 0 && size_t(cur) < p.blocks.size() &&
           seen2.insert(cur).second) {
      if (size_t(cur) == bi) return true;
      cur = p.blocks[cur].parent;
    }
  }
  return false;
}

std::string escape(const std::string& s) {
  // JSON-safe AND valid UTF-8: control chars and bytes >= 0x80 (corrupt
  // inputs can put arbitrary bytes in names) render as \xNN hex
  static const char* hex = "0123456789abcdef";
  std::string o;
  for (char c : s) {
    uint8_t b = uint8_t(c);
    if (c == '"' || c == '\\') {
      o += '\\';
      o += c;
    } else if (b < 0x20 || b >= 0x80) {
      o += "\\\\x";
      o += hex[b >> 4];
      o += hex[b & 0xf];
    } else {
      o += c;
    }
  }
  return o;
}

void validate(Parsed* p) {
  // block tree sanity
  for (size_t i = 0; i < p->blocks.size(); i++) {
    const auto& b = p->blocks[i];
    if (b.idx != int64_t(i))
      p->errors.push_back("block " + std::to_string(i) +
                          " has idx " + std::to_string(b.idx));
    if (b.parent >= int64_t(p->blocks.size()))
      p->errors.push_back("block " + std::to_string(i) +
                          " parent out of range");
    // raw names here; build_json applies the single JSON-level escape
    for (const auto& d : b.dup_vars)
      p->errors.push_back("duplicate var '" + d + "' in block " +
                          std::to_string(i));
    for (const auto& op : b.ops) {
      for (const auto& sb : op.sub_blocks)
        if (sb < 0 || sb >= int64_t(p->blocks.size()))
          p->errors.push_back("op '" + op.type +
                              "' references missing sub-block " +
                              std::to_string(sb));
      for (const auto& a : op.args) {
        if (a == "@EMPTY@") continue;  // grad-slot sentinel (backward.py)
        if (!resolves(*p, i, op, a)) {
          if (p->errors.size() < 64)
            p->errors.push_back("op '" + op.type + "' in block " +
                                std::to_string(i) +
                                " references undefined var '" + a + "'");
        }
      }
    }
  }
}

void build_json(Parsed* p) {
  size_t n_ops = 0, n_vars = 0;
  std::map<std::string, int> hist;
  for (const auto& b : p->blocks) {
    n_ops += b.ops.size();
    n_vars += b.vars.size();
    for (const auto& op : b.ops) hist[op.type]++;
  }
  std::string j = "{\"n_blocks\":" + std::to_string(p->blocks.size()) +
                  ",\"n_ops\":" + std::to_string(n_ops) +
                  ",\"n_vars\":" + std::to_string(n_vars) + ",\"ops\":{";
  bool first = true;
  for (const auto& kv : hist) {
    if (!first) j += ",";
    first = false;
    j += "\"" + escape(kv.first) + "\":" + std::to_string(kv.second);
  }
  j += "},\"errors\":[";
  for (size_t i = 0; i < p->errors.size(); i++) {
    if (i) j += ",";
    j += "\"" + escape(p->errors[i]) + "\"";
  }
  j += "]}";
  p->json = j;
  p->ok = p->errors.empty();
}

}  // namespace

extern "C" {

void* pd_parse(const char* buf, int64_t len) {
  auto* p = new Parsed();
  Reader r{reinterpret_cast<const uint8_t*>(buf),
           reinterpret_cast<const uint8_t*>(buf) + len};
  while (true) {
    auto [f, w] = r.tag();
    if (!f) break;
    if (f == 1 && w == 2) {
      parse_block(r.sub(), p);
    } else {
      r.skip(w);
    }
    if (r.fail) {
      p->errors.push_back("truncated or corrupt ProgramDesc wire data");
      break;
    }
  }
  if (p->blocks.empty())
    p->errors.push_back("no blocks in ProgramDesc");
  validate(p);
  build_json(p);
  return p;
}

int pd_ok(void* h) { return static_cast<Parsed*>(h)->ok ? 1 : 0; }

const char* pd_json(void* h) {
  return static_cast<Parsed*>(h)->json.c_str();
}

void pd_release(void* h) { delete static_cast<Parsed*>(h); }

}  // extern "C"
