"""Native (C++) runtime components, built on demand with g++ and loaded
via ctypes — the parts of the framework that stay host-native, mirroring
the reference's C++ runtime (data feed: framework/data_feed.cc)."""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIBS = {}


def _embed_flags(rpath: bool = False):
    """Compile/link flags for modules that embed CPython."""
    import sysconfig
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") \
        or sysconfig.get_config_var("VERSION")
    ld = [f"-L{libdir}"] if libdir else []
    if rpath and libdir:
        ld.append(f"-Wl,-rpath,{libdir}")
    return [f"-I{inc}"], ld + [f"-lpython{ver}"]


def _module_flags(name: str):
    """Extra compile/link flags per native module (capi embeds CPython)."""
    if name == "capi":
        # rpath so a standalone C program's dlopen finds libpython even
        # in a non-default prefix
        return _embed_flags(rpath=True)
    return [], []


def _build(name: str) -> str:
    src = os.path.join(_DIR, name + ".cpp")
    so = os.path.join(_DIR, "lib" + name + ".so")
    if (not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(src)):
        cflags, ldflags = _module_flags(name)
        cmd = (["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                "-pthread"] + cflags + [src, "-o", so] + ldflags)
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    return so


class _BuildFailed:
    """Sentinel cached when a native build fails: attempt once per
    process, don't re-spawn a failing compiler on every call."""

    def __init__(self, err: Exception):
        self.err = err


def build_executable(name: str) -> str:
    """Build paddle_tpu/native/<name>.cpp as a standalone binary (the C++
    train demo — reference paddle/fluid/train/). Same once-per-process
    failure caching and locking as load()."""
    key = "exe:" + name
    with _LOCK:
        cached = _LIBS.get(key)
        if isinstance(cached, _BuildFailed):
            raise RuntimeError(
                f"native executable '{name}' previously failed to "
                f"build: {cached.err}") from cached.err
        if isinstance(cached, str):
            return cached
        src = os.path.join(_DIR, name + ".cpp")
        exe = os.path.join(_DIR, name)
        try:
            if (not os.path.exists(exe)
                    or os.path.getmtime(exe) < os.path.getmtime(src)):
                cflags, ldflags = _embed_flags(rpath=True)
                cmd = (["g++", "-O2", "-std=c++17", "-pthread"] + cflags
                       + [src, "-o", exe] + ldflags)
                subprocess.run(cmd, check=True, capture_output=True,
                               text=True)
        except Exception as e:
            _LIBS[key] = _BuildFailed(e)
            raise
        _LIBS[key] = exe
        return exe


def load(name: str) -> ctypes.CDLL:
    """Build (if stale) and dlopen paddle_tpu/native/<name>.cpp."""
    with _LOCK:
        lib = _LIBS.get(name)
        if isinstance(lib, _BuildFailed):
            raise RuntimeError(
                f"native module '{name}' previously failed to build: "
                f"{lib.err}") from lib.err
        if lib is None:
            try:
                lib = _LIBS[name] = ctypes.CDLL(_build(name))
            except Exception as e:
                _LIBS[name] = _BuildFailed(e)
                raise
        return lib


def datafeed_lib() -> ctypes.CDLL:
    lib = load("datafeed")
    if not getattr(lib, "_sigs_done", False):
        c = ctypes
        lib.df_create.restype = c.c_void_p
        lib.df_create.argtypes = [c.c_char_p]
        lib.df_set_filelist.argtypes = [c.c_void_p,
                                        c.POINTER(c.c_char_p), c.c_int]
        lib.df_set_batch.argtypes = [c.c_void_p, c.c_int]
        lib.df_set_threads.argtypes = [c.c_void_p, c.c_int]
        lib.df_load_into_memory.argtypes = [c.c_void_p]
        lib.df_local_shuffle.argtypes = [c.c_void_p, c.c_uint64]
        lib.df_epoch_begin.argtypes = [c.c_void_p]
        lib.df_next_batch.restype = c.c_int
        lib.df_next_batch.argtypes = [c.c_void_p]
        lib.df_slot_total.restype = c.c_int64
        lib.df_slot_total.argtypes = [c.c_void_p, c.c_int]
        lib.df_slot_copy.argtypes = [c.c_void_p, c.c_int, c.c_void_p,
                                     c.POINTER(c.c_int64)]
        lib.df_memory_size.restype = c.c_int64
        lib.df_memory_size.argtypes = [c.c_void_p]
        lib.df_release.argtypes = [c.c_void_p]
        lib._sigs_done = True
    return lib


def programdesc_lib() -> ctypes.CDLL:
    """Native ProgramDesc wire parser/validator (programdesc.cpp)."""
    lib = load("programdesc")
    if not getattr(lib, "_sigs_done", False):
        c = ctypes
        lib.pd_parse.restype = c.c_void_p
        lib.pd_parse.argtypes = [c.c_char_p, c.c_int64]
        lib.pd_ok.restype = c.c_int
        lib.pd_ok.argtypes = [c.c_void_p]
        lib.pd_json.restype = c.c_char_p
        lib.pd_json.argtypes = [c.c_void_p]
        lib.pd_release.argtypes = [c.c_void_p]
        lib._sigs_done = True
    return lib


def inspect_program_bytes(data: bytes) -> dict:
    """Parse+validate a serialized ProgramDesc natively; returns the JSON
    summary dict {n_blocks, n_ops, n_vars, ops: {type: count}, errors}."""
    import json
    lib = programdesc_lib()
    h = lib.pd_parse(data, len(data))
    try:
        # names in corrupt inputs can hold arbitrary bytes; the C++ side
        # hex-escapes them, replace is belt-and-braces
        return json.loads(lib.pd_json(h).decode("utf-8", "replace"))
    finally:
        lib.pd_release(h)
