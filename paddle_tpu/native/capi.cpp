// Inference C API (reference: paddle/fluid/inference/capi/ — C wrappers
// over the AnalysisPredictor so C/C++ serving apps can run models).
//
// TPU framing: the predictor itself is the XLA path (load ProgramDesc →
// jit once → dispatch); this C ABI embeds the CPython runtime and drives
// paddle_tpu.inference.AnalysisPredictor through it. Works both from a
// standalone C program (initializes Python) and when dlopen'd inside an
// existing Python process (takes the GIL).
//
// Surface (float32 tensors; the reference's PD_PaddleBuf subset):
//   PD_NewPredictor(model_dir)                    -> handle | NULL
//   PD_GetInputNum / PD_GetOutputNum(handle)      -> int
//   PD_GetInputName / PD_GetOutputName(handle, i) -> const char*
//   PD_SetInput(handle, name, data, shape, ndim)  -> 0 | -1
//       (all dims concrete/positive; no -1 batch placeholders)
//   PD_RunPredictor(handle)                       -> 0 | -1
//   PD_GetOutput(handle, name, buf, cap, out_len, out_shape, out_ndim)
//       out_shape must hold 16 int64 slots; rc -2 = grow buf to *out_len
//       and retry; rc -3 = output rank exceeds 16
//   PD_DeletePredictor(handle)
//   PD_LastError()                                -> const char*
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

thread_local std::string g_err;

struct Predictor {
  PyObject* pred = nullptr;                 // AnalysisPredictor
  std::vector<std::string> input_names;
  std::vector<std::string> output_names;
};

struct Gil {
  PyGILState_STATE st;
  Gil() { st = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(st); }
};

bool record_py_error(const char* where) {
  if (!PyErr_Occurred()) {
    g_err = std::string(where) + ": unknown failure";
    return false;
  }
  PyObject *t, *v, *tb;
  PyErr_Fetch(&t, &v, &tb);
  PyObject* s = v ? PyObject_Str(v) : nullptr;
  g_err = std::string(where) + ": " +
          (s ? PyUnicode_AsUTF8(s) : "unprintable python error");
  Py_XDECREF(s);
  Py_XDECREF(t);
  Py_XDECREF(v);
  Py_XDECREF(tb);
  return false;
}

bool names_of(PyObject* pred, const char* method,
              std::vector<std::string>* out) {
  PyObject* lst = PyObject_CallMethod(pred, method, nullptr);
  if (!lst) return record_py_error(method);
  for (Py_ssize_t i = 0; i < PyList_Size(lst); i++)
    out->push_back(PyUnicode_AsUTF8(PyList_GetItem(lst, i)));
  Py_DECREF(lst);
  return true;
}

}  // namespace

extern "C" {

const char* PD_LastError() { return g_err.c_str(); }

void* PD_NewPredictor(const char* model_dir) {
  if (!Py_IsInitialized()) {
    Py_Initialize();
    // release the GIL acquired by initialization so OTHER threads'
    // PyGILState_Ensure can take it (C serving apps dispatch PD_* calls
    // from worker threads); every entry point below re-acquires via Gil
    PyEval_SaveThread();
  }
  Gil gil;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.inference");
  if (!mod) {
    record_py_error("import paddle_tpu.inference");
    return nullptr;
  }
  PyObject* cfg = PyObject_CallMethod(mod, "AnalysisConfig", "s",
                                      model_dir);
  if (!cfg) {
    Py_DECREF(mod);
    record_py_error("AnalysisConfig");
    return nullptr;
  }
  PyObject* pred = PyObject_CallMethod(mod, "create_predictor", "O", cfg);
  Py_DECREF(cfg);
  Py_DECREF(mod);
  if (!pred) {
    record_py_error("create_predictor");
    return nullptr;
  }
  auto* p = new Predictor();
  p->pred = pred;
  if (!names_of(pred, "get_input_names", &p->input_names) ||
      !names_of(pred, "get_output_names", &p->output_names)) {
    Py_DECREF(pred);
    delete p;
    return nullptr;
  }
  return p;
}

int PD_GetInputNum(void* h) {
  return int(static_cast<Predictor*>(h)->input_names.size());
}

int PD_GetOutputNum(void* h) {
  return int(static_cast<Predictor*>(h)->output_names.size());
}

const char* PD_GetInputName(void* h, int i) {
  auto* p = static_cast<Predictor*>(h);
  return (i >= 0 && i < int(p->input_names.size()))
             ? p->input_names[i].c_str()
             : nullptr;
}

const char* PD_GetOutputName(void* h, int i) {
  auto* p = static_cast<Predictor*>(h);
  return (i >= 0 && i < int(p->output_names.size()))
             ? p->output_names[i].c_str()
             : nullptr;
}

int PD_SetInput(void* h, const char* name, const float* data,
                const int64_t* shape, int ndim) {
  auto* p = static_cast<Predictor*>(h);
  Gil gil;
  if (ndim <= 0) {
    g_err = "PD_SetInput: ndim must be positive";
    return -1;
  }
  int64_t numel = 1;
  for (int i = 0; i < ndim; i++) {
    if (shape[i] <= 0) {  // concrete shapes only — no -1 batch dims here
      g_err = "PD_SetInput: all shape dims must be positive (got " +
              std::to_string(shape[i]) + ")";
      return -1;
    }
    numel *= shape[i];
  }
  PyObject* handle =
      PyObject_CallMethod(p->pred, "get_input_handle", "s", name);
  if (!handle) return record_py_error("get_input_handle"), -1;
  // build a numpy array from the raw buffer via the buffer-free path:
  // numpy.frombuffer(bytes, float32).reshape(shape)
  PyObject* np = PyImport_ImportModule("numpy");
  PyObject* bytes = np ? PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), numel * 4) : nullptr;
  if (!np || !bytes) {
    Py_XDECREF(np);
    Py_XDECREF(bytes);
    Py_DECREF(handle);
    return record_py_error("numpy buffer"), -1;
  }
  PyObject* flat = PyObject_CallMethod(np, "frombuffer", "Os", bytes,
                                       "float32");
  Py_DECREF(bytes);
  Py_DECREF(np);
  if (!flat) {
    Py_DECREF(handle);
    return record_py_error("frombuffer"), -1;
  }
  PyObject* shp = PyTuple_New(ndim);
  for (int i = 0; i < ndim; i++)
    PyTuple_SetItem(shp, i, PyLong_FromLongLong(shape[i]));
  PyObject* arr = PyObject_CallMethod(flat, "reshape", "O", shp);
  Py_DECREF(flat);
  Py_DECREF(shp);
  if (!arr) {
    Py_DECREF(handle);
    return record_py_error("reshape"), -1;
  }
  PyObject* r = PyObject_CallMethod(handle, "copy_from_cpu", "O", arr);
  Py_DECREF(arr);
  Py_DECREF(handle);
  if (!r) return record_py_error("copy_from_cpu"), -1;
  Py_DECREF(r);
  return 0;
}

int PD_RunPredictor(void* h) {
  auto* p = static_cast<Predictor*>(h);
  Gil gil;
  PyObject* r = PyObject_CallMethod(p->pred, "run", nullptr);
  if (!r) return record_py_error("run"), -1;
  Py_DECREF(r);
  return 0;
}

int PD_GetOutput(void* h, const char* name, float* buf,
                 int64_t capacity, int64_t* out_len, int64_t* out_shape,
                 int* out_ndim) {
  auto* p = static_cast<Predictor*>(h);
  Gil gil;
  PyObject* handle =
      PyObject_CallMethod(p->pred, "get_output_handle", "s", name);
  if (!handle) return record_py_error("get_output_handle"), -1;
  PyObject* arr = PyObject_CallMethod(handle, "copy_to_cpu", nullptr);
  Py_DECREF(handle);
  if (!arr) return record_py_error("copy_to_cpu"), -1;
  // float32 contiguous view → bytes
  PyObject* np = PyImport_ImportModule("numpy");
  PyObject* f32 = PyObject_CallMethod(np, "ascontiguousarray", "Os", arr,
                                      "float32");
  Py_DECREF(np);
  Py_DECREF(arr);
  if (!f32) return record_py_error("ascontiguousarray"), -1;
  PyObject* shape = PyObject_GetAttrString(f32, "shape");
  int nd = int(PyTuple_Size(shape));
  if (nd > 16) {
    Py_DECREF(shape);
    Py_DECREF(f32);
    g_err = "output rank exceeds the 16-slot out_shape contract";
    return -3;
  }
  int64_t numel = 1;
  for (int i = 0; i < nd; i++) {
    int64_t d = PyLong_AsLongLong(PyTuple_GetItem(shape, i));
    if (out_shape) out_shape[i] = d;
    numel *= d;
  }
  if (out_ndim) *out_ndim = nd;
  Py_DECREF(shape);
  if (out_len) *out_len = numel;
  if (numel > capacity) {
    Py_DECREF(f32);
    g_err = "output larger than caller buffer";
    return -2;  // caller: grow buffer to *out_len and retry
  }
  PyObject* bytes = PyObject_CallMethod(f32, "tobytes", nullptr);
  Py_DECREF(f32);
  if (!bytes) return record_py_error("tobytes"), -1;
  std::memcpy(buf, PyBytes_AsString(bytes), size_t(numel) * 4);
  Py_DECREF(bytes);
  return 0;
}

void PD_DeletePredictor(void* h) {
  auto* p = static_cast<Predictor*>(h);
  if (p) {
    Gil gil;
    Py_XDECREF(p->pred);
    delete p;
  }
}

}  // extern "C"
