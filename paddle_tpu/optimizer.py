"""paddle.optimizer 2.0-preview (reference: python/paddle/optimizer/
__init__.py — torch-style names over the fluid optimizers)."""
from __future__ import annotations

from .fluid.optimizer import (  # noqa: F401
    SGD, Momentum, Adagrad, Adam, Adamax, RMSProp, Adadelta, Ftrl, Lamb,
    LarsMomentum, DecayedAdagrad, Dpsgd, ModelAverage,
    ExponentialMovingAverage, PipelineOptimizer, RecomputeOptimizer,
    LookaheadOptimizer)
from .fluid.contrib.extend_optimizer import (
    extend_with_decoupled_weight_decay as _extend)
from .fluid.optimizer import Adam as _Adam

AdamW = _extend(_Adam)

__all__ = ["SGD", "Momentum", "Adagrad", "Adam", "AdamW", "Adamax",
           "RMSProp", "Adadelta", "Ftrl", "Lamb", "LarsMomentum",
           "DecayedAdagrad", "ModelAverage", "ExponentialMovingAverage",
           "PipelineOptimizer", "RecomputeOptimizer", "LookaheadOptimizer"]
