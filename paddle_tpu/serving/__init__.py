"""Online inference serving plane (docs/SERVING.md).

The reference ships a standalone inference engine — AnalysisConfig/
AnalysisPredictor, ZeroCopyTensor, predictor Clone() for multi-threaded
serving, PredictorPool (analysis_predictor.cc:288,:497) — and leaves
request batching and remote-table serving to the application. This
package is that missing production layer over `paddle_tpu.inference`:

  * `BatchingQueue` — continuous batcher: concurrent `predict()` calls
    coalesce into padded power-of-two buckets (PR 2 stack-and-mask,
    pad rows provably inert), `max_batch` / `max_queue_delay_ms` knobs.
  * `ServingEngine` — the predictor pool: N worker threads share ONE
    compiled executable + read-only param scope (reference Clone()
    semantics, zero weight copies); per-bucket jit caching so
    steady-state traffic never recompiles; `stats()` with QPS,
    batch-size histogram, p50/p99 and cache hit rate; cat="serve"
    profiler spans.
  * `EmbeddingCache` + `rewrite_sparse_lookups` — serving-time sparse
    path: `distributed_lookup_table` pulls over the PR 4 binary wire
    against live pservers, fronted by a TTL + LRU row cache, so
    wide_deep serves without materializing the table in-process (and a
    PR 6 drain/failover re-routes transparently mid-serving).
  * `ServingIngress` + `AdmissionController`/`TokenBucket` — the
    network front end and its overload-robustness contract: JSON-rows
    HTTP (`/predict`, `/healthz`, `/readyz`, `/stats`, multi-model
    routing), deadline propagation down to the PS row fetches, typed
    429/504 shedding with computed `Retry-After`, CoDel-style
    oldest-drop, serve-stale degraded mode under an open per-pserver
    circuit breaker, and SIGTERM graceful drain that loses zero
    accepted requests (docs/SERVING.md "Ingress & overload").
  * `fleet` — the self-healing multi-process layer (docs/SERVING.md
    "Fleet"): trainer→serving invalidation pub/sub over the PR 4 wire
    (`InvalidationPublisher`/`InvalidationSubscriber`), epoch-stamped
    serving membership with heartbeat eviction and zero-lost rolling
    drain (`FleetDirectory`/`FleetMember`/`FleetRouter`), and the
    SLO-holding `Autopilot` the chaos harness exercises.

Quick start::

    pred = inference.create_predictor(inference.Config(model_dir))
    with ServingEngine(pred, max_batch=32,
                       max_queue_delay_ms=2.0) as eng:
        eng.warm()
        (prob,) = eng.predict({"x": row})       # blocks, [1, *out]
        fut = eng.submit({"x": row})            # async, .wait()
        print(eng.stats()["qps"])
"""
from .admission import AdmissionController, TokenBucket
from .batching import BatchingQueue, Request, next_bucket
from .embedding_cache import EmbeddingCache
from .engine import ServingEngine
from .fleet import (Autopilot, FleetDirectory, FleetMember, FleetRouter,
                    InvalidationPublisher, InvalidationSubscriber,
                    NoLiveMembersError, SLO)
from .ingress import ServingIngress
from .sparse import rewrite_sparse_lookups

__all__ = ["ServingEngine", "ServingIngress", "AdmissionController",
           "TokenBucket", "BatchingQueue", "Request", "next_bucket",
           "EmbeddingCache", "rewrite_sparse_lookups",
           "InvalidationPublisher", "InvalidationSubscriber",
           "FleetDirectory", "FleetMember", "FleetRouter",
           "SLO", "Autopilot", "NoLiveMembersError"]
