"""Serving ingress — the network front end over one or more
``ServingEngine``s (docs/SERVING.md "Ingress & overload"; ROADMAP
item 1's missing half: "the engine is in-process only — no HTTP/RPC
ingress").

A threaded HTTP server (stdlib ``ThreadingHTTPServer`` — python
threads are the right tool: the handler is IO-bound glue, the work
happens in the engine's worker pool) speaking JSON rows:

  * ``POST /predict`` (default model) and
    ``POST /models/<name>/predict`` — body
    ``{"feed": {name: row|rows}, "many": bool}``; optional
    ``X-Deadline-Ms`` header carries the request budget (falls back to
    the server default). 200 bodies carry ``outputs`` (row-major
    lists; cast back to ``dtypes`` for the bit-exact values),
    ``degraded`` and ``latency_ms``.
  * ``GET /healthz`` — process liveness (200 while the server runs,
    draining included: a draining pod is alive, just not ready).
  * ``GET /readyz`` — admission readiness (503 once draining).
  * ``GET /stats`` — ingress counters + every model's engine stats.

The robustness contract enforced at this layer (the engine enforces
the rest — queue-expiry 504s, CoDel drops, PS fetch budgets):

  * **typed refusals** — ``core.OverloadedError`` → 429 with a
    ``Retry-After`` computed from the engine's rolling drain rate
    (monotone in queue depth), ``core.DeadlineExceededError`` → 504
    with the queue-wait evidence, engine closed / draining → 503 with
    ``Connection: close``. A refused request never holds a worker.
  * **rate gate** — an optional ``TokenBucket`` sheds sustained
    offered load past ``rate_qps`` at the edge, before it costs a
    queue slot.
  * **graceful drain** — ``drain()`` (or SIGTERM via
    ``install_signal_handlers``) stops admitting (503 +
    ``Connection: close``), lets every accepted request finish
    (engine queues drain to completion), then tears the engines and
    the listener down: a rolling restart loses ZERO accepted requests.
  * **bearer auth** — with ``auth_token`` set, ``/predict`` and
    ``/stats`` require a matching ``X-Auth-Token`` header
    (constant-time compare); a miss is a typed, counted 401.
    ``/healthz``, ``/readyz`` and ``/metrics`` stay open — probes and
    scrapers don't carry secrets. The token rides plaintext HTTP, so
    it only authenticates inside a trusted network segment; TLS
    termination (stdlib ``ssl.wrap`` of the listener or a fronting
    proxy) is documented future work, not a claim this layer makes.

Quick start::

    ing = ServingIngress({"mnist": engine}, default_deadline_ms=500,
                         rate_qps=2000, max_queue_rows=256).start()
    # curl -XPOST localhost:<port>/predict -d '{"feed":{"x":[...]}}'
    ing.close()   # graceful drain
"""
from __future__ import annotations

import hmac
import json
import logging
import math
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from paddle_tpu.fluid import core
from paddle_tpu.fluid import telemetry
from .admission import TokenBucket

__all__ = ["ServingIngress"]

_LOG = logging.getLogger("paddle_tpu.serving")


def _json_bytes(obj) -> bytes:
    return json.dumps(obj).encode("utf-8")


def _retry_after_header(s: float) -> str:
    """RFC 7231 Retry-After is integer delta-seconds (clients do
    int(header) — a fractional value is silently discarded); the
    precise float rides the JSON body as retry_after_ms."""
    return str(max(1, math.ceil(s)))


class ServingIngress:
    """HTTP front end + drain coordinator over named ServingEngines.

    ``models``: ``{name: ServingEngine}`` (or a bare engine, exposed as
    ``"default"``). ``default_model`` picks the ``/predict`` target
    (single-model maps default to that model). The ingress OWNS the
    engines' lifecycle when ``close_engines`` (default): ``close()``
    drains and closes them."""

    def __init__(self, models, *, host: str = "127.0.0.1", port: int = 0,
                 default_model: Optional[str] = None,
                 default_deadline_ms: Optional[float] = None,
                 rate_qps: Optional[float] = None,
                 rate_burst: Optional[float] = None,
                 close_engines: bool = True,
                 drain_timeout_s: float = 30.0,
                 max_body_bytes: int = 16 << 20,
                 auth_token: Optional[str] = None):
        if not isinstance(models, dict):
            models = {"default": models}
        if not models:
            raise ValueError("ServingIngress needs at least one model")
        self._models: Dict[str, Any] = dict(models)
        if default_model is None:
            default_model = (next(iter(models)) if len(models) == 1
                             else None)
        elif default_model not in models:
            raise ValueError(f"default_model {default_model!r} not in "
                             f"models {sorted(models)}")
        self._default_model = default_model
        self._default_deadline_s = (None if default_deadline_ms is None
                                    else float(default_deadline_ms) / 1e3)
        self._bucket = (TokenBucket(rate_qps, rate_burst)
                        if rate_qps else None)
        self._close_engines = bool(close_engines)
        self._drain_timeout_s = float(drain_timeout_s)
        self._max_body_bytes = int(max_body_bytes)
        if auth_token is None:
            auth_token = os.environ.get(
                "FLAGS_serving_auth_token") or None
        self._auth_token = (auth_token.encode("utf-8")
                            if auth_token else None)

        self._admitting = True
        self._closed = False
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._lock = threading.Lock()
        self._counters = {
            "requests": 0, "ok": 0, "shed_429": 0, "expired_504": 0,
            "unavailable_503": 0, "bad_request_400": 0,
            "not_found_404": 0, "upstream_5xx": 0, "rate_limited": 0,
            "degraded_responses": 0, "unauthorized_401": 0,
        }
        self._srv = ThreadingHTTPServer((host, int(port)),
                                        self._make_handler())
        self._srv.daemon_threads = True
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="serving-ingress",
            daemon=True)

    # ------------------------------------------------------------ admin
    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    @property
    def url(self) -> str:
        host = self._srv.server_address[0]
        return f"http://{host}:{self.port}"

    def start(self) -> "ServingIngress":
        # Prometheus surface (docs/OBSERVABILITY.md): the ingress's own
        # counters join the registry so GET /metrics (served below and
        # on the optional FLAGS_metrics_port sidecar) exposes them
        # beside every model engine's counters/views
        self._metrics_view = telemetry.REGISTRY.register_view(
            "serving_ingress", lambda: self.stats()["ingress"])
        telemetry.maybe_start_metrics_server()
        self._thread.start()
        return self

    def drain(self) -> None:
        """Stop admitting: /readyz flips 503, /predict answers 503 with
        ``Connection: close``. Accepted (already-queued) requests keep
        draining — this is the first half of the SIGTERM sequence."""
        self._admitting = False

    def close(self) -> None:
        """Graceful teardown: stop admitting, let every accepted
        request finish (engine queues drain; in-flight HTTP handlers
        flush their responses), then close the engines and the
        listener. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self.drain()
        if self._close_engines:
            for eng in self._models.values():
                try:
                    eng.close()  # drains the queue, joins the workers
                except Exception:
                    _LOG.exception("ingress: engine close failed")
        end = time.monotonic() + self._drain_timeout_s
        with self._inflight_cv:
            while self._inflight > 0:
                left = end - time.monotonic()
                if left <= 0:
                    _LOG.warning(
                        "ingress: %d HTTP handlers still in flight "
                        "after %.0fs drain — shutting down anyway",
                        self._inflight, self._drain_timeout_s)
                    break
                self._inflight_cv.wait(min(left, 0.5))
        view = getattr(self, "_metrics_view", None)
        if view is not None:
            telemetry.REGISTRY.unregister_view(view)
            self._metrics_view = None
        self._srv.shutdown()
        self._srv.server_close()

    def install_signal_handlers(self) -> bool:
        """SIGTERM → graceful drain+close on a helper thread (the
        rolling-restart contract). Returns False when not on the main
        thread (signal registration is main-thread-only)."""
        import signal

        def _on_term(signum, frame):
            _LOG.warning("ingress: SIGTERM — draining")
            threading.Thread(target=self.close, daemon=True,
                             name="ingress-sigterm-drain").start()

        try:
            signal.signal(signal.SIGTERM, _on_term)
            return True
        except ValueError:
            return False

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            counters = dict(self._counters)
        return {
            "ingress": {**counters, "admitting": self._admitting,
                        "inflight": self._inflight,
                        "default_model": self._default_model,
                        "rate_qps": (self._bucket.rate_qps
                                     if self._bucket else None)},
            "models": {name: eng.stats()
                       for name, eng in self._models.items()},
        }

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counters[key] += n

    # ---------------------------------------------------------- handler
    def _route(self, path: str):
        """'/predict' → default engine; '/models/<name>/predict' →
        named engine. Returns (name, engine) or (None, None)."""
        if path == "/predict":
            name = self._default_model
            if name is None:
                return None, None
            return name, self._models.get(name)
        parts = path.strip("/").split("/")
        if len(parts) == 3 and parts[0] == "models" \
                and parts[2] == "predict":
            return parts[1], self._models.get(parts[1])
        return None, None

    def _make_handler(self):
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            server_version = "paddle-tpu-serving"

            def log_message(self, fmt, *args):  # stay off stderr
                _LOG.debug("ingress %s " + fmt,
                           self.client_address[0], *args)

            # ---------------------------------------------- responses
            def _reply(self, status: int, obj,
                       headers: Optional[Dict[str, str]] = None,
                       close_conn: bool = False) -> None:
                body = _json_bytes(obj)
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                trace_id = getattr(self, "_trace_id", None)
                if trace_id:
                    # round-trip contract: every /predict response —
                    # 200, 429, 504, 400 alike — names the trace id the
                    # request ran under, minted here when the client
                    # sent none
                    self.send_header("X-Trace-Id", trace_id)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                if close_conn:
                    self.send_header("Connection", "close")
                    self.close_connection = True
                self.end_headers()
                self.wfile.write(body)

            def _check_auth(self) -> bool:
                """True when the request may proceed. With a token
                configured, compares X-Auth-Token in constant time
                (hmac.compare_digest — a plain == would leak the match
                prefix length through timing) and answers a typed,
                counted 401 on a miss."""
                tok = outer._auth_token
                if tok is None:
                    return True
                got = (self.headers.get("X-Auth-Token") or "") \
                    .encode("utf-8")
                if hmac.compare_digest(got, tok):
                    return True
                outer._bump("unauthorized_401")
                self._reply(
                    401, {"error": "unauthorized",
                          "detail": "missing or invalid X-Auth-Token"},
                    headers={"WWW-Authenticate": "X-Auth-Token"})
                return False

            def _reply_unavailable(self) -> None:
                outer._bump("unavailable_503")
                self._reply(
                    503, {"error": "draining",
                          "detail": "server is draining — not "
                                    "admitting new requests"},
                    headers={"Retry-After": "1"}, close_conn=True)

            # --------------------------------------------------- GETs
            def do_GET(self):
                # a keep-alive connection reuses this handler object:
                # a previous /predict's trace id must not leak onto an
                # unrelated GET response
                self._trace_id = None
                if self.path == "/healthz":
                    # liveness: a draining pod is alive, just not ready
                    self._reply(200, {"status": "ok"})
                    return
                if self.path == "/readyz":
                    if outer._admitting:
                        self._reply(200, {"status": "ready"})
                    else:
                        outer._bump("unavailable_503")
                        self._reply(503, {"status": "draining"},
                                    close_conn=True)
                    return
                if self.path == "/stats":
                    if not self._check_auth():
                        return
                    self._reply(200, outer.stats())
                    return
                if self.path == "/metrics":
                    # Prometheus text exposition over the process
                    # registry — counters here are the SAME objects
                    # stats() reads, so the two surfaces cannot drift
                    body = telemetry.REGISTRY.exposition() \
                        .encode("utf-8")
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                outer._bump("not_found_404")
                self._reply(404, {"error": "not_found",
                                  "detail": self.path})

            # --------------------------------------------------- POST
            def do_POST(self):
                with outer._inflight_cv:
                    outer._inflight += 1
                try:
                    self._predict()
                finally:
                    with outer._inflight_cv:
                        outer._inflight -= 1
                        outer._inflight_cv.notify_all()

            def _predict(self):
                outer._bump("requests")
                # trace correlation (docs/OBSERVABILITY.md): accept the
                # caller's X-Trace-Id (sanitized) or mint one; the
                # request executes under it and every response carries
                # it back
                hdr = self.headers.get("X-Trace-Id")
                if hdr:
                    hdr = "".join(ch for ch in hdr.strip()[:64]
                                  if ch.isalnum() or ch in "-_")
                self._trace_id = hdr or telemetry.new_trace_id()
                # consume the body FIRST: an early error return (404,
                # 429) that leaves it unread would desync the
                # keep-alive stream — the next request line would parse
                # from body bytes. JSON decoding still waits until
                # after the cheap gates.
                try:
                    n = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    n = -1
                if n < 0 or n > outer._max_body_bytes:
                    # bound the buffer BEFORE reading: the overload
                    # layer must not be OOM-able by one giant
                    # Content-Length. Close the connection — a body
                    # this size is not worth draining to stay in sync.
                    outer._bump("bad_request_400")
                    self._reply(
                        413 if n > 0 else 400,
                        {"error": "payload_too_large" if n > 0
                         else "bad_request",
                         "max_body_bytes": outer._max_body_bytes},
                        close_conn=True)
                    return
                try:
                    raw = self.rfile.read(n) if n > 0 else b""
                except OSError:
                    outer._bump("bad_request_400")
                    self._reply(400, {"error": "bad_request",
                                      "detail": "unreadable body"},
                                close_conn=True)
                    return
                # auth after the body read (keep-alive stays in sync)
                # but before anything that costs queue slots or tokens
                if not self._check_auth():
                    return
                if not outer._admitting:
                    self._reply_unavailable()
                    return
                name, eng = outer._route(self.path)
                if eng is None:
                    outer._bump("not_found_404")
                    self._reply(404, {
                        "error": "not_found",
                        "detail": f"no model at {self.path!r}; models: "
                                  f"{sorted(outer._models)}"})
                    return

                # edge rate gate: sustained load past the configured
                # QPS sheds here, before it costs a queue slot
                if outer._bucket is not None \
                        and not outer._bucket.try_acquire():
                    ra = outer._bucket.retry_after_s()
                    outer._bump("shed_429")
                    outer._bump("rate_limited")
                    self._reply(
                        429, {"error": "overloaded",
                              "where": "rate_gate",
                              "retry_after_ms": round(ra * 1e3, 3)},
                        headers={"Retry-After":
                                 _retry_after_header(ra)})
                    return

                try:
                    payload = json.loads(raw.decode("utf-8"))
                    feed_in = payload["feed"]
                    many = bool(payload.get("many", False))
                    feed = {k: np.asarray(v) for k, v in feed_in.items()}
                except Exception as e:
                    outer._bump("bad_request_400")
                    self._reply(400, {"error": "bad_request",
                                      "detail": repr(e)})
                    return

                deadline_s = outer._default_deadline_s
                hdr = self.headers.get("X-Deadline-Ms")
                if hdr is not None:
                    try:
                        deadline_s = float(hdr) / 1e3
                    except ValueError:
                        outer._bump("bad_request_400")
                        self._reply(400, {
                            "error": "bad_request",
                            "detail": f"X-Deadline-Ms: {hdr!r}"})
                        return

                t0 = time.perf_counter()
                wait_s = (120.0 if deadline_s is None
                          else deadline_s + 5.0)
                try:
                    # the submit runs under the request's trace: the
                    # engine stamps it on the Request, and the worker
                    # re-installs it around queue_wait/exec spans and
                    # the PS sparse fetches
                    with telemetry.trace_scope(trace_id=self._trace_id):
                        req = eng.submit(feed, many=many,
                                         deadline_s=deadline_s)
                    outs = req.wait(wait_s)
                except core.OverloadedError as e:
                    outer._bump("shed_429")
                    self._reply(
                        429, {"error": "overloaded",
                              "retry_after_ms": round(
                                  e.retry_after_s * 1e3, 3),
                              "detail": str(e)},
                        headers={"Retry-After": _retry_after_header(
                            e.retry_after_s)})
                    return
                except core.DeadlineExceededError as e:
                    outer._bump("expired_504")
                    body = {"error": "deadline_exceeded",
                            "detail": str(e)}
                    if e.queue_wait_s is not None:
                        body["queue_wait_ms"] = round(
                            e.queue_wait_s * 1e3, 3)
                    self._reply(504, body)
                    return
                except TimeoutError as e:
                    outer._bump("expired_504")
                    self._reply(504, {"error": "deadline_exceeded",
                                      "detail": repr(e)})
                    return
                except (KeyError, ValueError) as e:
                    # engine feed validation
                    outer._bump("bad_request_400")
                    self._reply(400, {"error": "bad_request",
                                      "detail": repr(e)})
                    return
                except RuntimeError as e:
                    if "closed" in str(e):
                        self._reply_unavailable()
                        return
                    outer._bump("upstream_5xx")
                    self._reply(502, {"error": "upstream_error",
                                      "detail": repr(e)})
                    return
                except Exception as e:
                    outer._bump("upstream_5xx")
                    self._reply(502, {"error": "upstream_error",
                                      "detail": repr(e)})
                    return

                outer._bump("ok")
                if req.degraded:
                    outer._bump("degraded_responses")
                # row-major float lists: f32 → f64 widening is exact
                # and repr(f64) round-trips, so casting back to the
                # shipped dtypes recovers the engine's bits exactly
                # (the HTTP bit-parity acceptance leg)
                self._reply(200, {
                    "model": name,
                    "outputs": [np.asarray(o).tolist() for o in outs],
                    "dtypes": [str(np.asarray(o).dtype) for o in outs],
                    "degraded": bool(req.degraded),
                    "latency_ms": round(
                        (time.perf_counter() - t0) * 1e3, 3),
                })

        return _Handler
