"""Admission control + load shedding for the serving ingress
(docs/SERVING.md "Ingress & overload").

A production engine at 4× capacity is defined by what it REFUSES: work
it cannot finish inside the caller's deadline must be shed immediately
with a typed answer (429 + Retry-After), never queued to die. Three
cooperating gates:

  * ``TokenBucket`` — a rate gate at the HTTP edge: sustained offered
    load beyond the configured QPS is refused before it costs a queue
    slot (reference role: BRPC's max_concurrency / ingress qps quota).
  * ``AdmissionController`` — a bounded admission queue: past
    ``max_queue_rows`` pending rows the engine sheds at submit with
    ``core.OverloadedError`` carrying a Retry-After computed from the
    rolling row-throughput estimate (monotone in queue depth).
  * CoDel-style oldest-drop (in ``ServingEngine._execute``): when the
    head-of-queue sojourn exceeds ``codel_target_ms`` continuously for
    ``codel_interval_ms``, the OLDEST request is dropped (typed 429) —
    head drops shrink everyone else's wait, which is what bounds
    accepted-request p99 under sustained overload (CoDel's insight;
    tail drops would punish the newest request while the queue stays
    just as stale).

The module also owns the per-dispatch DEGRADED scope: when the sparse
path serves beyond-TTL cache rows because the pservers are unreachable
(EmbeddingCache serve-stale under an open circuit breaker), it flags
the scope and the engine marks every request of the bucket
``degraded=True`` — a 200 with a warning label, not a 5xx.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from paddle_tpu.fluid import core

__all__ = ["TokenBucket", "AdmissionController", "degraded_scope",
           "note_degraded"]


class TokenBucket:
    """Classic token bucket: ``rate_qps`` tokens/s refill up to
    ``burst``. ``try_acquire`` never blocks — the ingress maps a refusal
    straight to 429 (shedding at the edge must not hold the socket).
    Thread-safe; injectable clock for tests."""

    def __init__(self, rate_qps: float, burst: Optional[float] = None):
        if rate_qps <= 0:
            raise ValueError(f"rate_qps must be > 0, got {rate_qps}")
        self.rate_qps = float(rate_qps)
        self.burst = float(burst if burst is not None
                           else max(1.0, rate_qps / 10.0))
        self._tokens = self.burst
        self._lock = threading.Lock()
        self._clock = time.monotonic
        self._t_last = self._clock()

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst,
                self._tokens + (now - self._t_last) * self.rate_qps)
            self._t_last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def retry_after_s(self, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens will have refilled — the
        Retry-After a rate-gate 429 carries."""
        with self._lock:
            deficit = max(0.0, n - self._tokens)
        return max(0.05, deficit / self.rate_qps)


class AdmissionController:
    """Queue-bound + CoDel knobs for one ServingEngine.

    ``max_queue_rows`` bounds the admission queue in ROWS (the unit the
    batcher flushes in); ``codel_target_ms``/``codel_interval_ms`` are
    the CoDel pair: sojourn above target for longer than interval ⇒
    drop the head. ``fallback_row_s`` prices a queued row when no
    throughput estimate exists yet (cold engine) so Retry-After is
    still monotone in depth from the first shed."""

    def __init__(self, max_queue_rows: int = 256,
                 codel_target_ms: float = 100.0,
                 codel_interval_ms: float = 500.0,
                 fallback_row_s: float = 0.005,
                 max_retry_after_s: float = 10.0):
        if max_queue_rows < 1:
            raise ValueError("max_queue_rows must be >= 1")
        self.max_queue_rows = int(max_queue_rows)
        self.codel_target_s = float(codel_target_ms) / 1e3
        self.codel_interval_s = float(codel_interval_ms) / 1e3
        self.fallback_row_s = float(fallback_row_s)
        self.max_retry_after_s = float(max_retry_after_s)

    def retry_after_s(self, pending_rows: int,
                      row_rate: float = 0.0) -> float:
        """Drain-time estimate for ``pending_rows`` at the engine's
        recent ``row_rate`` (rows/s; <=0 = unknown → fallback price).
        Monotone nondecreasing in pending_rows for a fixed rate — the
        contract the overload test asserts — and clamped so a transient
        stall can't tell clients to go away for minutes."""
        if row_rate > 0:
            est = pending_rows / row_rate
        else:
            est = pending_rows * self.fallback_row_s
        return min(self.max_retry_after_s, max(0.05, est))

    def admit(self, n_rows: int, pending_rows: int,
              row_rate: float = 0.0) -> None:
        """Raise typed ``core.OverloadedError`` when accepting
        ``n_rows`` more would exceed the queue bound; no-op otherwise.
        The shed happens BEFORE the queue ever sees the request —
        "never queued to die"."""
        if pending_rows + n_rows > self.max_queue_rows:
            raise core.OverloadedError(
                f"admission queue full ({pending_rows} rows pending, "
                f"bound {self.max_queue_rows}) — shedding",
                retry_after_s=self.retry_after_s(pending_rows, row_rate))


# ---------------------------------------------------------------------------
# degraded scope: per-dispatch thread-local accumulator. The engine
# enters it around a bucket's execution; EmbeddingCache.lookup bumps it
# when it serves beyond-TTL rows on a fetch failure (the lookup runs on
# the dispatching worker thread, so thread-local attribution is exact).
# ---------------------------------------------------------------------------
_DEGRADED = threading.local()


class degraded_scope:
    """Context manager collecting degraded-serve events on this thread.
    ``scope.count`` after exit = stale rows served inside it."""

    def __enter__(self):
        self._prev = getattr(_DEGRADED, "box", None)
        self._box = [0]
        _DEGRADED.box = self._box
        return self

    def __exit__(self, *exc):
        _DEGRADED.box = self._prev
        if self._prev is not None:
            self._prev[0] += self._box[0]  # nested scopes roll up
        return False

    @property
    def count(self) -> int:
        return self._box[0]


def note_degraded(n: int = 1) -> None:
    """Record ``n`` stale rows served degraded in the enclosing scope
    (no-op outside one)."""
    box = getattr(_DEGRADED, "box", None)
    if box is not None:
        box[0] += int(n)
