"""Dynamic/continuous request batching for the online serving plane.

The reference serves concurrent traffic by cloning AnalysisPredictor per
thread (analysis_predictor.cc Clone + paddle_inference_api.h
PredictorPool) and leaves batching to the application. Here batching is
the system's job: a ``BatchingQueue`` coalesces concurrent ``predict()``
calls — each a single row (or a small row group) — into ONE padded
power-of-two bucket per dispatch, the same stack-and-mask idiom the
PR 2 window machinery uses for training feeds (``WindowBatch.n_valid``):
pad rows repeat the last real row and are sliced away after the
dispatch, so they can never change a real row's output.

Flush policy (the continuous-batching contract):
  * a batch dispatches as soon as ``max_batch`` rows are pending, or
  * when the OLDEST pending request has waited ``max_queue_delay_ms``
    — a lone request never waits for company longer than the knob.
Requests are atomic: a multi-row request rides one bucket whole.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

__all__ = ["BatchingQueue", "Request", "next_bucket"]


def next_bucket(n: int) -> int:
    """Smallest power of two >= n — the compiled bucket a batch of n
    rows pads into. Bounding the shape set to powers of two is what
    makes steady-state traffic stop recompiling: every batch size in
    [1, max_batch] lands in one of log2(max_batch)+1 cached
    executables."""
    if n <= 1:
        return 1
    return 1 << (int(n - 1).bit_length())


class Request:
    """One in-flight predict() call: ``rows`` maps feed name to an
    [n, *sample] array; the worker fulfils ``_event`` with either the
    per-fetch row slices or an error. Also the future handed back by
    the async submit path."""

    __slots__ = ("rows", "n", "t_submit", "t_dispatch", "t_done",
                 "deadline", "degraded", "admin", "trace", "_event",
                 "_result", "_error")

    def __init__(self, rows: Dict[str, np.ndarray], n: int,
                 deadline: Optional[float] = None,
                 admin: bool = False):
        self.rows = rows
        self.n = int(n)
        # trace correlation (telemetry.trace_scope): the submitting
        # thread's context, re-installed by the worker around this
        # request's queue_wait/exec spans and PS sparse fetches so the
        # HTTP X-Trace-Id follows the request across the thread hop
        self.trace = None
        self.t_submit = time.perf_counter()
        self.t_dispatch = 0.0
        self.t_done = 0.0  # stamped at fulfilment (open-loop latency)
        # absolute perf_counter deadline (None = unbudgeted): checked at
        # take time (expired requests 504 instead of holding a worker)
        # and propagated into PS row fetches as the RPC call budget
        self.deadline = deadline
        # set by the worker when the bucket was served from beyond-TTL
        # stale cache rows (pservers unreachable) — a 200 with a
        # warning label, surfaced as degraded=true by the HTTP ingress
        self.degraded = False
        # admin requests (warm()) bypassed admission at submit and are
        # exempt from the CoDel head-drop too — shedding the compile
        # you asked for defeats the op
        self.admin = bool(admin)
        self._event = threading.Event()
        self._result: Optional[List[np.ndarray]] = None
        self._error: Optional[BaseException] = None

    # -------------------------------------------------- future surface
    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> List[np.ndarray]:
        """Block until the batch carrying this request executed; returns
        one [n, *out] array per fetch target, or re-raises the batch's
        error."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"predict() result not ready after {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result

    # worker-side
    def set_result(self, result: List[np.ndarray]) -> None:
        self._result = result
        self.t_done = time.perf_counter()
        self._event.set()

    def set_error(self, err: BaseException) -> None:
        self._error = err
        self.t_done = time.perf_counter()
        self._event.set()


class BatchingQueue:
    """The continuous batcher: clients ``submit`` row requests, worker
    threads ``take`` coalesced batches. Thread-safe; ``close()`` wakes
    every waiter (pending requests still drain — a server shutdown must
    not drop accepted work)."""

    def __init__(self, max_batch: int = 64,
                 max_queue_delay_ms: float = 2.0):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = int(max_batch)
        self.max_queue_delay_s = float(max_queue_delay_ms) / 1000.0
        self._pending: "deque[Request]" = deque()
        self._rows_pending = 0
        self._cv = threading.Condition()
        self._closed = False

    def __len__(self) -> int:
        with self._cv:
            return self._rows_pending

    def submit(self, req: Request) -> Request:
        with self._cv:
            if self._closed:
                raise RuntimeError("BatchingQueue is closed")
            self._pending.append(req)
            self._rows_pending += req.n
            self._cv.notify_all()
        return req

    def take(self, timeout: Optional[float] = None) -> List[Request]:
        """Block until a batch is ready under the flush policy and pop
        it (whole requests, up to ``max_batch`` rows — an oversized
        request larger than max_batch dispatches alone). Returns [] on
        ``timeout`` with nothing pending, or when closed and drained —
        the worker-loop poll shape."""
        deadline = None if timeout is None else time.perf_counter() + timeout
        with self._cv:
            while True:
                now = time.perf_counter()
                if self._pending:
                    flush_at = (self._pending[0].t_submit
                                + self.max_queue_delay_s)
                    if (self._rows_pending >= self.max_batch
                            or now >= flush_at or self._closed):
                        return self._pop_locked()
                    wait = flush_at - now
                    if deadline is not None:
                        wait = min(wait, deadline - now)
                else:
                    if self._closed:
                        return []
                    if deadline is not None:
                        wait = deadline - now
                        if wait <= 0:
                            return []
                    else:
                        wait = None
                self._cv.wait(wait if wait is None else max(wait, 1e-4))

    def _pop_locked(self) -> List[Request]:
        batch: List[Request] = []
        rows = 0
        while self._pending and (
                not batch
                or rows + self._pending[0].n <= self.max_batch):
            r = self._pending.popleft()
            batch.append(r)
            rows += r.n
        self._rows_pending -= rows
        return batch

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
