"""Client-side embedding-row cache for serving-time sparse lookups.

At serving QPS the hot ids of a CTR workload repeat heavily batch to
batch; pulling them from the pservers on every request wastes the wire
the PR 4 data plane made fast. This cache fronts
``distributed_lookup_table`` pulls (hook: ``fluid.ps_rpc
.install_row_cache``): a fully-hit lookup issues ZERO RPCs, misses
fan out to the pservers as usual and fill the cache.

Consistency contract (docs/SERVING.md "Embedding-cache staleness"): a
cached row is served for up to ``ttl_s`` seconds after its fetch even
if a trainer has since updated the table — online serving trades
bounded staleness for RPC elision, exactly like the reference's
serving-side quantized/compressed table snapshots. Set ``ttl_s=0`` to
make every lookup re-validate (cache becomes a dedup layer only), or
don't install the cache where bit-freshness matters.

Bounded: ``max_entries`` rows, LRU-evicted. All counters are exposed
via ``stats()`` and surface in ``ServingEngine.stats()``.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Dict

import numpy as np

__all__ = ["EmbeddingCache"]


class EmbeddingCache:
    """(table, id) -> row cache with TTL + max-entries LRU.

    ``lookup`` is the one entry point: resolves hits under the lock,
    fetches the missing ids through ``fetch_fn`` OUTSIDE the lock (an
    RPC must never block other threads' hit paths), then fills. Two
    threads missing the same id may both fetch it — benign duplicate
    work, never wrong data."""

    def __init__(self, ttl_s: float = 30.0, max_entries: int = 1_000_000):
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self.ttl_s = float(ttl_s)
        self.max_entries = int(max_entries)
        self._rows: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._lock = threading.Lock()
        # bumped by invalidate(): an in-flight miss fetch that STARTED
        # before the invalidation must not fill the cache afterwards —
        # it may carry pre-push rows, and caching them would defeat the
        # "visible immediately" contract for up to another ttl_s
        self._gen = 0
        # injectable clock so tests drive TTL expiry without sleeping
        self._clock = time.monotonic
        self.hits = 0
        self.misses = 0
        self.expired = 0      # staleness counter: TTL'd entries refetched
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def lookup(self, table: str, ids, fetch_fn: Callable) -> np.ndarray:
        """Rows for ``ids`` (any int array-like), cached where possible.
        ``fetch_fn(missing_ids)`` -> [len(missing), dim] array pulls the
        rest from the pservers. Returns [len(ids), dim] in input order,
        bit-identical to an uncached pull while the table is
        unchanged."""
        ids = np.asarray(ids).reshape(-1)
        out = [None] * len(ids)
        missing_idx = []
        now = self._clock()
        with self._lock:
            gen0 = self._gen
            for i, id_ in enumerate(ids.tolist()):
                key = (table, id_)
                ent = self._rows.get(key)
                if ent is not None:
                    row, stamp = ent
                    if self.ttl_s > 0 and (now - stamp) <= self.ttl_s:
                        self._rows.move_to_end(key)
                        out[i] = row
                        self.hits += 1
                        continue
                    # stale: drop now so a concurrent hit can't serve it
                    # while our refetch is in flight
                    del self._rows[key]
                    self.expired += 1
                self.misses += 1
                missing_idx.append(i)
        if missing_idx:
            miss_ids = ids[missing_idx]
            # duplicate ids within the miss set fetch once
            uniq, inv = np.unique(miss_ids, return_inverse=True)
            fetched = np.asarray(fetch_fn(uniq))
            if fetched.shape[0] != len(uniq):
                raise ValueError(
                    f"fetch_fn returned {fetched.shape[0]} rows for "
                    f"{len(uniq)} ids")
            now = self._clock()
            with self._lock:
                if self._gen == gen0:  # no invalidate() raced the fetch
                    for j, id_ in enumerate(uniq.tolist()):
                        # detach: the caller may mutate/donate its arrays
                        self._rows[(table, id_)] = (np.array(fetched[j]),
                                                    now)
                    while len(self._rows) > self.max_entries:
                        self._rows.popitem(last=False)
                        self.evictions += 1
            for k, i in enumerate(missing_idx):
                out[i] = fetched[inv[k]]
        return np.asarray(out)

    def invalidate(self, table: str = None) -> None:
        """Drop every entry (or just one table's) — e.g. after a model/
        table push the operator wants visible immediately. Also fences
        in-flight miss fetches: rows fetched before this call cannot
        fill the cache after it."""
        with self._lock:
            self._gen += 1
            if table is None:
                self._rows.clear()
                return
            for key in [k for k in self._rows if k[0] == table]:
                del self._rows[key]

    def stats(self) -> Dict[str, float]:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._rows),
                "max_entries": self.max_entries,
                "ttl_s": self.ttl_s,
                "hits": self.hits,
                "misses": self.misses,
                "expired": self.expired,
                "evictions": self.evictions,
                "hit_rate": (self.hits / total) if total else 0.0,
            }
