"""Client-side embedding-row cache for serving-time sparse lookups.

At serving QPS the hot ids of a CTR workload repeat heavily batch to
batch; pulling them from the pservers on every request wastes the wire
the PR 4 data plane made fast. This cache fronts
``distributed_lookup_table`` pulls (hook: ``fluid.ps_rpc
.install_row_cache``): a fully-hit lookup issues ZERO RPCs, misses
fan out to the pservers as usual and fill the cache.

Consistency contract (docs/SERVING.md "Embedding-cache staleness"): a
cached row is served for up to ``ttl_s`` seconds after its fetch even
if a trainer has since updated the table — online serving trades
bounded staleness for RPC elision, exactly like the reference's
serving-side quantized/compressed table snapshots. Set ``ttl_s=0`` to
make every lookup re-validate (cache becomes a dedup layer only), or
don't install the cache where bit-freshness matters.

Two robustness extensions (docs/SERVING.md "Ingress & overload"):

  * **serve-stale degraded mode** (``serve_stale=True``, the default):
    a refetch of beyond-TTL rows that dies with a transport-typed
    error (ConnectionError incl. the circuit breaker's fast-fail,
    timeout/deadline, ``WorkerDeadError``, a surfaced
    ``StaleClusterViewError`` mid-failover) is answered from the
    RETAINED stale copies instead of failing the request — flagged
    through ``admission.note_degraded`` so the engine marks the
    response ``degraded=True`` and counts it. Only rows the cache has
    EVER held qualify; an uncovered row re-raises (the caller's 5xx is
    honest there). Recovery is automatic: the moment a fetch succeeds
    again (breaker half-open probe, PR 6 replica promotion installing
    a new view), fresh rows overwrite and the degraded flag stops.
  * **trainer-pushed invalidation** (``invalidate_rows``):
    ``distributed_lookup_table_grad`` pushes call it inline for their
    row ids (the same hook contract the PR 8 ``PrefetchBuffer``
    defined), so in a train+serve process staleness is PUSH-bounded,
    not only TTL-bounded. Per-key stage-seq fences close the race the
    PrefetchBuffer closed: a miss fetch in flight ACROSS the push must
    not re-fill pre-push rows (its copy may predate the update), while
    a fetch that STARTED after the push is fresh and clears the fence.

Bounded: ``max_entries`` rows, LRU-evicted. All counters are exposed
via ``stats()`` and surface in ``ServingEngine.stats()``.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Dict

import numpy as np

from paddle_tpu.fluid import core, telemetry

__all__ = ["EmbeddingCache"]


# ------------------------------------------------------------------ metrics
# Registry-native invalidation evidence (docs/SERVING.md "Fleet"): the
# fleet acceptance numbers — rows invalidated by trainer pushes, fence
# overflows collapsing to the generation fence, and the push→applied
# staleness window — must be scrapeable at GET /metrics, not hand-probed
# from stats() dicts. Families are fetched per use (get-or-create) so a
# REGISTRY.reset() between tests can never leave dangling children.
def _m_rows_invalidated():
    return telemetry.REGISTRY.counter(
        "serving_cache_rows_invalidated_total",
        "embedding-cache rows dropped by trainer-push invalidations")


def _m_fence_overflow():
    return telemetry.REGISTRY.counter(
        "serving_cache_fence_overflow_total",
        "per-key fence maps collapsed to the generation fence")


def _m_staleness_window():
    return telemetry.REGISTRY.histogram(
        "serving_cache_staleness_window_seconds",
        "trainer push -> invalidation applied at a serving cache",
        buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                 0.1, 0.25, 0.5, 1.0, 2.5, 5.0))


def _m_event_freshness():
    # the streaming-lane acceptance number (docs/FAULT_TOLERANCE.md
    # "Streaming online learning"): event observed by the trainer →
    # FIRST served prediction that reads the refreshed row. Longer
    # buckets than the staleness window — it additionally spans the
    # wait until traffic next touches the key.
    return telemetry.REGISTRY.histogram(
        "serving_event_freshness_seconds",
        "trainer-observed event -> first served prediction reflecting it",
        buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5, 5.0, 10.0))

# fetch failures the serve-stale path may absorb: the transport family
# (breaker fast-fail CircuitOpenError ⊂ ConnectionError, deadline ⊂
# TimeoutError ⊂ OSError), the PR 3 typed worker-death, and a
# StaleClusterViewError that SURFACED (re-route budget spent while
# membership converges — rows are unreachable for the moment, not gone)
_STALE_SERVABLE = (ConnectionError, OSError, TimeoutError,
                   core.WorkerDeadError, core.StaleClusterViewError)


class EmbeddingCache:
    """(table, id) -> row cache with TTL + max-entries LRU.

    ``lookup`` is the one entry point: resolves hits under the lock,
    fetches the missing ids through ``fetch_fn`` OUTSIDE the lock (an
    RPC must never block other threads' hit paths), then fills. Two
    threads missing the same id may both fetch it — benign duplicate
    work, never wrong data."""

    # per-key fence-map bound: past this the invalidation degrades to
    # the global generation fence (conservative: NO in-flight fill may
    # land) instead of growing without bound on long-tail pushed ids
    _FENCE_CAP = 1 << 20

    def __init__(self, ttl_s: float = 30.0, max_entries: int = 1_000_000,
                 serve_stale: bool = True):
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self.ttl_s = float(ttl_s)
        self.max_entries = int(max_entries)
        self.serve_stale = bool(serve_stale)
        self._rows: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._lock = threading.Lock()
        # bumped by invalidate(): an in-flight miss fetch that STARTED
        # before the invalidation must not fill the cache afterwards —
        # it may carry pre-push rows, and caching them would defeat the
        # "visible immediately" contract for up to another ttl_s
        self._gen = 0
        # per-key push fences (invalidate_rows): key -> seq of the last
        # push; a fill whose fetch started at or before that seq skips
        # the key, one that started after it clears the fence
        self._seq = 0
        self._fence: Dict[tuple, int] = {}
        # event-freshness pending stamps (invalidate_rows t_event=):
        # key -> wall-clock time the trainer observed the event; popped
        # and observed into serving_event_freshness_seconds by the first
        # post-fence fill that serves the refreshed row. EARLIEST stamp
        # wins when pushes coalesce before a refetch — the conservative
        # (upper-bound) freshness sample.
        self._pending_fresh: Dict[tuple, float] = {}
        # injectable clock so tests drive TTL expiry without sleeping
        self._clock = time.monotonic
        self.hits = 0
        self.misses = 0
        self.expired = 0      # staleness counter: TTL'd entries refetched
        self.evictions = 0
        self.stale_served = 0      # degraded: beyond-TTL rows served
        self.invalidated_rows = 0  # trainer-pushed row invalidations
        self.fence_overflows = 0   # fence maps collapsed to generation
        self.freshness_samples = 0  # event→served samples observed

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def lookup(self, table: str, ids, fetch_fn: Callable) -> np.ndarray:
        """Rows for ``ids`` (any int array-like), cached where possible.
        ``fetch_fn(missing_ids)`` -> [len(missing), dim] array pulls the
        rest from the pservers. Returns [len(ids), dim] in input order,
        bit-identical to an uncached pull while the table is
        unchanged. A transport-dead refetch of rows the cache still
        holds beyond TTL serves the stale copies flagged degraded
        (``serve_stale`` above) instead of raising."""
        ids = np.asarray(ids).reshape(-1)
        out = [None] * len(ids)
        missing_idx = []
        stale_fallback: Dict[int, np.ndarray] = {}
        now = self._clock()
        with self._lock:
            gen0 = self._gen
            tok0 = self._seq
            for i, id_ in enumerate(ids.tolist()):
                key = (table, id_)
                ent = self._rows.get(key)
                if ent is not None:
                    row, stamp = ent
                    if self.ttl_s > 0 and (now - stamp) <= self.ttl_s:
                        self._rows.move_to_end(key)
                        out[i] = row
                        self.hits += 1
                        continue
                    # beyond TTL: refetch, but RETAIN the copy — hits
                    # check TTL so nothing serves it fresh, and it is
                    # the serve-stale fallback if the pservers are dark
                    self.expired += 1
                    stale_fallback[i] = row
                self.misses += 1
                missing_idx.append(i)
        if missing_idx:
            miss_ids = ids[missing_idx]
            # duplicate ids within the miss set fetch once
            uniq, inv = np.unique(miss_ids, return_inverse=True)
            try:
                fetched = np.asarray(fetch_fn(uniq))
            except _STALE_SERVABLE:
                if not self.serve_stale \
                        or any(i not in stale_fallback
                               for i in missing_idx):
                    raise  # an uncovered row: the failure is real
                from . import admission as _admission
                with self._lock:
                    self.stale_served += len(missing_idx)
                _admission.note_degraded(len(missing_idx))
                for i in missing_idx:
                    out[i] = stale_fallback[i]
                return np.asarray(out)
            if fetched.shape[0] != len(uniq):
                raise ValueError(
                    f"fetch_fn returned {fetched.shape[0]} rows for "
                    f"{len(uniq)} ids")
            now = self._clock()
            fresh_lags = []
            with self._lock:
                if self._gen == gen0:  # no invalidate() raced the fetch
                    wall = time.time()
                    for j, id_ in enumerate(uniq.tolist()):
                        key = (table, id_)
                        fence = self._fence.get(key)
                        if fence is not None:
                            if fence > tok0:
                                # pushed AFTER this fetch started: the
                                # fetched copy may predate the push —
                                # serve it (fresh enough for THIS call)
                                # but never cache it
                                continue
                            del self._fence[key]  # post-push fetch
                            # this fill serves the refreshed row: the
                            # pending event is now REFLECTED in a
                            # served prediction
                            stamp = self._pending_fresh.pop(key, None)
                            if stamp is not None:
                                fresh_lags.append(max(0.0, wall - stamp))
                                self.freshness_samples += 1
                        # detach: the caller may mutate/donate arrays
                        self._rows[key] = (np.array(fetched[j]), now)
                    while len(self._rows) > self.max_entries:
                        self._rows.popitem(last=False)
                        self.evictions += 1
            if fresh_lags:
                hist = _m_event_freshness()
                for lag in fresh_lags:
                    hist.observe(lag)
            for k, i in enumerate(missing_idx):
                out[i] = fetched[inv[k]]
        return np.asarray(out)

    def invalidate_rows(self, table: str, ids, t_event=None) -> None:
        """The trainer pushed grads for ``ids`` (called inline by
        ``distributed_lookup_table_grad`` BEFORE the push ships — the
        PR 8 row-cache hook contract): drop their cached rows and fence
        them out of any in-flight miss fetch, so the next lookup
        refetches post-push values. Staleness becomes push-bounded.

        ``t_event`` (wall-clock seconds): when the trainer OBSERVED the
        event behind this push (the publisher's t_pub on the fleet
        wire, time.time() on the inline path). Stamps the keys for the
        event→served freshness histogram; the first post-fence fill
        that serves a refreshed row observes ``now - t_event`` into
        ``serving_event_freshness_seconds``."""
        ids = np.asarray(ids).reshape(-1)
        dropped = 0
        overflowed = False
        with self._lock:
            self._seq += 1
            for id_ in ids.tolist():
                key = (table, int(id_))
                self._fence[key] = self._seq
                if t_event is not None:
                    self._pending_fresh.setdefault(key, float(t_event))
                if self._rows.pop(key, None) is not None:
                    self.invalidated_rows += 1
                    dropped += 1
            if len(self._fence) > self._FENCE_CAP:
                # long-tail overflow: collapse to the global generation
                # fence (no in-flight fill lands) instead of unbounded
                # per-key state
                self._fence.clear()
                self._gen += 1
                self.fence_overflows += 1
                overflowed = True
            if len(self._pending_fresh) > self._FENCE_CAP:
                # same bound: drop the stamps, not the correctness
                self._pending_fresh.clear()
        if dropped:
            _m_rows_invalidated().inc(dropped)
        if overflowed:
            _m_fence_overflow().inc()

    def note_staleness(self, lag_s: float) -> None:
        """Record one push→applied staleness-window sample (seconds) —
        called by the fleet invalidation subscriber with the publisher's
        stamp delta the moment it applies the event. Scrape
        ``serving_cache_staleness_window_seconds`` for the freshness
        acceptance number."""
        _m_staleness_window().observe(max(0.0, float(lag_s)))

    def invalidate(self, table: str = None) -> None:
        """Drop every entry (or just one table's) — e.g. after a model/
        table push the operator wants visible immediately. Also fences
        in-flight miss fetches: rows fetched before this call cannot
        fill the cache after it."""
        with self._lock:
            self._gen += 1
            if table is None:
                self._rows.clear()
                self._fence.clear()
                self._pending_fresh.clear()
                return
            for key in [k for k in self._rows if k[0] == table]:
                del self._rows[key]
            for key in [k for k in self._fence if k[0] == table]:
                del self._fence[key]
            for key in [k for k in self._pending_fresh
                        if k[0] == table]:
                del self._pending_fresh[key]

    def stats(self) -> Dict[str, float]:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._rows),
                "max_entries": self.max_entries,
                "ttl_s": self.ttl_s,
                "hits": self.hits,
                "misses": self.misses,
                "expired": self.expired,
                "evictions": self.evictions,
                "stale_served": self.stale_served,
                "invalidated_rows": self.invalidated_rows,
                "fence_overflows": self.fence_overflows,
                "freshness_samples": self.freshness_samples,
                "freshness_pending": len(self._pending_fresh),
                "hit_rate": (self.hits / total) if total else 0.0,
            }
