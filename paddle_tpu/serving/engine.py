"""ServingEngine — concurrent predictor pool over one shared compiled
executable, fed by the continuous batcher.

Reference shape (analysis_predictor.cc Clone + paddle_inference_api.h
PredictorPool): N serving threads share ONE params scope and one
prepared executor. TPU inversion: the "prepared executor" is a single
traced+jitted step (`fluid.executor._CompiledBlock`) whose parameters
are read-only jax arrays in the shared scope — worker threads dispatch
it concurrently with zero per-clone weight copies and no locking on the
happy path (jit dispatch is thread-safe; a forward program has no
mutable state to write back).

Execution modes (picked automatically, overridable):

  * ``scan`` (fully-compilable programs, the default): a bucket of K
    rows dispatches as ONE ``lax.scan`` over K single-row steps — the
    PR 2 window machinery driven at n_steps=K with every feed windowed.
    Per-row outputs are BIT-IDENTICAL to the single-row unbatched
    oracle by construction (each scan slice traces the exact single-row
    computation), which a fused batch-dim gemm is NOT: XLA CPU blocks
    reductions differently per batch size (measured up to ~1e-6
    relative drift — docs/SERVING.md "Batching contract"). Pad rows
    repeat the last real row and are sliced away: provably inert.
    The per-bucket scanned-jit cache (`_CompiledBlock._multi_jit`,
    keyed by K) is exactly the serving bucket cache — power-of-two
    padding bounds it to log2(max_batch)+1 executables, so steady-state
    traffic never recompiles.

  * ``fused``: the bucket runs as one batch-dim step (one gemm over
    [K, ...]). Fastest on real MXU hardware; per-row bits drift within
    fp tolerance across bucket sizes. Programs with stateful ops
    (serving-time ``distributed_lookup_table`` pulls, metrics) always
    take this mode through a lock-serialized private Executor — for the
    PS path batching is what coalesces B rows' ids into ONE deduped
    RPC fan-out per table.

Sparse serving: pass ``embedding_cache=EmbeddingCache(...)`` and the
engine installs it as the process row cache
(``fluid.ps_rpc.install_row_cache``) for its lifetime — cache-hit
lookups issue zero RPCs (docs/SERVING.md staleness caveat applies).
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from contextlib import nullcontext as _nullcontext

from .batching import BatchingQueue, Request, next_bucket

__all__ = ["ServingEngine", "percentiles_ms"]

# default telemetry labels: engine0, engine1, ... per process lifetime
_ENGINE_SERIAL = itertools.count()


def percentiles_ms(vals_s, qs=(50, 99), suffix: str = "") -> Dict[str, float]:
    """Latency percentiles in ms over seconds samples — the ONE helper
    both the engine's stats() and tools/serving_loadgen report through,
    so the two latency surfaces benches compare side by side can never
    drift in interpolation or units."""
    keys = [f"p{q}{suffix}" for q in qs] + [f"mean{suffix}",
                                            f"max{suffix}"]
    if not len(vals_s):
        return {k: 0.0 for k in keys}
    a = np.asarray(vals_s, np.float64) * 1e3
    out = {f"p{q}{suffix}": float(np.percentile(a, q)) for q in qs}
    out[f"mean{suffix}"] = float(a.mean())
    out[f"max{suffix}"] = float(a.max())
    return out


class ServingEngine:
    def __init__(self, predictor=None, *, program=None, scope=None,
                 feed_names: Optional[Sequence[str]] = None,
                 fetch_names: Optional[Sequence[str]] = None,
                 num_workers: int = 2, max_batch: int = 64,
                 max_queue_delay_ms: float = 2.0,
                 batch_mode: Optional[str] = None,
                 embedding_cache=None, seed: int = 0,
                 admission=None, default_deadline_s: float = None,
                 name: Optional[str] = None):
        import jax
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import core
        from paddle_tpu.fluid import executor as executor_mod
        from paddle_tpu.fluid import telemetry as _telemetry

        if predictor is not None:
            program = predictor._program
            scope = predictor._scope
            feed_names = list(predictor._feed_names)
            fetch_names = list(predictor._fetch_names)
        if program is None or scope is None or not feed_names \
                or not fetch_names:
            raise ValueError(
                "ServingEngine needs a predictor OR explicit "
                "program/scope/feed_names/fetch_names")
        self._program = program
        self._scope = scope
        self._feed_names = tuple(feed_names)
        self._fetch_names = tuple(
            n.name if hasattr(n, "name") else n for n in fetch_names)
        self._core = core

        block = program.global_block()
        ops = [op for op in block.ops if op.type not in ("feed", "fetch")]
        compilable = (core.globals_["FLAGS_executor_mode"] == "compiled"
                      and executor_mod._ops_compilable(ops))
        if batch_mode is None:
            batch_mode = "scan" if compilable else "fused"
        if batch_mode not in ("scan", "fused"):
            raise ValueError(f"batch_mode must be 'scan' or 'fused', "
                             f"got {batch_mode!r}")
        if batch_mode == "scan" and not compilable:
            raise ValueError(
                "batch_mode='scan' needs a fully-compilable program — "
                "this one has stateful/host ops (e.g. serving-time "
                "distributed_lookup_table); use batch_mode='fused'")
        self.batch_mode = batch_mode

        # feed sample shapes/dtypes from the block var descs: rows are
        # validated + cast ONCE at submit so a float64 client row can't
        # poison the jit cache with a second signature
        self._sample: Dict[str, Tuple[tuple, Any]] = {}
        for n in self._feed_names:
            v = block.vars.get(n)
            shape = tuple(getattr(v, "shape", ()) or ())
            if shape and int(shape[0]) < 0:
                shape = shape[1:]
            try:
                dt = np.dtype(core.dtype_to_np(v.dtype))
            except Exception:
                dt = np.dtype(np.float32)
            self._sample[n] = (tuple(int(d) for d in shape), dt)

        self._cb = None
        self._exe = None
        self._exe_lock = threading.Lock()
        self._rng = jax.random.PRNGKey(int(seed))
        if compilable:
            seed_v = program.random_seed or core.globals_["FLAGS_seed"]
            # ONE compiled block shared by every worker — the
            # PredictorPool "clone" that never copies weights. guard
            # off: a serving step has no optimizer state for the
            # numeric fault plane to select back.
            self._cb = executor_mod._CompiledBlock(
                program, tuple(sorted(self._feed_names)),
                self._fetch_names, scope, seed_v, guard=False)
        else:
            self._exe = fluid.Executor()
            # force segmentation even for tiny programs: the min-ops
            # heuristic is a training tradeoff (a small program isn't
            # worth the compile), but a serving step runs the same
            # bucket forever AND the eager per-op interpreter's fp
            # fusion drifts ~1 ulp from the compiled local-table oracle
            # — segmented dense chains are both faster and bit-exact
            # (docs/SERVING.md "Batching contract"). Per-instance
            # override: a co-resident training executor never sees it.
            self._exe._seg_min_ops_override = 1

        # ---- admission / robustness contract ------------------------
        # (docs/SERVING.md "Ingress & overload"): admission is an
        # AdmissionController or None (None = the pre-ingress engine,
        # nothing sheds); default_deadline_s stamps requests that carry
        # no explicit budget
        self._admission = admission
        self._default_deadline_s = (None if default_deadline_s is None
                                    else float(default_deadline_s))
        self._codel_above_since: Optional[float] = None

        # ---- stats --------------------------------------------------
        # The scalar counters live in the telemetry REGISTRY (PR 10,
        # docs/OBSERVABILITY.md), labeled by engine name; stats() reads
        # them back, so the dict API is a VIEW over the registry and
        # GET /metrics can never drift from stats(). Histograms /
        # latency deques stay engine-local (they reset with
        # reset_stats and are exposed through the stats view).
        self.name = name if name else f"engine{next(_ENGINE_SERIAL)}"
        self._telemetry = _telemetry
        reg = _telemetry.REGISTRY
        label = {"engine": self.name}

        self._m_families = []

        def _counter(cname, help):
            fam = reg.counter(cname, help, labelnames=("engine",))
            self._m_families.append(fam)
            return fam.labels(**label)
        self._m_requests = _counter(
            "serving_requests_total", "requests answered OK")
        self._m_rows = _counter(
            "serving_rows_total", "rows answered OK")
        self._m_batches = _counter(
            "serving_batches_total", "buckets dispatched")
        self._m_errors = _counter(
            "serving_errors_total", "worker-loop execution errors")
        self._m_shed = _counter(
            "serving_shed_total",
            "admission-bound + CoDel drops (typed 429s)")
        self._m_deadline_expired = _counter(
            "serving_deadline_expired_total", "typed 504s")
        self._m_degraded = _counter(
            "serving_degraded_total",
            "requests served from beyond-TTL stale cache rows")
        self._stats_lock = threading.Lock()
        self._t_start = time.perf_counter()
        self._batch_hist: Dict[int, int] = {}
        self._bucket_hist: Dict[int, int] = {}
        self._buckets_seen: set = set()  # survives reset_stats
        self._done: "deque[tuple]" = deque(maxlen=16384)  # (t, lat_s)
        self._qwait: "deque[float]" = deque(maxlen=16384)
        self._rows_done: "deque[tuple]" = deque(maxlen=4096)  # (t, rows)
        # rows taken by a worker but not yet answered: the admission
        # bound covers queued + executing (outstanding) rows — bounding
        # only the queue would let the worker pipeline hide a full
        # latency budget of invisible work
        self._inflight_rows = 0

        # ---- worker pool --------------------------------------------
        self._queue = BatchingQueue(max_batch=max_batch,
                                    max_queue_delay_ms=max_queue_delay_ms)
        self._closed = False
        self._workers = []
        for i in range(max(1, int(num_workers))):
            t = threading.Thread(target=self._worker_loop,
                                 name=f"serving-worker-{i}", daemon=True)
            t.start()
            self._workers.append(t)

        # ---- embedding cache (process-global hook) ------------------
        # installed LAST: every earlier init step can raise, and a
        # constructor that dies after installing would leak the cache
        # into the process (close() is unreachable on a half-built
        # engine) — all subsequent lookups would silently serve stale
        self.embedding_cache = embedding_cache
        self._cache_installed = False
        # registry views (docs/OBSERVABILITY.md): queue depth gauges +
        # the embedding cache's stats() dict, labeled by engine —
        # /metrics exposes serving_engine_queue_rows{engine=...} and
        # serving_cache_hits{engine=...} beside the counters above
        self._metrics_views = [
            _telemetry.REGISTRY.register_view(
                "serving_engine",
                lambda: {"queue_rows": len(self._queue),
                         "outstanding_rows": self.outstanding_rows()},
                labels={"engine": self.name})]
        if embedding_cache is not None:
            self._metrics_views.append(
                _telemetry.REGISTRY.register_view(
                    "serving_cache", embedding_cache.stats,
                    labels={"engine": self.name}))
        if embedding_cache is not None:
            from paddle_tpu.fluid import ps_rpc
            self._cache_prev = ps_rpc.install_row_cache(embedding_cache)
            self._cache_installed = True

    # ------------------------------------------------------------ client
    def _normalize(self, feed: Dict[str, Any], many: bool):
        missing = [n for n in self._feed_names if n not in feed]
        if missing:
            raise KeyError(f"predict(): feed missing {missing}")
        rows: Dict[str, np.ndarray] = {}
        n = None
        for name in self._feed_names:
            shape, dt = self._sample[name]
            a = np.asarray(feed[name])
            if a.dtype != dt:
                a = a.astype(dt)
            if many:
                if tuple(a.shape[1:]) != shape:
                    raise ValueError(
                        f"predict_many(): '{name}' rows must be "
                        f"[n, {shape}], got {a.shape}")
            else:
                if tuple(a.shape) == shape:
                    a = a[None]
                elif tuple(a.shape) != (1,) + shape:
                    raise ValueError(
                        f"predict(): '{name}' must be one sample of "
                        f"shape {shape} (or [1, *sample]), got {a.shape}")
            if n is None:
                n = a.shape[0]
            elif a.shape[0] != n:
                raise ValueError(
                    f"predict(): ragged row counts across feeds "
                    f"({n} vs {a.shape[0]} for '{name}')")
            rows[name] = a
        if n == 0:
            raise ValueError("predict(): zero rows")
        return rows, n

    def _recent_row_rate(self, window_s: float = 5.0) -> float:
        """Rows/s completed over the recent window — the drain-rate
        estimate Retry-After is computed from (0.0 = no evidence yet)."""
        now = time.perf_counter()
        with self._stats_lock:
            rows = [(t, n) for t, n in self._rows_done
                    if now - t <= window_s]
        if not rows:
            return 0.0
        span = max(now - rows[0][0], 1e-3)
        return sum(n for _t, n in rows) / span

    def outstanding_rows(self) -> int:
        """Rows admitted but unanswered: queued + taken-by-a-worker.
        The admission bound's denominator."""
        with self._stats_lock:
            inflight = self._inflight_rows
        return len(self._queue) + inflight

    def retry_after_s(self) -> float:
        """The server's current back-off advice (the Retry-After a shed
        carries): estimated drain time of the outstanding rows at the
        recent row rate. Monotone in queue depth."""
        adm = self._admission
        if adm is None:
            return 1.0
        return adm.retry_after_s(self.outstanding_rows(),
                                 self._recent_row_rate())

    def submit(self, feed: Dict[str, Any], many: bool = False,
               deadline_s: Optional[float] = None,
               _admit: bool = True) -> Request:
        """Async submit: returns the request future (``.wait()``).
        The open-loop loadgen path. ``deadline_s`` is this request's
        budget from NOW (falls back to the engine default); admission
        may shed with typed ``core.OverloadedError`` before the request
        ever queues — never queued to die. ``_admit=False`` bypasses
        the gates (internal: warm() is an admin op, not traffic)."""
        from paddle_tpu.fluid import profiler as _profiler

        if self._closed:
            raise RuntimeError("ServingEngine is closed")
        rows, n = self._normalize(feed, many)
        if not _admit:
            req = Request(rows, n, admin=True)
            req.trace = self._telemetry.current_trace()
            return self._queue.submit(req)
        if deadline_s is None:
            deadline_s = self._default_deadline_s
        deadline = None
        if deadline_s is not None:
            if deadline_s <= 0:
                self._m_deadline_expired.inc()
                raise self._core.DeadlineExceededError(
                    f"request budget {deadline_s * 1e3:.0f}ms already "
                    f"spent at submit", queue_wait_s=0.0)
            deadline = time.perf_counter() + float(deadline_s)
        if self._admission is not None:
            try:
                self._admission.admit(n, self.outstanding_rows(),
                                      self._recent_row_rate())
            except self._core.OverloadedError:
                self._m_shed.inc()
                _profiler.record_instant(
                    "serve:shed", cat="serve",
                    args={"rows": n, "where": "admission"})
                raise
        req = Request(rows, n, deadline=deadline)
        # the submitting thread's trace context follows the request to
        # the worker (the HTTP X-Trace-Id → queue_wait/exec/PS-fetch
        # span linkage)
        req.trace = self._telemetry.current_trace()
        return self._queue.submit(req)

    def predict(self, feed: Dict[str, Any],
                timeout: Optional[float] = 120.0,
                deadline_s: Optional[float] = None) -> List[np.ndarray]:
        """One sample in, one row out: blocks until this row's batch
        executed; returns one [1, *out] array per fetch target —
        exactly the shape ``AnalysisPredictor.run([sample[None]])``
        returns, so the single-row oracle comparison is direct."""
        return self.submit(feed, many=False,
                           deadline_s=deadline_s).wait(timeout)

    def predict_many(self, feed: Dict[str, Any],
                     timeout: Optional[float] = 120.0,
                     deadline_s: Optional[float] = None
                     ) -> List[np.ndarray]:
        """A row group [n, *sample] riding one bucket atomically;
        returns [n, *out] per fetch target."""
        return self.submit(feed, many=True,
                           deadline_s=deadline_s).wait(timeout)

    # ------------------------------------------------------------ worker
    def _worker_loop(self):
        while True:
            reqs = self._queue.take(timeout=0.2)
            if not reqs:
                if self._closed and not len(self._queue):
                    return
                continue
            try:
                self._execute(reqs)
            except BaseException as e:  # deliver, don't kill the worker
                for r in reqs:
                    # only genuinely unfulfilled requests get the error:
                    # an exception AFTER some set_result calls (e.g. a
                    # shape mismatch slicing a later request) must not
                    # turn an already-delivered good result into a
                    # spurious error for a client that hasn't woken yet
                    if not r.done():
                        r.set_error(e)
                self._m_errors.inc()

    def _expire_or_shed(self, reqs: List[Request],
                        t_take: float) -> List[Request]:
        """The robustness gate between take and dispatch
        (docs/SERVING.md "Ingress & overload"): requests whose deadline
        passed while queued answer a typed 504 NOW — with their
        serve:queue_wait span — instead of holding a worker; under
        sustained head-of-queue sojourn above the CoDel target the
        OLDEST request is dropped (typed 429) so the rest of the
        queue's wait shrinks and accepted-request p99 stays bounded."""
        from paddle_tpu.fluid import profiler as _profiler

        live: List[Request] = []
        n_expired = 0
        for r in reqs:
            if r.deadline is not None and t_take >= r.deadline:
                wait = t_take - r.t_submit
                # expiry evidence recorded under the REQUEST's trace so
                # a 504's queue_wait span is findable by X-Trace-Id
                with self._telemetry.trace_scope(adopt=r.trace) \
                        if r.trace else _nullcontext():
                    _profiler.record_span(
                        "serve:queue_wait", r.t_submit, t_take,
                        cat="serve",
                        args={"rows": r.n, "expired": True})
                    _profiler.record_instant(
                        "serve:deadline_expired", cat="serve",
                        args={"rows": r.n,
                              "queue_wait_ms": round(wait * 1e3, 3)})
                r.set_error(self._core.DeadlineExceededError(
                    f"deadline expired after {wait * 1e3:.1f}ms in the "
                    f"admission queue", queue_wait_s=wait))
                n_expired += 1
                continue
            live.append(r)
        if n_expired:
            self._m_deadline_expired.inc(n_expired)

        adm = self._admission
        if adm is not None and live:
            sojourn = t_take - live[0].t_submit
            # state machine under the stats lock: concurrent workers
            # racing an unlocked read-modify-write could double-drop
            # within one interval (or miss the interval edge)
            drop_head = False
            with self._stats_lock:
                if sojourn <= adm.codel_target_s:
                    self._codel_above_since = None
                elif self._codel_above_since is None:
                    self._codel_above_since = t_take
                elif (t_take - self._codel_above_since
                      >= adm.codel_interval_s):
                    # one drop per interval: restart the clock (admin
                    # requests — warm() compiles — are never shed)
                    drop_head = not live[0].admin
                    self._codel_above_since = t_take
            if drop_head:
                head = live.pop(0)
                head.set_error(self._core.OverloadedError(
                    f"shed by CoDel oldest-drop after "
                    f"{sojourn * 1e3:.1f}ms queued (target "
                    f"{adm.codel_target_s * 1e3:.0f}ms)",
                    retry_after_s=self.retry_after_s()))
                self._m_shed.inc()
                _profiler.record_instant(
                    "serve:shed", cat="serve",
                    args={"rows": head.n, "where": "codel",
                          "sojourn_ms": round(sojourn * 1e3, 3)})
        return live

    def _execute(self, reqs: List[Request]):
        t_take = time.perf_counter()
        reqs = self._expire_or_shed(reqs, t_take)
        if not reqs:
            return
        n_valid = sum(r.n for r in reqs)
        bucket = next_bucket(n_valid)
        with self._stats_lock:
            self._inflight_rows += n_valid
        try:
            self._dispatch(reqs, t_take, n_valid, bucket)
        finally:
            with self._stats_lock:
                self._inflight_rows -= n_valid

    def _dispatch(self, reqs: List[Request], t_take: float,
                  n_valid: int, bucket: int):
        # the bucket is ONE dispatch, so it runs under the FIRST
        # member's trace (new span parented on the request's HTTP/
        # submit span); every member's trace id is listed on the exec
        # span args — the documented batching caveat of trace
        # correlation (docs/OBSERVABILITY.md)
        tr = reqs[0].trace
        if tr is None:
            return self._dispatch_inner(reqs, t_take, n_valid, bucket)
        with self._telemetry.trace_scope(trace_id=tr.trace_id,
                                         parent_span_id=tr.span_id):
            return self._dispatch_inner(reqs, t_take, n_valid, bucket)

    def _dispatch_inner(self, reqs: List[Request], t_take: float,
                        n_valid: int, bucket: int):
        from paddle_tpu.fluid import profiler as _profiler
        from paddle_tpu.fluid import ps_rpc as _ps_rpc
        from . import admission as _admission_mod

        stacked: Dict[str, np.ndarray] = {}
        for name in self._feed_names:
            arr = (reqs[0].rows[name] if len(reqs) == 1
                   else np.concatenate([r.rows[name] for r in reqs],
                                       axis=0))
            if bucket > n_valid:
                # stack-and-mask idiom (WindowBatch.n_valid): pad rows
                # repeat the last real row, results sliced to n_valid
                arr = np.concatenate(
                    [arr, np.repeat(arr[-1:], bucket - n_valid, axis=0)],
                    axis=0)
            if self.batch_mode == "scan":
                arr = arr[:, None]  # [K, 1, *sample]: one row per step
            stacked[name] = arr
        for r in reqs:
            r.t_dispatch = t_take
        _profiler.record_span(
            "serve:queue_wait", reqs[0].t_submit, t_take, cat="serve",
            args={"rows": n_valid, "requests": len(reqs)})

        # deadline propagation into the dispatch: the bucket's PS row
        # fetches run under the TIGHTEST member deadline as the RPC
        # call budget (ps_rpc caps socket/connect timeouts at the
        # remainder and raises typed when spent); the degraded scope
        # collects serve-stale events so the whole bucket can be
        # flagged. perf_counter deadlines convert to the budget's
        # monotonic clock via the current offset.
        deadlines = [r.deadline for r in reqs if r.deadline is not None]
        budget = None
        if deadlines:
            budget = time.monotonic() + (min(deadlines)
                                         - time.perf_counter())
        dg = _admission_mod.degraded_scope()
        t0 = time.perf_counter()
        with dg, _ps_rpc.call_budget(budget):
            if self.batch_mode == "scan":
                if bucket == 1:
                    # the naive one-request-one-dispatch degenerate case
                    fetches, _health = self._cb.run(
                        self._scope,
                        {n: a[0] for n, a in stacked.items()},
                        self._rng)
                    outs = [np.asarray(f)[None] for f in fetches]
                else:
                    fetches, _health = self._cb.run_window(
                        self._scope, stacked, self._rng, 0, bucket,
                        window_names=tuple(stacked))
                    outs = [np.asarray(f) for f in fetches]
                # [K, 1, *out] -> [K, *out]
                outs = [o.reshape((o.shape[0],) + o.shape[2:])
                        for o in outs]
            elif self._cb is not None:
                fetches, _health = self._cb.run(self._scope, stacked,
                                                self._rng)
                outs = [np.asarray(f) for f in fetches]
            else:
                # stateful program (PS lookups, ...): lock-serialized
                # executor — batching still coalesces the RPC fan-out
                with self._exe_lock:
                    outs = self._exe.run(
                        self._program, feed=stacked,
                        fetch_list=list(self._fetch_names),
                        scope=self._scope, return_numpy=True)
        t1 = time.perf_counter()
        if dg.count:
            # beyond-TTL cache rows stood in for unreachable pservers:
            # the whole bucket shares the fetch, so every member is
            # flagged (a 200 with a warning label, never a 5xx)
            for r in reqs:
                r.degraded = True
            self._m_degraded.inc(len(reqs))
            _profiler.record_instant(
                "serve:degraded", cat="serve",
                args={"requests": len(reqs), "stale_rows": dg.count})
        exec_args = {"bucket": bucket, "n_valid": n_valid,
                     "mode": self.batch_mode}
        member_traces = [r.trace.trace_id for r in reqs
                         if r.trace is not None]
        if member_traces:
            # every bucket member is findable from the one exec span
            exec_args["trace_ids"] = member_traces[:32]
        _profiler.record_span(
            f"serve:exec[{bucket}]", t0, t1, cat="serve",
            args=exec_args)

        i0 = 0
        for r in reqs:
            r.set_result([o[i0:i0 + r.n] for o in outs])
            i0 += r.n
        t_done = time.perf_counter()
        self._m_requests.inc(len(reqs))
        self._m_rows.inc(n_valid)
        self._m_batches.inc()
        with self._stats_lock:
            self._batch_hist[n_valid] = \
                self._batch_hist.get(n_valid, 0) + 1
            self._bucket_hist[bucket] = \
                self._bucket_hist.get(bucket, 0) + 1
            self._buckets_seen.add(bucket)
            self._rows_done.append((t_done, n_valid))
            for r in reqs:
                self._done.append((t_done, t_done - r.t_submit))
                self._qwait.append(t_take - r.t_submit)

    # ------------------------------------------------------------- stats
    _pct = staticmethod(percentiles_ms)

    def buckets_compiled(self) -> List[int]:
        """The scanned-jit bucket cache keys — the no-recompile
        evidence surface (steady-state traffic must not grow it)."""
        if self._cb is None or self.batch_mode != "scan":
            # fused/executor paths: every bucket shares one step fn that
            # retraces per batch shape — the seen set IS the shape set
            return sorted(self._buckets_seen)
        # list() on the dict is a single GIL-atomic snapshot — a worker
        # inserting a first-seen bucket mid-stats() must not blow up a
        # monitoring thread's iteration
        keys = {k[0] for k in list(self._cb._multi_jit)}
        # bucket 1 runs the single-step jit, not a scanned one
        keys |= {b for b in self._buckets_seen if b == 1}
        return sorted(keys)

    def stats(self) -> Dict[str, Any]:
        """QPS / batch-size histogram / latency percentiles / cache hit
        rate — the ``stats`` RPC surface of the serving plane."""
        with self._stats_lock:
            now = time.perf_counter()
            done = list(self._done)
            window = [d for d in done if now - d[0] <= 60.0]
            span = (now - min(d[0] for d in window)) if window else 0.0
            n_rows = self._m_rows.value()
            n_batches = self._m_batches.value()
            st = {
                "requests": self._m_requests.value(),
                "rows": n_rows,
                "batches": n_batches,
                "errors": self._m_errors.value(),
                "uptime_s": now - self._t_start,
                "qps": (len(window) / span) if span > 1e-9 else 0.0,
                "avg_batch": (n_rows / n_batches
                              if n_batches else 0.0),
                "batch_size_hist": dict(sorted(self._batch_hist.items())),
                "bucket_hist": dict(sorted(self._bucket_hist.items())),
                "latency_ms": self._pct([d[1] for d in done]),
                "queue_wait_ms": self._pct(list(self._qwait)),
                "mode": self.batch_mode,
                "max_batch": self._queue.max_batch,
                "workers": len(self._workers),
                "buckets_compiled": self.buckets_compiled(),
                # overload/degrade evidence surface (docs/SERVING.md
                # "Ingress & overload"): sheds (admission bound +
                # CoDel), typed 504s, degraded responses
                "shed": self._m_shed.value(),
                "deadline_expired": self._m_deadline_expired.value(),
                "degraded": self._m_degraded.value(),
                "queue_rows": len(self._queue),
            }
        # per-endpoint circuit breakers (ps_rpc): open count + states
        from paddle_tpu.fluid import ps_rpc as _ps_rpc
        brk = _ps_rpc.breaker_states()
        st["breaker_open"] = sum(1 for b in brk.values()
                                 if b["state"] != "closed")
        if brk:
            st["breakers"] = brk
        if self.embedding_cache is not None:
            st["embedding_cache"] = self.embedding_cache.stats()
        return st

    def reset_stats(self) -> None:
        """Drop counters/histograms (benches call this after warmup so
        the reported histogram covers only the measured window)."""
        with self._stats_lock:
            self._t_start = time.perf_counter()
            for m in (self._m_requests, self._m_rows, self._m_batches,
                      self._m_errors, self._m_shed,
                      self._m_deadline_expired, self._m_degraded):
                m._reset()
            self._batch_hist.clear()
            self._bucket_hist.clear()
            self._done.clear()
            self._qwait.clear()
            self._rows_done.clear()

    # ------------------------------------------------------------- admin
    def warm(self, buckets: Optional[Sequence[int]] = None) -> List[int]:
        """Trace/compile the given buckets (default: every power of two
        up to max_batch) with zero-filled rows so live traffic never
        pays a compile. Returns the warmed bucket list."""
        if buckets is None:
            buckets = [1]
            while buckets[-1] < self._queue.max_batch:
                buckets.append(buckets[-1] * 2)
        for b in buckets:
            feed = {}
            for name in self._feed_names:
                shape, dt = self._sample[name]
                feed[name] = np.zeros((int(b),) + shape, dt)
            # admin traffic: bypass the admission gates (a warm bucket
            # larger than the queue bound is still worth compiling)
            self.submit(feed, many=True, _admit=False).wait(120.0)
        return list(buckets)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.close()
        for t in self._workers:
            t.join(timeout=30)
        if self._cache_installed:
            from paddle_tpu.fluid import ps_rpc
            # Restore only while OUR cache is the installed one. Engines
            # closed out of install order (fleets cycle members freely)
            # must not re-install a saved prev over a newer engine's
            # cache — or worse, resurrect an already-closed one.
            if ps_rpc.current_row_cache() is self.embedding_cache:
                ps_rpc.install_row_cache(self._cache_prev)
            self._cache_installed = False
        for v in self._metrics_views:
            self._telemetry.REGISTRY.unregister_view(v)
        self._metrics_views = []
        # drop this engine's labeled counter children too — a process
        # that cycles engines (reloads, test suites) must not export
        # frozen series for engines that no longer exist; the engine's
        # own stats() keeps working through its child references
        for fam in self._m_families:
            fam.remove(engine=self.name)
        self._m_families = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
