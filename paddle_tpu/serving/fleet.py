"""Self-healing serving fleet (docs/SERVING.md "Fleet").

One serving process (PRs 7/9/10) became N engines behaving as one
service. Three legs, each a robustness contract with a typed-error
budget of zero:

  1. **Trainer→serving invalidation wire** — ``InvalidationPublisher``
     (trainer side) + ``InvalidationSubscriber`` (serving side): a
     pub/sub channel over the PR 4 binary wire (v3 ``_hello``
     negotiation and the dedup plane for free, because both ends are
     plain ``VarServer``/``VarClient``) fanning the PR 9 in-process
     ``invalidate_rows`` hook contract cross-process. The grad-push
     site (``_distributed_lookup_table_grad``) publishes the pushed row
     ids through ``ps_rpc.install_invalidation_publisher``; every
     remote ``EmbeddingCache`` applies them with the same per-key
     stage-seq fences, so fleet-wide serving staleness is push-bounded.
     The push→applied window is measured per event into the
     registry-scraped ``serving_cache_staleness_window_seconds``
     histogram. Events are idempotent row invalidations, so replays
     (retry, dedup, resync) are safe by construction; a subscriber
     outage degrades to TTL-bounded staleness — typed, counted, never
     silent.

  2. **Serving membership** — ``FleetDirectory`` + ``FleetMember`` +
     ``FleetRouter``: engines join/drain as epoch-stamped
     ``ClusterView`` participants (the PR 6 machinery, on a
     fleet-scoped view separate from the PS slot view). A rolling
     restart drains each member (directory first — the router stops
     routing to it — then the PR 9 ingress drain finishes every
     accepted request), so zero accepted requests are lost. A
     SIGKILLed member is detected by heartbeat and evicted within
     ~2×``heartbeat_timeout_s``; the router fails its in-flight
     requests typed (connection reset → counted retry) and replays
     them against a live replica.

  3. **Chaos autopilot** — ``Autopilot``: a controller loop scraping
     the PR 10 registry surface across the fleet (queue_rows, shed
     rate, breaker states, p99) and calling ``spawn_fn``/``drain_fn``
     to hold an ``SLO``. ``decide`` is a pure function (decision-table
     tested); the chaos harness (``tools/chaos_ps.py --scenario
     serving_fleet``) injects kills/restarts around it and asserts the
     SLO held.

1-core caveat: on the bench box every member time-slices one core, so
fleet-vs-single QPS is trend-only; the acceptance evidence arm is
per-member parity + the freshness/chaos contracts (docs/SERVING.md).
"""
from __future__ import annotations

import http.client
import json
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu.fluid import core, ps_membership, telemetry
from paddle_tpu.fluid.ps_membership import ClusterView
from paddle_tpu.fluid.ps_rpc import VarClient, VarServer

__all__ = [
    "InvalidationPublisher", "InvalidationSubscriber",
    "FleetDirectory", "FleetMember", "FleetRouter",
    "SLO", "Autopilot", "decide", "NoLiveMembersError",
]

_LOG = logging.getLogger("paddle_tpu.fleet")


class NoLiveMembersError(ConnectionError):
    """Every fleet member refused or dropped the request — the typed
    "fleet dark" failure the router raises instead of a bare socket
    error (callers map it to 503, never a silent hang)."""


# ---------------------------------------------------------------------------
# leg 1: trainer→serving invalidation wire
# ---------------------------------------------------------------------------
class InvalidationPublisher:
    """Trainer-side end of the invalidation wire: a seq-stamped ring of
    ``(table, ids)`` events that remote subscribers long-poll over the
    PR 4 wire. ``publish`` is enqueue-only (the grad-push path must
    never block on a slow serving box); ``inv_poll`` is the one wire
    method — read-only and cursor-idempotent, so dedup replays and
    transport retries are safe by construction.

    Ring overflow is the bounded-staleness escape hatch: a subscriber
    whose cursor fell off the ring is told to RESYNC (full cache
    invalidate — conservative, never stale) instead of replaying an
    unbounded backlog.
    """

    def __init__(self, endpoint: Optional[str] = None,
                 ring_capacity: int = 4096):
        if ring_capacity < 1:
            raise ValueError("ring_capacity must be >= 1")
        self._endpoint = endpoint
        self._cap = int(ring_capacity)
        self._cv = threading.Condition()
        self._events: List[dict] = []   # oldest first
        self._seq = 0                   # seq of the newest event
        self._floor = 0                 # seq of the newest DROPPED event
        self._server: Optional[VarServer] = None
        self._owns_server = False
        self.published_total = 0
        self.dropped_total = 0
        self._pollers: Dict[str, int] = {}   # subscriber -> last cursor
        self._view_handle = None

    # ------------------------------------------------------------- publish
    def publish(self, table: str, ids) -> int:
        """Enqueue one invalidation event; returns its seq. ``t_pub``
        is wall-clock (time.time()) — subscribers difference it against
        their own clock for the staleness-window histogram, so on one
        box the number is exact and across boxes it carries the NTP
        skew (the hello clock-offset estimate bounds it)."""
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        with self._cv:
            self._seq += 1
            self._events.append({
                "seq": self._seq, "table": str(table),
                "ids": ids.tolist(), "t_pub": time.time()})
            self.published_total += 1
            while len(self._events) > self._cap:
                dropped = self._events.pop(0)
                self._floor = dropped["seq"]
                self.dropped_total += 1
            self._cv.notify_all()
            return self._seq

    # ---------------------------------------------------------------- wire
    def inv_poll(self, cursor: int = 0, wait_s: float = 0.0,
                 subscriber: str = "", max_events: int = 512):
        """Long-poll for events past ``cursor``. Returns
        ``{"events": [...], "cursor": n}`` or, when ``cursor`` fell off
        the ring, ``{"reset": True, "cursor": head}`` — the subscriber
        must fully invalidate its cache and resume from ``head``."""
        cursor = int(cursor)
        deadline = time.monotonic() + max(0.0, float(wait_s))
        with self._cv:
            if subscriber:
                self._pollers[str(subscriber)] = cursor
            while self._seq <= cursor:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    break
                self._cv.wait(rem)
            if cursor < self._floor:
                return {"reset": True, "cursor": self._seq,
                        "t_floor": time.time()}
            out = [e for e in self._events if e["seq"] > cursor]
            out = out[:max(1, int(max_events))]
            new_cursor = out[-1]["seq"] if out else cursor
            return {"events": out, "cursor": new_cursor}

    def handlers(self) -> Dict[str, Callable]:
        """Wire handlers, attachable to an existing ``VarServer`` (a
        pserver can host its own invalidation feed) or served by the
        publisher's own server via ``start()``."""
        return {"inv_poll": self.inv_poll}

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "InvalidationPublisher":
        if self._endpoint is None:
            raise ValueError("publisher has no endpoint to serve on "
                             "(attach handlers() to a VarServer instead)")
        self._server = VarServer(self._endpoint, self.handlers()).start()
        self._owns_server = True
        self._view_handle = telemetry.REGISTRY.register_view(
            "fleet_pub", self.stats)
        return self

    def close(self) -> None:
        with self._cv:
            self._cv.notify_all()
        if self._view_handle is not None:
            telemetry.REGISTRY.unregister_view(self._view_handle)
            self._view_handle = None
        if self._owns_server and self._server is not None:
            self._server.shutdown()
            self._server = None

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            return {
                "published_total": self.published_total,
                "dropped_total": self.dropped_total,
                "ring": len(self._events),
                "seq": self._seq,
                "floor": self._floor,
                "subscribers": len(self._pollers),
            }


class InvalidationSubscriber:
    """Serving-side end: a background thread long-polling a publisher
    and applying events to the local ``EmbeddingCache`` via the same
    ``invalidate_rows`` (per-key stage-seq fence) contract the
    in-process hook uses — so the fence-vs-in-flight-fetch race is
    closed identically cross-process.

    Outage contract: when the publisher is unreachable the subscriber
    counts the outage (``outages_total``), flips ``connected`` false
    (both registry-scraped), and keeps retrying with backoff — the
    cache's ``ttl_s`` still bounds staleness, so the degradation is
    TTL-bounded and TYPED, never silent-unbounded. On reconnect after
    a ring overflow the publisher orders a RESYNC (full invalidate):
    bounded-conservative, counted in ``resyncs_total``.
    """

    def __init__(self, endpoint: str, cache, name: str = "",
                 poll_wait_s: float = 1.0, retry_s: float = 0.2):
        self._endpoint = str(endpoint)
        self._cache = cache
        self.name = name or f"sub@{endpoint}"
        self._poll_wait_s = float(poll_wait_s)
        self._retry_s = float(retry_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._client: Optional[VarClient] = None
        self._lock = threading.Lock()
        self._cursor = 0
        self.connected = False
        self.events_applied = 0
        self.rows_applied = 0
        self.resyncs = 0
        self.outages = 0
        self.last_error = ""
        self.last_lag_s = 0.0
        self._view_handle = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "InvalidationSubscriber":
        self._thread = threading.Thread(
            target=self._run, name=f"inv-sub-{self.name}", daemon=True)
        self._view_handle = telemetry.REGISTRY.register_view(
            "fleet_sub", self.stats, labels={"subscriber": self.name})
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._client is not None:
            try:
                self._client.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._view_handle is not None:
            telemetry.REGISTRY.unregister_view(self._view_handle)
            self._view_handle = None

    # ---------------------------------------------------------------- loop
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                if self._client is None:
                    # resolve=False: the publisher endpoint is not a PS
                    # slot; channels=1 keeps the long-poll serialized
                    self._client = VarClient(
                        self._endpoint, connect_timeout=5.0,
                        channels=1, resolve=False)
                resp = self._client.call(
                    "inv_poll", cursor=self._cursor,
                    wait_s=self._poll_wait_s, subscriber=self.name,
                    _rpc_timeout=self._poll_wait_s + 10.0,
                    _rpc_retries=0)
                self._apply(resp)
                with self._lock:
                    if not self.connected:
                        self.connected = True
            except Exception as e:  # typed + counted, then retry
                if self._stop.is_set():
                    break
                with self._lock:
                    if self.connected or not self.last_error:
                        self.outages += 1
                    self.connected = False
                    self.last_error = type(e).__name__
                if self._client is not None:
                    try:
                        self._client.close()
                    except OSError:
                        pass
                    self._client = None
                self._stop.wait(self._retry_s)

    def _apply(self, resp: dict) -> None:
        now = time.time()
        if resp.get("reset"):
            # cursor fell off the publisher ring: conservative full
            # invalidate — bounded staleness, never a silent gap
            self._cache.invalidate()
            with self._lock:
                self.resyncs += 1
                self._cursor = int(resp.get("cursor", self._cursor))
            return
        events = resp.get("events") or []
        for ev in events:
            try:
                # t_event stamps the keys for the event→served
                # freshness histogram (EmbeddingCache; a non-cache
                # sink without the kwarg still gets the invalidation)
                self._cache.invalidate_rows(
                    ev["table"], np.asarray(ev["ids"], dtype=np.int64),
                    t_event=float(ev.get("t_pub", now)))
            except TypeError:
                self._cache.invalidate_rows(
                    ev["table"], np.asarray(ev["ids"], dtype=np.int64))
            lag = now - float(ev.get("t_pub", now))
            note = getattr(self._cache, "note_staleness", None)
            if note is not None:
                note(lag)
            with self._lock:
                self.events_applied += 1
                self.rows_applied += len(ev["ids"])
                self.last_lag_s = lag
                self._cursor = max(self._cursor, int(ev["seq"]))
        if not events:
            with self._lock:
                self._cursor = max(self._cursor,
                                   int(resp.get("cursor", self._cursor)))

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "connected": int(self.connected),
                "cursor": self._cursor,
                "events_applied": self.events_applied,
                "rows_applied": self.rows_applied,
                "resyncs": self.resyncs,
                "outages": self.outages,
                "last_lag_s": self.last_lag_s,
            }


# ---------------------------------------------------------------------------
# leg 2: serving membership
# ---------------------------------------------------------------------------
class FleetDirectory:
    """Membership authority for the serving fleet: members join/beat/
    drain/leave; silence past ~2×``heartbeat_timeout_s`` evicts. Every
    membership change mints a NEW epoch-stamped ``ClusterView`` (slot
    name = member name, primary = its HTTP endpoint) — the PR 6
    monotonic-install contract, on a fleet-scoped view that never
    touches the process-global PS slot view.

    Runs in-process (call the methods directly) or as a wire service
    (``start()`` serves ``fleet_join``/``fleet_beat``/``fleet_drain``/
    ``fleet_leave``/``fleet_view`` on its own ``VarServer``). A beat
    from an evicted or unknown member answers a typed
    ``StaleClusterViewError`` carrying the current view — the member
    knows it was evicted and rejoins fresh instead of serving under a
    dead epoch.
    """

    def __init__(self, endpoint: Optional[str] = None,
                 heartbeat_timeout_s: float = 2.0):
        self._endpoint = endpoint
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self._lock = threading.Lock()
        # name -> {"endpoint", "last_beat", "state"}
        self._members: Dict[str, Dict[str, Any]] = {}
        self._epoch = 0
        self._view = ClusterView({}, epoch=0)
        self._server: Optional[VarServer] = None
        self._monitor: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.joins_total = 0
        self.drains_total = 0
        self.evictions_total = 0
        self._view_handle = None

    # ---------------------------------------------------------- view mint
    def _mint_locked(self) -> None:
        """Rebuild the view from live (non-draining) members; epoch
        bumps monotonically on EVERY membership change."""
        self._epoch += 1
        slots = {name: {"primary": m["endpoint"], "replicas": []}
                 for name, m in self._members.items()
                 if m["state"] == ps_membership.ACTIVE}
        self._view = ClusterView(slots, epoch=self._epoch)

    def view(self) -> ClusterView:
        with self._lock:
            return self._view

    # ---------------------------------------------------------------- wire
    def fleet_join(self, name: str, endpoint: str) -> dict:
        with self._lock:
            self._members[str(name)] = {
                "endpoint": str(endpoint),
                "last_beat": time.monotonic(),
                "state": ps_membership.ACTIVE}
            self.joins_total += 1
            self._mint_locked()
            return self._view.to_dict()

    def fleet_beat(self, name: str, epoch: int = 0) -> dict:
        with self._lock:
            m = self._members.get(str(name))
            if m is None:
                raise core.StaleClusterViewError(
                    f"fleet member {name!r} is not in the view "
                    f"(evicted or never joined) — rejoin required",
                    view=self._view.to_dict())
            m["last_beat"] = time.monotonic()
            if int(epoch) < self._epoch:
                return {"epoch": self._epoch,
                        "view": self._view.to_dict()}
            return {"epoch": self._epoch}

    def fleet_drain(self, name: str) -> dict:
        """Phase 1 of a graceful exit: the member leaves the ROUTABLE
        view (routers stop sending new work) but stays a heartbeating
        member while its ingress drains accepted requests."""
        with self._lock:
            m = self._members.get(str(name))
            if m is None:
                raise core.StaleClusterViewError(
                    f"fleet member {name!r} unknown",
                    view=self._view.to_dict())
            if m["state"] != ps_membership.DRAINING:
                m["state"] = ps_membership.DRAINING
                self.drains_total += 1
                self._mint_locked()
            return self._view.to_dict()

    def fleet_leave(self, name: str) -> dict:
        with self._lock:
            if self._members.pop(str(name), None) is not None:
                self._mint_locked()
            return self._view.to_dict()

    def fleet_view(self) -> dict:
        with self._lock:
            return self._view.to_dict()

    def handlers(self) -> Dict[str, Callable]:
        return {"fleet_join": self.fleet_join,
                "fleet_beat": self.fleet_beat,
                "fleet_drain": self.fleet_drain,
                "fleet_leave": self.fleet_leave,
                "fleet_view": self.fleet_view}

    # ------------------------------------------------------------- monitor
    def check_eviction(self) -> List[str]:
        """One monitor pass: evict members silent past 2×hb. Returns
        the evicted names (the monitor thread calls this; tests drive
        it directly for determinism)."""
        now = time.monotonic()
        bound = 2.0 * self.heartbeat_timeout_s
        evicted = []
        with self._lock:
            for name, m in list(self._members.items()):
                if now - m["last_beat"] > bound:
                    del self._members[name]
                    evicted.append(name)
                    self.evictions_total += 1
            if evicted:
                self._mint_locked()
        for name in evicted:
            _LOG.warning("fleet: evicted silent member %s (>%gs)",
                         name, bound)
        return evicted

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_timeout_s / 2.0):
            self.check_eviction()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "FleetDirectory":
        if self._endpoint is not None:
            self._server = VarServer(self._endpoint,
                                     self.handlers()).start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="fleet-dir-monitor",
            daemon=True)
        self._monitor.start()
        self._view_handle = telemetry.REGISTRY.register_view(
            "fleet_dir", self.stats)
        return self

    def close(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=10)
        if self._view_handle is not None:
            telemetry.REGISTRY.unregister_view(self._view_handle)
            self._view_handle = None
        if self._server is not None:
            self._server.shutdown()
            self._server = None

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "members": len(self._members),
                "routable": len(self._view.slots),
                "epoch": self._epoch,
                "joins_total": self.joins_total,
                "drains_total": self.drains_total,
                "evictions_total": self.evictions_total,
            }


class FleetMember:
    """One serving process's membership agent: joins the directory,
    heartbeats, and sequences the graceful exit — directory drain
    FIRST (routers stop sending), then the PR 9 ingress drain (every
    accepted request completes), then leave. A beat answered with
    ``StaleClusterViewError`` means this member was evicted (e.g. a
    long GC pause outlived 2×hb): it rejoins fresh and counts it.
    """

    def __init__(self, name: str, directory_ep: str, advertise_ep: str,
                 ingress=None, beat_interval_s: float = 0.5):
        self.name = str(name)
        self._dir_ep = str(directory_ep)
        self._advertise = str(advertise_ep)
        self._ingress = ingress
        self._interval = float(beat_interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._epoch = 0
        self.rejoins = 0
        self.beat_errors = 0
        self.draining = False

    def _cli(self) -> VarClient:
        return VarClient(self._dir_ep, connect_timeout=5.0, channels=1,
                         resolve=False)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "FleetMember":
        cli = self._cli()
        try:
            view = cli.call("fleet_join", name=self.name,
                            endpoint=self._advertise,
                            _rpc_timeout=10.0)
            with self._lock:
                self._epoch = int(view.get("epoch", 0))
        finally:
            cli.close()
        self._thread = threading.Thread(
            target=self._beat_loop, name=f"fleet-beat-{self.name}",
            daemon=True)
        self._thread.start()
        return self

    def _beat_loop(self) -> None:
        while not self._stop.wait(self._interval):
            cli = None
            try:
                cli = self._cli()
                resp = cli.call("fleet_beat", name=self.name,
                                epoch=self._epoch, _rpc_timeout=5.0,
                                _rpc_retries=0)
                with self._lock:
                    self._epoch = int(resp.get("epoch", self._epoch))
            except core.StaleClusterViewError:
                # evicted while alive (paused past 2×hb): rejoin fresh
                # unless this member is deliberately on its way out
                if self.draining or self._stop.is_set():
                    break
                try:
                    view = cli.call("fleet_join", name=self.name,
                                    endpoint=self._advertise,
                                    _rpc_timeout=10.0)
                    with self._lock:
                        self._epoch = int(view.get("epoch", 0))
                        self.rejoins += 1
                except Exception:
                    with self._lock:
                        self.beat_errors += 1
            except Exception:
                with self._lock:
                    self.beat_errors += 1
            finally:
                if cli is not None:
                    cli.close()

    def drain(self) -> None:
        """The rolling-restart exit: unroutable first, then drain the
        ingress to empty (zero lost accepted requests), then leave."""
        self.draining = True
        cli = self._cli()
        try:
            cli.call("fleet_drain", name=self.name, _rpc_timeout=10.0)
        except Exception:
            pass  # directory gone: the ingress drain still holds
        finally:
            cli.close()
        if self._ingress is not None:
            self._ingress.drain()
        self.leave()

    def leave(self) -> None:
        self._stop.set()
        cli = self._cli()
        try:
            cli.call("fleet_leave", name=self.name, _rpc_timeout=10.0)
        except Exception:
            pass
        finally:
            cli.close()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def close(self) -> None:
        if not self._stop.is_set():
            self.leave()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"epoch": self._epoch, "rejoins": self.rejoins,
                    "beat_errors": self.beat_errors,
                    "draining": int(self.draining)}


class FleetRouter:
    """Client-side front router: holds a monotonically-installed fleet
    view and spreads HTTP requests round-robin over routable members.
    A 503 (member draining — its directory exit may not have reached
    us yet) or a transport drop (SIGKILLed member) fails TYPED, is
    counted per endpoint, triggers a view refresh, and the request is
    retried against the next live member — an accepted request is only
    lost if EVERY member refuses it, which surfaces as the typed
    ``NoLiveMembersError`` (the zero-lost-accepted contract's honest
    boundary).

    Also usable endpoint-pinned (``endpoints=[...]`` without a
    directory) — the shape ``tools/serving_loadgen.py`` builds its
    multi-endpoint loops on.
    """

    def __init__(self, directory_ep: Optional[str] = None,
                 endpoints: Optional[Sequence[str]] = None,
                 timeout_s: float = 30.0, max_attempts: Optional[int] = None):
        if directory_ep is None and not endpoints:
            raise ValueError("need a directory endpoint or a static "
                             "endpoint list")
        self._dir_ep = directory_ep
        self._timeout_s = float(timeout_s)
        self._max_attempts = max_attempts
        self._lock = threading.Lock()
        self._view = ClusterView({}, epoch=0)
        self._static = [str(e) for e in (endpoints or [])]
        self._rr = 0
        self._conns: Dict[str, http.client.HTTPConnection] = {}
        # per-endpoint breakdown: ep -> {"ok": n, "retries": n, ...}
        self.by_endpoint: Dict[str, Dict[str, int]] = {}
        self.reroutes = 0
        if directory_ep is not None:
            self.refresh()

    # ----------------------------------------------------------- membership
    def install_view(self, view: ClusterView) -> bool:
        """Monotonic install (the PR 6 rule): an older epoch can never
        overwrite a newer one — a late fleet_view response racing an
        eviction must not resurrect the dead member."""
        with self._lock:
            if view.epoch < self._view.epoch:
                return False
            self._view = view
            return True

    def refresh(self) -> ClusterView:
        if self._dir_ep is None:
            return self._view
        cli = VarClient(self._dir_ep, connect_timeout=5.0, channels=1,
                        resolve=False)
        try:
            d = cli.call("fleet_view", _rpc_timeout=5.0)
            view = ClusterView.from_dict(d)
            self.install_view(view)
            return view
        finally:
            cli.close()

    def endpoints(self) -> List[str]:
        with self._lock:
            eps = self._view.endpoints()
            return eps if eps else list(self._static)

    # ---------------------------------------------------------------- http
    def _bump(self, ep: str, key: str, n: int = 1) -> None:
        with self._lock:
            d = self.by_endpoint.setdefault(ep, {})
            d[key] = d.get(key, 0) + n

    def _request(self, ep: str, method: str, path: str, body, headers):
        host, port = ep.rsplit(":", 1)
        with self._lock:
            conn = self._conns.pop(ep, None)
        if conn is None:
            conn = http.client.HTTPConnection(host, int(port),
                                              timeout=self._timeout_s)
        try:
            conn.request(method, path, body=body, headers=headers or {})
            r = conn.getresponse()
            data = r.read()
        except (http.client.HTTPException, OSError):
            try:
                conn.close()
            except OSError:
                pass
            raise
        if r.will_close:
            conn.close()
        else:
            with self._lock:
                old = self._conns.pop(ep, None)
                self._conns[ep] = conn
            if old is not None and old is not conn:
                try:
                    old.close()
                except OSError:
                    pass
        try:
            obj = json.loads(data) if data else {}
        except ValueError:
            obj = {"raw": data.decode("utf-8", "replace")}
        return r.status, obj

    def request(self, method: str, path: str, body=None, headers=None):
        """One routed request: round-robin start, retry across members
        on 503/transport-drop (counted per endpoint + ``reroutes``).
        Non-retriable statuses (200, 429, 504, 400...) return as-is —
        shedding is a RESULT, not a routing failure."""
        eps = self.endpoints()
        if not eps:
            self.refresh()
            eps = self.endpoints()
        if not eps:
            raise NoLiveMembersError("fleet view has no routable members")
        attempts = (self._max_attempts if self._max_attempts is not None
                    else len(eps) + 1)
        with self._lock:
            start = self._rr
            self._rr += 1
        last_err: Optional[BaseException] = None
        for i in range(attempts):
            ep = eps[(start + i) % len(eps)]
            t0 = time.perf_counter()
            try:
                status, obj = self._request(ep, method, path, body,
                                            headers)
            except (http.client.HTTPException, OSError) as e:
                self._bump(ep, "transport")
                last_err = e
            else:
                self._bump(ep, str(status) if status != 200 else "ok")
                if status != 503:
                    self._bump_lat(ep, time.perf_counter() - t0)
                    return status, obj, ep
                last_err = None
            # 503/drop: this member is draining or dead — refresh the
            # view (the directory may have already evicted it) and
            # re-route to the next member
            with self._lock:
                self.reroutes += 1
            try:
                self.refresh()
            except Exception:
                pass
            new_eps = self.endpoints()
            if new_eps:
                eps = new_eps
        raise NoLiveMembersError(
            f"every fleet member refused {method} {path} "
            f"after {attempts} attempts"
            + (f" (last: {last_err!r})" if last_err else ""))

    def _bump_lat(self, ep: str, lat_s: float) -> None:
        with self._lock:
            d = self.by_endpoint.setdefault(ep, {})
            d["lat_sum_ms"] = d.get("lat_sum_ms", 0.0) + lat_s * 1e3
            d["lat_n"] = d.get("lat_n", 0) + 1

    def predict(self, feed: dict, model: Optional[str] = None,
                deadline_ms: Optional[float] = None, many: bool = False):
        path = ("/predict" if model is None
                else f"/models/{model}/predict")
        body = json.dumps({
            "feed": {k: np.asarray(v).tolist() for k, v in feed.items()},
            "many": many})
        headers = {"Content-Type": "application/json"}
        if deadline_ms is not None:
            headers["X-Deadline-Ms"] = str(float(deadline_ms))
        status, obj, ep = self.request("POST", path, body, headers)
        return status, obj

    def close(self) -> None:
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"epoch": self._view.epoch,
                    "members": len(self._view.slots) or len(self._static),
                    "reroutes": self.reroutes,
                    "by_endpoint": {
                        ep: dict(d)
                        for ep, d in self.by_endpoint.items()}}


# ---------------------------------------------------------------------------
# leg 3: SLO autopilot
# ---------------------------------------------------------------------------
class SLO:
    """The service-level objective the autopilot holds: accepted-p99
    under ``p99_ms``, shed rate under ``max_shed_rate``, fleet queue
    depth under ``max_queue_rows``; member count in
    [min_members, max_members]."""

    def __init__(self, p99_ms: float = 500.0, max_shed_rate: float = 0.05,
                 max_queue_rows: int = 64, min_members: int = 1,
                 max_members: int = 8):
        self.p99_ms = float(p99_ms)
        self.max_shed_rate = float(max_shed_rate)
        self.max_queue_rows = int(max_queue_rows)
        self.min_members = int(min_members)
        self.max_members = int(max_members)


def decide(snap: Dict[str, float], slo: SLO) -> str:
    """The scale decision as a PURE function of one aggregated scrape —
    decision-table tested, no clock, no side effects.

    ``snap``: members, p99_ms, shed_rate, queue_rows, breakers_open.
    Returns "up", "down", or "hold".

    Up wins over down (a breached SLO scales even if some signal looks
    idle); a breached SLO at max_members holds — the autopilot reports
    the breach instead of flapping.
    """
    members = int(snap.get("members", 0))
    breach = (snap.get("p99_ms", 0.0) > slo.p99_ms
              or snap.get("shed_rate", 0.0) > slo.max_shed_rate
              or snap.get("queue_rows", 0.0) > slo.max_queue_rows
              or snap.get("breakers_open", 0.0) > 0)
    if members < slo.min_members:
        return "up"
    if breach:
        return "up" if members < slo.max_members else "hold"
    idle = (snap.get("p99_ms", 0.0) < 0.5 * slo.p99_ms
            and snap.get("shed_rate", 0.0) == 0.0
            and snap.get("queue_rows", 0.0)
            <= 0.25 * slo.max_queue_rows)
    if idle and members > slo.min_members:
        return "down"
    return "hold"


def scrape_http_member(endpoint: str, timeout_s: float = 5.0
                       ) -> Dict[str, float]:
    """Scrape one member's PR 10 stats surface (GET /stats) into the
    autopilot's snapshot shape. Raises on transport failure — the
    autopilot counts that member dark (its share of the fleet is the
    breach signal, not a silent hole)."""
    host, port = str(endpoint).rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port),
                                      timeout=timeout_s)
    try:
        conn.request("GET", "/stats")
        r = conn.getresponse()
        obj = json.loads(r.read() or b"{}")
    finally:
        conn.close()
    agg = {"p99_ms": 0.0, "shed": 0.0, "requests": 0.0,
           "queue_rows": 0.0, "breakers_open": 0.0}
    for eng in (obj.get("models") or {}).values():
        agg["p99_ms"] = max(agg["p99_ms"],
                            float(eng.get("latency_ms", {}).get("p99", 0)
                                  or 0))
        agg["shed"] += float(eng.get("shed", 0) or 0)
        agg["requests"] += float(eng.get("requests", 0) or 0)
        agg["queue_rows"] += float(eng.get("queue_rows", 0) or 0)
        agg["breakers_open"] += float(eng.get("breaker_open", 0) or 0)
    return agg


class Autopilot:
    """The SLO-holding controller loop: each tick scrapes every member
    (``scrape_fn`` → list of per-member snapshots, dark members as
    None), aggregates, runs ``decide``, and calls ``spawn_fn()`` /
    ``drain_fn()`` under a cooldown (no flapping). Shed RATE is
    windowed from the cumulative counters between ticks. Chaos mode is
    external (tools/chaos_ps.py kills members around a running
    autopilot); ``history`` + ``snapshot()`` are the assertion surface
    — the chaos harness checks the SLO held and the autopilot healed
    the fleet back to target."""

    def __init__(self, scrape_fn: Callable[[], List[Optional[dict]]],
                 slo: SLO, spawn_fn: Callable[[], Any],
                 drain_fn: Callable[[], Any],
                 interval_s: float = 1.0, cooldown_s: float = 3.0):
        self._scrape = scrape_fn
        self.slo = slo
        self._spawn = spawn_fn
        self._drain = drain_fn
        self._interval = float(interval_s)
        self._cooldown = float(cooldown_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._last_action_t = 0.0
        self._prev_counters: Dict[str, float] = {}
        self.history: List[dict] = []   # [{t, snap, decision, acted}]
        self.breaches = 0
        self.dark_scrapes = 0
        self._view_handle = None

    # ------------------------------------------------------------ one tick
    def tick(self) -> dict:
        """One scrape→aggregate→decide→act pass (the loop calls this;
        tests drive it directly for determinism)."""
        per_member = self._scrape()
        live = [m for m in per_member if m is not None]
        dark = len(per_member) - len(live)
        if dark:
            with self._lock:
                self.dark_scrapes += dark
        shed = sum(float(m.get("shed", 0)) for m in live)
        req = sum(float(m.get("requests", 0)) for m in live)
        d_shed = shed - self._prev_counters.get("shed", 0.0)
        d_req = req - self._prev_counters.get("requests", 0.0)
        self._prev_counters = {"shed": shed, "requests": req}
        snap = {
            "members": len(live),
            "dark": dark,
            "p99_ms": max([float(m.get("p99_ms", 0)) for m in live],
                          default=0.0),
            "queue_rows": sum(float(m.get("queue_rows", 0))
                              for m in live),
            "breakers_open": sum(float(m.get("breakers_open", 0))
                                 for m in live),
            # windowed rate over the tick, from cumulative counters; a
            # counter reset (member restart) clamps at 0, never negative
            "shed_rate": (max(0.0, d_shed) / max(1.0, max(0.0, d_req))
                          if d_req > 0 else (1.0 if d_shed > 0 else 0.0)),
        }
        decision = decide(snap, self.slo)
        now = time.monotonic()
        acted = False
        if decision != "hold" \
                and now - self._last_action_t >= self._cooldown:
            try:
                (self._spawn if decision == "up" else self._drain)()
                acted = True
                self._last_action_t = now
            except Exception:
                _LOG.exception("autopilot %s action failed", decision)
        breach = (snap["p99_ms"] > self.slo.p99_ms
                  or snap["shed_rate"] > self.slo.max_shed_rate
                  or snap["breakers_open"] > 0)
        with self._lock:
            if breach:
                self.breaches += 1
            self.history.append({"t": time.time(), "snap": snap,
                                 "decision": decision, "acted": acted})
            if len(self.history) > 1024:
                del self.history[:512]
        return {"snap": snap, "decision": decision, "acted": acted}

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.tick()
            except Exception:
                _LOG.exception("autopilot tick failed")

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "Autopilot":
        self._thread = threading.Thread(
            target=self._loop, name="fleet-autopilot", daemon=True)
        self._thread.start()
        self._view_handle = telemetry.REGISTRY.register_view(
            "fleet_autopilot", self.stats)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._view_handle is not None:
            telemetry.REGISTRY.unregister_view(self._view_handle)
            self._view_handle = None

    def snapshot(self) -> Optional[dict]:
        with self._lock:
            return dict(self.history[-1]) if self.history else None

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            last = self.history[-1] if self.history else None
            return {
                "ticks": len(self.history),
                "breaches": self.breaches,
                "dark_scrapes": self.dark_scrapes,
                "last_members": (last["snap"]["members"] if last else 0),
                "last_p99_ms": (last["snap"]["p99_ms"] if last else 0.0),
                "last_decision": (
                    {"hold": 0, "up": 1, "down": -1}[last["decision"]]
                    if last else 0),
            }
