"""Serving-time sparse path: rewrite a loaded inference program's
embedding lookups into ``distributed_lookup_table`` pulls against live
pservers.

This is the serving-side half of the DistributeTranspiler rewrite
(fluid/transpiler/distribute_transpiler.py ``_build_trainer_program``):
training bakes the pserver endpoints into the TRAINER program, but an
inference program saved by ``io.save_inference_model`` still carries
plain ``lookup_table`` ops — serving it would require materializing the
full table in the predictor process, exactly what a beyond-HBM table
cannot do. ``rewrite_sparse_lookups`` clones the program and points the
marked tables at the PS plane instead; the predictor process then never
holds table rows beyond what the ``EmbeddingCache`` pins.

The rewritten ops ride the whole PR 4/6 client stack unchanged: binary
wire, per-endpoint channel pools, concurrent shard fan-out, duplicate-id
dedup, and — because pulls resolve slots through the installed
ClusterView — a pserver drain/failover mid-serving re-routes
transparently inside the call (``StaleClusterViewError`` replay).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

__all__ = ["rewrite_sparse_lookups"]

_LOOKUP_TYPES = ("lookup_table", "lookup_table_v2")


def rewrite_sparse_lookups(program, endpoints: Sequence[str],
                           tables: Optional[Sequence[str]] = None,
                           trainer_id: int = 0) -> Tuple[object, List[str]]:
    """Clone ``program`` with its sparse lookups rewritten to remote
    pulls row-sharded across ``endpoints`` (id % n_pservers — the same
    routing the training transpiler bakes in, so a table sharded by
    training is served from the same shards).

    ``tables``: table var names to rewrite; default = every lookup
    marked ``is_distributed`` (the wide_deep ``is_distributed=True``
    build). Returns ``(rewritten_program, rewritten_table_names)``;
    raises ``ValueError`` when nothing matches — a silent no-op rewrite
    would serve from a local table the caller believes is remote."""
    eps = [str(e) for e in endpoints if e]
    if not eps:
        raise ValueError("rewrite_sparse_lookups: empty endpoint list")
    # Seed the epoch-0 ClusterView exactly like the training transpiler
    # does (distribute_transpiler.py): a serving-only process never
    # transpiles, and without a bootstrap view ps_membership.resolve is
    # a pass-through and refresh_view_for can't probe replicas — so a
    # pserver failover would leave serving dialing the dead physical
    # endpoint until its deadline instead of re-routing to the promoted
    # replica. Same slot-set rule: a DIFFERENT slot set is a new
    # cluster, so drop any stale high-epoch view first.
    from ..fluid import ps_membership
    cur = ps_membership.current_view()
    if cur is not None and set(cur.slots) != set(eps):
        ps_membership.reset_views()
    ps_membership.install_view(ps_membership.ClusterView.initial(eps))
    want = set(tables) if tables is not None else None
    prog = program.clone()
    block = prog.global_block()
    hit: List[str] = []
    for op in block.ops:
        if op.type not in _LOOKUP_TYPES:
            continue
        w = op.input("W")[0]
        if want is None:
            if not op.attrs.get("is_distributed"):
                continue
        elif w not in want:
            continue
        op.type = "distributed_lookup_table"
        op.inputs = {"Ids": op.input("Ids"), "W": [w]}
        op.outputs = {"Outputs": op.output("Out")}
        op.attrs.update({
            "table_names": [w],
            "epmap": list(eps),
            "trainer_id": int(trainer_id),
            "is_distributed": True,
        })
        hit.append(w)
    if not hit:
        raise ValueError(
            "rewrite_sparse_lookups: no lookup_table op matched "
            + ("tables=" + repr(sorted(want)) if want is not None
               else "is_distributed=True")
            + " — the program would silently keep serving local tables")
    if want is not None:
        missed = want - set(hit)
        if missed:
            raise ValueError(
                f"rewrite_sparse_lookups: tables {sorted(missed)} have "
                f"no lookup_table op in the program")
    return prog, hit
