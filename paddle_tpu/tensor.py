"""paddle.tensor 2.0-preview namespace (reference: python/paddle/tensor/ —
creation.py / linalg.py / math.py / manipulation.py / search.py / logic.py
re-exports of fluid ops under torch-style names)."""
from __future__ import annotations

from .fluid import layers as _L
from .fluid.layer_helper import LayerHelper as _LayerHelper


def _build_op(op_type, ins, attrs=None, n_out=1, dtype=None,
              out_slot="Out"):
    """Generic single-output op builder (works in static and dygraph modes
    through append_op routing)."""
    helper = _LayerHelper(op_type)
    if dtype is None:
        for vals in ins.values():
            for v in (vals if isinstance(vals, (list, tuple)) else [vals]):
                if v is not None and hasattr(v, "dtype"):
                    dtype = v.dtype
                    break
            if dtype is not None:
                break
    outs = [helper.create_variable_for_type_inference(dtype)
            for _ in range(n_out)]
    helper.append_op(type=op_type, inputs=ins,
                     outputs={out_slot: outs}, attrs=attrs or {})
    return outs[0] if n_out == 1 else outs


# ops registered in the op set but without fluid.layers wrappers —
# exposed here under their 2.0 names (reference tensor/linalg.py math.py)
def bmm(x, y, name=None):
    return _build_op("bmm", {"X": [x], "Y": [y]})


def dot(x, y, name=None):
    return _build_op("dot", {"X": [x], "Y": [y]})


def cross(x, y, axis=None, name=None):
    if axis is None:
        # reference default: the first axis of length 3
        for i, d in enumerate(x.shape):
            if d == 3:
                axis = i
                break
        else:
            raise ValueError(
                "cross: no axis of length 3 found; pass axis explicitly")
    return _build_op("cross", {"X": [x], "Y": [y]}, {"dim": axis})


def cholesky(x, upper=False, name=None):
    return _build_op("cholesky", {"X": [x]}, {"upper": upper})


def inverse(x, name=None):
    return _build_op("inverse", {"Input": [x]}, out_slot="Output")


def dist(x, y, p=2.0, name=None):
    return _build_op("dist", {"X": [x], "Y": [y]}, {"p": float(p)})


def kron(x, y, name=None):
    return _build_op("kron", {"X": [x], "Y": [y]})


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _build_op("trace", {"Input": [x]},
                     {"offset": offset, "axis1": axis1, "axis2": axis2})


def flip(x, axis, name=None):
    return _build_op("flip", {"X": [x]},
                     {"axis": [axis] if isinstance(axis, int) else
                      list(axis)})


def meshgrid(*args, name=None):
    inputs = list(args[0]) if len(args) == 1 and isinstance(
        args[0], (list, tuple)) else list(args)
    return _build_op("meshgrid", {"X": inputs}, n_out=len(inputs))


def full(shape, fill_value, dtype=None, name=None):
    if dtype is None:
        from .framework import get_default_dtype
        dtype = get_default_dtype()
    return _L.fill_constant(shape, dtype, fill_value)


def tile(x, repeat_times, name=None):
    return _L.expand(x, list(repeat_times))


def logsumexp(x, axis=None, keepdim=False, name=None):
    m = _L.reduce_max(x, dim=axis, keep_dim=True)
    s = _L.reduce_sum(_L.exp(_L.elementwise_sub(x, m)), dim=axis,
                      keep_dim=keepdim)
    m_out = m if keepdim or axis is None else _L.squeeze(
        m, [axis] if isinstance(axis, int) else list(axis))
    if axis is None and not keepdim:
        m_out = _L.reshape(m, [1])
        s = _L.reshape(s, [1])
    return _L.elementwise_add(_L.log(s), m_out)


def nonzero(x, as_tuple=False):
    from .fluid import framework as _fw
    if _fw.in_dygraph_mode():
        # dynamic output shape: computed on host (the static where_index
        # op is scope-interpreted for the same reason)
        import numpy as _np
        import jax.numpy as _jnp
        from .fluid.dygraph.base import VarBase
        idx = _np.argwhere(_np.asarray(x.numpy()))
        if as_tuple:
            return tuple(VarBase(_jnp.asarray(idx[:, i]))
                         for i in range(idx.shape[1]))
        return VarBase(_jnp.asarray(idx))
    if as_tuple:
        raise NotImplementedError(
            "nonzero(as_tuple=True) needs dygraph mode — static programs "
            "have static shapes")
    return _build_op("where_index", {"Condition": [x]}, dtype="int64")

# creation
ones = _L.ones
zeros = _L.zeros
ones_like = _L.ones_like
zeros_like = _L.zeros_like
fill_constant = _L.fill_constant
arange = _L.range
linspace = _L.linspace
eye = _L.eye
diag = _L.diag

# math
add = _L.elementwise_add
subtract = _L.elementwise_sub
multiply = _L.elementwise_mul
divide = _L.elementwise_div
pow = _L.pow
sqrt = _L.sqrt
exp = _L.exp
log = _L.log
abs = _L.abs
sign = _L.sign
floor = _L.floor
ceil = _L.ceil
round = _L.round
sin = _L.sin
cos = _L.cos
tanh = _L.tanh
sum = _L.reduce_sum
mean = _L.reduce_mean
max = _L.reduce_max
min = _L.reduce_min
prod = _L.reduce_prod
cumsum = _L.cumsum
clip = _L.clip

# linalg
matmul = _L.matmul
norm = getattr(_L, "l2_normalize", None)

# manipulation
concat = _L.concat
stack = _L.stack
unstack = _L.unstack
split = _L.split
squeeze = _L.squeeze
unsqueeze = _L.unsqueeze
reshape = _L.reshape
transpose = _L.transpose
roll = getattr(_L, "roll", None)
gather = _L.gather
gather_nd = _L.gather_nd
scatter = _L.scatter
slice = _L.slice
strided_slice = _L.strided_slice
expand = _L.expand
flatten = _L.flatten
unbind = getattr(_L, "unbind", None)
unique = _L.unique
where = _L.where

# search / sort
argmax = getattr(_L, "argmax", None)
argmin = getattr(_L, "argmin", None)
argsort = _L.argsort
topk = _L.topk
index_select = getattr(_L, "index_select", None)
index_sample = getattr(_L, "index_sample", None)


# --- 2.0 conveniences over the op set ------------------------------------
def rand(shape, dtype="float32", name=None):
    return _L.uniform_random(shape, dtype=dtype, min=0.0, max=1.0)


def randn(shape, dtype="float32", name=None):
    return _L.gaussian_random(shape, mean=0.0, std=1.0, dtype=dtype)


def clamp(x, min=None, max=None, name=None):
    lo = float("-1e38") if min is None else float(min)
    hi = float("1e38") if max is None else float(max)
    return _L.clip(x, lo, hi)


def full_like(x, fill_value, dtype=None, name=None):
    return _build_op("fill_any_like", {"X": [x]},
                     {"value": float(fill_value)}, dtype=dtype)


def log_softmax(x, axis=-1, dtype=None, name=None):
    sm = _L.softmax(x, axis=axis)
    return _L.log(sm)


def addcmul(input, tensor1, tensor2, value=1.0, name=None):
    return _L.elementwise_add(
        input, _L.scale(_L.elementwise_mul(tensor1, tensor2),
                        scale=float(value)))


def t(x, name=None):
    nd = len(x.shape)
    if nd > 2:
        raise ValueError("paddle.t only transposes 0/1/2-D tensors")
    if nd < 2:
        return x
    return _L.transpose(x, [1, 0])


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    mu = _L.reduce_mean(x, dim=axis, keep_dim=True)
    sq = _L.square(_L.elementwise_sub(x, mu))
    out = _L.reduce_mean(sq, dim=axis, keep_dim=keepdim)
    if unbiased:
        # reduced-element count at runtime (batch dims are dynamic):
        # n = numel(x) / numel(mean_keepdim)
        n = _L.elementwise_div(
            _L.cast(_L.reshape(_L.size(x), [1]), x.dtype),
            _L.cast(_L.reshape(_L.size(mu), [1]), x.dtype))
        factor = _L.elementwise_div(
            n, _L.elementwise_sub(n, _L.fill_constant([1], x.dtype, 1.0)))
        out = _L.elementwise_mul(out, factor)
    return out


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _L.sqrt(var(x, axis, unbiased, keepdim))


def numel(x, name=None):
    return _L.size(x)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _build_op("allclose", {"Input": [x], "Other": [y]},
                     {"rtol": float(rtol), "atol": float(atol),
                      "equal_nan": bool(equal_nan)}, dtype="bool")
