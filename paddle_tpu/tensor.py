"""paddle.tensor 2.0-preview namespace (reference: python/paddle/tensor/ —
creation/linalg/math/manipulation/search re-exports of fluid ops)."""
from __future__ import annotations

from .fluid import layers as _L

# creation
ones = _L.ones
zeros = _L.zeros
ones_like = _L.ones_like
zeros_like = _L.zeros_like
fill_constant = _L.fill_constant
full = getattr(_L, "full", None)
arange = _L.range
linspace = _L.linspace
eye = _L.eye
diag = _L.diag

# math
add = _L.elementwise_add
subtract = _L.elementwise_sub
multiply = _L.elementwise_mul
divide = _L.elementwise_div
pow = _L.pow
sqrt = _L.sqrt
exp = _L.exp
log = _L.log
abs = _L.abs
sign = _L.sign
floor = _L.floor
ceil = _L.ceil
round = _L.round
sin = _L.sin
cos = _L.cos
tanh = _L.tanh
sum = _L.reduce_sum
mean = _L.reduce_mean
max = _L.reduce_max
min = _L.reduce_min
prod = _L.reduce_prod
cumsum = _L.cumsum
clip = _L.clip
logsumexp = getattr(_L, "logsumexp", None)
kron = getattr(_L, "kron", None)
trace = getattr(_L, "trace", None)

# linalg
matmul = _L.matmul
bmm = getattr(_L, "bmm", None)
dot = getattr(_L, "dot", None)
dist = getattr(_L, "dist", None)
norm = getattr(_L, "l2_normalize", None)
cholesky = getattr(_L, "cholesky", None)
cross = getattr(_L, "cross", None)
inverse = getattr(_L, "inverse", None)

# manipulation
concat = _L.concat
stack = _L.stack
unstack = _L.unstack
split = _L.split
squeeze = _L.squeeze
unsqueeze = _L.unsqueeze
reshape = _L.reshape
transpose = _L.transpose
flip = getattr(_L, "flip", None)
roll = getattr(_L, "roll", None)
gather = _L.gather
gather_nd = _L.gather_nd
scatter = _L.scatter
slice = _L.slice
strided_slice = _L.strided_slice
expand = _L.expand
tile = getattr(_L, "tile", None)
flatten = _L.flatten
unbind = getattr(_L, "unbind", None)
unique = _L.unique
where = _L.where
meshgrid = getattr(_L, "meshgrid", None)

# search / sort
argmax = getattr(_L, "argmax", None)
argmin = getattr(_L, "argmin", None)
argsort = _L.argsort
topk = _L.topk
index_select = getattr(_L, "index_select", None)
index_sample = getattr(_L, "index_sample", None)
nonzero = getattr(_L, "where_index", None)
