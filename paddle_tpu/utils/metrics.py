"""Shared metric math used by the auc op, fluid.metrics.Auc and
FleetUtil.get_global_auc (one implementation so the three call sites cannot
diverge; reference formula: operators/metrics/auc_op.h trapezoid sweep)."""
from __future__ import annotations

import numpy as np

__all__ = ["auc_from_histograms"]


def auc_from_histograms(stat_pos, stat_neg) -> float:
    """ROC AUC from per-threshold-bucket positive/negative counts.

    Descending-threshold trapezoid sweep in (FP, TP) space: each bucket
    contributes width = neg[i] at mean height = TP_before + pos[i]/2."""
    pos = np.asarray(stat_pos, np.float64).reshape(-1)
    neg = np.asarray(stat_neg, np.float64).reshape(-1)
    tot_pos = tot_neg = area = 0.0
    for i in range(len(pos) - 1, -1, -1):
        area += neg[i] * (tot_pos + pos[i] / 2.0)
        tot_pos += pos[i]
        tot_neg += neg[i]
    if tot_pos * tot_neg == 0:
        return 0.0
    return float(area / (tot_pos * tot_neg))
