"""Remaining book models (reference: python/paddle/fluid/tests/book/ —
test_fit_a_line.py, test_image_classification.py VGG branch,
notest_understand_sentiment.py, test_recommender_system.py,
test_label_semantic_roles.py). Each builder returns
(main, startup, feed_names, loss[, extras]) like the other model modules;
data comes from paddle_tpu.dataset (synthetic offline stand-ins)."""
from __future__ import annotations

from ..fluid import layers

__all__ = ["build_fit_a_line", "vgg16", "build_vgg_cifar",
           "convolution_net", "build_sentiment_program",
           "build_recommender_program", "build_srl_crf_program"]


# --------------------------------------------------------------------------
# fit_a_line — the book's first program (linear regression on uci_housing)
# --------------------------------------------------------------------------
def build_fit_a_line(lr=0.01):
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[13], dtype="float32")
        y = fluid.data("y", shape=[1], dtype="float32")
        pred = layers.fc(x, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(lr).minimize(loss)
    return main, startup, ["x", "y"], loss


# --------------------------------------------------------------------------
# VGG — the book's image_classification vgg branch (img_conv_group stacks)
# --------------------------------------------------------------------------
def vgg16(input, class_dim=10):
    from ..fluid import nets

    def group(inp, num, filters):
        return nets.img_conv_group(
            inp, conv_num_filter=[filters] * num, pool_size=2,
            pool_stride=2, conv_act="relu", conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=0.0)

    x = group(input, 2, 64)
    x = group(x, 2, 128)
    x = group(x, 3, 256)
    x = group(x, 3, 512)
    x = group(x, 3, 512)
    x = layers.fc(x, 512, act=None)
    x = layers.batch_norm(x, act="relu")
    x = layers.fc(x, 512, act=None)
    return layers.fc(x, class_dim, act="softmax")


def build_vgg_cifar(class_dim=10, image_size=32, lr=1e-3, depth="small"):
    """depth="small": a 2-group VGG for test-speed; "16": full VGG16."""
    import paddle_tpu.fluid as fluid
    from ..fluid import nets
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data("img", shape=[3, image_size, image_size],
                         dtype="float32")
        label = fluid.data("label", shape=[1], dtype="int64")
        if depth == "16":
            pred = vgg16(img, class_dim)
        else:
            x = nets.img_conv_group(img, conv_num_filter=[32, 32],
                                    pool_size=2, pool_stride=2,
                                    conv_act="relu",
                                    conv_with_batchnorm=True)
            x = nets.img_conv_group(x, conv_num_filter=[64, 64],
                                    pool_size=2, pool_stride=2,
                                    conv_act="relu",
                                    conv_with_batchnorm=True)
            x = layers.fc(x, 128, act="relu")
            pred = layers.fc(x, class_dim, act="softmax")
        loss = layers.mean(layers.cross_entropy(pred, label))
        acc = layers.accuracy(pred, label)
        fluid.optimizer.Adam(lr).minimize(loss)
    return main, startup, ["img", "label"], loss, acc


# --------------------------------------------------------------------------
# understand_sentiment — text conv net over LoD word ids
# --------------------------------------------------------------------------
def convolution_net(data, dict_dim, class_dim=2, emb_dim=32, hid_dim=32):
    """The book's conv_net: embedding + two sequence_conv_pool branches
    (notest_understand_sentiment.py convolution_net)."""
    from ..fluid import nets
    emb = layers.embedding(data, size=[dict_dim, emb_dim], is_sparse=True)
    conv3 = nets.sequence_conv_pool(emb, num_filters=hid_dim, filter_size=3,
                                    act="tanh", pool_type="sqrt")
    conv4 = nets.sequence_conv_pool(emb, num_filters=hid_dim, filter_size=4,
                                    act="tanh", pool_type="sqrt")
    return layers.fc([conv3, conv4], class_dim, act="softmax")


def build_sentiment_program(dict_dim, class_dim=2, lr=1e-3):
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        words = fluid.data("words", shape=[1], dtype="int64", lod_level=1)
        label = fluid.data("label", shape=[1], dtype="int64")
        pred = convolution_net(words, dict_dim, class_dim)
        loss = layers.mean(layers.cross_entropy(pred, label))
        acc = layers.accuracy(pred, label)
        fluid.optimizer.Adagrad(lr).minimize(loss)
    return main, startup, ["words", "label"], loss, acc


# --------------------------------------------------------------------------
# recommender_system — the book's user/movie embedding model
# --------------------------------------------------------------------------
def build_recommender_program(n_users, n_movies, n_jobs=21, n_ages=7,
                              n_cates=18, title_vocab=1000, emb=16, lr=5e-3):
    """User tower (id+gender+age+job embeddings → fc) and movie tower
    (id emb + category/title pooled embs → fc), cosine-scaled score vs the
    5-star rating (test_recommender_system.py model)."""
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        uid = fluid.data("user_id", shape=[1], dtype="int64")
        gender = fluid.data("gender_id", shape=[1], dtype="int64")
        age = fluid.data("age_id", shape=[1], dtype="int64")
        job = fluid.data("job_id", shape=[1], dtype="int64")
        mid = fluid.data("movie_id", shape=[1], dtype="int64")
        cats = fluid.data("category_id", shape=[1], dtype="int64",
                          lod_level=1)
        title = fluid.data("movie_title", shape=[1], dtype="int64",
                           lod_level=1)
        score = fluid.data("score", shape=[1], dtype="float32")

        def emb_fc(ids, size):
            e = layers.embedding(ids, size=[size, emb], is_sparse=True)
            return layers.reshape(e, [-1, emb])

        usr = layers.concat(
            [emb_fc(uid, n_users + 1), emb_fc(gender, 2),
             emb_fc(age, n_ages), emb_fc(job, n_jobs)], axis=1)
        usr = layers.fc(usr, 32, act="relu")

        mov_id = emb_fc(mid, n_movies + 1)
        cat_e = layers.embedding(cats, size=[n_cates, emb], is_sparse=True)
        cat_p = layers.sequence_pool(cat_e, pool_type="sum")
        ttl_e = layers.embedding(title, size=[title_vocab, emb],
                                 is_sparse=True)
        ttl_p = layers.sequence_pool(ttl_e, pool_type="sum")
        mov = layers.concat([mov_id, cat_p, ttl_p], axis=1)
        mov = layers.fc(mov, 32, act="relu")

        sim = layers.cos_sim(usr, mov)
        pred = layers.scale(sim, scale=5.0)
        loss = layers.mean(layers.square_error_cost(pred, score))
        fluid.optimizer.Adam(lr).minimize(loss)
    feeds = ["user_id", "gender_id", "age_id", "job_id", "movie_id",
             "category_id", "movie_title", "score"]
    return main, startup, feeds, loss


# --------------------------------------------------------------------------
# label_semantic_roles — sequence tagging with a linear-chain CRF
# --------------------------------------------------------------------------
def build_srl_crf_program(word_dict_len, label_dict_len, emb=32, hidden=64,
                          lr=1e-2):
    """Simplified SRL tagger (test_label_semantic_roles.py shape): word
    embeddings → fc stack → linear_chain_crf loss + crf_decoding."""
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        word = fluid.data("word", shape=[1], dtype="int64", lod_level=1)
        target = fluid.data("target", shape=[1], dtype="int64", lod_level=1)
        e = layers.embedding(word, size=[word_dict_len, emb])
        e = layers.reshape(e, [-1, emb])
        h = layers.fc(e, hidden, act="tanh")
        feature = layers.fc(h, label_dict_len, act=None)
        crf_cost = layers.linear_chain_crf(
            input=feature, label=target,
            param_attr=fluid.ParamAttr(name="crfw"))
        loss = layers.mean(crf_cost)
        decode = layers.crf_decoding(
            input=feature, param_attr=fluid.ParamAttr(name="crfw"))
        fluid.optimizer.SGD(lr).minimize(loss)
    return main, startup, ["word", "target"], loss, decode
