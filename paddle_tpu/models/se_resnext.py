"""SE-ResNeXt (reference: tests/unittests/test_imperative_se_resnext.py /
dist_se_resnext.py — ResNeXt bottlenecks with cardinality-grouped 3x3 convs
plus squeeze-and-excitation channel gating).

TPU notes: grouped conv lowers to XLA's feature_group_count (MXU-friendly);
SE's global pool + two tiny FCs fuse into the surrounding computation."""
from __future__ import annotations

from ..fluid import layers
from ..fluid.param_attr import ParamAttr

__all__ = ["se_resnext50", "build_se_resnext_train_program"]

_DEPTH_CFG = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}


def _conv_bn(x, num_filters, filter_size, stride=1, groups=1, act=None):
    conv = layers.conv2d(x, num_filters, filter_size, stride=stride,
                         padding=(filter_size - 1) // 2, groups=groups,
                         bias_attr=False)
    return layers.batch_norm(conv, act=act)


def _squeeze_excitation(x, num_channels, reduction_ratio=16):
    pool = layers.pool2d(x, pool_type="avg", global_pooling=True)
    squeeze = layers.fc(pool, num_channels // reduction_ratio, act="relu")
    excite = layers.fc(squeeze, num_channels, act="sigmoid")
    excite = layers.unsqueeze(layers.unsqueeze(excite, [2]), [3])
    return layers.elementwise_mul(x, excite, axis=0)


def _bottleneck(x, num_filters, stride, cardinality=32,
                reduction_ratio=16):
    conv0 = _conv_bn(x, num_filters, 1, act="relu")
    conv1 = _conv_bn(conv0, num_filters, 3, stride=stride,
                     groups=cardinality, act="relu")
    conv2 = _conv_bn(conv1, num_filters * 2, 1)
    se = _squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    in_c = x.shape[1]
    if in_c != num_filters * 2 or stride != 1:
        short = _conv_bn(x, num_filters * 2, 1, stride=stride)
    else:
        short = x
    return layers.relu(layers.elementwise_add(short, se))


def se_resnext50(x, class_dim=1000, depth=50, cardinality=32):
    if depth not in _DEPTH_CFG:
        raise ValueError(f"depth must be one of {sorted(_DEPTH_CFG)}")
    blocks = _DEPTH_CFG[depth]
    x = _conv_bn(x, 64, 7, stride=2, act="relu")
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                      pool_type="max")
    num_filters = [128, 256, 512, 1024]
    for stage, n in enumerate(blocks):
        for i in range(n):
            x = _bottleneck(x, num_filters[stage],
                            stride=2 if i == 0 and stage != 0 else 1,
                            cardinality=cardinality)
    pool = layers.pool2d(x, pool_type="avg", global_pooling=True)
    drop = layers.dropout(pool, dropout_prob=0.5)
    return layers.fc(drop, class_dim, act="softmax",
                     param_attr=ParamAttr(name="fc_out_w"))


def build_se_resnext_train_program(class_dim=1000, image_size=224,
                                   depth=50, lr=0.1, momentum=0.9):
    """Returns (main, startup, feed_names, loss, acc)."""
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data("image", shape=[3, image_size, image_size],
                         dtype="float32")
        label = fluid.data("label", shape=[1], dtype="int64")
        pred = se_resnext50(img, class_dim, depth)
        loss = layers.mean(layers.cross_entropy(pred, label))
        acc = layers.accuracy(pred, label)
        fluid.optimizer.Momentum(lr, momentum=momentum,
                                 use_nesterov=True).minimize(loss)
    return main, startup, ["image", "label"], loss, acc
