"""PTB LSTM language model (reference: tests/unittests/
test_imperative_ptb_rnn.py / the book's RNN LM — embedding → stacked LSTM
→ projection, trained with per-position cross entropy).

TPU shape discipline: fixed [B, T] windows (the PTB setup is already
fixed-length truncated BPTT); the LSTM runs as a lax.scan inside the one
jitted step."""
from __future__ import annotations

from ..fluid import layers
from ..fluid.param_attr import ParamAttr

__all__ = ["build_ptb_lm_program"]


def build_ptb_lm_program(vocab_size=1000, hidden_size=64, num_layers=1,
                         num_steps=20, init_scale=0.1, lr=1.0,
                         max_grad_norm=5.0):
    """Returns (main, startup, feed_names, loss, last_hidden, last_cell)."""
    import paddle_tpu.fluid as fluid
    from ..fluid.initializer import UniformInitializer
    main, startup = fluid.Program(), fluid.Program()
    u = lambda: UniformInitializer(-init_scale, init_scale)
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[num_steps], dtype="int64")
        y = fluid.data("y", shape=[num_steps, 1], dtype="int64")
        emb = layers.embedding(
            x, [vocab_size, hidden_size],
            param_attr=ParamAttr(name="embedding_para",
                                 initializer=u()))
        # stacked LSTM over the whole window (lstm op → lax.scan)
        init_h = layers.fill_constant_batch_size_like(
            emb, [-1, num_layers, hidden_size], "float32", 0.0)
        init_c = layers.fill_constant_batch_size_like(
            emb, [-1, num_layers, hidden_size], "float32", 0.0)
        init_h = layers.transpose(init_h, [1, 0, 2])
        init_c = layers.transpose(init_c, [1, 0, 2])
        rnn_out, last_h, last_c = layers.lstm(
            emb, init_h, init_c, num_steps, hidden_size, num_layers)
        logits = layers.fc(rnn_out, vocab_size, num_flatten_dims=2,
                           param_attr=ParamAttr(name="softmax_w",
                                                initializer=u()),
                           bias_attr=ParamAttr(name="softmax_b",
                                               initializer=u()))
        probs = layers.softmax(logits)
        ce = layers.cross_entropy(probs, y)        # [B, T, 1]
        loss = layers.reduce_mean(layers.reduce_sum(ce, dim=1))
        clip = fluid.clip.GradientClipByGlobalNorm(max_grad_norm)
        fluid.optimizer.SGD(lr, grad_clip=clip).minimize(loss)
    return main, startup, ["x", "y"], loss, last_h, last_c
