"""Wide & Deep CTR model — the reference's flagship sparse/parameter-server
workload (reference: the PS stack is built for exactly this shape —
distributed_lookup_table + SelectedRows grads, fleet PS modes; CTR test
workload tests/unittests/dist_fleet_ctr.py; README.md:48's
"100 billions of features" claim is this model family).

TPU framing: the deep embeddings + MLP compile into one jitted step (MXU
matmuls, embedding gathers); the wide part and beyond-HBM tables use
`is_sparse`/`is_distributed` lookups so the same program transpiles onto
the host-RAM PS plane (fluid/ps_rpc.py) for tables that exceed device
memory.
"""
from __future__ import annotations

from ..fluid import layers

__all__ = ["wide_deep_net", "build_wide_deep_program", "ctr_reader"]


def wide_deep_net(dense, sparse_slots, sparse_dim=int(1e4), embedding_dim=16,
                  hidden=(400, 400, 400), is_sparse=False,
                  is_distributed=False):
    """Wide: per-slot 1-d hashed linear embeddings summed with the dense
    projection. Deep: per-slot dense embeddings + MLP. Returns the click
    probability [N, 1]."""
    # ---- wide: linear over sparse ids (one shared 1-d table) + dense
    wide_embs = []
    for i, slot in enumerate(sparse_slots):
        w = layers.embedding(
            slot, size=[sparse_dim, 1], is_sparse=is_sparse,
            is_distributed=is_distributed,
            param_attr="wide_emb_%d" % i)
        wide_embs.append(layers.reshape(w, [-1, 1]))
    wide = layers.fc(dense, 1, param_attr="wide_dense_w",
                     bias_attr="wide_dense_b")
    for e in wide_embs:
        wide = layers.elementwise_add(wide, e)

    # ---- deep: per-slot embeddings -> concat with dense -> MLP
    deep_embs = []
    for i, slot in enumerate(sparse_slots):
        e = layers.embedding(
            slot, size=[sparse_dim, embedding_dim], is_sparse=is_sparse,
            is_distributed=is_distributed,
            param_attr="deep_emb_%d" % i)
        deep_embs.append(layers.reshape(e, [-1, embedding_dim]))
    deep = layers.concat([dense] + deep_embs, axis=1)
    for j, h in enumerate(hidden):
        deep = layers.fc(deep, h, act="relu",
                         param_attr="deep_fc_w_%d" % j,
                         bias_attr="deep_fc_b_%d" % j)
    deep = layers.fc(deep, 1, param_attr="deep_out_w",
                     bias_attr="deep_out_b")

    return layers.sigmoid(layers.elementwise_add(wide, deep))


def build_wide_deep_program(num_dense=13, num_slots=26, sparse_dim=int(1e4),
                            embedding_dim=16, hidden=(400, 400, 400),
                            lr=1e-3, is_sparse=False, is_distributed=False,
                            optimizer=None, with_auc=True):
    """Returns (main, startup, feed_names, loss, auc_var).

    ``is_distributed=True`` marks the embedding tables for the
    DistributeTranspiler's distributed_lookup_table rewrite (tables live on
    pservers); the driver then trains via the fleet PS mode exactly like
    the reference CTR jobs.

    ``with_auc``: keep the streaming AUC metric op in the train program
    (the reference CTR shape). The op is stateful (host-side histogram
    update), so the executor runs the block SEGMENTED — fwd+bwd+update as
    compiled jitted segments, auc as an interpreted island
    (fluid/executor.py _SegmentedBlock). ``with_auc=False`` drops the
    metric for a fully-compiled step — the A/B pair that isolates the
    segmentation overhead in bench.py. Returns auc_var=None then."""
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        dense = fluid.data("dense", shape=[num_dense], dtype="float32")
        slots = [fluid.data("slot_%d" % i, shape=[1], dtype="int64")
                 for i in range(num_slots)]
        label = fluid.data("label", shape=[1], dtype="int64")
        prob = wide_deep_net(dense, slots, sparse_dim, embedding_dim,
                             hidden, is_sparse, is_distributed)
        labelf = fluid.layers.cast(label, "float32")
        loss = layers.mean(layers.log_loss(prob, labelf))
        auc = None
        if with_auc:
            auc, _ = layers.auc(layers.concat(
                [1.0 - prob, prob], axis=1), label)
        opt = optimizer or fluid.optimizer.Adam(lr)
        opt.minimize(loss)
    feeds = ["dense"] + ["slot_%d" % i for i in range(num_slots)] + ["label"]
    return main, startup, feeds, loss, auc


def ctr_reader(batch, num_dense=13, num_slots=26, sparse_dim=int(1e4),
               seed=0):
    """Synthetic CTR batches with learnable structure: the label correlates
    with a few slots' ids and the dense part."""
    import numpy as np
    rng = np.random.RandomState(seed)
    w_dense = rng.randn(num_dense) * 3.0
    # informative slots draw from a small id range so their "hot" id is
    # frequent enough to learn
    n_info = min(4, num_slots)
    info_range = min(8, sparse_dim)
    hot = rng.randint(0, info_range, size=n_info)

    def next_batch():
        dense = rng.rand(batch, num_dense).astype("float32")
        slots = [rng.randint(0, info_range if i < n_info else sparse_dim,
                             (batch, 1)).astype("int64")
                 for i in range(num_slots)]
        logit = (dense - 0.5) @ w_dense
        for i, s in enumerate(slots[:n_info]):
            logit = logit + 2.0 * ((s[:, 0] == hot[i]) - 1.0 / info_range)
        p = 1.0 / (1.0 + np.exp(-logit))
        label = (rng.rand(batch) < p).astype("int64").reshape(-1, 1)
        feed = {"dense": dense, "label": label}
        for i, s in enumerate(slots):
            feed["slot_%d" % i] = s
        return feed
    return next_batch
