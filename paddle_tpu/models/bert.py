"""BERT-base / transformer encoder built on the fluid layers API
(reference models: the transformer encoder used by
python/paddle/fluid/tests/unittests/test_imperative_transformer* and the
ERNIE/BERT configs named in BASELINE.md; fused attention replaces the
reference's fused/multihead_matmul_op.cu).

Attention goes through the `multihead_matmul` op, which dispatches to the
Pallas flash-attention kernel on TPU (ops/pallas/flash_attention.py) and a
plain jax composition elsewhere."""
from __future__ import annotations

import math

from .. import fluid
from ..fluid import layers
from ..fluid.framework import Variable
from ..fluid.layer_helper import LayerHelper
from ..fluid.param_attr import ParamAttr

__all__ = ["multi_head_attention", "encoder_layer", "encoder",
           "bert_base_config", "build_bert_pretrain_program"]


def bert_base_config():
    return dict(vocab_size=30522, hidden=768, layers=12, heads=12,
                ffn=3072, max_len=512, type_vocab=2)


def fused_multihead_attention(q, k, v, n_head, dropout_rate=0.0,
                              attn_bias=None, causal=False):
    """One fused attention op (Pallas on TPU). q/k/v: [B, S, H];
    attn_bias: optional additive mask broadcastable to [B, H, Sq, Sk]."""
    helper = LayerHelper("multihead_matmul")
    out = helper.create_variable_for_type_inference(q.dtype)
    out.shape = q.shape
    ins = {"Q": [q], "K": [k], "V": [v]}
    if attn_bias is not None:
        ins["Bias"] = [attn_bias]
    helper.append_op(type="fused_attention_qkv",
                     inputs=ins,
                     outputs={"Out": [out]},
                     attrs={"num_heads": n_head,
                            "dropout_rate": dropout_rate,
                            "causal": causal})
    return out


def multi_head_attention(queries, keys, values, d_model, n_head,
                         dropout_rate=0.0, param_initializer=None,
                         attn_bias=None, causal=False):
    keys = queries if keys is None else keys
    values = keys if values is None else values
    q = layers.fc(queries, d_model, num_flatten_dims=2,
                  param_attr=ParamAttr(initializer=param_initializer))
    k = layers.fc(keys, d_model, num_flatten_dims=2,
                  param_attr=ParamAttr(initializer=param_initializer))
    v = layers.fc(values, d_model, num_flatten_dims=2,
                  param_attr=ParamAttr(initializer=param_initializer))
    ctx = fused_multihead_attention(q, k, v, n_head, dropout_rate,
                                    attn_bias=attn_bias, causal=causal)
    return layers.fc(ctx, d_model, num_flatten_dims=2,
                     param_attr=ParamAttr(initializer=param_initializer))


def positionwise_ffn(x, d_inner, d_model, dropout_rate=0.0,
                     param_initializer=None):
    h = layers.fc(x, d_inner, num_flatten_dims=2, act="gelu",
                  param_attr=ParamAttr(initializer=param_initializer))
    if dropout_rate:
        h = layers.dropout(h, dropout_rate,
                           dropout_implementation="upscale_in_train")
    return layers.fc(h, d_model, num_flatten_dims=2,
                     param_attr=ParamAttr(initializer=param_initializer))


def _add_norm(x, y, dropout_rate=0.0):
    if dropout_rate:
        y = layers.dropout(y, dropout_rate,
                           dropout_implementation="upscale_in_train")
    return layers.layer_norm(layers.elementwise_add(x, y),
                             begin_norm_axis=len(x.shape) - 1)


def encoder_layer(x, d_model, n_head, d_inner, dropout_rate=0.0,
                  param_initializer=None, attn_bias=None):
    attn = multi_head_attention(x, None, None, d_model, n_head,
                                dropout_rate, param_initializer,
                                attn_bias=attn_bias)
    x = _add_norm(x, attn, dropout_rate)
    ffn = positionwise_ffn(x, d_inner, d_model, dropout_rate,
                           param_initializer)
    return _add_norm(x, ffn, dropout_rate)


def encoder(x, n_layer, d_model, n_head, d_inner, dropout_rate=0.0,
            param_initializer=None, attn_bias=None,
            collect_layer_outs=None):
    """``collect_layer_outs``: a list that receives each layer's output
    var — the natural RecomputeOptimizer checkpoint boundaries."""
    for _ in range(n_layer):
        x = encoder_layer(x, d_model, n_head, d_inner, dropout_rate,
                          param_initializer, attn_bias=attn_bias)
        if collect_layer_outs is not None:
            collect_layer_outs.append(x)
    return x


def padding_attn_bias(input_mask):
    """[B, S] 1/0 keep-mask → additive bias [B, 1, 1, S] for the fused
    attention ops (pads get -1e9)."""
    neg = layers.scale(input_mask, scale=-1.0, bias=1.0)
    bias = layers.scale(neg, scale=-1e9)
    return layers.unsqueeze(layers.unsqueeze(bias, [1]), [1])


def bert_embedding(src_ids, pos_ids, sent_ids, cfg, dropout_rate=0.0):
    from ..fluid.initializer import TruncatedNormal
    init = TruncatedNormal(scale=0.02)
    emb = layers.embedding(src_ids, [cfg["vocab_size"], cfg["hidden"]],
                           param_attr=ParamAttr(name="word_embedding",
                                                initializer=init))
    pos = layers.embedding(pos_ids, [cfg["max_len"], cfg["hidden"]],
                           param_attr=ParamAttr(name="pos_embedding",
                                                initializer=init))
    sent = layers.embedding(sent_ids, [cfg["type_vocab"], cfg["hidden"]],
                            param_attr=ParamAttr(name="sent_embedding",
                                                 initializer=init))
    x = layers.elementwise_add(layers.elementwise_add(emb, pos), sent)
    x = layers.layer_norm(x, begin_norm_axis=len(x.shape) - 1)
    if dropout_rate:
        x = layers.dropout(x, dropout_rate,
                           dropout_implementation="upscale_in_train")
    return x


def build_bert_pretrain_program(cfg=None, seq_len=128, dropout=0.0,
                                lr=1e-4, mlm_frac=0.15, use_amp=False,
                                use_input_mask=False, recompute=False):
    """Masked-LM pretraining step program. Feeds: src_ids, pos_ids,
    sent_ids [B,S] int64; mask_pos [M] int64 (flattened positions),
    mask_label [M,1] int64; plus input_mask [B,S] float32 when
    use_input_mask (pads excluded from attention). use_amp: bf16
    activations via contrib.mixed_precision (f32 master weights + f32
    norm/softmax). recompute: per-encoder-layer RecomputeOptimizer
    checkpoints — trade ~1/3 more FLOPs for per-layer activation
    memory (bigger batches on a fixed HBM budget)."""
    cfg = cfg or bert_base_config()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.data("src_ids", shape=[seq_len], dtype="int64")
        pos = fluid.data("pos_ids", shape=[seq_len], dtype="int64")
        sent = fluid.data("sent_ids", shape=[seq_len], dtype="int64")
        mask_pos = fluid.data("mask_pos", shape=[1], dtype="int64",
                              append_batch_size=True)
        mask_label = fluid.data("mask_label", shape=[1], dtype="int64")
        attn_bias = None
        extra_feeds = []
        if use_input_mask:
            input_mask = fluid.data("input_mask", shape=[seq_len],
                                    dtype="float32")
            attn_bias = padding_attn_bias(input_mask)
            extra_feeds = [input_mask]
        x = bert_embedding(src, pos, sent, cfg, dropout)
        layer_outs = [] if recompute else None
        enc = encoder(x, cfg["layers"], cfg["hidden"], cfg["heads"],
                      cfg["ffn"], dropout, attn_bias=attn_bias,
                      collect_layer_outs=layer_outs)
        flat = layers.reshape(enc, [-1, cfg["hidden"]])
        picked = layers.gather(flat, mask_pos)
        logits = layers.fc(picked, cfg["vocab_size"])
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, mask_label))
        opt = fluid.optimizer.Adam(lr)
        if use_amp:
            from ..fluid.contrib import mixed_precision
            opt = mixed_precision.decorate(opt)
        if recompute:
            opt = fluid.optimizer.RecomputeOptimizer(opt)
            opt._set_checkpoints(layer_outs[:-1])
        opt.minimize(loss)
    return main, startup, \
        [src, pos, sent, mask_pos, mask_label] + extra_feeds, [loss]
