"""recognize_digits — the book's first model, MLP and LeNet-style conv
variants (reference: python/paddle/fluid/tests/book/
test_recognize_digits.py — mlp and conv nets trained to threshold)."""
from __future__ import annotations

from ..fluid import layers

__all__ = ["mlp", "convnet", "build_mnist_program"]


def mlp(img):
    h1 = layers.fc(img, 128, act="relu")
    h2 = layers.fc(h1, 64, act="relu")
    return layers.fc(h2, 10, act="softmax")


def convnet(img):
    """LeNet-ish conv-pool x2 + fc (reference conv_net)."""
    x = layers.conv2d(img, num_filters=20, filter_size=5, act="relu")
    x = layers.pool2d(x, pool_size=2, pool_stride=2, pool_type="max")
    x = layers.batch_norm(x)
    x = layers.conv2d(x, num_filters=50, filter_size=5, act="relu")
    x = layers.pool2d(x, pool_size=2, pool_stride=2, pool_type="max")
    return layers.fc(x, 10, act="softmax")


def build_mnist_program(net="mlp", lr=0.01):
    """Returns (main, startup, feed_names, loss, acc)."""
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        if net == "mlp":
            img = fluid.data("img", shape=[784], dtype="float32")
            pred = mlp(img)
        elif net == "conv":
            img = fluid.data("img", shape=[1, 28, 28], dtype="float32")
            pred = convnet(img)
        else:
            raise ValueError("net must be 'mlp' or 'conv'")
        label = fluid.data("label", shape=[1], dtype="int64")
        loss = layers.mean(layers.cross_entropy(pred, label))
        acc = layers.accuracy(pred, label)
        fluid.optimizer.Adam(lr).minimize(loss)
    return main, startup, ["img", "label"], loss, acc
