"""word2vec — skip-gram with negative sampling / hierarchical sigmoid
(reference: python/paddle/fluid/tests/book/test_word2vec.py — the N-gram
neural LM variant — and the NCE/hsigmoid ops it exercises,
operators/nce_op.cc, hierarchical_sigmoid_op.cc)."""
from __future__ import annotations

from ..fluid import layers
from ..fluid.param_attr import ParamAttr

__all__ = ["build_ngram_lm_program", "build_skipgram_program"]


def build_ngram_lm_program(dict_size=2048, emb_dim=32, hid_dim=256,
                           window=4, lr=1e-3):
    """The book's N-gram LM: concat of N-1 word embeddings → fc → softmax
    over the vocab (reference test_word2vec.py). Returns
    (main, startup, feed_names, loss)."""
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        words = [fluid.data(f"word_{i}", shape=[1], dtype="int64")
                 for i in range(window)]
        target = fluid.data("target", shape=[1], dtype="int64")
        embs = [layers.embedding(
            w, [dict_size, emb_dim], is_sparse=True,
            param_attr=ParamAttr(name="shared_w")) for w in words]
        concat = layers.concat(embs, axis=1)
        hidden = layers.fc(concat, hid_dim, act="sigmoid")
        predict = layers.fc(hidden, dict_size, act="softmax")
        loss = layers.mean(layers.cross_entropy(predict, target))
        fluid.optimizer.SGD(lr).minimize(loss)
    return main, startup, [w.name for w in words] + ["target"], loss


def build_skipgram_program(dict_size=2048, emb_dim=32, neg_num=5,
                           lr=1e-3, loss_type="nce"):
    """Skip-gram: center word predicts a context word; loss via NCE
    (sampled) or hierarchical sigmoid. Returns
    (main, startup, feed_names, loss)."""
    import paddle_tpu.fluid as fluid
    if loss_type not in ("nce", "hsigmoid"):
        raise ValueError("loss_type must be 'nce' or 'hsigmoid'")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        center = fluid.data("center", shape=[1], dtype="int64")
        context = fluid.data("context", shape=[1], dtype="int64")
        emb = layers.embedding(center, [dict_size, emb_dim],
                               is_sparse=True,
                               param_attr=ParamAttr(name="emb"))
        emb = layers.squeeze(emb, [1]) if len(emb.shape) == 3 else emb
        if loss_type == "nce":
            cost = layers.nce(input=emb, label=context,
                              num_total_classes=dict_size,
                              num_neg_samples=neg_num)
        else:
            cost = layers.hsigmoid(input=emb, label=context,
                                   num_classes=dict_size)
        loss = layers.mean(cost)
        fluid.optimizer.Adagrad(lr).minimize(loss)
    return main, startup, ["center", "context"], loss
