"""Model zoo — the reference's book/test model families built on the fluid
front end (reference: python/paddle/fluid/tests/book/ +
test_imperative_{resnet,se_resnext,transformer,ptb_rnn}.py)."""
from . import bert  # noqa: F401
from . import resnet  # noqa: F401
from . import transformer  # noqa: F401
from . import word2vec  # noqa: F401
from . import ptb_lm  # noqa: F401
from . import se_resnext  # noqa: F401
from . import mnist  # noqa: F401
from . import wide_deep  # noqa: F401
from . import book_extra  # noqa: F401
