"""ResNet built on the fluid layers API (reference models: the resnet used
by python/paddle/fluid/tests/unittests/dist_se_resnext.py and
test_imperative_resnet.py — conv2d/batch_norm/pool2d stacks; BASELINE.md
names ResNet-50 ImageNet as a headline config).

TPU notes: NCHW layout feeds lax.conv_general_dilated; XLA re-lays out for
the MXU internally. bf16 via fluid.contrib.mixed_precision.decorate."""
from __future__ import annotations

from .. import fluid
from ..fluid import layers

__all__ = ["resnet50", "build_resnet_train_program"]

_DEPTH_CFG = {
    18: ([2, 2, 2, 2], "basic"),
    34: ([3, 4, 6, 3], "basic"),
    50: ([3, 4, 6, 3], "bottleneck"),
    101: ([3, 4, 23, 3], "bottleneck"),
    152: ([3, 8, 36, 3], "bottleneck"),
}


def _conv_bn(x, num_filters, filter_size, stride=1, act=None, name=None):
    conv = layers.conv2d(x, num_filters, filter_size, stride=stride,
                         padding=(filter_size - 1) // 2, bias_attr=False,
                         name=name)
    return layers.batch_norm(conv, act=act)


def _shortcut(x, num_filters, stride):
    in_c = x.shape[1]
    if in_c != num_filters or stride != 1:
        return _conv_bn(x, num_filters, 1, stride)
    return x


def _bottleneck(x, num_filters, stride):
    conv0 = _conv_bn(x, num_filters, 1, act="relu")
    conv1 = _conv_bn(conv0, num_filters, 3, stride=stride, act="relu")
    conv2 = _conv_bn(conv1, num_filters * 4, 1)
    short = _shortcut(x, num_filters * 4, stride)
    return layers.elementwise_add(short, conv2, act="relu")


def _basic(x, num_filters, stride):
    conv0 = _conv_bn(x, num_filters, 3, stride=stride, act="relu")
    conv1 = _conv_bn(conv0, num_filters, 3)
    short = _shortcut(x, num_filters, stride)
    return layers.elementwise_add(short, conv1, act="relu")


def resnet(x, class_dim=1000, depth=50):
    blocks, kind = _DEPTH_CFG[depth]
    num_filters = [64, 128, 256, 512]
    y = _conv_bn(x, 64, 7, stride=2, act="relu")
    y = layers.pool2d(y, pool_size=3, pool_stride=2, pool_padding=1,
                      pool_type="max")
    fn = _bottleneck if kind == "bottleneck" else _basic
    for stage, n in enumerate(blocks):
        for i in range(n):
            y = fn(y, num_filters[stage], stride=2 if i == 0 and stage > 0 else 1)
    y = layers.pool2d(y, pool_type="avg", global_pooling=True)
    y = layers.flatten(y, axis=1)
    return layers.fc(y, class_dim, act="softmax")


def resnet50(x, class_dim=1000):
    return resnet(x, class_dim, 50)


def build_resnet_train_program(depth=50, class_dim=1000, image_size=224,
                               lr=0.1, momentum=0.9):
    """Returns (main, startup, feeds, fetches) for a ResNet train step."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data("image", shape=[3, image_size, image_size],
                         dtype="float32")
        label = fluid.data("label", shape=[1], dtype="int64")
        pred = resnet(img, class_dim, depth)
        loss = layers.mean(layers.cross_entropy(pred, label))
        acc = layers.accuracy(pred, label)
        opt = fluid.optimizer.Momentum(lr, momentum=momentum)
        opt.minimize(loss)
    return main, startup, [img, label], [loss, acc]
