"""Transformer for machine translation — the WMT config of the reference's
book/test suite (reference: python/paddle/fluid/tests/unittests/
dist_transformer.py + test_machine_translation.py; the 2017 "Attention is
All You Need" base/big configs).

TPU-first shape discipline: fixed [B, S] batches (padding masks as additive
attention bias), every step one jitted XLA computation; decode runs the
compiled step in a host loop writing growing prefixes (static shapes per
length bucket).
"""
from __future__ import annotations

import numpy as np

from ..fluid import layers
from ..fluid.param_attr import ParamAttr
from ..fluid.initializer import Xavier
from .bert import (multi_head_attention, positionwise_ffn, _add_norm,
                   padding_attn_bias)

__all__ = ["transformer_base_config", "transformer_big_config",
           "encoder_stack", "decoder_stack", "build_wmt_train_program",
           "build_greedy_decode_program"]


def transformer_base_config():
    return dict(src_vocab=37000, trg_vocab=37000, d_model=512, d_inner=2048,
                heads=8, enc_layers=6, dec_layers=6, max_len=256,
                dropout=0.1, label_smooth=0.1)


def transformer_big_config():
    cfg = transformer_base_config()
    cfg.update(d_model=1024, d_inner=4096, heads=16, dropout=0.3)
    return cfg


def _embed(ids, vocab, d_model, name):
    emb = layers.embedding(
        ids, [vocab, d_model],
        param_attr=ParamAttr(name=name, initializer=Xavier()))
    emb = layers.scale(emb, scale=float(d_model) ** 0.5)
    # sinusoidal positions (reference add_position_encoding op)
    return layers.add_position_encoding(emb, alpha=1.0, beta=1.0)


def _pad_bias(pad_mask, n_head):
    """[B, S] 1/0 keep-mask → additive bias [B, 1, 1, S]."""
    return padding_attn_bias(pad_mask)


def encoder_stack(src_emb, cfg, src_bias=None):
    x = src_emb
    for _ in range(cfg["enc_layers"]):
        attn = multi_head_attention(x, None, None, cfg["d_model"],
                                    cfg["heads"], cfg["dropout"],
                                    attn_bias=src_bias)
        x = _add_norm(x, attn, cfg["dropout"])
        ffn = positionwise_ffn(x, cfg["d_inner"], cfg["d_model"],
                               cfg["dropout"])
        x = _add_norm(x, ffn, cfg["dropout"])
    return x


def decoder_stack(trg_emb, enc_out, cfg, trg_bias=None, src_bias=None):
    x = trg_emb
    for _ in range(cfg["dec_layers"]):
        self_attn = multi_head_attention(x, None, None, cfg["d_model"],
                                         cfg["heads"], cfg["dropout"],
                                         attn_bias=trg_bias, causal=True)
        x = _add_norm(x, self_attn, cfg["dropout"])
        cross = multi_head_attention(x, enc_out, enc_out, cfg["d_model"],
                                     cfg["heads"], cfg["dropout"],
                                     attn_bias=src_bias)
        x = _add_norm(x, cross, cfg["dropout"])
        ffn = positionwise_ffn(x, cfg["d_inner"], cfg["d_model"],
                               cfg["dropout"])
        x = _add_norm(x, ffn, cfg["dropout"])
    return x


def _logits(dec_out, cfg):
    return layers.fc(dec_out, cfg["trg_vocab"], num_flatten_dims=2,
                     param_attr=ParamAttr(name="trg_proj",
                                          initializer=Xavier()))


def build_wmt_train_program(cfg=None, src_len=32, trg_len=32, lr=1e-3,
                            warmup_steps=4000):
    """Full training program: feeds src_ids/src_mask/trg_ids/trg_mask/
    labels; label-smoothed CE; Adam with Noam LR (reference dist_transformer
    training setup). Returns (main, startup, feeds, loss)."""
    import paddle_tpu.fluid as fluid
    cfg = cfg or transformer_base_config()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.data("src_ids", shape=[src_len], dtype="int64")
        smask = fluid.data("src_mask", shape=[src_len], dtype="float32")
        trg = fluid.data("trg_ids", shape=[trg_len], dtype="int64")
        tmask = fluid.data("trg_mask", shape=[trg_len], dtype="float32")
        label = fluid.data("labels", shape=[trg_len, 1], dtype="int64")
        src_bias = _pad_bias(smask, cfg["heads"])
        trg_bias = _pad_bias(tmask, cfg["heads"])
        enc = encoder_stack(_embed(src, cfg["src_vocab"], cfg["d_model"],
                                   "src_embedding"), cfg, src_bias)
        dec = decoder_stack(_embed(trg, cfg["trg_vocab"], cfg["d_model"],
                                   "trg_embedding"), enc, cfg,
                            trg_bias, src_bias)
        logits = _logits(dec, cfg)
        probs = layers.softmax(logits)
        one_hot = layers.one_hot(label, cfg["trg_vocab"])
        smooth = layers.label_smooth(one_hot,
                                     epsilon=cfg["label_smooth"])
        ce = layers.cross_entropy(probs, smooth, soft_label=True)
        # mask out padding positions
        ce = layers.elementwise_mul(layers.squeeze(ce, [2]), tmask)
        denom = layers.reduce_sum(tmask)
        loss = layers.elementwise_div(layers.reduce_sum(ce), denom)
        from ..fluid.layers.learning_rate_scheduler import noam_decay
        sched = noam_decay(cfg["d_model"], warmup_steps) if lr is None \
            else lr
        fluid.optimizer.Adam(learning_rate=sched, beta1=0.9,
                             beta2=0.997, epsilon=1e-9).minimize(loss)
    feeds = ["src_ids", "src_mask", "trg_ids", "trg_mask", "labels"]
    return main, startup, feeds, loss


def build_greedy_decode_program(cfg=None, src_len=32, max_out_len=32):
    """Greedy decode: runs the decoder over a fixed trg window each step
    (host loop re-feeds the grown prefix; each length hits a cached XLA
    executable). Returns (program, startup, feeds, next_token_logits)."""
    import paddle_tpu.fluid as fluid
    cfg = cfg or transformer_base_config()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.data("src_ids", shape=[src_len], dtype="int64")
        smask = fluid.data("src_mask", shape=[src_len], dtype="float32")
        trg = fluid.data("trg_ids", shape=[max_out_len], dtype="int64")
        src_bias = _pad_bias(smask, cfg["heads"])
        enc = encoder_stack(_embed(src, cfg["src_vocab"], cfg["d_model"],
                                   "src_embedding"), cfg, src_bias)
        dec = decoder_stack(_embed(trg, cfg["trg_vocab"], cfg["d_model"],
                                   "trg_embedding"), enc, cfg,
                            None, src_bias)
        logits = _logits(dec, cfg)  # [B, max_out_len, V]; host loop takes
        # argmax at the current position and re-feeds the grown prefix
    return main, startup, ["src_ids", "src_mask", "trg_ids"], logits
