"""Program debugging helpers (reference: python/paddle/fluid/debugger.py —
pprint_program_codes / draw_block_graphviz).

``repr_program`` renders a Program as readable pseudo-code;
``draw_block_graphviz`` re-exported from net_drawer."""
from __future__ import annotations

from .net_drawer import draw_block_graphviz

__all__ = ["pprint_program_codes", "pprint_block_codes", "repr_program",
           "draw_block_graphviz"]


def _fmt_attr(v):
    if hasattr(v, "idx"):  # sub-block
        return f"block[{v.idx}]"
    r = repr(v)
    return r if len(r) <= 40 else r[:37] + "..."


def pprint_block_codes(block, show_backward=False) -> str:
    lines = [f"# block {block.idx} (parent {block.parent_idx})"]
    for v in block.vars.values():
        flag = " persistable" if v.persistable else ""
        lines.append(f"var {v.name}: shape={list(v.shape)}{flag}")
    for op in block.ops:
        if not show_backward and op.type.endswith("_grad"):
            continue
        outs = ", ".join(f"{s}={ns}" for s, ns in op.outputs.items())
        ins = ", ".join(f"{s}={ns}" for s, ns in op.inputs.items())
        attrs = ", ".join(f"{k}={_fmt_attr(v)}"
                          for k, v in sorted(op.attrs.items())
                          if not k.startswith("_") and k != "op_role_var")
        lines.append(f"{outs} = {op.type}({ins})  # {attrs}")
    return "\n".join(lines)


def pprint_program_codes(program, show_backward=False) -> str:
    text = "\n\n".join(pprint_block_codes(b, show_backward)
                       for b in program.blocks)
    print(text)
    return text


repr_program = pprint_program_codes
