"""DyGraph data parallel (reference: python/paddle/fluid/dygraph/parallel.py
— ParallelEnv:54, prepare_context:30, DataParallel:223 with scale_loss:290
and apply_collective_grads:382 bucketed NCCL allreduce; NCCL bootstrap
imperative/nccl_context.cc).

TPU design: per-process SPMD over jax.distributed. scale_loss divides by
world size; apply_collective_grads psums grads across hosts via
jax.experimental.multihost_utils when world>1 (ICI/DCN), identity on one
process. Bucketing is unnecessary: XLA coalesces collectives."""
from __future__ import annotations

import os

import numpy as np
import jax

from .layers import Layer
from .base import VarBase

__all__ = ["prepare_context", "ParallelEnv", "DataParallel"]


class ParallelEnv:
    """Reads the same PADDLE_* launch env contract as the reference
    (role_maker/launch env: PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM,
    PADDLE_CURRENT_ENDPOINT, PADDLE_TRAINER_ENDPOINTS)."""

    def __init__(self):
        self._nranks = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        self._local_rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._dev_id = int(os.getenv("FLAGS_selected_tpus",
                                     os.getenv("FLAGS_selected_gpus", "0"))
                           .split(",")[0])
        self._current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")
        self._trainer_endpoints = os.getenv("PADDLE_TRAINER_ENDPOINTS",
                                            "").split(",")

    @property
    def nranks(self):
        return self._nranks

    @property
    def local_rank(self):
        return self._local_rank

    @property
    def dev_id(self):
        return self._dev_id

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints


Env = ParallelEnv


def prepare_context(strategy=None):
    """reference dygraph/parallel.py:30 — initialize the distributed runtime
    (NCCL id exchange ⇒ jax.distributed.initialize over the same envs)."""
    env = ParallelEnv()
    if env.nranks > 1 and not jax.distributed.is_initialized():
        jax.distributed.initialize(
            coordinator_address=env.trainer_endpoints[0],
            num_processes=env.nranks, process_id=env.local_rank)
    return strategy


class DataParallel(Layer):
    """reference dygraph/parallel.py:223."""

    def __init__(self, layers, strategy=None):
        super().__init__()
        self._layers = layers
        self._env = ParallelEnv()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        if self._env.nranks <= 1:
            return loss
        import jax.numpy as jnp
        return VarBase(loss._array / self._env.nranks,
                       stop_gradient=loss.stop_gradient)

    def apply_collective_grads(self):
        if self._env.nranks <= 1:
            return
        from jax.experimental import multihost_utils
        for p in self._layers.parameters():
            if p._grad is not None:
                # DCN/ICI all-reduce of the grad across processes
                summed = multihost_utils.process_allgather(p._grad)
                p._grad = summed.sum(axis=0)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_dict(self, *a, **k):
        return self._layers.set_dict(*a, **k)

    def load_dict(self, *a, **k):
        return self._layers.load_dict(*a, **k)
