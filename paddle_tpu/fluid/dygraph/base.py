"""DyGraph core: VarBase + Tracer + tape autograd.

Reference mapping: VarBase (imperative/layer.h:56), Tracer::TraceOp
(imperative/tracer.cc:45) which creates the op, runs it, and records a grad
node; BasicEngine::Execute (imperative/basic_engine.cc:159) which sweeps the
grad DAG with GradientAccumulators.

TPU design: ops execute eagerly as jax calls (async dispatch gives the
pipelining the reference gets from CUDA streams); the tape stores (op, ins,
outs, attrs) and backward replays it with the same vjp machinery the static
executor uses (ops/registry.py run_generic_grad) — one grad semantics for
both modes. ``dygraph.jit`` re-traces functions into jax.jit for the
compiled path (reference dygraph_to_static / TracedLayer)."""
from __future__ import annotations

import contextlib
import functools
from typing import Any, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .. import core, framework, unique_name
from ..core import VarDesc, convert_np_dtype_to_dtype_, dtype_to_jnp
from ...ops.registry import OPS, run_generic_grad, GRAD_SUFFIX

__all__ = ["guard", "to_variable", "enabled", "no_grad", "grad", "VarBase",
           "Tracer", "enable_dygraph", "disable_dygraph",
           "BackwardStrategy"]


class BackwardStrategy:
    """reference: pybind imperative.cc BackwardStrategy — sort_sum_gradient
    forces deterministic gradient accumulation order. The tape here sums
    fan-in in recorded order, which is already deterministic, so the knob
    is accepted and recorded only."""

    def __init__(self):
        self.sort_sum_gradient = False


class VarBase:
    """Imperative tensor (reference imperative/layer.h:56)."""

    def __init__(self, array=None, name: Optional[str] = None,
                 stop_gradient: bool = True, persistable: bool = False,
                 trainable: bool = False, dtype=None, shape=None):
        if array is not None and not isinstance(array, jax.Array):
            array = jnp.asarray(array)
        self._array = array
        self.name = name or unique_name.generate("generated_var")
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.trainable = trainable
        self._grad: Optional[jnp.ndarray] = None
        self._declared_dtype = dtype
        self._declared_shape = shape
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_data = False
        self.lod_level = 0
        self.type = VarDesc.VarType.LOD_TENSOR

    # -- data -------------------------------------------------------------
    @property
    def shape(self):
        if self._array is not None:
            return tuple(self._array.shape)
        return tuple(self._declared_shape or ())

    @shape.setter
    def shape(self, value):
        # static layer helpers annotate declared shape before the op runs;
        # once an array exists, its real shape wins
        self._declared_shape = tuple(value)

    @property
    def dtype(self):
        if self._array is not None:
            return core.np_to_dtype(np.dtype(str(self._array.dtype))
                                    if self._array.dtype != jnp.bfloat16
                                    else "bfloat16")
        return self._declared_dtype or VarDesc.VarType.FP32

    @property
    def array(self):
        return self._array

    def numpy(self):
        return np.asarray(self._array)

    def set_value(self, value):
        if isinstance(value, VarBase):
            value = value._array
        self._array = jnp.asarray(np.asarray(value)) \
            if not isinstance(value, jax.Array) else value

    def detach(self):
        return VarBase(self._array, stop_gradient=True)

    def astype(self, dtype):
        return _trace_simple("cast", {"X": [self]},
                             {"in_dtype": self.dtype,
                              "out_dtype": convert_np_dtype_to_dtype_(dtype)
                              if not isinstance(dtype, int) else dtype})

    # -- autograd ---------------------------------------------------------
    def backward(self, backward_strategy=None):
        tracer = framework._dygraph_tracer()
        assert tracer is not None, "backward() outside dygraph guard"
        tracer.run_backward(self)

    def gradient(self):
        return np.asarray(self._grad) if self._grad is not None else None

    @property
    def _grad_ivar(self):
        if self._grad is None:
            return None
        return VarBase(self._grad, name=self.name + "@GRAD",
                       stop_gradient=True)

    def clear_gradient(self):
        self._grad = None

    # -- operator sugar ---------------------------------------------------
    def _binary(self, other, op_type, reverse=False):
        if not isinstance(other, VarBase):
            other = VarBase(jnp.asarray(other, dtype_to_jnp(self.dtype)),
                            stop_gradient=True)
        x, y = (other, self) if reverse else (self, other)
        return _trace_simple(op_type, {"X": [x], "Y": [y]}, {"axis": -1})

    __add__ = lambda s, o: s._binary(o, "elementwise_add")
    __radd__ = lambda s, o: s._binary(o, "elementwise_add", True)
    __sub__ = lambda s, o: s._binary(o, "elementwise_sub")
    __rsub__ = lambda s, o: s._binary(o, "elementwise_sub", True)
    __mul__ = lambda s, o: s._binary(o, "elementwise_mul")
    __rmul__ = lambda s, o: s._binary(o, "elementwise_mul", True)
    __truediv__ = lambda s, o: s._binary(o, "elementwise_div")
    __rtruediv__ = lambda s, o: s._binary(o, "elementwise_div", True)
    __pow__ = lambda s, o: s._binary(o, "elementwise_pow")
    __lt__ = lambda s, o: s._binary(o, "less_than")
    __le__ = lambda s, o: s._binary(o, "less_equal")
    __gt__ = lambda s, o: s._binary(o, "greater_than")
    __ge__ = lambda s, o: s._binary(o, "greater_equal")

    def __bool__(self):
        return bool(np.asarray(self._array).reshape(-1)[0]) \
            if np.asarray(self._array).size == 1 \
            else bool(np.asarray(self._array).any())

    def __float__(self):
        return float(np.asarray(self._array).reshape(-1)[0])

    def __int__(self):
        return int(np.asarray(self._array).reshape(-1)[0])

    def __len__(self):
        return int(self.shape[0]) if self.shape else 0

    def __repr__(self):
        return (f"VarBase(name={self.name}, shape={list(self.shape)}, "
                f"stop_gradient={self.stop_gradient})\n{self.numpy()}")

    # block attr for API compat with static Variable
    @property
    def block(self):
        return framework.default_main_program().global_block()


class _TapeEntry:
    __slots__ = ("op_type", "ins", "outs", "attrs")

    def __init__(self, op_type, ins, outs, attrs):
        self.op_type = op_type
        self.ins = ins
        self.outs = outs
        self.attrs = attrs


class Tracer:
    """reference imperative/tracer.cc:45 — eager exec + grad-node record."""

    def __init__(self):
        self._tape: List[_TapeEntry] = []
        self._no_grad = False
        self._train_mode = True
        self._rng_counter = 0
        self._params: Dict[str, VarBase] = {}

    # ---------------------------------------------------------------- ops
    def trace_op(self, op_type, inputs, outputs, attrs):
        attrs = dict(attrs or {})
        info = OPS.get(op_type)
        ins_vb: Dict[str, List[VarBase]] = {}
        for slot, vals in (inputs or {}).items():
            if vals is None:
                continue
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            ins_vb[slot] = [v for v in vals]
        ins_arrays = {slot: [v._array if isinstance(v, VarBase) else
                             (v.array if hasattr(v, "array") else jnp.asarray(v))
                             for v in vals]
                      for slot, vals in ins_vb.items()}
        if info.needs_rng:
            if attrs.get("fix_seed", False) or attrs.get("seed", 0):
                attrs["_rng"] = jax.random.key(int(attrs.get("seed", 0)))
            else:
                attrs["_rng"] = jax.random.fold_in(
                    jax.random.key(core.globals_["FLAGS_seed"]),
                    self._rng_counter)
                self._rng_counter += 1
        if info.stateful:
            raise RuntimeError(
                f"op {op_type} is host-stateful and has no dygraph path")
        outs_arrays = info.kernel(ins_arrays, attrs)
        outs_vb: Dict[str, List[VarBase]] = {}
        fresh: List[VarBase] = []
        for slot, vals in (outputs or {}).items():
            if vals is None:
                continue
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            produced = (outs_arrays or {}).get(slot, [])
            lst = []
            for k, ov in enumerate(vals):
                arr = produced[k] if k < len(produced) else None
                if isinstance(ov, VarBase):
                    was_fresh = ov._array is None
                    if arr is not None:
                        ov._array = arr
                    if was_fresh:
                        fresh.append(ov)
                    lst.append(ov)
                else:
                    nv = VarBase(arr)
                    fresh.append(nv)
                    lst.append(nv)
            outs_vb[slot] = lst
        # default-constructed outputs for slots the layer didn't pass
        for slot, produced in (outs_arrays or {}).items():
            if slot not in outs_vb:
                outs_vb[slot] = [VarBase(a) for a in produced]
                fresh.extend(outs_vb[slot])

        requires_grad = (not self._no_grad and not info.no_grad and any(
            isinstance(v, VarBase) and not v.stop_gradient
            for vals in ins_vb.values() for v in vals))
        # only fresh outputs inherit requires_grad; pre-existing vars
        # (in-place params of optimizer ops) keep their own flag
        for v in fresh:
            v.stop_gradient = not requires_grad
        if requires_grad:
            self._tape.append(_TapeEntry(op_type, ins_vb, outs_vb,
                                         {k: v for k, v in attrs.items()}))
        first_slot = next(iter(outs_vb.values()), [None])
        return first_slot[0] if len(outs_vb) == 1 and len(first_slot) == 1 \
            else outs_vb

    # ---------------------------------------------------------- backward
    def run_backward(self, loss: VarBase):
        grads: Dict[int, jnp.ndarray] = {
            id(loss): jnp.ones_like(loss._array)}
        for entry in reversed(self._tape):
            ograds_present = any(
                id(v) in grads for vals in entry.outs.values() for v in vals)
            if not ograds_present:
                continue
            if entry.op_type == "@functional@":
                # a dygraph.grad(create_graph=True) node: backward is the
                # vjp of the recorded grad computation (vjp-of-the-vjp)
                in_vbs = entry.ins["In"]
                out_vbs = entry.outs["Out"]
                in_arrays = [v._array for v in in_vbs]
                outs_vals, vjp_fn = jax.vjp(entry.attrs["_fn"], *in_arrays)
                cots = tuple(
                    grads.get(id(v), None) if grads.get(id(v), None)
                    is not None else jnp.zeros_like(o)
                    for v, o in zip(out_vbs, outs_vals))
                for v, g in zip(in_vbs, vjp_fn(cots)):
                    if isinstance(v, VarBase) and not v.stop_gradient:
                        prev = grads.get(id(v))
                        grads[id(v)] = g if prev is None else prev + g
                continue
            info = OPS.get(entry.op_type)
            ins = {slot: [v._array for v in vals]
                   for slot, vals in entry.ins.items()}
            for slot, vals in entry.outs.items():
                ins.setdefault(slot, [v._array for v in vals])
                ins[slot + GRAD_SUFFIX] = [grads.get(id(v)) for v in vals]
            wanted = []
            for slot, vals in entry.ins.items():
                if any(isinstance(v, VarBase) and not v.stop_gradient
                       for v in vals):
                    wanted.append(slot + GRAD_SUFFIX)
            if not wanted:
                continue
            grad_kernel_type = entry.op_type + "_grad"
            if OPS.has(grad_kernel_type):
                gouts = OPS.get(grad_kernel_type).kernel(ins, entry.attrs)
            else:
                gouts = run_generic_grad(entry.op_type, ins, entry.attrs,
                                         wanted,
                                         list(entry.ins.keys()))
            for slot, vals in entry.ins.items():
                gvals = (gouts or {}).get(slot + GRAD_SUFFIX)
                if gvals is None:
                    continue
                for v, g in zip(vals, gvals):
                    if g is None or not isinstance(v, VarBase) \
                            or v.stop_gradient:
                        continue
                    # GradientAccumulator: sum fan-in
                    prev = grads.get(id(v))
                    grads[id(v)] = g if prev is None else prev + g
        # write grads onto leaves (params + any var the user watches) —
        # ONCE per var: grads[] already holds the fan-in total, and a var
        # appearing in several tape entries (x*x, residual reuse) must
        # not have its total added per occurrence (round-4 fix: y=x*x
        # used to report dx=4x). The += below is only the accumulation
        # ACROSS separate backward() calls, per reference semantics.
        written_leaves = set()
        for entry in self._tape:
            for vals in entry.ins.values():
                for v in vals:
                    if isinstance(v, VarBase) and not v.stop_gradient \
                            and id(v) in grads \
                            and id(v) not in written_leaves:
                        written_leaves.add(id(v))
                        g = grads[id(v)]
                        v._grad = g if v._grad is None else v._grad + g
        self._tape.clear()

    # ------------------------------------------------------------ params
    def create_parameter(self, name, shape, dtype, initializer, trainable,
                         optimize_attr=None, regularizer=None):
        if name in self._params:
            return self._params[name]
        arr = _run_initializer(initializer, shape, dtype, self)
        p = VarBase(arr, name=name, stop_gradient=not trainable,
                    persistable=True, trainable=trainable)
        p.optimize_attr = optimize_attr or {"learning_rate": 1.0}
        p.regularizer = regularizer
        self._params[name] = p
        return p

    def init_variable(self, var, initializer):
        if isinstance(var, VarBase) and var._array is None:
            var._array = _run_initializer(initializer, var.shape, var.dtype,
                                          self)
        return var

    @contextlib.contextmanager
    def _no_grad_guard(self):
        old = self._no_grad
        self._no_grad = True
        try:
            yield
        finally:
            self._no_grad = old


def _run_initializer(initializer, shape, dtype, tracer: Tracer):
    """Run an initializer's op spec eagerly to produce the param array."""
    from ..initializer import (ConstantInitializer, UniformInitializer,
                               NormalInitializer, TruncatedNormalInitializer,
                               XavierInitializer, MSRAInitializer,
                               NumpyArrayInitializer)
    if not isinstance(dtype, int):
        dtype = convert_np_dtype_to_dtype_(dtype)
    jdt = dtype_to_jnp(dtype)
    key = jax.random.fold_in(jax.random.key(core.globals_["FLAGS_seed"]),
                             tracer._rng_counter)
    tracer._rng_counter += 1
    shape = [int(s) for s in shape]
    if initializer is None:
        initializer = XavierInitializer()
    if isinstance(initializer, ConstantInitializer):
        return jnp.full(shape, initializer._value, jdt)
    if isinstance(initializer, UniformInitializer):
        return jax.random.uniform(key, shape, jdt, initializer._low,
                                  initializer._high)
    if isinstance(initializer, NormalInitializer):
        return initializer._mean + initializer._std * jax.random.normal(
            key, shape, jdt)
    if isinstance(initializer, TruncatedNormalInitializer):
        return initializer._mean + initializer._std * \
            jax.random.truncated_normal(key, -2.0, 2.0, shape, jdt)
    if isinstance(initializer, NumpyArrayInitializer):
        return jnp.asarray(initializer._value.astype(np.dtype(jdt)))
    if isinstance(initializer, (XavierInitializer, MSRAInitializer)):
        class _V:
            pass
        v = _V()
        v.shape = shape
        fin, fout = initializer._compute_fans(v)
        import math
        if isinstance(initializer, XavierInitializer):
            fin = initializer._fan_in or fin
            fout = initializer._fan_out or fout
            if initializer._uniform:
                lim = math.sqrt(6.0 / (fin + fout))
                return jax.random.uniform(key, shape, jdt, -lim, lim)
            std = math.sqrt(2.0 / (fin + fout))
            return std * jax.random.normal(key, shape, jdt)
        fin = initializer._fan_in or fin
        if initializer._uniform:
            lim = math.sqrt(6.0 / fin)
            return jax.random.uniform(key, shape, jdt, -lim, lim)
        return math.sqrt(2.0 / fin) * jax.random.normal(key, shape, jdt)
    raise TypeError(f"unsupported dygraph initializer {initializer}")


def _trace_simple(op_type, ins, attrs):
    tracer = framework._dygraph_tracer()
    return tracer.trace_op(op_type, ins, {"Out": [VarBase(None)]}, attrs)


# --------------------------------------------------------------------------
# mode management (reference dygraph/base.py guard/enabled/no_grad)
# --------------------------------------------------------------------------
_global_tracer: Optional[Tracer] = None


def enabled():
    return framework.in_dygraph_mode()


@contextlib.contextmanager
def guard(place=None):
    tracer = Tracer()
    with framework.program_guard(framework.Program(), framework.Program()):
        with unique_name.guard():
            with framework._dygraph_guard(tracer):
                with framework._dygraph_place_guard(
                        place or framework._current_expected_place()):
                    yield


def enable_dygraph(place=None):
    global _global_tracer
    _global_tracer = Tracer()
    framework._dygraph_tracer_ = _global_tracer


def disable_dygraph():
    global _global_tracer
    framework._dygraph_tracer_ = None
    _global_tracer = None


def to_variable(value, name=None, zero_copy=None):
    if isinstance(value, VarBase):
        return value
    arr = np.asarray(value)
    return VarBase(jnp.asarray(arr), name=name, stop_gradient=True)


def no_grad(fn=None):
    tracer = framework._dygraph_tracer()
    if fn is None:
        if tracer is None:
            return contextlib.nullcontext()
        return tracer._no_grad_guard()

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        t = framework._dygraph_tracer()
        if t is None:
            return fn(*args, **kwargs)
        with t._no_grad_guard():
            return fn(*args, **kwargs)
    return wrapper


def _reachable(tape, inputs, no_grad_ids):
    """Structural reachability: ids of every var transitively computed
    from ``inputs`` along the tape (no kernels executed)."""
    live = {id(v) for v in inputs if id(v) not in no_grad_ids}
    for entry in tape:
        if any(id(v) in live
               for vals in entry.ins.values() for v in vals):
            live.update(id(v) for vals in entry.outs.values()
                        for v in vals)
    return live


def _replayable_fn(tape, inputs, outputs, no_grad_ids):
    """Build a PURE function f(*input_arrays) -> output_arrays by
    replaying the tape segment between ``inputs`` and ``outputs`` with
    the recorded attrs (rng keys included, so dropout replays the same
    mask). Vars outside the input-reachable set enter as recorded
    constants. An input that is ITSELF produced by a replayed entry
    (grad(z, [x, y]) with y on the x→z path) is rebound as
    recomputed + (arg − stop_gradient(arg)): the value stays the
    recomputed one (total derivative flows through to x) while the
    identity residual routes the partial ∂/∂y to the y argument —
    the reference/PyTorch multi-input grad contract."""
    input_ids = {id(v): k for k, v in enumerate(inputs)}

    def f(*in_arrays):
        env = {id(v): a for v, a in zip(inputs, in_arrays)
               if id(v) not in no_grad_ids}

        def bind(v, a):
            k = input_ids.get(id(v))
            if k is None:
                env[id(v)] = a
            else:
                arg = in_arrays[k]
                env[id(v)] = a + (arg - jax.lax.stop_gradient(arg))

        for entry in tape:
            if entry.op_type == "@functional@":
                if not any(id(v) in env for v in entry.ins["In"]):
                    continue
                vals = [env.get(id(v), v._array) for v in entry.ins["In"]]
                outs = entry.attrs["_fn"](*vals)
                for v, a in zip(entry.outs["Out"], outs):
                    bind(v, a)
                continue
            if not any(id(v) in env
                       for vals in entry.ins.values() for v in vals):
                continue
            ins = {slot: [env.get(id(v), v._array) for v in vals]
                   for slot, vals in entry.ins.items()}
            outs = OPS.get(entry.op_type).kernel(ins, entry.attrs)
            for slot, vals in entry.outs.items():
                produced = (outs or {}).get(slot)
                if produced is None:
                    continue
                for v, a in zip(vals, produced):
                    if a is not None:
                        bind(v, a)
        return tuple(env.get(id(o), o._array) for o in outputs)
    return f


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None, backward_strategy=None):
    """Gradients of ``outputs`` w.r.t. ``inputs`` over the live tape
    (reference imperative/partial_grad_engine.cc). The tape segment is
    replayed as a pure function and differentiated with jax.vjp; with
    ``create_graph=True`` the grad computation is recorded back onto the
    tape as a functional node whose backward is the vjp-of-the-vjp, so
    losses built from these grads (gradient penalty) differentiate
    correctly. ``grad_outputs`` seeds the cotangents (None entries mean
    ones); ``allow_unused`` returns None for disconnected inputs instead
    of raising. The tape is NOT consumed (retain_graph semantics are
    automatic; pass retain_graph=False alongside create_graph=False to
    release it)."""
    if not only_inputs:
        raise NotImplementedError("only_inputs=False is deprecated in the "
                                  "reference and unsupported here")
    tracer = framework._dygraph_tracer()
    assert tracer is not None, "dygraph.grad() outside dygraph guard"
    outputs = list(outputs) if isinstance(outputs, (list, tuple)) \
        else [outputs]
    inputs = list(inputs) if isinstance(inputs, (list, tuple)) \
        else [inputs]
    if grad_outputs is not None:
        grad_outputs = list(grad_outputs) \
            if isinstance(grad_outputs, (list, tuple)) else [grad_outputs]
        if len(grad_outputs) != len(outputs):
            raise ValueError("grad_outputs must match outputs length")
    no_grad_ids = {id(v) for v in (no_grad_vars or [])}
    tape = list(tracer._tape)
    out_ids = {id(o) for o in outputs}
    # per-input structural connectivity (which outputs each input reaches)
    input_connected = [bool(out_ids & _reachable(tape, [v], no_grad_ids))
                       for v in inputs]
    if not any(input_connected):
        if not allow_unused:
            raise RuntimeError(
                "dygraph.grad: outputs are not connected to inputs "
                "(pass allow_unused=True to get None)")
        return [None for _ in inputs]

    # every OTHER differentiable leaf the segment reads (params, earlier
    # activations from outside the segment): they must be real arguments
    # of the replayed function, not captured constants — otherwise
    # create_graph second-order grads can't flow to them (the gradient-
    # penalty-to-weights path)
    seen = {id(v) for v in inputs}
    produced = {id(v) for e in tape
                for vals in e.outs.values() for v in vals}
    extras: List[VarBase] = []
    for e in tape:
        for vals in e.ins.values():
            for v in vals:
                if isinstance(v, VarBase) and not v.stop_gradient \
                        and id(v) not in seen and id(v) not in produced \
                        and id(v) not in no_grad_ids:
                    seen.add(id(v))
                    extras.append(v)

    f = _replayable_fn(tape, inputs + extras, outputs, no_grad_ids)

    cots = []
    cot_vbs = []  # VarBase cotangents participate in the graph
    for k, o in enumerate(outputs):
        g = grad_outputs[k] if grad_outputs is not None else None
        if g is None:
            cots.append(jnp.ones_like(o._array))
            cot_vbs.append(None)
        elif isinstance(g, VarBase):
            cots.append(g._array)
            cot_vbs.append(g)
        else:
            cots.append(jnp.asarray(g))
            cot_vbs.append(None)

    n_in, n_out, n_extra = len(inputs), len(outputs), len(extras)

    def gfn(*arrays):
        """arrays = input vals + cotangent vals + extra-leaf vals ->
        grads w.r.t. the inputs only."""
        ivals = arrays[:n_in]
        cvals = arrays[n_in:n_in + n_out]
        evals = arrays[n_in + n_out:]
        _, vjp_fn = jax.vjp(f, *(tuple(ivals) + tuple(evals)))
        return tuple(vjp_fn(tuple(cvals))[:n_in])

    call_args = [v._array for v in inputs] + cots \
        + [v._array for v in extras]
    gin = gfn(*call_args)

    # disconnected inputs -> None per the reference contract
    results: List[Optional[VarBase]] = []
    for k, (v, g) in enumerate(zip(inputs, gin)):
        if not input_connected[k]:
            if not allow_unused:
                raise RuntimeError(
                    f"dygraph.grad: input {v.name} is unreachable from "
                    f"outputs (pass allow_unused=True to get None)")
            results.append(None)
            continue
        results.append(VarBase(g, name=v.name + "@GRAD",
                               stop_gradient=not create_graph))

    if create_graph:
        # record the whole grad computation as ONE tape node; its
        # backward is jax.vjp(gfn, ...) — true second order, with
        # cotangents flowing to inputs, VarBase grad_outputs AND the
        # extra leaves (params)
        live_cots = [c for c in cot_vbs if c is not None]
        in_vbs = list(inputs) + live_cots + list(extras)

        def gfn_tape(*arrays):
            ins = list(arrays[:n_in])
            j = n_in
            cs = list(cots)
            for k, c in enumerate(cot_vbs):
                if c is not None:
                    cs[k] = arrays[j]
                    j += 1
            evals = list(arrays[j:])
            full = gfn(*(ins + cs + evals))
            return tuple(full[k] for k in range(len(inputs))
                         if results[k] is not None)

        tracer._tape.append(_TapeEntry(
            "@functional@", {"In": in_vbs},
            {"Out": [r for r in results if r is not None]},
            {"_fn": gfn_tape}))
    return results


# hooks used by Optimizer in dygraph mode
def _dygraph_backward(optimizer, loss, parameter_list):
    loss.backward()
    params = parameter_list or list(
        framework._dygraph_tracer()._params.values())
    return [(p, p._grad_ivar) for p in params
            if p.trainable and p._grad_ivar is not None]


def _dygraph_minimize(optimizer, loss, startup_program, parameter_list,
                      no_grad_set):
    params_grads = _dygraph_backward(optimizer, loss, parameter_list)
    optimize_ops = optimizer._create_optimization_pass(params_grads)
    return optimize_ops, params_grads


def _clear_gradients(parameter_list):
    tracer = framework._dygraph_tracer()
    params = parameter_list or (list(tracer._params.values())
                                if tracer else [])
    for p in params:
        if isinstance(p, VarBase):
            p.clear_gradient()
