"""DyGraph LR schedulers (reference: dygraph/learning_rate_scheduler.py —
LearningRateDecay subclasses recomputed per step on the host)."""
from __future__ import annotations

import math

import numpy as np

__all__ = ["NoamDecay", "PiecewiseDecay", "NaturalExpDecay",
           "ExponentialDecay", "InverseTimeDecay", "PolynomialDecay",
           "CosineDecay", "LinearLrWarmup", "ReduceLROnPlateau"]


class LearningRateDecay:
    def __init__(self, begin=0, step=1, dtype="float32"):
        self.step_num = begin
        self.step_size = step
        self.dtype = dtype

    def __call__(self):
        lr = self.step()
        self.step_num += self.step_size
        return float(lr)

    def step(self):
        raise NotImplementedError


class PiecewiseDecay(LearningRateDecay):
    def __init__(self, boundaries, values, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.boundaries = boundaries
        self.values = values

    def step(self):
        for i, b in enumerate(self.boundaries):
            if self.step_num < b:
                return self.values[i]
        return self.values[-1]


class NaturalExpDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def step(self):
        d = self.step_num / self.decay_steps
        if self.staircase:
            d = math.floor(d)
        return self.learning_rate * math.exp(-self.decay_rate * d)


class ExponentialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def step(self):
        d = self.step_num / self.decay_steps
        if self.staircase:
            d = math.floor(d)
        return self.learning_rate * (self.decay_rate ** d)


class InverseTimeDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def step(self):
        d = self.step_num / self.decay_steps
        if self.staircase:
            d = math.floor(d)
        return self.learning_rate / (1 + self.decay_rate * d)


class PolynomialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, end_learning_rate=0.0001,
                 power=1.0, cycle=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.end_learning_rate = end_learning_rate
        self.power = power
        self.cycle = cycle

    def step(self):
        n = self.step_num
        ds = self.decay_steps
        if self.cycle:
            div = math.ceil(n / ds) if n > 0 else 1
            ds = ds * div
        else:
            n = min(n, ds)
        return (self.learning_rate - self.end_learning_rate) * \
            ((1 - n / ds) ** self.power) + self.end_learning_rate


class CosineDecay(LearningRateDecay):
    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0,
                 step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.step_each_epoch = step_each_epoch
        self.epochs = epochs

    def step(self):
        epoch = math.floor(self.step_num / self.step_each_epoch)
        return self.learning_rate * 0.5 * (
            math.cos(epoch * math.pi / self.epochs) + 1)


class NoamDecay(LearningRateDecay):
    def __init__(self, d_model, warmup_steps, begin=1, step=1,
                 dtype="float32"):
        super().__init__(begin, step, dtype)
        self.d_model = d_model
        self.warmup_steps = warmup_steps

    def step(self):
        n = max(self.step_num, 1)
        return (self.d_model ** -0.5) * min(n ** -0.5,
                                            n * (self.warmup_steps ** -1.5))


class LinearLrWarmup(LearningRateDecay):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 begin=1, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.lr = learning_rate
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr

    def step(self):
        if self.step_num < self.warmup_steps:
            return self.start_lr + (self.end_lr - self.start_lr) * \
                self.step_num / self.warmup_steps
        base = self.lr
        return base() if callable(base) else base


class ReduceLROnPlateau:
    def __init__(self, *a, **k):
        raise NotImplementedError("ReduceLROnPlateau: pending")
