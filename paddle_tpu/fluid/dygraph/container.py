"""Layer containers (reference: dygraph/container.py — Sequential,
ParameterList, LayerList)."""
from __future__ import annotations

from .layers import Layer

__all__ = ["Sequential", "ParameterList", "LayerList"]


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if layers and isinstance(layers[0], (list, tuple)) and not \
                isinstance(layers[0], Layer):
            layers = layers[0]
        for i, l in enumerate(layers):
            if isinstance(l, (list, tuple)):
                name, l = l
            else:
                name = str(i)
            self.add_sublayer(name, l)

    def __getitem__(self, i):
        return list(self._sub_layers.values())[i]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, input):
        for l in self._sub_layers.values():
            input = l(input)
        return input


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, i):
        return self._parameters[str(i)]

    def __len__(self):
        return len(self._parameters)

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, i):
        return list(self._sub_layers.values())[i]

    def __iter__(self):
        return iter(self._sub_layers.values())

    def __len__(self):
        return len(self._sub_layers)

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self
