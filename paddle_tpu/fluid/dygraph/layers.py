"""Layer module system (reference: python/paddle/fluid/dygraph/layers.py)."""
from __future__ import annotations

import collections
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from .. import framework, unique_name
from ..core import convert_np_dtype_to_dtype_, VarDesc
from ..param_attr import ParamAttr
from .base import VarBase, _run_initializer

__all__ = ["Layer"]


class _HookRemoveHelper:
    """Removable handle for a registered hook (reference:
    layers.py HookRemoveHelper)."""

    _next_id = 0

    def __init__(self, hooks):
        self._hooks = hooks
        self._hook_id = _HookRemoveHelper._next_id
        _HookRemoveHelper._next_id += 1

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype=VarDesc.VarType.FP32):
        if name_scope is None:
            name_scope = unique_name.generate(
                self.__class__.__name__.lower())
        self._full_name = name_scope
        self._dtype = dtype
        self.training = True
        self._parameters: "collections.OrderedDict[str, VarBase]" = \
            collections.OrderedDict()
        self._sub_layers: "collections.OrderedDict[str, Layer]" = \
            collections.OrderedDict()
        self._buffers: "collections.OrderedDict[str, VarBase]" = \
            collections.OrderedDict()
        self._forward_pre_hooks: "collections.OrderedDict[int, object]" = \
            collections.OrderedDict()
        self._forward_post_hooks: "collections.OrderedDict[int, object]" = \
            collections.OrderedDict()

    def full_name(self):
        return self._full_name

    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False

    # ------------------------------------------------------------ params
    def create_parameter(self, shape, attr=None, dtype="float32",
                         is_bias=False, default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        if default_initializer is None:
            if is_bias:
                attr._set_default_bias_initializer()
            else:
                attr._set_default_param_initializer()
        else:
            attr._set_default_initializer(default_initializer)
        name = attr.name or unique_name.generate(
            self._full_name + ("_b" if is_bias else "_w"))
        tracer = framework._dygraph_tracer()
        if tracer is not None:
            return tracer.create_parameter(
                name, shape, dtype, attr.initializer, attr.trainable,
                optimize_attr={"learning_rate": attr.learning_rate},
                regularizer=attr.regularizer)
        # static-mode module reuse (Layer used inside static graph)
        from ..layer_helper import LayerHelper
        helper = LayerHelper(self._full_name)
        return helper.create_parameter(attr, shape, dtype, is_bias)

    def create_variable(self, name=None, persistable=None, dtype=None):
        return VarBase(None, name=name, persistable=bool(persistable),
                       dtype=dtype)

    def parameters(self, include_sublayers=True):
        ret = list(self._parameters.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                ret.extend(l.parameters(True))
        return ret

    def named_parameters(self, prefix="", include_sublayers=True):
        for name, p in self._parameters.items():
            yield (prefix + ("." if prefix else "") + name, p)
        if include_sublayers:
            for lname, l in self._sub_layers.items():
                yield from l.named_parameters(
                    prefix + ("." if prefix else "") + lname, True)

    def sublayers(self, include_sublayers=True):
        ret = []
        for l in self._sub_layers.values():
            ret.append(l)
            if include_sublayers:
                ret.extend(l.sublayers(True))
        return ret

    def named_sublayers(self, prefix="", include_sublayers=True,
                        include_self=False):
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            p = prefix + ("." if prefix else "") + name
            yield p, l
            if include_sublayers:
                yield from l.named_sublayers(p, True)

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        return tensor

    def buffers(self, include_sublayers=True):
        return [b for _n, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, buf in self._buffers.items():
            if buf is not None:
                yield (prefix + ("." if prefix else "") + name, buf)
        if include_sublayers:
            for lname, sub in self._sub_layers.items():
                sp = prefix + ("." if prefix else "") + lname
                yield from sub.named_buffers(sp, include_sublayers)

    def apply(self, fn):
        """Apply ``fn`` to self and every sublayer (reference layers.py
        Layer.apply — init helpers)."""
        for sub in self.sublayers():
            sub.apply(fn)
        fn(self)
        return self

    # ------------------------------------------------------------- magic
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        if isinstance(value, VarBase) and value.persistable and \
                params is not None:
            params[name] = value
            return
        if isinstance(value, Layer) and layers is not None:
            layers[name] = value
            return
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for d in ("_parameters", "_sub_layers", "_buffers"):
            dd = self.__dict__.get(d)
            if dd is not None and name in dd:
                return dd[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    # ------------------------------------------------------------- hooks
    def register_forward_pre_hook(self, hook):
        """hook(layer, inputs) -> None | new inputs (reference
        layers.py register_forward_pre_hook + HookRemoveHelper)."""
        helper = _HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._hook_id] = hook
        return helper

    def register_forward_post_hook(self, hook):
        """hook(layer, inputs, outputs) -> None | new outputs."""
        helper = _HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._hook_id] = hook
        return helper

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    # ------------------------------------------------------------- state
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix=""):
        dest = destination if destination is not None else \
            collections.OrderedDict()
        for name, p in self.named_parameters():
            dest[p.name] = p
        for name, b in self._buffers.items():
            dest[b.name] = b
        return dest

    def set_dict(self, stat_dict, include_sublayers=True,
                 use_structured_name=True):
        self.load_dict(stat_dict, include_sublayers)

    def load_dict(self, stat_dict, include_sublayers=True):
        import jax.numpy as jnp
        for name, p in list(self.named_parameters()):
            if p.name in stat_dict:
                v = stat_dict[p.name]
                arr = v.numpy() if hasattr(v, "numpy") else np.asarray(v)
                p._array = jnp.asarray(arr)
