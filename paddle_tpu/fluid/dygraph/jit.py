"""dygraph.jit — trace imperative code into compiled functions (reference:
dygraph/jit.py TracedLayer:224, declarative:121 + dygraph_to_static/).

TPU inversion: the reference re-traces Python into a ProgramDesc; here the
natural compile target is jax.jit directly — the layer's forward becomes a
pure function of (params, inputs) and XLA compiles it once per shape."""
from __future__ import annotations

import functools
from typing import Any, Dict, List

import numpy as np
import jax
import jax.numpy as jnp

from .. import framework
from .base import VarBase, guard
from .layers import Layer

__all__ = ["TracedLayer", "declarative", "dygraph_to_static_func"]


def _functionalize(layer: Layer):
    """Build fn(params_dict, *arrays) -> arrays from a dygraph Layer."""
    named = dict(layer.named_parameters())

    def fn(params: Dict[str, Any], *args):
        # swap real param arrays for traced ones, run forward, restore
        originals = {}
        for name, p in named.items():
            originals[name] = p._array
            p._array = params[name]
        try:
            outs = layer(*[VarBase(a, stop_gradient=True) for a in args])
        finally:
            for name, p in named.items():
                p._array = originals[name]
        if isinstance(outs, (list, tuple)):
            return [o._array for o in outs]
        return outs._array
    return fn, named


class TracedLayer:
    """reference dygraph/jit.py:224 — here a jax.jit wrapper with the same
    static_graph-deployable contract (save_inference_model exports a
    Program via the static re-trace, pending)."""

    def __init__(self, layer: Layer):
        self._layer = layer
        self._fn, self._named = _functionalize(layer)
        self._jitted = jax.jit(self._fn)

    @staticmethod
    def trace(layer: Layer, inputs: List[VarBase]):
        tl = TracedLayer(layer)
        outs = tl(*inputs)
        return outs, tl

    def __call__(self, *inputs):
        arrays = [i._array if isinstance(i, VarBase) else jnp.asarray(i)
                  for i in inputs]
        params = {n: p._array for n, p in self._named.items()}
        outs = self._jitted(params, *arrays)
        if isinstance(outs, (list, tuple)):
            return [VarBase(o, stop_gradient=True) for o in outs]
        return VarBase(outs, stop_gradient=True)

    def save_inference_model(self, dirname, feed=None, fetch=None):
        raise NotImplementedError(
            "TracedLayer.save_inference_model: static re-trace pending "
            "(dygraph_to_static batch)")


def declarative(fn):
    """@declarative — compile an imperative function with jax.jit on first
    call (reference dygraph/jit.py:121 builds a static program instead)."""
    jitted = {}

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return fn(*args, **kwargs)  # eager; jit handled by TracedLayer path
    wrapper._is_declarative = True
    return wrapper


dygraph_to_static_func = declarative
