"""dygraph.jit — compile imperative code (reference: dygraph/jit.py
TracedLayer:224, declarative:121 + dygraph_to_static/).

Two compile paths, both ending in one XLA computation:

* ``TracedLayer`` — data-flow-only layers traced straight into ``jax.jit``
  over (params, inputs);
* ``@declarative`` — the full dygraph_to_static pipeline: AST transpile of
  tensor control flow (if/while/for → cond/while ops → lax.cond /
  lax.while_loop), static Program build, jit of the whole program, exact
  grads via jax.vjp through the run_program_dy tape op.
"""
from __future__ import annotations

from typing import Any, Dict, List

import numpy as np
import jax
import jax.numpy as jnp

from .. import framework
from ..core import Scope
from .base import VarBase, guard
from .layers import Layer
from .dygraph_to_static import (declarative, ProgramTranslator,
                                StaticFunction)

__all__ = ["TracedLayer", "declarative", "dygraph_to_static_func",
           "ProgramTranslator"]


def _functionalize(layer: Layer):
    """Build fn(params_dict, *arrays) -> arrays from a dygraph Layer."""
    named = dict(layer.named_parameters())

    def fn(params: Dict[str, Any], *args):
        # swap real param arrays for traced ones, run forward, restore
        originals = {}
        for name, p in named.items():
            originals[name] = p._array
            p._array = params[name]
        try:
            outs = layer(*[VarBase(a, stop_gradient=True) for a in args])
        finally:
            for name, p in named.items():
                p._array = originals[name]
        if isinstance(outs, (list, tuple)):
            return [o._array for o in outs]
        return outs._array
    return fn, named


class TracedLayer:
    """reference dygraph/jit.py:224 — a jax.jit wrapper with the same
    static-graph-deployable contract: save_inference_model re-traces the
    layer's forward into a static Program via dygraph_to_static."""

    def __init__(self, layer: Layer):
        self._layer = layer
        self._fn, self._named = _functionalize(layer)
        self._jitted = jax.jit(self._fn)
        self._input_spec: List[VarBase] = []

    @staticmethod
    def trace(layer: Layer, inputs: List[VarBase]):
        tl = TracedLayer(layer)
        tl._input_spec = list(inputs)
        outs = tl(*inputs)
        return outs, tl

    def __call__(self, *inputs):
        arrays = [i._array if isinstance(i, VarBase) else jnp.asarray(i)
                  for i in inputs]
        params = {n: p._array for n, p in self._named.items()}
        outs = self._jitted(params, *arrays)
        if isinstance(outs, (list, tuple)):
            return [VarBase(o, stop_gradient=True) for o in outs]
        return VarBase(outs, stop_gradient=True)

    def save_inference_model(self, dirname, feed=None, fetch=None):
        """Export a deployable static Program + params (reference
        TracedLayer.save_inference_model): re-trace the layer's forward
        through dygraph_to_static, then io.save_inference_model."""
        if not self._input_spec:
            raise RuntimeError(
                "TracedLayer.save_inference_model requires the layer to "
                "have been built via TracedLayer.trace(layer, inputs)")
        from .. import io as fluid_io
        from ..executor import Executor, scope_guard
        from ..core import LoDTensor
        sf = declarative(type(self._layer).forward)
        cp = sf.concrete_program(self._layer, *self._input_spec)
        block = cp.main_program.global_block()
        feed_names = list(cp.feed_names)
        if feed is not None:
            feed_names = [feed_names[i] for i in feed]
        targets = [block.vars[n] for n in cp.fetch_names]
        if fetch is not None:
            targets = [targets[i] for i in fetch]
        scope = Scope()
        for n, p in cp.param_vars.items():
            scope.var(n).set_value(LoDTensor(p._array))
        exe = Executor()
        with framework._dygraph_guard(None), scope_guard(scope):
            return fluid_io.save_inference_model(
                dirname, feed_names, targets, exe,
                main_program=cp.main_program)


dygraph_to_static_func = declarative
