"""DyGraph layer library (reference: python/paddle/fluid/dygraph/nn.py —
Conv2D:42, Pool2D:697, Linear:868, InstanceNorm:975, BatchNorm:1101,
Dropout:1335, Embedding:1444, LayerNorm:1600, PRelu:2186,
BilinearTensorProduct:2290, Conv2DTranspose:2402, GroupNorm:2810 …).
Modules own their parameters; forward issues ops through the tracer."""
from __future__ import annotations

import numpy as np

from .. import framework
from ..core import VarDesc, convert_np_dtype_to_dtype_
from ..initializer import Constant
from ..param_attr import ParamAttr
from .base import VarBase
from .layers import Layer

__all__ = ["Conv2D", "Conv3D", "Pool2D", "Linear", "BatchNorm", "Dropout",
           "Embedding", "LayerNorm", "GRUUnit", "InstanceNorm", "PRelu",
           "BilinearTensorProduct", "Conv2DTranspose", "GroupNorm",
           "SpectralNorm", "NCE", "TreeConv", "SequenceConv", "RowConv",
           "Conv3DTranspose"]


def _op(type_, ins, outs_spec, attrs):
    tracer = framework._dygraph_tracer()
    if tracer is None:
        # to-static trace in progress (dygraph_to_static): build the op into
        # the current static program. Inputs may be static Variables or
        # VarBase parameters — ops record names either way; the program
        # translator registers matching persistable vars for the params.
        from ..layer_helper import LayerHelper
        helper = LayerHelper(type_)
        dtype = None
        for vals in ins.values():
            for v in vals or []:
                if v is not None and dtype is None:
                    dtype = v.dtype
        outs = {slot: [helper.create_variable_for_type_inference(
                    dtype if dtype is not None else VarDesc.VarType.FP32)
                    for _ in range(n)]
                for slot, n in outs_spec.items()}
        helper.append_op(type=type_, inputs=ins, outputs=outs, attrs=attrs)
        first_slot = next(iter(outs.values()), [None])
        return first_slot[0] if len(outs) == 1 and len(first_slot) == 1 \
            else outs
    outs = {slot: [VarBase(None) for _ in range(n)]
            for slot, n in outs_spec.items()}
    res = tracer.trace_op(type_, ins, outs, attrs)
    return res


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        self._act = act
        self.weight = self.create_parameter([input_dim, output_dim],
                                            attr=param_attr, dtype=dtype)
        self.bias = self.create_parameter([output_dim], attr=bias_attr,
                                          dtype=dtype, is_bias=True)

    def forward(self, input):
        out = _op("mul", {"X": [input], "Y": [self.weight]}, {"Out": 1},
                  {"x_num_col_dims": len(input.shape) - 1,
                   "y_num_col_dims": 1})
        if self.bias is not None:
            out = _op("elementwise_add", {"X": [out], "Y": [self.bias]},
                      {"Out": 1}, {"axis": len(input.shape) - 1})
        if self._act:
            out = _op(self._act, {"X": [out]}, {"Out": 1}, {})
        return out


class Conv2D(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=None, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__()
        groups = groups or 1
        self._groups = groups
        self._stride = [stride] * 2 if isinstance(stride, int) else list(stride)
        self._padding = [padding] * 2 if isinstance(padding, int) else list(padding)
        self._dilation = [dilation] * 2 if isinstance(dilation, int) else list(dilation)
        self._act = act
        fsz = [filter_size] * 2 if isinstance(filter_size, int) \
            else list(filter_size)
        import math
        from ..initializer import Normal
        fan_in = (num_channels // groups) * fsz[0] * fsz[1]
        std = math.sqrt(2.0 / fan_in)
        self.weight = self.create_parameter(
            [num_filters, num_channels // groups] + fsz, attr=param_attr,
            dtype=dtype, default_initializer=Normal(0.0, std))
        self.bias = self.create_parameter([num_filters], attr=bias_attr,
                                          dtype=dtype, is_bias=True)

    def forward(self, input):
        out = _op("conv2d", {"Input": [input], "Filter": [self.weight]},
                  {"Output": 1},
                  {"strides": self._stride, "paddings": self._padding,
                   "dilations": self._dilation, "groups": self._groups,
                   "padding_algorithm": "EXPLICIT", "data_format": "NCHW"})
        if self.bias is not None:
            out = _op("elementwise_add", {"X": [out], "Y": [self.bias]},
                      {"Out": 1}, {"axis": 1})
        if self._act:
            out = _op(self._act, {"X": [out]}, {"Out": 1}, {})
        return out


class Conv3D(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=None, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__()
        groups = groups or 1
        self._groups = groups
        _3 = lambda v: [v] * 3 if isinstance(v, int) else list(v)
        self._stride, self._padding, self._dilation = \
            _3(stride), _3(padding), _3(dilation)
        self._act = act
        fsz = _3(filter_size)
        self.weight = self.create_parameter(
            [num_filters, num_channels // groups] + fsz, attr=param_attr,
            dtype=dtype)
        self.bias = self.create_parameter([num_filters], attr=bias_attr,
                                          dtype=dtype, is_bias=True)

    def forward(self, input):
        out = _op("conv3d", {"Input": [input], "Filter": [self.weight]},
                  {"Output": 1},
                  {"strides": self._stride, "paddings": self._padding,
                   "dilations": self._dilation, "groups": self._groups,
                   "padding_algorithm": "EXPLICIT", "data_format": "NCDHW"})
        if self.bias is not None:
            out = _op("elementwise_add", {"X": [out], "Y": [self.bias]},
                      {"Out": 1}, {"axis": 1})
        if self._act:
            out = _op(self._act, {"X": [out]}, {"Out": 1}, {})
        return out


class Conv2DTranspose(Layer):
    def __init__(self, num_channels, num_filters, filter_size,
                 output_size=None, padding=0, stride=1, dilation=1,
                 groups=None, param_attr=None, bias_attr=None,
                 use_cudnn=True, act=None, dtype="float32"):
        super().__init__()
        groups = groups or 1
        self._groups = groups
        _2 = lambda v: [v] * 2 if isinstance(v, int) else list(v)
        self._stride, self._padding, self._dilation = \
            _2(stride), _2(padding), _2(dilation)
        self._output_size = _2(output_size) if output_size else []
        self._act = act
        fsz = _2(filter_size)
        self.weight = self.create_parameter(
            [num_channels, num_filters // groups] + fsz, attr=param_attr,
            dtype=dtype)
        self.bias = self.create_parameter([num_filters], attr=bias_attr,
                                          dtype=dtype, is_bias=True)

    def forward(self, input):
        out = _op("conv2d_transpose",
                  {"Input": [input], "Filter": [self.weight]}, {"Output": 1},
                  {"strides": self._stride, "paddings": self._padding,
                   "dilations": self._dilation, "groups": self._groups,
                   "output_size": self._output_size,
                   "padding_algorithm": "EXPLICIT", "data_format": "NCHW"})
        if self.bias is not None:
            out = _op("elementwise_add", {"X": [out], "Y": [self.bias]},
                      {"Out": 1}, {"axis": 1})
        if self._act:
            out = _op(self._act, {"X": [out]}, {"Out": 1}, {})
        return out


class Pool2D(Layer):
    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True):
        super().__init__()
        _2 = lambda v: [v] * 2 if isinstance(v, int) else list(v)
        self._attrs = {"pooling_type": pool_type, "ksize": _2(pool_size),
                       "global_pooling": global_pooling,
                       "strides": _2(pool_stride),
                       "paddings": _2(pool_padding), "ceil_mode": ceil_mode,
                       "exclusive": exclusive, "data_format": "NCHW",
                       "padding_algorithm": "EXPLICIT"}

    def forward(self, input):
        return _op("pool2d", {"X": [input]}, {"Out": 1}, dict(self._attrs))


class BatchNorm(Layer):
    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW", in_place=False,
                 moving_mean_name=None, moving_variance_name=None,
                 do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__()
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_layout = data_layout
        self._act = act
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter([num_channels], attr=param_attr,
                                            dtype=dtype,
                                            default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                          dtype=dtype, is_bias=True)
        self._mean = self.create_parameter(
            [num_channels], attr=ParamAttr(name=moving_mean_name,
                                           initializer=Constant(0.0),
                                           trainable=False), dtype=dtype)
        self._variance = self.create_parameter(
            [num_channels], attr=ParamAttr(name=moving_variance_name,
                                           initializer=Constant(1.0),
                                           trainable=False), dtype=dtype)
        self._mean.stop_gradient = True
        self._variance.stop_gradient = True

    def forward(self, input):
        res = _op("batch_norm",
                  {"X": [input], "Scale": [self.weight], "Bias": [self.bias],
                   "Mean": [self._mean], "Variance": [self._variance]},
                  {"Y": 1, "MeanOut": 1, "VarianceOut": 1, "SavedMean": 1,
                   "SavedVariance": 1},
                  {"momentum": self._momentum, "epsilon": self._epsilon,
                   "is_test": not self.training,
                   "data_layout": self._data_layout,
                   "use_global_stats": self._use_global_stats})
        self._mean._array = res["MeanOut"][0]._array
        self._variance._array = res["VarianceOut"][0]._array
        y = res["Y"][0]
        if self._act:
            y = _op(self._act, {"X": [y]}, {"Out": 1}, {})
        return y


class Dropout(Layer):
    def __init__(self, p=0.5, seed=None, dropout_implementation=
                 "downgrade_in_infer", is_test=False):
        super().__init__()
        self._p = p
        self._seed = seed
        self._impl = dropout_implementation

    def forward(self, input):
        res = _op("dropout", {"X": [input]}, {"Out": 1, "Mask": 1},
                  {"dropout_prob": self._p, "is_test": not self.training,
                   "fix_seed": self._seed is not None,
                   "seed": self._seed or 0,
                   "dropout_implementation": self._impl})
        return res["Out"][0]


class Embedding(Layer):
    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__()
        self._size = size
        self._padding_idx = -1 if padding_idx is None else (
            padding_idx if padding_idx >= 0 else size[0] + padding_idx)
        self.weight = self.create_parameter(size, attr=param_attr,
                                            dtype=dtype)

    def forward(self, input):
        return _op("lookup_table_v2",
                   {"W": [self.weight], "Ids": [input]}, {"Out": 1},
                   {"padding_idx": self._padding_idx, "is_sparse": False,
                    "is_distributed": False, "remote_prefetch": False})


class LayerNorm(Layer):
    def __init__(self, normalized_shape, scale=True, shift=True,
                 epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self._act = act
        n = int(np.prod(self._normalized_shape))
        self.weight = self.create_parameter(
            [n], attr=param_attr, dtype=dtype,
            default_initializer=Constant(1.0)) if scale else None
        self.bias = self.create_parameter([n], attr=bias_attr, dtype=dtype,
                                          is_bias=True) if shift else None

    def forward(self, input):
        bna = len(input.shape) - len(self._normalized_shape)
        ins = {"X": [input]}
        if self.weight is not None:
            ins["Scale"] = [self.weight]
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        res = _op("layer_norm", ins, {"Y": 1, "Mean": 1, "Variance": 1},
                  {"epsilon": self._epsilon, "begin_norm_axis": bna})
        y = res["Y"][0]
        if self._act:
            y = _op(self._act, {"X": [y]}, {"Out": 1}, {})
        return y


class InstanceNorm(Layer):
    def __init__(self, num_channels, epsilon=1e-5, param_attr=None,
                 bias_attr=None, dtype="float32"):
        super().__init__()
        self._epsilon = epsilon
        self.scale = self.create_parameter([num_channels], attr=param_attr,
                                           dtype=dtype,
                                           default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                          dtype=dtype, is_bias=True)

    def forward(self, input):
        res = _op("instance_norm",
                  {"X": [input], "Scale": [self.scale], "Bias": [self.bias]},
                  {"Y": 1, "SavedMean": 1, "SavedVariance": 1},
                  {"epsilon": self._epsilon})
        return res["Y"][0]


class GroupNorm(Layer):
    def __init__(self, channels, groups, epsilon=1e-5, param_attr=None,
                 bias_attr=None, act=None, data_layout="NCHW",
                 dtype="float32"):
        super().__init__()
        self._groups = groups
        self._epsilon = epsilon
        self._act = act
        self.weight = self.create_parameter([channels], attr=param_attr,
                                            dtype=dtype,
                                            default_initializer=Constant(1.0))
        self.bias = self.create_parameter([channels], attr=bias_attr,
                                          dtype=dtype, is_bias=True)

    def forward(self, input):
        res = _op("group_norm",
                  {"X": [input], "Scale": [self.weight], "Bias": [self.bias]},
                  {"Y": 1, "Mean": 1, "Variance": 1},
                  {"groups": self._groups, "epsilon": self._epsilon,
                   "data_layout": "NCHW"})
        y = res["Y"][0]
        if self._act:
            y = _op(self._act, {"X": [y]}, {"Out": 1}, {})
        return y


class PRelu(Layer):
    def __init__(self, mode, input_shape=None, param_attr=None,
                 dtype="float32"):
        super().__init__()
        self._mode = mode
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            shape = [input_shape[1]]
        else:
            shape = list(input_shape[1:])
        self.weight = self.create_parameter(
            shape, attr=param_attr, dtype=dtype,
            default_initializer=Constant(0.25))

    def forward(self, input):
        return _op("prelu", {"X": [input], "Alpha": [self.weight]},
                   {"Out": 1}, {"mode": self._mode})


class BilinearTensorProduct(Layer):
    def __init__(self, input1_dim, input2_dim, output_dim, name=None,
                 act=None, param_attr=None, bias_attr=None, dtype="float32"):
        super().__init__()
        self._act = act
        self.weight = self.create_parameter(
            [output_dim, input1_dim, input2_dim], attr=param_attr,
            dtype=dtype)
        self.bias = self.create_parameter([1, output_dim], attr=bias_attr,
                                          dtype=dtype, is_bias=True)

    def forward(self, x, y):
        ins = {"X": [x], "Y": [y], "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        out = _op("bilinear_tensor_product", ins, {"Out": 1}, {})
        if self._act:
            out = _op(self._act, {"X": [out]}, {"Out": 1}, {})
        return out


def _nyi_layer(name):
    class _L(Layer):
        def __init__(self, *a, **k):
            raise NotImplementedError(f"dygraph.{name}: pending batch")
    _L.__name__ = name
    return _L


GRUUnit = _nyi_layer("GRUUnit")
SpectralNorm = _nyi_layer("SpectralNorm")
NCE = _nyi_layer("NCE")
TreeConv = _nyi_layer("TreeConv")
SequenceConv = _nyi_layer("SequenceConv")
RowConv = _nyi_layer("RowConv")
Conv3DTranspose = _nyi_layer("Conv3DTranspose")
