"""save_dygraph / load_dygraph (reference: dygraph/checkpoint.py) —
state-dict pickles with the reference's .pdparams/.pdopt suffixes."""
from __future__ import annotations

import os
import pickle

import numpy as np

__all__ = ["save_dygraph", "load_dygraph"]


def save_dygraph(state_dict, model_path):
    suffix = ".pdparams"
    for v in state_dict.values():
        if not getattr(v, "trainable", True):
            pass
    if state_dict and all(not getattr(v, "persistable", True)
                          for v in state_dict.values()):
        suffix = ".pdopt"
    d = {}
    for k, v in state_dict.items():
        d[k] = v.numpy() if hasattr(v, "numpy") else np.asarray(v)
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    with open(model_path + suffix, "wb") as f:
        pickle.dump(d, f)


def load_dygraph(model_path, keep_name_table=False):
    params, opt = None, None
    if os.path.exists(model_path + ".pdparams"):
        with open(model_path + ".pdparams", "rb") as f:
            params = pickle.load(f)
    if os.path.exists(model_path + ".pdopt"):
        with open(model_path + ".pdopt", "rb") as f:
            opt = pickle.load(f)
    if params is None and opt is None:
        raise ValueError(f"no checkpoint at {model_path}")
    return params, opt
