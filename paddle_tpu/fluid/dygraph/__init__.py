"""DyGraph — imperative mode (reference: python/paddle/fluid/dygraph/).
Eager op execution on jax arrays with an autograd tape; traces into jax.jit
via TracedLayer/declarative. Implementation in base.py/layers.py/nn.py."""
from . import base
from .base import (guard, to_variable, enabled, no_grad, grad,
                   enable_dygraph, disable_dygraph, BackwardStrategy)
from .layers import Layer
from . import nn
from .nn import *  # noqa: F401,F403
from .base import VarBase
from .parallel import DataParallel, ParallelEnv, prepare_context
from .checkpoint import save_dygraph, load_dygraph
from . import jit
from .jit import TracedLayer, declarative, ProgramTranslator
from . import dygraph_to_static
from .learning_rate_scheduler import *  # noqa: F401,F403
from .container import Sequential, ParameterList, LayerList
