"""AST transpiler: rewrite Python control flow over tensors into converter
calls (reference: python/paddle/fluid/dygraph/dygraph_to_static/
ast_transformer.py + ifelse_transformer / loop_transformer).

The transform is semantics-preserving for plain Python values (converters
fall back to host control flow) and turns tensor-dependent ``if`` / ``while``
/ ``for range()`` / ``and`` / ``or`` / ``not`` into ``layers.cond`` /
``layers.while_loop`` graph ops during a to-static trace — which the TPU
executor compiles to ``lax.cond`` / ``lax.while_loop`` inside one XLA
computation (no host round-trips inside the step).
"""
from __future__ import annotations

import ast
import inspect
import textwrap
from typing import List, Set

__all__ = ["DygraphToStaticAst", "convert_to_static", "transformed_source"]

_JST = "_jst"  # module alias injected into the transformed function's globals


# --------------------------------------------------------------------------
# name analysis
# --------------------------------------------------------------------------
class _AssignedNames(ast.NodeVisitor):
    """Names bound by simple assignments in a statement list (no descent
    into nested function/class definitions)."""

    def __init__(self):
        self.names: Set[str] = set()

    def visit_FunctionDef(self, node):  # do not descend
        self.names.add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.names.add(node.name)

    def visit_Assign(self, node):
        for t in node.targets:
            self._target(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._target(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def _target(self, t):
        if isinstance(t, ast.Name):
            self.names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e)


def _assigned_in(stmts: List[ast.stmt]) -> Set[str]:
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _HasNode(ast.NodeVisitor):
    def __init__(self, kinds):
        self.kinds = kinds
        self.found = False

    def generic_visit(self, node):
        if isinstance(node, self.kinds):
            self.found = True
            return
        # don't descend into nested function defs: their returns are theirs
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        super().generic_visit(node)


def _contains(stmts, kinds) -> bool:
    v = _HasNode(kinds)
    for s in stmts:
        v.visit(s)
    return v.found


# --------------------------------------------------------------------------
# the transformer
# --------------------------------------------------------------------------
class DygraphToStaticAst(ast.NodeTransformer):
    def __init__(self):
        self._counter = 0

    def _uid(self) -> int:
        self._counter += 1
        return self._counter

    # -------------------------------------------------------------- exprs
    def visit_BoolOp(self, node: ast.BoolOp):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        expr = node.values[0]
        for rhs in node.values[1:]:
            expr = ast.Call(
                func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                                   attr=fn, ctx=ast.Load()),
                args=[ast.Lambda(args=_empty_args(), body=expr),
                      ast.Lambda(args=_empty_args(), body=rhs)],
                keywords=[])
        return expr

    def visit_UnaryOp(self, node: ast.UnaryOp):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _jst_call("convert_logical_not", [node.operand])
        return node

    # -------------------------------------------------------------- stmts
    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        uid = self._uid()
        body, orelse = node.body, node.orelse or [ast.Pass()]
        body_returns = _contains(body, ast.Return)
        else_returns = _contains(orelse, ast.Return)

        if body_returns or else_returns:
            if not (body_returns and else_returns):
                raise NotImplementedError(
                    "dygraph_to_static: an `if` where only one branch "
                    "returns is not supported — give both branches a "
                    "return (or assign to a variable and return after "
                    "the if)")
            # both branches return: branch fns keep their returns; the
            # whole statement becomes `return convert_ifelse(...)`
            t_def = _make_fn(f"_jst_true_fn_{uid}", [], body)
            f_def = _make_fn(f"_jst_false_fn_{uid}", [], orelse)
            call = _jst_call("convert_ifelse",
                             [node.test,
                              ast.Name(id=t_def.name, ctx=ast.Load()),
                              ast.Name(id=f_def.name, ctx=ast.Load())])
            return [t_def, f_def, ast.Return(value=call)]

        assigned = sorted(_assigned_in(body) | _assigned_in(orelse))
        ret_tuple = ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in assigned],
            ctx=ast.Load())
        # branch fns take the assigned names as PARAMETERS: a branch that
        # assigns `s` makes `s` local, so it cannot read the pre-branch
        # value through a closure
        t_def = _make_fn(f"_jst_true_fn_{uid}", assigned,
                         body + [ast.Return(value=ret_tuple)])
        f_def = _make_fn(f"_jst_false_fn_{uid}", assigned,
                         orelse + [ast.Return(value=ret_tuple)])
        call = _jst_call("convert_ifelse",
                         [node.test,
                          ast.Name(id=t_def.name, ctx=ast.Load()),
                          ast.Name(id=f_def.name, ctx=ast.Load()),
                          ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Load())
                                          for n in assigned],
                                    ctx=ast.Load())])
        if assigned:
            tgt = ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in assigned],
                ctx=ast.Store())
            res = ast.Assign(targets=[tgt], value=call)
        else:
            res = ast.Expr(value=call)
        return _undef_guards(assigned) + [t_def, f_def, res]

    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if _contains(node.body, (ast.Break, ast.Continue, ast.Return)):
            raise NotImplementedError(
                "dygraph_to_static: break/continue/return inside a `while` "
                "over tensors is not supported — restructure with the loop "
                "condition")
        uid = self._uid()
        loop_vars = sorted(_assigned_in(node.body))
        args = _name_args(loop_vars)
        ret_tuple = ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in loop_vars],
            ctx=ast.Load())
        cond_def = _make_fn(f"_jst_cond_{uid}", loop_vars,
                            [ast.Return(value=node.test)])
        body_def = _make_fn(f"_jst_body_{uid}", loop_vars,
                            node.body + [ast.Return(value=ret_tuple)])
        guards = _undef_guards(loop_vars)
        call = _jst_call("convert_while_loop",
                         [ast.Name(id=cond_def.name, ctx=ast.Load()),
                          ast.Name(id=body_def.name, ctx=ast.Load()),
                          ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Load())
                                          for n in loop_vars],
                                    ctx=ast.Load())])
        if loop_vars:
            tgt = ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in loop_vars],
                ctx=ast.Store())
            res = ast.Assign(targets=[tgt], value=call)
        else:
            res = ast.Expr(value=call)
        return guards + [cond_def, body_def, res]

    def visit_For(self, node: ast.For):
        # only `for <name> in range(...)` is rewritten (tensor trip counts);
        # other iterables keep Python semantics
        if not (isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "range"
                and isinstance(node.target, ast.Name)
                and not node.iter.keywords
                and not node.orelse):
            self.generic_visit(node)
            return node
        uid = self._uid()
        i = node.target.id
        start_n, stop_n, step_n = (f"_jst_start_{uid}", f"_jst_stop_{uid}",
                                   f"_jst_step_{uid}")
        init = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store())
                      for n in (start_n, stop_n, step_n)],
                ctx=ast.Store())],
            value=_jst_call("normalize_range", list(node.iter.args)))
        set_i = ast.Assign(targets=[ast.Name(id=i, ctx=ast.Store())],
                           value=ast.Name(id=start_n, ctx=ast.Load()))
        test = _jst_call("range_cond",
                         [ast.Name(id=i, ctx=ast.Load()),
                          ast.Name(id=stop_n, ctx=ast.Load()),
                          ast.Name(id=step_n, ctx=ast.Load())])
        inc = ast.Assign(
            targets=[ast.Name(id=i, ctx=ast.Store())],
            value=ast.BinOp(left=ast.Name(id=i, ctx=ast.Load()),
                            op=ast.Add(),
                            right=ast.Name(id=step_n, ctx=ast.Load())))
        loop = ast.While(test=test, body=node.body + [inc], orelse=[])
        out = [init, set_i]
        res = self.visit_While(loop)
        out.extend(res if isinstance(res, list) else [res])
        return out


def _undef_guards(names):
    """For each name: bind the UNDEFINED sentinel if currently unbound, so
    pre-branch/pre-loop value tuples can always be built."""
    guards = []
    for n in names:
        guards.append(ast.Try(
            body=[ast.Expr(value=ast.Name(id=n, ctx=ast.Load()))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(
                    elts=[ast.Name(id="NameError", ctx=ast.Load()),
                          ast.Name(id="UnboundLocalError", ctx=ast.Load())],
                    ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[ast.Name(id=n, ctx=ast.Store())],
                    value=ast.Attribute(
                        value=ast.Name(id=_JST, ctx=ast.Load()),
                        attr="UNDEFINED", ctx=ast.Load()))])],
            orelse=[], finalbody=[]))
    return guards


def _empty_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                         kw_defaults=[], kwarg=None, defaults=[])


def _name_args(names):
    return ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=n) for n in names], vararg=None,
        kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])


def _make_fn(name, argnames, body):
    return ast.FunctionDef(
        name=name, args=_name_args(argnames), body=body, decorator_list=[],
        returns=None, type_comment=None, type_params=[])


def _jst_call(fn, args):
    return ast.Call(
        func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                           attr=fn, ctx=ast.Load()),
        args=args, keywords=[])


# --------------------------------------------------------------------------
# function-level entry points
# --------------------------------------------------------------------------
def _transform_tree(fn) -> ast.Module:
    src = textwrap.dedent(inspect.getsource(fn))
    tree = ast.parse(src)
    fdef = tree.body[0]
    fdef.decorator_list = []  # strip @declarative etc. to avoid recursion
    DygraphToStaticAst().visit(tree)
    ast.fix_missing_locations(tree)
    return tree


def transformed_source(fn) -> str:
    """Source of the converted function (ProgramTranslator.get_code)."""
    return ast.unparse(_transform_tree(fn))


def convert_to_static(fn):
    """Return a new function object with tensor control flow routed through
    the converters. Closure variables of the original are rebound."""
    from . import convert_operators
    tree = _transform_tree(fn)
    g = dict(fn.__globals__)
    g[_JST] = convert_operators
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                g[name] = cell.cell_contents
            except ValueError:  # empty cell
                pass
    code = compile(tree, filename=f"<dygraph_to_static {fn.__qualname__}>",
                   mode="exec")
    ns: dict = {}
    exec(code, g, ns)
    new_fn = ns[fn.__name__]
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    return new_fn
