"""AST transpiler: rewrite Python control flow over tensors into converter
calls (reference: python/paddle/fluid/dygraph/dygraph_to_static/
ast_transformer.py + ifelse_transformer / loop_transformer).

The transform is semantics-preserving for plain Python values (converters
fall back to host control flow) and turns tensor-dependent ``if`` / ``while``
/ ``for range()`` / ``and`` / ``or`` / ``not`` into ``layers.cond`` /
``layers.while_loop`` graph ops during a to-static trace — which the TPU
executor compiles to ``lax.cond`` / ``lax.while_loop`` inside one XLA
computation (no host round-trips inside the step).
"""
from __future__ import annotations

import ast
import inspect
import textwrap
from typing import List, Set

__all__ = ["DygraphToStaticAst", "convert_to_static", "transformed_source"]

_JST = "_jst"  # module alias injected into the transformed function's globals


# --------------------------------------------------------------------------
# name analysis
# --------------------------------------------------------------------------
class _AssignedNames(ast.NodeVisitor):
    """Names bound by simple assignments in a statement list (no descent
    into nested function/class definitions)."""

    def __init__(self):
        self.names: Set[str] = set()

    def visit_FunctionDef(self, node):  # do not descend
        self.names.add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.names.add(node.name)

    def visit_Assign(self, node):
        for t in node.targets:
            self._target(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._target(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def _target(self, t):
        if isinstance(t, ast.Name):
            self.names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e)


def _assigned_in(stmts: List[ast.stmt]) -> Set[str]:
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _HasNode(ast.NodeVisitor):
    def __init__(self, kinds):
        self.kinds = kinds
        self.found = False

    def generic_visit(self, node):
        if isinstance(node, self.kinds):
            self.found = True
            return
        # don't descend into nested function defs: their returns are theirs
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        super().generic_visit(node)


def _contains(stmts, kinds) -> bool:
    v = _HasNode(kinds)
    for s in stmts:
        v.visit(s)
    return v.found


# --------------------------------------------------------------------------
# early-exit pre-pass: break/continue/return inside loops
# --------------------------------------------------------------------------
# The reference handles these in loop_transformer.py / break_continue_
# transformer.py / return_transformer.py with the early-exit-flag recipe;
# this pre-pass applies the same recipe BEFORE the main transform, so the
# main transform only ever sees clean loops:
#   * `break`    → `_jst_break_K = True`, loop test gains `not _jst_break_K`,
#                  statements after a possible break are guarded.
#   * `continue` → `_jst_continue_K = True` (reset each iteration),
#                  following statements guarded.
#   * `return e` inside a loop → function-wide return unification:
#                  `_jst_ret_flag/_jst_ret_val` assignments, every loop the
#                  return can escape gains `not _jst_ret_flag` in its test,
#                  and ONE `return _jst_ret_val` is appended at the end.
# Flags start as Python bools; convert_while_loop promotes them to BOOL
# loop vars when the loop goes static, so a tensor-dependent
# `if cond: break` composes into the compiled while condition.
_RET_FLAG = "_jst_ret_flag"
_RET_VAL = "_jst_ret_val"


def _assign(name, value_node):
    return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                      value=value_node)


def _bool_const(v):
    return ast.Constant(value=bool(v))


def _stores_name(node, names) -> bool:
    """Does `node` (at any depth, skipping nested function defs) assign
    one of `names`?"""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)) and sub is not node:
            continue
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store) \
                and sub.id in names:
            return True
    return False


def _not_any(flags):
    """`not (f1 or f2 or ...)` — converted later into tensor logic when
    the flags go static."""
    test = ast.Name(id=flags[0], ctx=ast.Load())
    if len(flags) > 1:
        test = ast.BoolOp(op=ast.Or(),
                          values=[ast.Name(id=f, ctx=ast.Load())
                                  for f in flags])
    return ast.UnaryOp(op=ast.Not(), operand=test)


def _guard_rest(stmts, flags):
    """After any statement that may set one of `flags`, wrap the remaining
    statements in `if not (f1 or ...): ...`."""
    if not flags:
        return list(stmts)
    out = []
    for idx, s in enumerate(stmts):
        out.append(s)
        rest = stmts[idx + 1:]
        if rest and _stores_name(s, set(flags)):
            out.append(ast.If(test=_not_any(flags),
                              body=_guard_rest(rest, flags), orelse=[]))
            return out
    return out


class _EarlyExitTransformer(ast.NodeTransformer):
    """Rewrites break/continue/return-in-loop into flag form (see module
    note above). Applied to one FunctionDef before DygraphToStaticAst."""

    def __init__(self):
        self._uid = 0
        self.uses_ret = False

    def run(self, fdef: ast.FunctionDef):
        self.uses_ret = self._has_return_in_loop(fdef.body)
        body = [self._process(s) for s in fdef.body]
        body = _flatten(body)
        if self.uses_ret:
            body = self._rewrite_returns(body)
            body = _guard_rest(body, [_RET_FLAG])
            body = ([_assign(_RET_FLAG, _bool_const(False)),
                     _assign(_RET_VAL, ast.Constant(value=None))]
                    + body
                    + [ast.Return(value=ast.Name(id=_RET_VAL,
                                                 ctx=ast.Load()))])
        fdef.body = body
        return fdef

    # -- analysis ---------------------------------------------------------
    def _has_return_in_loop(self, stmts) -> bool:
        for s in stmts:
            for sub in ast.walk(s):
                if isinstance(sub, (ast.While, ast.For)) \
                        and _contains(sub.body, ast.Return):
                    return True
        return False

    # -- recursive processing --------------------------------------------
    def _process(self, stmt):
        """Returns a stmt or list of stmts with loops rewritten."""
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return stmt  # nested defs keep their own control flow
        if isinstance(stmt, ast.While):
            return self._process_loop(stmt, for_parts=None)
        if isinstance(stmt, ast.For):
            return self._process_for(stmt)
        # compound statements: process blocks in place
        for field in ("body", "orelse", "finalbody"):
            blk = getattr(stmt, field, None)
            if blk:
                setattr(stmt, field,
                        _flatten([self._process(s) for s in blk]))
        for h in getattr(stmt, "handlers", []) or []:
            h.body = _flatten([self._process(s) for s in h.body])
        return stmt

    def _process_for(self, node: ast.For):
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and isinstance(node.target, ast.Name)
                    and not node.iter.keywords)
        direct_exits = self._direct_exits(node.body)
        has_any_exit = direct_exits or (self.uses_ret
                                        and _contains(node.body, ast.Return))
        if not is_range:
            if not has_any_exit:
                node.body = _flatten([self._process(s) for s in node.body])
                return node
            # host iterable with early exits: a native break/continue
            # cannot survive the if-branch functionization, so lower to
            # an indexed range loop over the materialized sequence and
            # recurse (matches the reference loop_transformer's
            # iterable→index rewrite; generators are materialized)
            self._uid += 1
            seq_n = f"_jst_seq_{self._uid}"
            idx_n = f"_jst_i_{self._uid}"
            mk_seq = _assign(seq_n, ast.Call(
                func=ast.Name(id="list", ctx=ast.Load()),
                args=[node.iter], keywords=[]))
            get_item = ast.Assign(
                targets=[node.target],
                value=ast.Subscript(
                    value=ast.Name(id=seq_n, ctx=ast.Load()),
                    slice=ast.Name(id=idx_n, ctx=ast.Load()),
                    ctx=ast.Load()))
            rng = ast.Call(func=ast.Name(id="range", ctx=ast.Load()),
                           args=[ast.Call(
                               func=ast.Name(id="len", ctx=ast.Load()),
                               args=[ast.Name(id=seq_n, ctx=ast.Load())],
                               keywords=[])],
                           keywords=[])
            lowered = ast.For(target=ast.Name(id=idx_n, ctx=ast.Store()),
                              iter=rng, body=[get_item] + node.body,
                              orelse=node.orelse)
            return [mk_seq] + _as_list(self._process_for(lowered))
        if not (direct_exits or _contains(node.body, ast.Return)):
            node.body = _flatten([self._process(s) for s in node.body])
            return node
        if node.orelse:
            raise NotImplementedError(
                "dygraph_to_static: for/else with early exits is not "
                "supported")
        # lower `for i in range(...)` to a while over a HIDDEN counter,
        # assigning `i = start + k*step` at body top — so after the loop
        # (break OR natural exit) `i` holds its last iterate, exactly
        # Python's for semantics; the k increment runs even on `continue`
        self._uid += 1
        uid = self._uid
        i = node.target.id
        start_n, stop_n, step_n, k_n = (
            f"_jst_start_{uid}", f"_jst_stop_{uid}", f"_jst_step_{uid}",
            f"_jst_k_{uid}")
        init = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store())
                      for n in (start_n, stop_n, step_n)],
                ctx=ast.Store())],
            value=_jst_call("normalize_range", list(node.iter.args)))
        set_k = _assign(k_n, ast.Constant(value=0))

        def cur_i():
            return ast.BinOp(
                left=ast.Name(id=start_n, ctx=ast.Load()), op=ast.Add(),
                right=ast.BinOp(left=ast.Name(id=k_n, ctx=ast.Load()),
                                op=ast.Mult(),
                                right=ast.Name(id=step_n, ctx=ast.Load())))

        test = _jst_call("range_cond",
                         [cur_i(), ast.Name(id=stop_n, ctx=ast.Load()),
                          ast.Name(id=step_n, ctx=ast.Load())])
        set_i = _assign(i, cur_i())
        inc = _assign(k_n, ast.BinOp(
            left=ast.Name(id=k_n, ctx=ast.Load()), op=ast.Add(),
            right=ast.Constant(value=1)))
        loop = ast.While(test=test, body=[set_i] + node.body, orelse=[])
        out = [init, set_k]
        out.extend(_as_list(self._process_loop(loop, for_parts=(inc,))))
        return out

    def _process_loop(self, node: ast.While, for_parts):
        # inner loops first (bottom-up), so remaining exits are OURS
        body = _flatten([self._process(s) for s in node.body])
        exits = self._direct_exits(body)
        if node.orelse and exits:
            raise NotImplementedError(
                "dygraph_to_static: while/else with early exits is not "
                "supported")
        has_ret = self.uses_ret and _contains(body, ast.Return)
        if not (exits or has_ret or _stores_name(
                ast.Module(body=body, type_ignores=[]), {_RET_FLAG})):
            node.body = body + list(for_parts or ())
            return node
        self._uid += 1
        uid = self._uid
        brk = f"_jst_break_{uid}" if (ast.Break in exits or has_ret) \
            else None
        cont = f"_jst_continue_{uid}" if ast.Continue in exits else None
        if has_ret:
            body = self._rewrite_returns(body)
        body = self._rewrite_break_continue(body, brk, cont)
        flags = [f for f in (brk, cont) if f] \
            + ([_RET_FLAG] if _stores_name(
                ast.Module(body=body, type_ignores=[]), {_RET_FLAG})
               else [])
        body = _guard_rest(body, flags)
        if cont:
            body = [_assign(cont, _bool_const(False))] + body
        exit_flags = [f for f in flags if f != cont]
        # the hidden-counter increment runs even on `continue` (Python's
        # for advances the iterator); the loop variable itself is
        # assigned at body TOP from the counter, so break/return leave it
        # at its last iterate
        body = body + list(for_parts or ())
        pre = []
        if brk:
            pre.append(_assign(brk, _bool_const(False)))
        # loop exits when a break/return flag is up
        test = node.test
        if exit_flags:
            test = ast.BoolOp(op=ast.And(),
                              values=[_not_any(exit_flags), test])
        new_loop = ast.While(test=test, body=body, orelse=node.orelse)
        return pre + [new_loop]

    # -- exit rewriting ---------------------------------------------------
    def _direct_exits(self, stmts):
        """Break/Continue kinds directly in these statements (not inside
        nested loops or function defs)."""
        found = set()

        def scan(ss):
            for s in ss:
                if isinstance(s, (ast.Break, ast.Continue)):
                    found.add(type(s))
                    continue
                if isinstance(s, (ast.While, ast.For, ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                    continue
                for field in ("body", "orelse", "finalbody"):
                    blk = getattr(s, field, None)
                    if blk:
                        scan(blk)
                for h in getattr(s, "handlers", []) or []:
                    scan(h.body)
        scan(stmts)
        return found

    def _rewrite_block(self, stmts, match, replace):
        """Replace statements matching `match(stmt)` with `replace(stmt)`
        (a list); statements after a replaced exit in the same list are
        unreachable and dropped. Does not descend into loops/defs."""
        out = []
        for s in stmts:
            if match(s):
                out.extend(replace(s))
                break  # the rest of this list is dead code
            if not isinstance(s, (ast.While, ast.For, ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                for field in ("body", "orelse", "finalbody"):
                    blk = getattr(s, field, None)
                    if blk:
                        setattr(s, field,
                                self._rewrite_block(blk, match, replace))
                for h in getattr(s, "handlers", []) or []:
                    h.body = self._rewrite_block(h.body, match, replace)
            out.append(s)
        return out

    def _rewrite_break_continue(self, stmts, brk, cont):
        if brk:
            stmts = self._rewrite_block(
                stmts, lambda s: isinstance(s, ast.Break),
                lambda s: [_assign(brk, _bool_const(True))])
        if cont:
            stmts = self._rewrite_block(
                stmts, lambda s: isinstance(s, ast.Continue),
                lambda s: [_assign(cont, _bool_const(True))])
        return stmts

    def _rewrite_returns(self, stmts, after=()):
        def repl(s):
            val = s.value if s.value is not None \
                else ast.Constant(value=None)
            # value FIRST, flag LAST: _guard_rest guards everything after
            # the statement that stores the flag — the pair must not be
            # split by its own guard
            return [_assign(_RET_VAL, val),
                    _assign(_RET_FLAG, _bool_const(True))] + list(after)
        return self._rewrite_block(
            stmts, lambda s: isinstance(s, ast.Return), repl)


def _as_list(x):
    return x if isinstance(x, list) else [x]


def _flatten(items):
    out = []
    for it in items:
        out.extend(it if isinstance(it, list) else [it])
    return out


# --------------------------------------------------------------------------
# the transformer
# --------------------------------------------------------------------------
class DygraphToStaticAst(ast.NodeTransformer):
    def __init__(self):
        self._counter = 0

    def _uid(self) -> int:
        self._counter += 1
        return self._counter

    # -------------------------------------------------------------- exprs
    def visit_BoolOp(self, node: ast.BoolOp):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        expr = node.values[0]
        for rhs in node.values[1:]:
            expr = ast.Call(
                func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                                   attr=fn, ctx=ast.Load()),
                args=[ast.Lambda(args=_empty_args(), body=expr),
                      ast.Lambda(args=_empty_args(), body=rhs)],
                keywords=[])
        return expr

    def visit_UnaryOp(self, node: ast.UnaryOp):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _jst_call("convert_logical_not", [node.operand])
        return node

    # -------------------------------------------------------------- stmts
    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        uid = self._uid()
        body, orelse = node.body, node.orelse or [ast.Pass()]
        body_returns = _contains(body, ast.Return)
        else_returns = _contains(orelse, ast.Return)

        if body_returns or else_returns:
            if not (body_returns and else_returns):
                raise NotImplementedError(
                    "dygraph_to_static: an `if` where only one branch "
                    "returns is not supported — give both branches a "
                    "return (or assign to a variable and return after "
                    "the if)")
            # both branches return: branch fns keep their returns; the
            # whole statement becomes `return convert_ifelse(...)`
            t_def = _make_fn(f"_jst_true_fn_{uid}", [], body)
            f_def = _make_fn(f"_jst_false_fn_{uid}", [], orelse)
            call = _jst_call("convert_ifelse",
                             [node.test,
                              ast.Name(id=t_def.name, ctx=ast.Load()),
                              ast.Name(id=f_def.name, ctx=ast.Load())])
            return [t_def, f_def, ast.Return(value=call)]

        assigned = sorted(_assigned_in(body) | _assigned_in(orelse))
        ret_tuple = ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in assigned],
            ctx=ast.Load())
        # branch fns take the assigned names as PARAMETERS: a branch that
        # assigns `s` makes `s` local, so it cannot read the pre-branch
        # value through a closure
        t_def = _make_fn(f"_jst_true_fn_{uid}", assigned,
                         body + [ast.Return(value=ret_tuple)])
        f_def = _make_fn(f"_jst_false_fn_{uid}", assigned,
                         orelse + [ast.Return(value=ret_tuple)])
        call = _jst_call("convert_ifelse",
                         [node.test,
                          ast.Name(id=t_def.name, ctx=ast.Load()),
                          ast.Name(id=f_def.name, ctx=ast.Load()),
                          ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Load())
                                          for n in assigned],
                                    ctx=ast.Load())])
        if assigned:
            tgt = ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in assigned],
                ctx=ast.Store())
            res = ast.Assign(targets=[tgt], value=call)
        else:
            res = ast.Expr(value=call)
        return _undef_guards(assigned) + [t_def, f_def, res]

    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if _contains(node.body, (ast.Break, ast.Continue, ast.Return)):
            # the early-exit pre-pass rewrites these into flag form before
            # this transform runs — reaching here means it missed a case
            raise NotImplementedError(
                "dygraph_to_static: unhandled break/continue/return inside "
                "a `while` (early-exit pre-pass missed it) — please report")
        uid = self._uid()
        loop_vars = sorted(_assigned_in(node.body))
        args = _name_args(loop_vars)
        ret_tuple = ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in loop_vars],
            ctx=ast.Load())
        cond_def = _make_fn(f"_jst_cond_{uid}", loop_vars,
                            [ast.Return(value=node.test)])
        body_def = _make_fn(f"_jst_body_{uid}", loop_vars,
                            node.body + [ast.Return(value=ret_tuple)])
        guards = _undef_guards(loop_vars)
        call = _jst_call("convert_while_loop",
                         [ast.Name(id=cond_def.name, ctx=ast.Load()),
                          ast.Name(id=body_def.name, ctx=ast.Load()),
                          ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Load())
                                          for n in loop_vars],
                                    ctx=ast.Load())])
        if loop_vars:
            tgt = ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in loop_vars],
                ctx=ast.Store())
            res = ast.Assign(targets=[tgt], value=call)
        else:
            res = ast.Expr(value=call)
        return guards + [cond_def, body_def, res]

    def visit_For(self, node: ast.For):
        # only `for <name> in range(...)` is rewritten (tensor trip counts);
        # other iterables keep Python semantics
        if not (isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "range"
                and isinstance(node.target, ast.Name)
                and not node.iter.keywords
                and not node.orelse):
            self.generic_visit(node)
            return node
        uid = self._uid()
        i = node.target.id
        # hidden-counter lowering (same recipe as the early-exit
        # pre-pass): `i = start + k*step` at body top keeps Python's
        # after-loop value of the target (last iterate, not one past)
        start_n, stop_n, step_n, k_n = (
            f"_jst_start_{uid}", f"_jst_stop_{uid}", f"_jst_step_{uid}",
            f"_jst_k_{uid}")
        init = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store())
                      for n in (start_n, stop_n, step_n)],
                ctx=ast.Store())],
            value=_jst_call("normalize_range", list(node.iter.args)))
        set_k = ast.Assign(targets=[ast.Name(id=k_n, ctx=ast.Store())],
                           value=ast.Constant(value=0))

        def cur_i():
            return ast.BinOp(
                left=ast.Name(id=start_n, ctx=ast.Load()), op=ast.Add(),
                right=ast.BinOp(left=ast.Name(id=k_n, ctx=ast.Load()),
                                op=ast.Mult(),
                                right=ast.Name(id=step_n, ctx=ast.Load())))

        test = _jst_call("range_cond",
                         [cur_i(), ast.Name(id=stop_n, ctx=ast.Load()),
                          ast.Name(id=step_n, ctx=ast.Load())])
        set_i = ast.Assign(targets=[ast.Name(id=i, ctx=ast.Store())],
                           value=cur_i())
        inc = ast.Assign(
            targets=[ast.Name(id=k_n, ctx=ast.Store())],
            value=ast.BinOp(left=ast.Name(id=k_n, ctx=ast.Load()),
                            op=ast.Add(), right=ast.Constant(value=1)))
        loop = ast.While(test=test, body=[set_i] + node.body + [inc],
                         orelse=[])
        out = [init, set_k]
        res = self.visit_While(loop)
        out.extend(res if isinstance(res, list) else [res])
        return out


def _undef_guards(names):
    """For each name: bind the UNDEFINED sentinel if currently unbound, so
    pre-branch/pre-loop value tuples can always be built."""
    guards = []
    for n in names:
        guards.append(ast.Try(
            body=[ast.Expr(value=ast.Name(id=n, ctx=ast.Load()))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(
                    elts=[ast.Name(id="NameError", ctx=ast.Load()),
                          ast.Name(id="UnboundLocalError", ctx=ast.Load())],
                    ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[ast.Name(id=n, ctx=ast.Store())],
                    value=ast.Attribute(
                        value=ast.Name(id=_JST, ctx=ast.Load()),
                        attr="UNDEFINED", ctx=ast.Load()))])],
            orelse=[], finalbody=[]))
    return guards


def _empty_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                         kw_defaults=[], kwarg=None, defaults=[])


def _name_args(names):
    return ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=n) for n in names], vararg=None,
        kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])


def _make_fn(name, argnames, body):
    return ast.FunctionDef(
        name=name, args=_name_args(argnames), body=body, decorator_list=[],
        returns=None, type_comment=None, type_params=[])


def _jst_call(fn, args):
    return ast.Call(
        func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                           attr=fn, ctx=ast.Load()),
        args=args, keywords=[])


# --------------------------------------------------------------------------
# function-level entry points
# --------------------------------------------------------------------------
def _transform_tree(fn) -> ast.Module:
    src = textwrap.dedent(inspect.getsource(fn))
    tree = ast.parse(src)
    fdef = tree.body[0]
    fdef.decorator_list = []  # strip @declarative etc. to avoid recursion
    _EarlyExitTransformer().run(fdef)  # break/continue/return in loops
    DygraphToStaticAst().visit(tree)
    ast.fix_missing_locations(tree)
    return tree


def transformed_source(fn) -> str:
    """Source of the converted function (ProgramTranslator.get_code)."""
    return ast.unparse(_transform_tree(fn))


def convert_to_static(fn):
    """Return a new function object with tensor control flow routed through
    the converters. Closure variables of the original are rebound."""
    from . import convert_operators
    tree = _transform_tree(fn)
    g = dict(fn.__globals__)
    g[_JST] = convert_operators
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                g[name] = cell.cell_contents
            except ValueError:  # empty cell
                pass
    code = compile(tree, filename=f"<dygraph_to_static {fn.__qualname__}>",
                   mode="exec")
    ns: dict = {}
    exec(code, g, ns)
    new_fn = ns[fn.__name__]
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    return new_fn
