"""Runtime converters the AST transpiler targets (reference:
python/paddle/fluid/dygraph/dygraph_to_static/convert_operators.py-era
behavior inside program_translator.py + loop/ifelse transformers).

Each converter dispatches on the runtime type of its tensor arguments:

* static ``framework.Variable`` (to-static trace in progress) — build the
  real control-flow ops (``layers.cond`` / ``layers.while_loop``), which the
  TPU executor lowers to ``lax.cond`` / ``lax.while_loop`` inside the one
  jitted step function;
* dygraph ``VarBase`` holding a concrete array — plain Python control flow
  on the host value (eager semantics, reference Tracer behavior);
* plain Python values — untouched Python semantics.
"""
from __future__ import annotations

import numpy as np

from ... import framework

__all__ = [
    "convert_ifelse", "convert_while_loop", "convert_logical_and",
    "convert_logical_or", "convert_logical_not", "convert_len",
    "normalize_range", "range_cond", "UNDEFINED",
]


class _Undefined:
    """Sentinel for loop vars first assigned inside the loop body."""
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<undefined>"


UNDEFINED = _Undefined()


def _is_static_var(x) -> bool:
    return isinstance(x, framework.Variable)


def _to_bool(x) -> bool:
    """Host truth value of a dygraph tensor / numpy / python value."""
    if hasattr(x, "numpy"):
        x = x.numpy()
    arr = np.asarray(x)
    return bool(arr.reshape(-1)[0]) if arr.size == 1 else bool(arr.any())


def convert_ifelse(pred, true_fn, false_fn, init_args=()):
    """``if pred: ... else: ...`` → layers.cond when pred is a static
    Variable (→ lax.cond on TPU), else Python branch selection.

    ``init_args`` holds the pre-branch values of every name either branch
    assigns (the transpiler passes them as parameters — branch bodies can't
    read them through closures because assignment makes them function-local).
    """
    init_args = tuple(init_args)
    if _is_static_var(pred):
        from ...layers import control_flow
        return control_flow.cond(pred, lambda: true_fn(*init_args),
                                 lambda: false_fn(*init_args))
    if _to_bool(pred):
        return true_fn(*init_args)
    return false_fn(*init_args)


def convert_while_loop(cond_fn, body_fn, loop_vars):
    """``while cond: body`` over ``loop_vars`` (tuple).

    Static path: promotes Python scalars to fill_constant vars and builds a
    while op (→ lax.while_loop). Loop vars that are ``UNDEFINED`` on entry
    (first assigned inside the body) stay host-side — they cannot carry
    state across compiled iterations, matching Python scoping."""
    loop_vars = tuple(loop_vars)
    probe = cond_fn(*loop_vars)
    if not _is_static_var(probe):
        while _to_bool(probe):
            new_vars = body_fn(*loop_vars)
            loop_vars = tuple(new_vars) if isinstance(
                new_vars, (list, tuple)) else (new_vars,)
            probe = cond_fn(*loop_vars)
        return loop_vars

    # ---- static trace: build the while op over the Variable subset ----
    from ...layers import control_flow, tensor as ltensor
    from ...core import VarDesc

    promoted = []
    for v in loop_vars:
        if _is_static_var(v) or v is UNDEFINED:
            promoted.append(v)
        elif isinstance(v, bool):
            promoted.append(ltensor.fill_constant([1], VarDesc.VarType.BOOL,
                                                  v))
        elif isinstance(v, int):
            promoted.append(ltensor.fill_constant([1], VarDesc.VarType.INT64,
                                                  v))
        elif isinstance(v, float):
            promoted.append(ltensor.fill_constant([1], VarDesc.VarType.FP32,
                                                  v))
        else:
            # non-tensor loop-carried object (list, dict, ...) — cannot be
            # compiled state; keep it closed-over/host-side
            promoted.append(v)
    carried_idx = [i for i, v in enumerate(promoted) if _is_static_var(v)]

    def _expand(carried):
        full = list(promoted)
        for i, v in zip(carried_idx, carried):
            full[i] = v
        return full

    def _cond(*carried):
        return cond_fn(*_expand(carried))

    def _body(*carried):
        new_vars = body_fn(*_expand(carried))
        if not isinstance(new_vars, (list, tuple)):
            new_vars = (new_vars,)
        return [new_vars[i] for i in carried_idx]

    carried = [promoted[i] for i in carried_idx]
    out = control_flow.while_loop(_cond, _body, carried)
    return tuple(_expand(out))


def convert_logical_and(x_fn, y_fn):
    x = x_fn()
    if _is_static_var(x):
        from ...layers.nn import logical_and
        return logical_and(x, _as_static_bool(y_fn()))
    return _to_bool(x) and y_fn()


def convert_logical_or(x_fn, y_fn):
    x = x_fn()
    if _is_static_var(x):
        from ...layers.nn import logical_or
        return logical_or(x, _as_static_bool(y_fn()))
    return _to_bool(x) or y_fn()


def convert_logical_not(x):
    if _is_static_var(x):
        from ...layers.nn import logical_not
        return logical_not(x)
    return not _to_bool(x)


def _as_static_bool(y):
    if _is_static_var(y):
        return y
    from ...layers import tensor as ltensor
    from ...core import VarDesc
    return ltensor.fill_constant([1], VarDesc.VarType.BOOL, bool(y))


def convert_len(x):
    if _is_static_var(x):
        from ...layer_helper import LayerHelper
        from ...core import VarDesc
        helper = LayerHelper("convert_len")
        shp = helper.create_variable_for_type_inference(VarDesc.VarType.INT32)
        helper.append_op(type="shape", inputs={"Input": [x]},
                         outputs={"Out": [shp]})
        out = helper.create_variable_for_type_inference(VarDesc.VarType.INT32)
        helper.append_op(type="slice", inputs={"Input": [shp]},
                         outputs={"Out": [out]},
                         attrs={"axes": [0], "starts": [0], "ends": [1]})
        return out
    return len(x)


def normalize_range(*args):
    """range(stop) / range(start, stop[, step]) → (start, stop, step)."""
    if len(args) == 1:
        return 0, args[0], 1
    if len(args) == 2:
        return args[0], args[1], 1
    return args[0], args[1], args[2]


def range_cond(i, stop, step):
    """Continue-iterating predicate valid for either sign of step:
    (i - stop) * sign(step) < 0 — works on Python ints and tensors."""
    if _is_static_var(i) or _is_static_var(stop) or _is_static_var(step):
        from ...layers import math_op, sign

        def _v(x):
            if _is_static_var(x):
                return x
            from ...layers import tensor as ltensor
            ref = i if _is_static_var(i) else (
                stop if _is_static_var(stop) else step)
            return ltensor.fill_constant([1], ref.dtype, x)
        i_v, stop_v, step_v = _v(i), _v(stop), _v(step)
        diff = math_op("elementwise_sub", i_v, stop_v)
        signed = math_op("elementwise_mul", diff,
                         sign(step_v.astype(diff.dtype)))
        from ...layers import tensor as ltensor
        zero = ltensor.fill_constant([1], signed.dtype, 0)
        return signed < zero
    if step > 0:
        return _host_val(i) < _host_val(stop)
    return _host_val(i) > _host_val(stop)


def _host_val(x):
    if hasattr(x, "numpy"):
        return np.asarray(x.numpy()).reshape(-1)[0]
    return x
