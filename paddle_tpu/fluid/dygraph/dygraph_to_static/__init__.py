"""dygraph_to_static — AST transpiler + program translator (reference:
python/paddle/fluid/dygraph/dygraph_to_static/)."""
from .ast_transformer import (DygraphToStaticAst, convert_to_static,
                              transformed_source)
from .convert_operators import (convert_ifelse, convert_while_loop,
                                convert_logical_and, convert_logical_or,
                                convert_logical_not, convert_len)
from .program_translator import (ProgramTranslator, ConcreteProgram,
                                 StaticFunction, declarative)

__all__ = [
    "DygraphToStaticAst", "convert_to_static", "transformed_source",
    "convert_ifelse", "convert_while_loop", "convert_logical_and",
    "convert_logical_or", "convert_logical_not", "convert_len",
    "ProgramTranslator", "ConcreteProgram", "StaticFunction", "declarative",
]
