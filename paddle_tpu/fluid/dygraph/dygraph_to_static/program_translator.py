"""ProgramTranslator — dygraph function → static Program → compiled XLA step
(reference: python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py ProgramTranslator/ConcreteProgram + the run_program
op bridge, paddle/fluid/operators/run_program_op.cc).

TPU inversion of the reference design: the reference re-traces Python into a
ProgramDesc and executes it op-by-op through a nested PartialProgram. Here
the traced Program is compiled ONCE into a pure jitted function
``(feeds, params, rng) -> (outputs, updated_state)`` and the dygraph side
sees it as a single tape op (``run_program_dy``) whose gradient is the exact
``jax.vjp`` of that function — so a @declarative forward participates in
eager autograd while running as one fused XLA computation.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ... import core, framework, unique_name
from ...core import LoDTensor, Scope, VarDesc
from ....ops.registry import OPS, register_op
from ..base import VarBase
from ..layers import Layer
from .ast_transformer import convert_to_static, transformed_source

__all__ = ["ProgramTranslator", "ConcreteProgram", "StaticFunction",
           "declarative"]


def _one_sig(a):
    if isinstance(a, VarBase):
        return ("VB", tuple(a.shape), int(a.dtype))
    if isinstance(a, (np.ndarray, jax.Array)):
        return ("ARR", tuple(a.shape), str(a.dtype))
    if isinstance(a, Layer):
        return ("LAYER", id(a))
    return ("PY", repr(a))


def _sig_of(args, kwargs) -> Tuple:
    parts = [_one_sig(a) for a in args]
    for k in sorted(kwargs):
        parts.append((k,) + _one_sig(kwargs[k]))
    return tuple(parts)


def _is_tensor(a) -> bool:
    return isinstance(a, (VarBase, np.ndarray, jax.Array))


class ConcreteProgram:
    """One (function, input-spec) trace: static Program + compiled step."""

    def __init__(self, func, args, kwargs, param_sources: Dict[str, VarBase]):
        self.main_program = framework.Program()
        self.startup_program = framework.Program()
        self.feed_names: List[str] = []
        static_inputs: List[Any] = []
        static_kwargs: Dict[str, Any] = {}
        self._input_pos: List[int] = []   # arg positions that are tensors
        self._input_keys: List[str] = []  # kwarg names that are tensors

        with framework.program_guard(self.main_program,
                                     self.startup_program):
            block = self.main_program.global_block()

            def _lift(a, tag):
                shape = tuple(a.shape)
                dtype = (a.dtype if isinstance(a, VarBase)
                         else core.np_to_dtype(str(np.asarray(a).dtype)))
                name = unique_name.generate(f"_jst_input_{tag}")
                v = block.create_var(name=name, shape=shape, dtype=dtype,
                                     is_data=True, need_check_feed=True,
                                     stop_gradient=False)
                self.feed_names.append(name)
                return v

            for i, a in enumerate(args):
                if _is_tensor(a):
                    static_inputs.append(_lift(a, str(i)))
                    self._input_pos.append(i)
                else:
                    static_inputs.append(a)
            for k in sorted(kwargs):
                if _is_tensor(kwargs[k]):
                    static_kwargs[k] = _lift(kwargs[k], k)
                    self._input_keys.append(k)
                else:
                    static_kwargs[k] = kwargs[k]
            with framework._dygraph_guard(None):  # static trace
                outputs = func(*static_inputs, **static_kwargs)

        self._single_out = not isinstance(outputs, (list, tuple))
        out_list = [outputs] if self._single_out else list(outputs)
        for o in out_list:
            if not isinstance(o, framework.Variable):
                raise TypeError(
                    "dygraph_to_static: converted function must return "
                    f"static Variables, got {type(o).__name__}")
        self.fetch_names = [o.name for o in out_list]

        # resolve names referenced by ops but not defined in any block →
        # dygraph parameters/buffers (reference param_guard behavior)
        defined = set(self.feed_names)
        for b in self.main_program.blocks:
            defined.update(b.vars.keys())
        self.param_vars: Dict[str, VarBase] = {}
        gb = self.main_program.global_block()
        for b in self.main_program.blocks:
            for op in b.ops:
                for n in list(op.input_arg_names) + list(op.output_arg_names):
                    if n in defined or n in self.param_vars:
                        continue
                    src = param_sources.get(n)
                    if src is None or src._array is None:
                        raise KeyError(
                            f"dygraph_to_static: op '{op.type}' references "
                            f"'{n}' which is neither produced by the traced "
                            f"program nor a known dygraph parameter/buffer")
                    gb.create_var(name=n, shape=tuple(src.shape),
                                  dtype=src.dtype, persistable=True,
                                  stop_gradient=src.stop_gradient)
                    self.param_vars[n] = src
        self._cb = None

    # ------------------------------------------------------------ compile
    def _ensure_compiled(self):
        if self._cb is not None:
            return
        from ...executor import _CompiledBlock
        scope = Scope()
        for n, p in self.param_vars.items():
            scope.var(n).set_value(LoDTensor(p._array))
        self._scope = scope
        # guard=False: the numeric fault plane's policies live in
        # Executor.run — this tape op has no post-step host hook, so a
        # baked-in guard would silently REVERT a NaN step with nobody
        # reading the verdict. Dygraph keeps the pre-guard behavior
        # (the NaN propagates visibly into params/loss); the eager
        # kernels remain covered by the interpreter-path check.
        self._cb = _CompiledBlock(
            self.main_program, tuple(self.feed_names),
            tuple(self.fetch_names), scope,
            self.main_program.random_seed or core.globals_["FLAGS_seed"],
            guard=False)
        self.mut_names = list(self._cb.mut_state)
        self.ro_names = list(self._cb.ro_state)
        self.state_names = self.mut_names + self.ro_names
        cb = self._cb

        def _flat(xs, mut_ps, ro_ps, rng):
            fetches, new_mut, _extra, _health = cb._step(
                dict(zip(self.mut_names, mut_ps)),
                dict(zip(self.ro_names, ro_ps)),
                dict(zip(self.feed_names, xs)), rng)
            return tuple(fetches), tuple(new_mut[n] for n in self.mut_names)

        self._flat = _flat
        self._jitted = jax.jit(_flat)

    def run_kernel(self, ins, attrs):
        """Pure kernel body for the run_program_dy tape op. Dispatches the
        jitted whole-program function (one fused XLA computation); under
        jax.vjp the jitted call is differentiated as a unit."""
        self._ensure_compiled()
        xs = tuple(ins.get("X") or [])
        ps = tuple(ins.get("Params") or [])
        k = len(self.mut_names)
        fetches, new_mut = self._jitted(xs, ps[:k], ps[k:], attrs["_rng"])
        return {"Out": list(fetches), "ParamsOut": list(new_mut)}

    # ------------------------------------------------------------- invoke
    def call_dygraph(self, args, kwargs):
        self._ensure_compiled()
        tracer = framework._dygraph_tracer()
        input_vbs = []
        for a in ([args[i] for i in self._input_pos]
                  + [kwargs[k] for k in self._input_keys]):
            input_vbs.append(a if isinstance(a, VarBase)
                             else VarBase(jnp.asarray(a)))
        param_vbs = [self.param_vars[n] for n in self.state_names]
        out_vbs = [VarBase(None) for _ in self.fetch_names]
        mut_vbs = [self.param_vars[n] for n in self.mut_names]
        tracer.trace_op(
            "run_program_dy",
            {"X": input_vbs, "Params": param_vbs},
            {"Out": out_vbs, "ParamsOut": mut_vbs},
            {"_cp": self})
        if self._single_out:
            return out_vbs[0]
        return out_vbs


@register_op("run_program_dy", needs_rng=True,
             diff_inputs=("X", "Params"), inputs=("X", "Params"),
             outputs=("Out", "ParamsOut"))
def _run_program_dy(ins, attrs):
    """Compiled-program bridge op (reference: run_program_op.cc — the
    dygraph↔static boundary). Forward executes the jitted program; the
    gradient falls out of the generic jax.vjp machinery because this kernel
    is a pure traceable function of its tensor inputs."""
    return attrs["_cp"].run_kernel(ins, attrs)


class StaticFunction:
    """Callable (and descriptor, so it works on methods) wrapping a
    converted function with a per-input-spec ConcreteProgram cache."""

    def __init__(self, fn):
        functools.update_wrapper(self, fn)
        self._fn = fn
        self._converted = None
        self._cache: Dict[Tuple, ConcreteProgram] = {}
        self._is_declarative = True

    def __get__(self, instance, owner):
        if instance is None:
            return self
        bound = functools.partial(self.__call__, instance)
        bound.__wrapped__ = self  # for introspection
        return bound

    @property
    def converted(self):
        if self._converted is None:
            self._converted = convert_to_static(self._fn)
        return self._converted

    def code(self) -> str:
        return transformed_source(self._fn)

    def _param_sources(self, args) -> Dict[str, VarBase]:
        sources: Dict[str, VarBase] = {}
        tracer = framework._dygraph_tracer()
        if tracer is not None:
            sources.update(tracer._params)
        for a in args:
            if isinstance(a, Layer):
                for _, p in a.named_parameters():
                    sources[p.name] = p
                for _, sub in a.named_sublayers(include_self=True):
                    for b in sub._buffers.values():
                        if isinstance(b, VarBase):
                            sources[b.name] = b
        return sources

    def concrete_program(self, *args, **kwargs) -> ConcreteProgram:
        key = _sig_of(args, kwargs)
        cp = self._cache.get(key)
        if cp is None:
            cp = ConcreteProgram(self.converted, args, kwargs,
                                 self._param_sources(args))
            self._cache[key] = cp
        return cp

    def __call__(self, *args, **kwargs):
        if (not framework.in_dygraph_mode()
                or not ProgramTranslator().enable_to_static):
            # already building a static graph (or to-static disabled with
            # no dygraph tracer): run the converted function directly so
            # control flow lowers into the current program
            if not framework.in_dygraph_mode():
                return self.converted(*args, **kwargs)
            return self._fn(*args, **kwargs)  # disabled: plain eager
        cp = self.concrete_program(*args, **kwargs)
        return cp.call_dygraph(args, kwargs)


def declarative(fn):
    """@declarative — convert + compile a dygraph function on first call
    (reference dygraph/jit.py:121)."""
    if isinstance(fn, StaticFunction):
        return fn
    return StaticFunction(fn)


class ProgramTranslator:
    """Singleton control surface (reference program_translator.py)."""
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance.enable_to_static = True
        return cls._instance

    def enable(self, enable_to_static: bool):
        self.enable_to_static = bool(enable_to_static)

    # ----- reference API: get_output / get_func / get_program / get_code
    def get_func(self, dygraph_func):
        return declarative(dygraph_func).converted

    def get_code(self, dygraph_func):
        return transformed_source(dygraph_func)

    def get_output(self, dygraph_func, *args, **kwargs):
        return declarative(dygraph_func)(*args, **kwargs)

    def get_program(self, dygraph_func, *args, **kwargs):
        cp = declarative(dygraph_func).concrete_program(*args, **kwargs)
        inputs = [cp.main_program.global_block().vars[n]
                  for n in cp.feed_names]
        outputs = [cp.main_program.global_block().vars[n]
                   if n in cp.main_program.global_block().vars else n
                   for n in cp.fetch_names]
        return cp.main_program, cp.startup_program, inputs, outputs
