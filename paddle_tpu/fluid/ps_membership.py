"""Elastic PS membership plane — epoch-stamped cluster views, live
pserver drain/rejoin, replica failover (docs/FAULT_TOLERANCE.md
"Elastic membership").

The transpiler's static shard map (pserver endpoint list + round-robin
param placement) becomes a versioned ``ClusterView``: slot i is named by
its epoch-0 endpoint forever, and the view maps each slot to the
endpoint CURRENTLY serving it (plus warm replicas). Programs keep slot
endpoints baked into their op attrs; the RPC client resolves a slot to
its current server at connect time, so membership changes never touch a
compiled program.

Three moving parts:

  * client side — a process-global view registry (``install_view`` /
    ``resolve``). ``VarClient`` resolves through it on every (re)connect
    and installs newer views shipped back in typed
    ``StaleClusterViewError`` responses, then replays the SAME encoded
    frame — same dedup token — against the new owner (exactly-once
    survives the re-route). During an outage ``refresh_view_for`` polls
    the slot's replicas for a newer view (the promotion path).

  * server side — ``MembershipPlane`` holds one pserver's state machine
    (ACTIVE → DRAINING → DRAINED for a drain; STANDBY → ACTIVE for a
    join/promotion) and answers the data-plane guard: a server that no
    longer owns its shard raises ``StaleClusterViewError`` carrying its
    current view instead of silently serving stale parameters.

  * the drain protocol itself lives in ``ops/distributed_ops.py``
    (listen_and_serv owns the scope, grad lock, and barriers); this
    module only keeps the pieces both sides share.

Reference analogue: the PSLib stack's fixed pserver set (SURVEY
§distributed) has no such plane — a resize is a full restart from
checkpoint. Here the PR 3 barrier/dedup primitives plus the PR 4 binary
wire make the resharding epoch a between-rounds view flip.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

_LOG = logging.getLogger("paddle_tpu.ps")

# membership states a pserver slot-server moves through
ACTIVE = "active"        # owns its shard, serves data RPCs
STANDBY = "standby"      # warm spare: accepts handoffs/forwards only
DRAINING = "draining"    # handoff in progress; still the owner
DRAINED = "drained"      # handed off; answers StaleClusterViewError


class ClusterView:
    """Epoch-stamped slot → endpoint map. A slot is named by its
    epoch-0 endpoint (what the transpiler baked into the programs);
    ``resolve`` returns the endpoint currently serving it. Immutable:
    membership changes mint a NEW view with a bumped epoch."""

    __slots__ = ("epoch", "slots")

    def __init__(self, slots: Dict[str, Dict[str, Any]], epoch: int = 0):
        # slots: {slot_ep: {"primary": ep, "replicas": [ep, ...]}}
        self.epoch = int(epoch)
        self.slots = {
            s: {"primary": str(e.get("primary") or s),
                "replicas": [str(r) for r in (e.get("replicas") or [])]}
            for s, e in slots.items()}

    # ------------------------------------------------------------ builders
    @classmethod
    def initial(cls, endpoints: List[str],
                replica_map: Optional[Dict[str, str]] = None
                ) -> "ClusterView":
        """Epoch-0 view: every slot serves itself. ``replica_map``
        (slot → replica endpoint) defaults to the
        ``PADDLE_PS_REPLICA_MAP`` env var ("slot=replica,..."), the one
        source both trainers and pservers read so every process starts
        from the same view."""
        if replica_map is None:
            replica_map = parse_replica_map_env()
        slots = {}
        for ep in endpoints:
            ep = str(ep)
            reps = [replica_map[ep]] if ep in replica_map else []
            slots[ep] = {"primary": ep, "replicas": reps}
        return cls(slots, epoch=0)

    def moved(self, slot: str, new_primary: str,
              epoch: Optional[int] = None) -> "ClusterView":
        """New view with ``slot`` served by ``new_primary`` (a committed
        drain, or a replica promotion). The new primary is removed from
        the slot's replica list; the OLD primary does not become a
        replica (it drained or died — a rejoin is a fresh standby).
        ``epoch`` overrides the default self.epoch+1 — minting servers
        must clear the cluster-wide floor their MembershipPlane tracks,
        not just their own view's epoch."""
        slots = {s: {"primary": e["primary"],
                     "replicas": list(e["replicas"])}
                 for s, e in self.slots.items()}
        if slot not in slots:
            raise KeyError(f"unknown pserver slot {slot!r}")
        slots[slot]["primary"] = str(new_primary)
        slots[slot]["replicas"] = [r for r in slots[slot]["replicas"]
                                   if r != str(new_primary)]
        return ClusterView(
            slots, epoch=(self.epoch + 1 if epoch is None else int(epoch)))

    # ------------------------------------------------------------- queries
    def resolve(self, ep: str) -> str:
        """Current server for ``ep``; endpoints that aren't slot names
        (replicas, handoff destinations, raw test servers) pass
        through unchanged."""
        entry = self.slots.get(ep)
        return entry["primary"] if entry is not None else ep

    def replicas(self, slot: str) -> List[str]:
        entry = self.slots.get(slot)
        return list(entry["replicas"]) if entry is not None else []

    def endpoints(self) -> List[str]:
        """Every currently-serving primary, slot order preserved."""
        return [e["primary"] for e in self.slots.values()]

    # --------------------------------------------------------------- wire
    def to_dict(self) -> Dict[str, Any]:
        return {"epoch": self.epoch,
                "slots": {s: {"primary": e["primary"],
                              "replicas": list(e["replicas"])}
                          for s, e in self.slots.items()}}

    @classmethod
    def from_dict(cls, d) -> "ClusterView":
        return cls(d.get("slots") or {}, epoch=int(d.get("epoch", 0)))

    def __repr__(self):
        parts = ", ".join(
            f"{s}→{e['primary']}" + (f"+{len(e['replicas'])}r"
                                     if e["replicas"] else "")
            for s, e in self.slots.items())
        return f"ClusterView(epoch={self.epoch}, {parts})"


def parse_replica_map_env() -> Dict[str, str]:
    """PADDLE_PS_REPLICA_MAP="slot_ep=replica_ep,slot2=replica2"."""
    raw = os.environ.get("PADDLE_PS_REPLICA_MAP", "")
    out: Dict[str, str] = {}
    for pair in raw.split(","):
        pair = pair.strip()
        if not pair:
            continue
        if "=" not in pair:
            raise ValueError(
                f"PADDLE_PS_REPLICA_MAP entry {pair!r} is not "
                f"'slot_ep=replica_ep'")
        slot, rep = pair.split("=", 1)
        out[slot.strip()] = rep.strip()
    return out


# ---------------------------------------------------------------------------
# process-global view registry (client side)
# ---------------------------------------------------------------------------
_view_lock = threading.Lock()
_current_view: Optional[ClusterView] = None
# refresh_view_for rate limiter: slot -> last probe time
_refresh_at: Dict[str, float] = {}
_REFRESH_INTERVAL = 0.25


def install_view(view) -> Optional[ClusterView]:
    """Install a (possibly newer) view process-wide. Accepts a
    ClusterView or its dict form; epochs are MONOTONIC — an older or
    equal epoch never replaces a newer one (a late stale-error from a
    long-dead server can't roll the process back). Returns the view now
    in force."""
    global _current_view
    if view is None:
        return _current_view
    if not isinstance(view, ClusterView):
        view = ClusterView.from_dict(view)
    with _view_lock:
        if _current_view is None or view.epoch > _current_view.epoch:
            if _current_view is not None and \
                    view.epoch > _current_view.epoch:
                _LOG.info("cluster view updated: epoch %d -> %d (%r)",
                          _current_view.epoch, view.epoch, view)
            _current_view = view
        return _current_view


def current_view() -> Optional[ClusterView]:
    with _view_lock:
        return _current_view


def current_epoch() -> Optional[int]:
    v = current_view()
    return None if v is None else v.epoch


def resolve(ep: str) -> str:
    v = current_view()
    return ep if v is None else v.resolve(ep)


def reset_views() -> None:
    """Drop the process view (tests)."""
    global _current_view
    with _view_lock:
        _current_view = None
        _refresh_at.clear()


def refresh_view_for(slot: str) -> bool:
    """Failover probe: ask ``slot``'s replicas for their view and
    install any newer one (a promoted replica answers with the epoch it
    minted at promotion). Called from the RPC client's reconnect poll
    while the slot's primary is unreachable; rate-limited so the poll
    loop doesn't hammer the standby. Returns True when a newer view was
    installed."""
    view = current_view()
    if view is None:
        return False
    now = time.time()
    with _view_lock:
        if now - _refresh_at.get(slot, 0.0) < _REFRESH_INTERVAL:
            return False
        _refresh_at[slot] = now
    candidates = view.replicas(slot)
    before = view.epoch
    for ep in candidates:
        try:
            from .ps_rpc import VarClient
            cli = VarClient(ep, connect_timeout=1.0, channels=1,
                            resolve=False)
            try:
                got = cli.call("get_view", _rpc_timeout=2.0,
                               _rpc_retries=0)
            finally:
                cli.close()
        except Exception:  # standby down/unreachable — try the next one
            continue
        if got:
            installed = install_view(got)
            if installed is not None and installed.epoch > before:
                return True
    return False


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------
# the canonical tensor data plane: every method that ships tensor
# payloads between trainers and pservers. ps_rpc derives its
# quantization and fault-injection allowlists from THIS set (explicit
# deltas only), so a new data method added here picks up stale-view
# refusal, wire quantization, and WAN-delay coverage in one place.
TENSOR_DATA_METHODS = frozenset({
    "send_var", "send_vars_batch", "get_var", "get_vars_batch",
    "prefetch_rows", "geo_delta", "dgc_send",
})

# data-plane methods that carry the client's view epoch and are refused
# (typed StaleClusterViewError) by a server that no longer owns its
# shard — the tensor plane plus the round/introspection calls that must
# also land on the current owner
DATA_METHODS = TENSOR_DATA_METHODS | {"barrier", "table_stats"}

# test hook (tests/faultinject.py corrupt_handoff): maps a section's
# payload bytes just before they leave the draining source — AFTER the
# manifest CRCs were stamped — so the destination's validation must
# catch the corruption
_corrupt_section_hook = None


class MembershipPlane:
    """One pserver's membership state machine + counters. Owned by the
    listen_and_serv op; the VarServer consults ``pre_dispatch`` before
    every data RPC, and write handlers re-check ``check_serving`` under
    the grad lock (the race-free guard a drain commit relies on)."""

    def __init__(self, slot: str, bind: str, view: ClusterView,
                 state: str = ACTIVE, replica_of: str = ""):
        self.slot = slot
        self.bind = bind
        self.state = state
        self.view = view
        self.replica_of = replica_of
        # highest view epoch this server has SEEN anywhere — its own
        # view, client gossip (``_view``/``_view_epoch`` on data RPCs),
        # primary→replica forwards, get_view probes. Epochs are minted
        # by different servers (each drain source, each promoting
        # replica), so every locally minted epoch must clear this floor
        # or monotonic clients would reject it and never re-route.
        self._max_seen = view.epoch if view is not None else 0
        self.promotions = 0
        self.demotions = 0
        self.handoff = {"bytes": 0, "sections_done": 0,
                        "total_sections": 0, "in_progress": False,
                        "aborts": 0, "completed": 0}
        self.replication = {"forwarded_calls": 0, "forward_failures": 0}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- guards
    def serving(self) -> bool:
        return self.state in (ACTIVE, DRAINING)

    def stale_error(self) -> Any:
        from . import core
        v = self.view
        return core.StaleClusterViewError(
            f"pserver slot {self.slot!r} is {self.state} at {self.bind} "
            f"— shard served by "
            f"{v.resolve(self.slot) if v else 'unknown'} "
            f"(view epoch {v.epoch if v else '?'})",
            view=None if v is None else v.to_dict())

    def pre_dispatch(self, method: str, epoch, view=None) -> None:
        """VarServer hook, called before dispatching any method carrying
        (or eligible to carry) a view epoch. Absorbs the client's view
        gossip FIRST (even from a call about to be refused — a stale
        server still learns), then guards. Replays from the dedup
        cache are exempt one layer up — a retry of an already-applied
        call must replay even on a drained server."""
        if epoch is not None or view is not None:
            self.note_gossip(epoch=epoch, view=view)
        if method in DATA_METHODS and not self.serving():
            _LOG.info("membership: refusing %s on %s (state=%s, "
                      "view epoch %s, client epoch %s)", method,
                      self.bind, self.state,
                      None if self.view is None else self.view.epoch,
                      epoch)
            raise self.stale_error()

    def check_serving(self) -> None:
        """Under-the-grad-lock write guard: the drain commit flips
        ``state`` to DRAINED while holding that lock, so a write that
        passed ``pre_dispatch`` but lost the race to the handoff is
        refused HERE instead of mutating a shard that already moved.
        DRAINING still serves: the drain QUIESCES by waiting for the
        in-flight round to complete — refusing its writes would
        deadlock the round it is waiting on."""
        if not self.serving():
            raise self.stale_error()

    # ------------------------------------------------------------ changes
    def note_gossip(self, epoch=None, view=None) -> None:
        """Absorb membership gossip: a FULL view (client ``_view``
        stamps on data RPCs, primary→replica forwards/beats) installs
        when newer; a bare epoch number (``_view_epoch``) only raises
        the minting floor. Without this, a replica that never saw the
        epochs other slots' drains minted would promote at an epoch
        monotonic clients reject — and they would never re-route.

        Fencing: when the absorbed view is NEWER and maps this slot to
        a DIFFERENT endpoint while we think we are ACTIVE, someone else
        was legitimately made the owner (a false-positive promotion
        after a GC pause / partition that has since healed) — serving
        on would split the shard, so step down to STANDBY and answer
        data RPCs with the newer view from here on."""
        if view is not None:
            self.install(view)
            with self._lock:
                v = self.view
                if (self.state == ACTIVE and v is not None
                        and v.resolve(self.slot) != self.bind):
                    self.state = STANDBY
                    self.demotions += 1
                    _LOG.warning(
                        "membership: %s DEMOTED — a newer view (epoch "
                        "%d) maps slot %s to %s; this server was "
                        "presumed dead and replaced. Serving on would "
                        "fork the shard; stepping down to standby.",
                        self.bind, v.epoch, self.slot,
                        v.resolve(self.slot))
        if epoch is not None:
            with self._lock:
                if int(epoch) > self._max_seen:
                    self._max_seen = int(epoch)

    def install(self, view) -> ClusterView:
        if not isinstance(view, ClusterView):
            view = ClusterView.from_dict(view)
        with self._lock:
            if view.epoch > self._max_seen:
                self._max_seen = view.epoch
            if self.view is None or view.epoch > self.view.epoch:
                self.view = view
        install_view(view)  # keep the process registry in step
        return self.view

    def mint_moved(self, slot: str, new_primary: str) -> ClusterView:
        """Mint the drain-commit view: ``slot`` → ``new_primary`` at an
        epoch above BOTH this server's own view and every epoch gossip
        has shown it (two successive drains of different slots each
        mint on a different server — without the shared floor the
        second would re-mint an epoch clients already hold)."""
        with self._lock:
            base = self.view
            return base.moved(slot, new_primary,
                              epoch=max(base.epoch, self._max_seen) + 1)

    def promote(self) -> Optional[ClusterView]:
        """Replica → primary (dead-primary listener). Mints the new
        view locally — slot served by this server's bind endpoint, at
        an epoch clearing the gossip floor — and installs it. Returns
        the new view (None when not a standby)."""
        with self._lock:
            if self.state != STANDBY:
                return None
            self.state = ACTIVE
            self.promotions += 1
            base = self.view or ClusterView.initial([self.slot], {})
            floor = max(base.epoch, self._max_seen)
            self.view = base.moved(self.slot, self.bind, epoch=floor + 1)
            self._max_seen = floor + 1
        install_view(self.view)
        _LOG.warning(
            "membership: replica %s PROMOTED to primary for slot %s "
            "(view epoch %d)", self.bind, self.slot, self.view.epoch)
        return self.view

    # -------------------------------------------------------------- stats
    def stats_section(self) -> Dict[str, Any]:
        v = self.view
        return {"membership": {
            "slot": self.slot,
            "bind": self.bind,
            "state": self.state,
            "epoch": None if v is None else v.epoch,
            "shards_owned": ([self.slot] if self.state == ACTIVE else []),
            "replica_of": self.replica_of or None,
            "promotions": self.promotions,
            "demotions": self.demotions,
            "handoff": dict(self.handoff),
            "replication": dict(self.replication),
        }}
