"""fluid.install_check — post-install sanity check (reference:
python/paddle/fluid/install_check.py run_check — builds a tiny linear
model, runs it single-device and data-parallel, prints the verdict)."""
from __future__ import annotations

import numpy as np

__all__ = ["run_check"]


def run_check():
    """Train one step of a 2-feature linear model on the default device,
    then over every available device via a mesh (the reference's
    ParallelExecutor leg)."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core

    prog = fluid.Program()
    startup = fluid.Program()
    scope = core.Scope()
    with fluid.program_guard(prog, startup), fluid.unique_name.guard():
        x = fluid.data("inp", shape=[2], dtype="float32")
        linear = fluid.layers.fc(x, 4)
        loss = fluid.layers.mean(linear)
        fluid.optimizer.SGD(0.01).minimize(loss)
    exe = fluid.Executor()
    feed = {"inp": np.ones((2, 2), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(prog, feed=feed, fetch_list=[loss.name])

    n_dev = len(jax.devices())
    if n_dev > 1:
        from paddle_tpu.parallel.mesh import build_mesh
        mesh = build_mesh(n_dev)
        with fluid.scope_guard(scope):
            exe.run(prog, feed={"inp": np.ones((2 * n_dev, 2), np.float32)},
                    fetch_list=[loss.name], mesh=mesh)
        print("Your paddle-tpu works well on MUTIPLE devices.")
    print("Your paddle-tpu is installed successfully! Let's start deep "
          "Learning with paddle-tpu now")
