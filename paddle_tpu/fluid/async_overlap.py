"""Async overlap plane — trainer-side machinery that hides the PS wire
behind the compiled step (docs/PS_DATA_PLANE.md "Async overlap";
ROADMAP item 3; reference: HalfAsyncCommunicator's decoupled send
threads, communicator.h:299, and parameter_prefetch.cc's
section-overlap pulls).

Three overlapped streams, all gated on ``FLAGS_async_staleness > 0``:

  * bounded-staleness rounds — the transpiler's async-mode rewrite
    collapses the sync comm tail into one ``ps_round`` op; its kernel
    submits push→barrier→pull→barrier to the communicator's
    ``RoundPipeline`` and returns, so the executor launches window i+1
    while round i drains. ``FLAGS_async_staleness`` bounds the
    submitted-but-unacked rounds (ps_rpc.AckWindow); =0 runs the round
    inline, bit-identical to the pre-overlap 4-op tail.
  * sparse prefetch (this module) — while window i computes, a
    background thread pulls window i+1's embedding rows into a
    per-step ``PrefetchBuffer`` that ``distributed_lookup_table``
    consumes through the PR 7 row-cache consult hook
    (ps_rpc.install_row_cache); a fully-hit lookup issues ZERO RPCs.
    The buffer invalidates rows the trainer pushes grads for
    (``invalidate_rows`` from distributed_lookup_table_grad).
  * double-buffered dense pulls — each round's ``get_vars_batch``
    lands in the pipeline's latest-pull buffer; the next ``ps_round``
    installs it into the scope at the step boundary.

Staleness contract: every value a step consumes — dense params,
prefetched sparse rows — is at most ``FLAGS_async_staleness`` rounds
old, and a trainer never runs more than that many rounds ahead of its
own acknowledged comm. Prefetched rows are additionally at most one
round staler than a synchronous pull would see (they are fetched
while the PREVIOUS round may still be releasing).
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import core

__all__ = ["PrefetchBuffer", "OverlapPlane", "maybe_plane",
           "active_plane", "reset_plane", "prefetch_plan"]

_LOG = logging.getLogger("paddle_tpu.ps")


class PrefetchBuffer:
    """Per-step sparse prefetch buffer, (table, id) -> row.

    Implements the ``lookup(table, ids, fetch_fn)`` row-cache interface
    the serving EmbeddingCache defined (ps_rpc.install_row_cache), so
    the lookup op consults it with zero new plumbing — but the policy
    is different: a row is served AT MOST ONCE (consumed on hit — rows
    change every round, so nothing is ever served across windows), a
    fill MERGES the staged window's rows into the buffer (window i's
    unconsumed rows survive window i+1's early-landing fill), lookup
    misses are NOT cached (they were fetched fresh; caching them would
    serve them stale next step), and ``invalidate_rows`` drops rows
    the trainer just pushed grads for, including out of an in-flight
    fill (the dirty set)."""

    # a runaway buffer (lookups never consuming what stages fill) is
    # dropped wholesale rather than silently growing; warned once
    _MAX_ROWS_PER_TABLE = 1 << 20

    def __init__(self, wait_pending_s: float = 5.0):
        self.wait_pending_s = float(wait_pending_s)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._rows: Dict[str, Dict[int, np.ndarray]] = {}
        # id -> fence stage-seq: a fill whose fetch STARTED at or
        # before this seq must skip the id (its fetched copy may
        # predate the grad push); a fill staged after the push is
        # fresh-enough again (bounded staleness) and clears the fence
        self._dirty: Dict[str, Dict[int, int]] = {}
        self._stage_seq: Dict[str, int] = {}
        # per-table id set of every fill currently in flight (several
        # stages can be queued behind one prefetch thread). A lookup
        # that needs one of those ids waits for its fill (bounded)
        # instead of re-issuing the very RPCs the prefetch thread is
        # already running; lookups for unrelated ids never wait. Each
        # fill removes only ITS ids — an earlier fill completing must
        # not unblock lookups still waiting on a later one.
        self._pending_ids: Dict[str, set] = {}
        self._warned_overflow = False
        self.hits = 0
        self.misses = 0
        self.staged_rows = 0
        self.invalidated_rows = 0

    def begin_fill(self, table: str, ids) -> int:
        """Register an in-flight fill; returns its stage token (passed
        back to ``fill`` so invalidation can tell pre-push fetches from
        post-push ones)."""
        ids = np.asarray(ids).reshape(-1)
        with self._cv:
            self._pending_ids.setdefault(table, set()).update(
                int(i) for i in ids.tolist())
            token = self._stage_seq.get(table, 0) + 1
            self._stage_seq[table] = token
            return token

    def fill(self, table: str, ids: np.ndarray, rows: np.ndarray,
             token: int) -> None:
        """Merge one staged window's rows into the buffer (``token``
        from the matching ``begin_fill``). Ids invalidated after the
        fetch was staged are skipped — the trainer pushed grads for
        them and the fetched copy may predate that push."""
        ids = np.asarray(ids).reshape(-1)
        with self._cv:
            dirty = self._dirty.get(table) or {}
            tbl = self._rows.setdefault(table, {})
            if len(tbl) + len(ids) > self._MAX_ROWS_PER_TABLE:
                if not self._warned_overflow:
                    self._warned_overflow = True
                    _LOG.warning(
                        "PrefetchBuffer: table %r exceeded %d buffered "
                        "rows (lookups are not consuming the staged "
                        "windows) — dropping the stale buffer", table,
                        self._MAX_ROWS_PER_TABLE)
                tbl.clear()
            n = 0
            for k, i in enumerate(ids.tolist()):
                i = int(i)
                fence = dirty.get(i)
                if fence is not None:
                    if token <= fence:
                        continue  # fetch started before the push: drop
                    del dirty[i]  # post-push fetch supersedes the fence
                tbl[i] = rows[k]
                n += 1
            self.staged_rows += n
            if dirty:
                # prune dead fences: fills complete in stage order (one
                # FIFO prefetch thread), so every still-in-flight fill
                # has a token > this one and a fence < token can never
                # fire again — without the prune, ids pushed but never
                # re-prefetched (long-tail CTR ids) accumulate forever
                live = {i: f for i, f in dirty.items() if f >= token}
                if len(live) != len(dirty):
                    self._dirty[table] = live
            self._unpend_locked(table, ids)

    def _unpend_locked(self, table: str, ids) -> None:
        pend = self._pending_ids.get(table)
        if pend is not None:
            pend.difference_update(int(i) for i in ids.tolist())
            if not pend:
                del self._pending_ids[table]
        self._cv.notify_all()

    def abort_fill(self, table: str, ids) -> None:
        with self._cv:
            self._unpend_locked(table, np.asarray(ids).reshape(-1))

    def lookup(self, table: str, ids, fetch_fn) -> np.ndarray:
        """Row-cache hook entry point (called by the lookup op with the
        DEDUPED id set). Buffered rows serve without an RPC and are
        consumed; the rest fan out through ``fetch_fn``. When a fill
        covering some of these ids is in flight it is awaited (bounded)
        — the residual wait is strictly less than what the synchronous
        pull would have spent."""
        ids = np.asarray(ids).reshape(-1)
        id_list = [int(i) for i in ids.tolist()]
        end = time.monotonic() + self.wait_pending_s
        out = [None] * len(ids)
        missing_idx: List[int] = []
        with self._cv:
            while True:
                pend = self._pending_ids.get(table)
                if pend is None or not any(i in pend for i in id_list):
                    break
                left = end - time.monotonic()
                if left <= 0:
                    _LOG.warning(
                        "PrefetchBuffer: fill for table %r still in "
                        "flight after %.1fs — falling through to a "
                        "direct pull", table, self.wait_pending_s)
                    break
                self._cv.wait(min(left, 0.5))
            rows = self._rows.get(table) or {}
            for i, id_ in enumerate(ids.tolist()):
                row = rows.pop(int(id_), None)  # consume on hit
                if row is not None:
                    out[i] = row
                    self.hits += 1
                else:
                    missing_idx.append(i)
                    self.misses += 1
        if missing_idx:
            fetched = np.asarray(fetch_fn(ids[missing_idx]))
            for k, i in enumerate(missing_idx):
                out[i] = fetched[k]
        return np.asarray(out)

    def invalidate_rows(self, table: str, ids) -> None:
        """The trainer pushed grads for ``ids``: drop their buffered
        rows and fence them out of any in-flight fill. Called inline
        (main thread) by distributed_lookup_table_grad BEFORE the push
        ships, so a lookup can never race a known-dirty row."""
        ids = np.asarray(ids).reshape(-1)
        with self._lock:
            rows = self._rows.get(table)
            dirty = self._dirty.setdefault(table, {})
            fence = self._stage_seq.get(table, 0)
            for id_ in ids.tolist():
                id_ = int(id_)
                dirty[id_] = fence
                if rows is not None and rows.pop(id_, None) is not None:
                    self.invalidated_rows += 1

    def invalidate(self, table: Optional[str] = None) -> None:
        with self._lock:
            if table is None:
                self._rows.clear()
            else:
                self._rows.pop(table, None)

    def stats(self) -> Dict[str, float]:
        with self._lock:
            total = self.hits + self.misses
            return {
                "hits": self.hits, "misses": self.misses,
                "hit_rate": (self.hits / total) if total else 0.0,
                "staged_rows": self.staged_rows,
                "invalidated_rows": self.invalidated_rows,
                "tables": len(self._rows),
            }


class OverlapPlane:
    """Owns the prefetch thread + buffer and the row-cache hook install.
    One per trainer process (module-global, like the row cache); created
    lazily by ``maybe_plane`` when FLAGS_async_staleness > 0."""

    def __init__(self):
        from . import ps_rpc
        from . import telemetry
        self.prefetch = PrefetchBuffer()
        self._q: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._installed_over = None
        self.stages = 0
        # metrics view (docs/OBSERVABILITY.md): hit rate / staged rows /
        # invalidations scrape as ps_prefetch_* gauges
        self._metrics_view = telemetry.REGISTRY.register_view(
            "ps_prefetch", self.stats)
        if ps_rpc.current_row_cache() is None:
            # never fight a serving EmbeddingCache for the hook — a
            # process that serves AND trains keeps the serving cache
            # (its TTL bounds staleness there); prefetch just degrades
            # to direct pulls
            self._installed_over = ps_rpc.install_row_cache(self.prefetch)
            self._hook_owned = True
        else:
            self._hook_owned = False

    # ------------------------------------------------------------- stage
    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="ps-sparse-prefetch",
                    daemon=True)
                self._thread.start()

    def stage(self, table: str, ids, eps: List[str]) -> None:
        """Queue a prefetch of ``ids`` (the NEXT window slice's id feed)
        for ``table``, row-sharded across ``eps`` — issued on the
        prefetch thread while the current step computes."""
        ids = np.asarray(ids).reshape(-1)
        if not self._hook_owned:
            # a serving EmbeddingCache owns the consult hook: lookups
            # would never see this buffer, so fetching into it would
            # just duplicate the row-pull RPC traffic every window —
            # prefetch degrades to direct pulls, as documented
            return
        if len(ids) == 0 or not eps or not eps[0]:
            return
        uniq = np.unique(ids)
        self._ensure_thread()
        self.stages += 1
        token = self.prefetch.begin_fill(table, uniq)
        self._q.put((table, uniq, list(eps), token))

    def _loop(self):
        from . import profiler as _profiler
        from ..ops.distributed_ops import _pull_rows_sharded
        while True:
            item = self._q.get()
            if item is None:
                return
            table, uniq, eps, token = item
            try:
                if _profiler.is_profiling():
                    with _profiler.RecordEvent(
                            f"prefetch[{table}]", cat="comm",
                            args={"ids": int(len(uniq))}):
                        rows = _pull_rows_sharded(eps, table, uniq,
                                                  prefetch=True)
                else:
                    rows = _pull_rows_sharded(eps, table, uniq,
                                              prefetch=True)
                self.prefetch.fill(table, uniq, rows, token)
            except Exception as e:  # noqa: BLE001 — prefetch is advisory
                # a failed prefetch must never fail the step: the
                # lookup just misses and pulls directly (which will
                # surface a real outage with proper retries/typing)
                self.prefetch.abort_fill(table, uniq)
                _LOG.warning("sparse prefetch for %r failed (%r) — the "
                             "lookup will pull directly", table, e)

    def stats(self) -> Dict[str, float]:
        s = self.prefetch.stats()
        s["stages"] = self.stages
        return s

    def close(self):
        from . import ps_rpc
        from . import telemetry
        if self._metrics_view is not None:
            telemetry.REGISTRY.unregister_view(self._metrics_view)
            self._metrics_view = None
        if self._hook_owned and ps_rpc.current_row_cache() is \
                self.prefetch:
            ps_rpc.install_row_cache(self._installed_over)
        if self._thread is not None and self._thread.is_alive():
            self._q.put(None)
            self._thread.join(timeout=2.0)


_plane: Optional[OverlapPlane] = None
_plane_lock = threading.Lock()


def overlap_active() -> bool:
    return int(core.globals_["FLAGS_async_staleness"]) > 0


def maybe_plane() -> Optional[OverlapPlane]:
    """The process OverlapPlane iff the overlap plane is on
    (FLAGS_async_staleness > 0 and FLAGS_sparse_prefetch); created on
    first use."""
    if not overlap_active() or not core.globals_["FLAGS_sparse_prefetch"]:
        return None
    global _plane
    with _plane_lock:
        if _plane is None:
            _plane = OverlapPlane()
        return _plane


def active_plane() -> Optional[OverlapPlane]:
    return _plane


def reset_plane():
    global _plane
    with _plane_lock:
        plane, _plane = _plane, None
    if plane is not None:
        plane.close()


# --------------------------------------------------------------------------
# program scan: which feed vars carry embedding ids for which tables
# --------------------------------------------------------------------------
def prefetch_plan(program) -> Tuple[Tuple[str, str, Tuple[str, ...]], ...]:
    """(table, ids_var_name, endpoints) per distributed_lookup_table op
    whose Ids input could be a direct feed — cached on the program. The
    executor's window fallback stages slice i+1 of every windowed id
    feed named here."""
    key = ("_prefetch_plan", program._version)
    cached = program.__dict__.get("_prefetch_plan_cache")
    if cached is not None and cached[0] == key:
        return cached[1]
    plan: List[Tuple[str, str, Tuple[str, ...]]] = []
    for op in program.global_block().ops:
        if op.type != "distributed_lookup_table":
            continue
        eps = tuple(e for e in (op.attrs.get("epmap") or []) if e)
        if not eps:
            continue
        table = (op.attrs.get("table_names") or op.input("W"))[0]
        for nm in op.input("Ids"):
            plan.append((table, nm, eps))
    result = tuple(plan)
    program.__dict__["_prefetch_plan_cache"] = (key, result)
    return result
