"""Program visualization (reference: python/paddle/fluid/net_drawer.py —
emits Graphviz of ops/vars). Writes .dot text (graphviz python binding not
required); ``dot -Tpng`` renders it."""
from __future__ import annotations

from typing import Optional

__all__ = ["draw_graph", "draw_block_graphviz"]


def _esc(s: str) -> str:
    return s.replace('"', r'\"')


def draw_block_graphviz(block, highlights=None, path: Optional[str] = None
                        ) -> str:
    """One block → dot digraph: op nodes (boxes) wired through var nodes
    (ellipses)."""
    highlights = set(highlights or [])
    lines = ["digraph G {", "  rankdir=TB;"]
    var_nodes = set()

    def var_node(name):
        vid = f"var_{abs(hash(name)) % (10 ** 10)}"
        if name not in var_nodes:
            var_nodes.add(name)
            color = ', style=filled, fillcolor="lightsalmon"' \
                if name in highlights else ""
            lines.append(f'  {vid} [label="{_esc(name)}", shape=ellipse'
                         f'{color}];')
        return vid

    for i, op in enumerate(block.ops):
        oid = f"op_{i}"
        lines.append(f'  {oid} [label="{_esc(op.type)}", shape=box, '
                     f'style=filled, fillcolor="lightblue"];')
        for name in op.input_arg_names:
            lines.append(f"  {var_node(name)} -> {oid};")
        for name in op.output_arg_names:
            lines.append(f"  {oid} -> {var_node(name)};")
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot


def draw_graph(startup_program, main_program, path: Optional[str] = None,
               **kwargs) -> str:
    """reference net_drawer.draw_graph — main program block 0."""
    return draw_block_graphviz(main_program.global_block(), path=path)
