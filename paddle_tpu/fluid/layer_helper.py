"""LayerHelper — shared machinery for layer functions (reference:
python/paddle/fluid/layer_helper.py + layer_helper_base.py): parameter
creation wired to startup-program init ops, temp variable creation,
activation append, dtype inference."""
from __future__ import annotations

from typing import Any, Dict, Optional

from . import core, unique_name
from .core import VarDesc
from .framework import (Parameter, Variable, default_main_program,
                        default_startup_program, in_dygraph_mode,
                        _dygraph_tracer)
from .initializer import Constant, Xavier
from .param_attr import ParamAttr

__all__ = ["LayerHelper"]


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        if name is None:
            self.kwargs["name"] = unique_name.generate(layer_type)

    @property
    def name(self):
        return self.kwargs["name"]

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    # ------------------------------------------------------------------
    def append_op(self, *args, **kwargs):
        return self.main_program.current_block().append_op(*args, **kwargs)

    def multiple_input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name, [])
        if isinstance(inputs, Variable):
            return [inputs]
        return list(inputs)

    def input(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError(f"{self.layer_type} expects one input")
        return inputs[0]

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr"))

    def multiple_param_attr(self, length):
        pa = self.param_attr
        if isinstance(pa, ParamAttr):
            pa = [pa]
        if len(pa) == 1 and length != 1:
            pa = pa + [copy_attr(pa[0]) for _ in range(length - 1)]
        return pa

    def input_dtype(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for inp in inputs:
            if dtype is None:
                dtype = inp.dtype
        return dtype

    # ------------------------------------------------------------------
    def create_parameter(self, attr, shape, dtype=None, is_bias=False,
                         default_initializer=None, stop_gradient=False,
                         type=VarDesc.VarType.LOD_TENSOR):
        if attr is False:
            return None
        attr = attr if isinstance(attr, ParamAttr) else ParamAttr._to_attr(attr)
        if attr is False:
            return None
        if default_initializer is None:
            if is_bias:
                attr._set_default_bias_initializer()
            else:
                attr._set_default_param_initializer()
        else:
            attr._set_default_initializer(default_initializer)
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, "w" if not is_bias else "b"]))
        if dtype is None:
            dtype = self.input_dtype() or VarDesc.VarType.FP32

        if in_dygraph_mode():
            return _dygraph_tracer().create_parameter(
                attr.name, shape, dtype, attr.initializer, attr.trainable,
                optimize_attr={"learning_rate": attr.learning_rate},
                regularizer=attr.regularizer)

        startup_block = self.startup_program.global_block()
        main_block = self.main_program.global_block()
        # parameter in both programs (reference layer_helper_base.py behavior)
        existing = main_block.vars.get(attr.name)
        if existing is not None:
            return existing
        sp = startup_block.create_parameter(
            shape=shape, dtype=dtype, **attr._to_kwargs())
        attr.initializer(sp, startup_block)
        param = main_block.create_parameter(
            shape=shape, dtype=dtype, **attr._to_kwargs())
        param.stop_gradient = stop_gradient
        return param

    def get_parameter(self, name: str):
        param = self.main_program.global_block().vars.get(name)
        if param is None:
            raise ValueError(f"parameter '{name}' not found")
        return param

    def create_variable_for_type_inference(self, dtype,
                                           stop_gradient=False) -> Variable:
        if in_dygraph_mode():
            from .dygraph.base import VarBase
            return VarBase(None, stop_gradient=stop_gradient, dtype=dtype)
        return self.main_program.current_block().create_var(
            name=unique_name.generate_with_ignorable_key(
                ".".join([self.name, "tmp"])),
            dtype=dtype, persistable=False, stop_gradient=stop_gradient)

    create_tmp_variable = create_variable_for_type_inference

    def create_variable(self, *args, **kwargs):
        return self.main_program.current_block().create_var(*args, **kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable,
            name=unique_name.generate_with_ignorable_key(
                ".".join([self.name, "tmp"])), **kwargs)

    def create_or_get_global_variable(self, name, *args, **kwargs):
        block = self.main_program.global_block()
        if name in block.vars:
            return block.vars[name]
        kwargs.setdefault("persistable", True)
        return block.create_var(*args, name=name, **kwargs)

    def set_variable_initializer(self, var, initializer):
        if in_dygraph_mode():
            return _dygraph_tracer().init_variable(var, initializer)
        startup = self.startup_program.global_block()
        sv = startup.create_var(name=var.name, dtype=var.dtype,
                                shape=var.shape, persistable=True)
        initializer(sv, startup)
        return var

    # ------------------------------------------------------------------
    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        bias_attr = self.bias_attr
        if not bias_attr:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        b = self.create_parameter(attr=bias_attr, shape=size,
                                  dtype=input_var.dtype, is_bias=True)
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        tmp.shape = input_var.shape
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": dim_start})
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        else:
            act = dict(act)
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        tmp.shape = input_var.shape
        self.append_op(type=act_type, inputs={"X": [input_var]},
                       outputs={"Out": [tmp]}, attrs=act)
        return tmp


def copy_attr(attr: ParamAttr) -> ParamAttr:
    # the NAME is kept (reference layer_helper_base.create_parameter
    # deepcopies the attr): a named attr shared across a multi-input fc
    # means ONE shared parameter, never silently-fresh per-input weights
    return ParamAttr(name=attr.name, initializer=attr.initializer,
                     learning_rate=attr.learning_rate,
                     regularizer=attr.regularizer, trainable=attr.trainable,
                     gradient_clip=attr.gradient_clip)
