"""Disk tier for ``core.LazyEmbeddingTable`` — the capacity half of the
reference's PSLib SSD-tiered sparse tables (reference:
framework/fleet/fleet_wrapper.h DownpourSparseTable + the
``distributed/`` SSD table stack: tables far larger than host RAM keep a
pinned hot set resident and page cold features through a disk log).

``SpillStore`` is a per-table, append-only, CRC-stamped segment log:

  * one segment = one eviction batch (ids + encoded rows), written as a
    single contiguous record and read back with ONE mmap slice — a cold
    ``get_rows`` costs one I/O fan-in per touched segment, never one
    seek per id;
  * every record carries its crc32 in the in-RAM directory and is
    verified on every read — a torn, bit-flipped, or deleted log
    surfaces ``core.SpillCorruptionError`` (the PR 3 checkpoint
    contract: corrupt state is REFUSED, never served);
  * rows are encoded AT REST with the PR 11 wire codec
    (``ps_rpc._quant_int8`` / fp16 downcast): ``""`` raw, ``"fp16"``
    half-precision, ``"int8"`` per-row absmax scales — ~2×/~3.6× row
    density over f32 before a byte even spills. A segment containing
    non-finite rows stores RAW so dequant-on-touch sees the poison
    exactly (the FLAGS_ps_reject_nonfinite guard decides, docs/
    PS_DATA_PLANE.md "Capacity tier");
  * dead bytes (promoted/shrunk rows, freed segments) are compacted
    away once they exceed the live half of the log.

The section-stream helpers at the bottom (``table_sections`` /
``build_table_from_sections``) are the ONE serialization of a tiered
table, shared by the PR 6 drain/rejoin handoff and ``io.save_checkpoint``
— both stream a part-spilled table section-by-section without ever
materializing it in RAM (spilled segments travel as their VERBATIM
encoded records, so a handoff is bit-identical by construction).
"""
from __future__ import annotations

import json
import mmap
import os
import threading
import zlib
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from . import core

__all__ = ["SpillStore", "encode_rows", "decode_rows",
           "table_sections", "build_table_from_sections",
           "scan_section_headers", "iter_section_stream",
           "write_section_stream", "merge_tier_stats",
           "SLAB_STREAM_MAGIC"]

# at-rest quantization modes (same vocabulary as FLAGS_ps_wire_quant)
QUANT_MODES = ("", "fp16", "int8")


def _wire_codec():
    # the PR 11 wire codec, imported lazily: core must stay importable
    # without the RPC stack, and by the time a table spills the pserver
    # has ps_rpc loaded anyway
    from . import ps_rpc
    return ps_rpc._quant_int8, ps_rpc._dequant_int8


def encode_rows(rows: np.ndarray, quant: str) -> Tuple[bytes, str, int]:
    """Encode one eviction batch for the log. Returns ``(payload,
    quant_used, row_bytes)`` — ``quant_used`` may downgrade to ``""``
    when the rows are non-float, already narrower than the target, or
    contain non-finite values (poison must reach dequant-on-touch
    exactly; masking it behind a lossy encode would let a NaN row
    round-trip as a finite one). ``row_bytes`` is the stored byte count
    attributable to row data (incl. int8 scales) — the density-gauge
    numerator's denominator."""
    rows = np.ascontiguousarray(rows)
    if quant not in QUANT_MODES:
        raise ValueError(f"at-rest quant mode {quant!r} — expected one "
                         f"of {QUANT_MODES}")
    if quant and (not np.issubdtype(rows.dtype, np.floating)
                  or not np.isfinite(rows).all()):
        quant = ""
    if quant == "fp16" and rows.dtype.itemsize <= 2:
        quant = ""
    if quant == "int8":
        # same expansion gate as the wire codec: the 4-byte per-row
        # scale EXPANDS very narrow rows (a [*, 1] wide table stored
        # int8 would be 5 B/row vs 4 B raw) — store those raw
        dim = rows.shape[-1] if rows.ndim > 1 else rows.size
        if dim * rows.dtype.itemsize <= dim + 4:
            quant = ""
    if quant == "fp16":
        with np.errstate(over="ignore"):  # overflow detected just below
            cast = rows.astype(np.float16)
        if not np.isfinite(cast).all():
            # a FINITE row overflowed the fp16 range (|v| > 65504):
            # storing the inf would mint poison out of healthy values
            # (and trip/skip the non-finite guard wrongly) — store raw
            blob = rows.tobytes()
            return blob, "", len(blob)
        blob = cast.tobytes()
        return blob, "fp16", len(blob)
    if quant == "int8":
        qi8, _ = _wire_codec()
        q, scale = qi8(rows.astype(np.float32, copy=False))
        blob = scale.astype(np.float32).tobytes() + q.tobytes()
        return blob, "int8", len(blob)
    blob = rows.tobytes()
    return blob, "", len(blob)


def decode_rows(payload: bytes, quant: str, n_rows: int, dim: int,
                dtype: np.dtype) -> np.ndarray:
    """Inverse of ``encode_rows`` — dequant-on-touch. Accepts any
    buffer (mmap slices included); always returns a fresh writable
    array in the table's dtype."""
    dtype = np.dtype(dtype)
    if quant == "fp16":
        arr = np.frombuffer(payload, np.float16).reshape(n_rows, dim)
        return arr.astype(dtype)
    if quant == "int8":
        _, dq = _wire_codec()
        scale = np.frombuffer(payload, np.float32, n_rows)
        q = np.frombuffer(payload, np.int8, n_rows * dim,
                          offset=n_rows * 4).reshape(n_rows, dim)
        return dq(q, scale, dtype).copy()
    return np.frombuffer(payload, dtype).reshape(n_rows, dim).copy()


class _Seg:
    __slots__ = ("off", "nbytes", "crc", "n_rows", "quant", "row_bytes")

    def __init__(self, off, nbytes, crc, n_rows, quant, row_bytes):
        self.off = int(off)
        self.nbytes = int(nbytes)
        self.crc = int(crc)
        self.n_rows = int(n_rows)
        self.quant = quant
        self.row_bytes = int(row_bytes)

    def meta(self) -> Dict[str, Any]:
        return {"n_rows": self.n_rows, "quant": self.quant,
                "row_bytes": self.row_bytes, "crc": self.crc,
                "nbytes": self.nbytes}


class SpillStore:
    """Append-only segment log for one table's cold rows.

    Record layout (all offsets/CRCs live in the in-RAM directory — the
    log is a CACHE tier, rebuilt from handoff/checkpoint sections on
    restart, so it needs no self-describing framing):

        int64 ids[n_rows] | encoded rows payload (encode_rows)

    Reads go through one ``mmap`` remapped as the file grows; the CRC
    of the whole record is verified on EVERY read, so serving a row
    from a torn or bit-flipped log is impossible
    (``core.SpillCorruptionError``, tests/faultinject.corrupt_spill)."""

    def __init__(self, path: str, dim: int, dtype=np.float32):
        self.path = str(path)
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._f = open(self.path, "wb+")
        self._mm: Optional[mmap.mmap] = None
        self._next_seg = 0
        self._segs: Dict[int, _Seg] = {}
        self._lock = threading.Lock()
        self._dead_bytes = 0
        self._live_bytes = 0  # incremental mirror of sum(seg.nbytes)
        # counters (scraped through the table's tier stats)
        self.reads = 0
        self.writes = 0
        self.compactions = 0
        self.crc_failures = 0

    # -- write side -------------------------------------------------------
    def append(self, ids: np.ndarray, rows: np.ndarray,
               quant: str = "") -> int:
        """Write one eviction batch; returns its segment id."""
        ids = np.ascontiguousarray(np.asarray(ids, np.int64).reshape(-1))
        payload, quant_used, row_bytes = encode_rows(rows, quant)
        record = ids.tobytes() + payload
        return self._append_record(record, len(ids), quant_used,
                                   row_bytes)

    def append_raw(self, record: bytes, n_rows: int, quant: str,
                   row_bytes: int, expect_crc: Optional[int] = None) -> int:
        """Install a VERBATIM record (handoff/checkpoint rebuild). The
        caller supplies the directory fields; ``expect_crc`` re-checks
        the bytes against the source's stamp before they enter the log."""
        if expect_crc is not None:
            crc = zlib.crc32(record) & 0xFFFFFFFF
            if crc != int(expect_crc):
                self.crc_failures += 1
                raise core.SpillCorruptionError(
                    f"spill segment rebuild for {self.path}: record CRC "
                    f"{crc:#x} != manifest {int(expect_crc):#x}")
        return self._append_record(bytes(record), int(n_rows), quant,
                                   int(row_bytes))

    def _append_record(self, record: bytes, n_rows: int, quant: str,
                       row_bytes: int) -> int:
        with self._lock:
            self._f.seek(0, os.SEEK_END)
            off = self._f.tell()
            self._f.write(record)
            self._f.flush()
            sid = self._next_seg
            self._next_seg += 1
            self._segs[sid] = _Seg(off, len(record),
                                   zlib.crc32(record) & 0xFFFFFFFF,
                                   n_rows, quant, row_bytes)
            self._live_bytes += len(record)
            self.writes += 1
            return sid

    # -- read side --------------------------------------------------------
    def _record_view(self, seg: _Seg) -> memoryview:
        """Zero-copy view of one record via the shared mmap (remapped
        when the file has grown past the current mapping)."""
        end = seg.off + seg.nbytes
        if self._mm is None or len(self._mm) < end:
            if self._mm is not None:
                self._mm.close()
                self._mm = None
            size = os.path.getsize(self.path)
            if size < end:
                self.crc_failures += 1
                raise core.SpillCorruptionError(
                    f"spill log {self.path} truncated: segment needs "
                    f"bytes [{seg.off}, {end}) but the file holds "
                    f"{size}")
            self._mm = mmap.mmap(self._f.fileno(), size,
                                 access=mmap.ACCESS_READ)
        return memoryview(self._mm)[seg.off:end]

    def read(self, seg_id: int) -> Tuple[np.ndarray, np.ndarray]:
        """(ids, rows) of one segment — ONE mmap slice, CRC-verified,
        rows dequantized to the table dtype."""
        with self._lock:
            seg = self._segs[seg_id]
            try:
                view = self._record_view(seg)
            except (OSError, ValueError) as e:
                self.crc_failures += 1
                raise core.SpillCorruptionError(
                    f"spill log {self.path} unreadable for segment "
                    f"{seg_id}: {e}") from e
            if (zlib.crc32(view) & 0xFFFFFFFF) != seg.crc:
                self.crc_failures += 1
                raise core.SpillCorruptionError(
                    f"spill segment {seg_id} of {self.path} failed its "
                    f"CRC check (torn write or bit rot) — refusing to "
                    f"serve its rows")
            ids = np.frombuffer(view, np.int64, seg.n_rows).copy()
            rows = decode_rows(view[seg.n_rows * 8:], seg.quant,
                               seg.n_rows, self.dim, self.dtype)
            self.reads += 1
            return ids, rows

    def read_record(self, seg_id: int) -> Tuple[bytes, _Seg]:
        """Verbatim (record bytes, directory entry) — the handoff/
        checkpoint stream leg. CRC-verified like ``read``."""
        with self._lock:
            seg = self._segs[seg_id]
            try:
                view = self._record_view(seg)
            except (OSError, ValueError) as e:
                self.crc_failures += 1
                raise core.SpillCorruptionError(
                    f"spill log {self.path} unreadable for segment "
                    f"{seg_id}: {e}") from e
            if (zlib.crc32(view) & 0xFFFFFFFF) != seg.crc:
                self.crc_failures += 1
                raise core.SpillCorruptionError(
                    f"spill segment {seg_id} of {self.path} failed its "
                    f"CRC check — refusing to export it")
            return bytes(view), seg

    # -- lifecycle --------------------------------------------------------
    def free(self, seg_id: int) -> None:
        """Drop a fully-promoted/shrunk segment; compact when dead
        bytes outweigh live ones."""
        with self._lock:
            seg = self._segs.pop(seg_id, None)
            if seg is None:
                return
            self._dead_bytes += seg.nbytes
            self._live_bytes -= seg.nbytes
            need_compact = (self._dead_bytes
                            > max(self._live_bytes, 1 << 20))
        if need_compact:
            self.compact()

    def compact(self) -> None:
        """Rewrite live segments into a fresh log (one segment in RAM
        at a time), dropping dead bytes. Directory offsets update;
        segment ids are stable, so table-side (seg, row) refs survive."""
        with self._lock:
            tmp_path = self.path + ".compact"
            tmp = open(tmp_path, "wb+")
            new_off = {}
            try:
                for sid, seg in self._segs.items():
                    view = self._record_view(seg)
                    try:
                        if (zlib.crc32(view) & 0xFFFFFFFF) != seg.crc:
                            self.crc_failures += 1
                            raise core.SpillCorruptionError(
                                f"spill segment {sid} of {self.path} "
                                f"failed its CRC during compaction — "
                                f"log abandoned")
                        new_off[sid] = tmp.tell()
                        tmp.write(view)
                    finally:
                        # an exported view would make the mmap close
                        # below raise BufferError
                        view.release()
            except BaseException:
                # any failure (CRC, truncated-log read) must not leak
                # the temp file or its fd
                tmp.close()
                try:
                    os.unlink(tmp_path)
                except OSError:
                    pass
                raise
            tmp.flush()
            if self._mm is not None:
                self._mm.close()
                self._mm = None
            self._f.close()
            os.replace(tmp_path, self.path)
            self._f = tmp
            for sid, off in new_off.items():
                self._segs[sid].off = off
            self._dead_bytes = 0
            self.compactions += 1

    def clear(self) -> None:
        """Drop EVERY segment and truncate the log in one step — the
        wholesale-replace path (``import_state``). Per-segment
        ``free()`` there would trip compaction repeatedly, rewriting
        segments that are about to be dropped anyway."""
        with self._lock:
            if self._mm is not None:
                self._mm.close()
                self._mm = None
            self._segs.clear()
            self._dead_bytes = 0
            self._live_bytes = 0
            self._f.seek(0)
            self._f.truncate()
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if self._mm is not None:
                self._mm.close()
                self._mm = None
            try:
                self._f.close()
            except Exception:
                pass

    def unlink(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass
        # the rebuild paths mkdtemp() a private "pt-…" dir per table
        # when no spill dir is configured — remove it once its log is
        # gone (rmdir refuses non-empty dirs, so a shared configured
        # dir is never touched; the prefix guard keeps us off any
        # user-named dir that happens to be empty)
        parent = os.path.dirname(self.path)
        if os.path.basename(parent).startswith("pt-"):
            try:
                os.rmdir(parent)
            except OSError:
                pass

    # -- introspection ----------------------------------------------------
    def segments(self) -> List[int]:
        with self._lock:
            return sorted(self._segs)

    def seg_meta(self, seg_id: int) -> Dict[str, Any]:
        with self._lock:
            return self._segs[seg_id].meta()

    def file_bytes(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def live_bytes(self) -> int:
        with self._lock:
            return self._live_bytes


# ===========================================================================
# section streams — the ONE serialization of a (possibly tiered) table.
#
# Section vocabulary (names are relative; callers prefix the var name):
#   tier:meta     json — table meta + tier config + layout (hot chunking,
#                 segment order + per-segment directory fields, live maps)
#   tier:hotids   int64 hot ids in LRU order (oldest first)
#   tier:hot:<k>  raw rows of hot chunk k, table dtype, LRU order
#   tier:seg:<j>  VERBATIM spill-log record of the j-th live segment
#   tier:state    gate/shrink state: score ids+f32 scores, freq ids+i64
#                 counts (empty arrays when tracking is off)
#
# Every section is bounded (hot chunks at HOT_CHUNK_ROWS, segments at the
# eviction batch size), so both producing and consuming sides stay
# RSS-bounded no matter how large the spilled table is.
# ===========================================================================
HOT_CHUNK_ROWS = 65536

# process-monotonic suffix for rebuilt spill logs (two rebuilds into one
# configured spill dir must never truncate each other's live log)
import itertools as _itertools  # noqa: E402
_REBUILD_SEQ = _itertools.count()


def merge_tier_stats(stats_list) -> Dict[str, Any]:
    """Aggregate tier_stats() dicts (across tables or across servers):
    numeric leaves sum, then the RATIO gauges — hit_rate, density_x —
    are recomputed from the summed counters (summed ratios are
    garbage). The ONE merge rule the pserver slab snapshot and the
    bench evidence scrape share."""
    agg: Dict[str, Any] = {}
    n = 0
    for s in stats_list:
        if not s:
            continue
        n += 1
        for k, v in s.items():
            if isinstance(v, (int, float)):
                agg[k] = agg.get(k, 0) + v
    if not n:
        return {}
    touches = agg.get("hits", 0) + agg.get("misses", 0)
    agg["hit_rate"] = round(agg.get("hits", 0) / touches, 4) \
        if touches else 0.0
    sp = agg.get("spilled_bytes", 0)
    agg["density_x"] = round(
        agg.get("logical_spilled_bytes", 0) / sp, 3) if sp else 0.0
    # second-level merges (bench over per-server aggregates) already
    # carry summed table counts — keep them; first-level merges count
    # the input dicts
    if "tables" not in agg:
        agg["tables"] = n
    return agg


def _pack_arrays(*arrays: np.ndarray) -> bytes:
    parts = []
    for a in arrays:
        a = np.ascontiguousarray(a)
        parts.append(np.int64(a.nbytes).tobytes())
        parts.append(a.tobytes())
    return b"".join(parts)


def _unpack_arrays(blob, specs) -> List[np.ndarray]:
    out, off = [], 0
    view = memoryview(blob)
    for dtype in specs:
        (nbytes,) = np.frombuffer(view, np.int64, 1, offset=off)
        off += 8
        out.append(np.frombuffer(view, np.dtype(dtype),
                                 int(nbytes) // np.dtype(dtype).itemsize,
                                 offset=off).copy())
        off += int(nbytes)
    return out


def table_sections(tbl, with_crc: bool = True
                   ) -> "OrderedDict[str, Dict[str, Any]]":
    """Streaming export of ANY LazyEmbeddingTable: an ordered map of
    section name → {"kind", "meta", "read"} where ``read()``
    regenerates the section's bytes on demand. With ``with_crc`` (the
    handoff path) per-section crc32/size are precomputed ONE bounded
    section at a time so the CRC manifest can be built without holding
    the payload; the checkpoint path passes False — its integrity is
    the manifest's whole-file CRC, and the per-section pass would
    encode+CRC the hot slab twice. Deterministic as long as the table
    is not mutated between the crc pass and the stream pass (the
    handoff holds the grad lock across both). Spill segments carry
    their directory crc/size either way (free, and verbatim bytes)."""
    tier = tbl._tier
    meta = dict(tbl.export_meta())
    sections: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()

    def _add(name, read_fn, kind="tier"):
        sec = {"kind": kind, "meta": {}, "read": read_fn}
        if with_crc:
            blob = read_fn()
            sec["size"] = len(blob)
            sec["crc32"] = zlib.crc32(blob) & 0xFFFFFFFF
        sections[name] = sec

    n_hot = len(tbl._index)
    hot_ids = np.fromiter(tbl._index.keys(), np.int64, n_hot)
    hot_slots = np.fromiter(tbl._index.values(), np.int64, n_hot)
    chunks = []
    for k in range(0, max(n_hot, 1), HOT_CHUNK_ROWS):
        lo, hi = k, min(k + HOT_CHUNK_ROWS, n_hot)
        chunks.append((lo, hi))

    seg_dir = []
    if tier is not None and tier.store is not None:
        # segment stream order = segment id order (append order); the
        # per-segment LIVE map (which record rows are still cold) rides
        # the meta so the rebuild can skip promoted-out rows
        live_by_seg: Dict[int, List[Tuple[int, int]]] = {}
        for rid, (sid, pos) in tier.cold.items():
            live_by_seg.setdefault(sid, []).append((pos, int(rid)))
        for sid in tier.store.segments():
            live = sorted(live_by_seg.get(sid, []))
            if not live:
                # backing-only segment: every ref is a CLEAN hot row,
                # whose value ships in the hot sections — the record
                # itself has nothing the destination needs
                continue
            sm = tier.store.seg_meta(sid)
            sm["sid"] = sid
            # run-length encode the live positions (fresh segments are
            # fully live = one run; promotions punch holes) — keeps
            # the manifest metadata O(runs), not O(spilled rows)
            runs: List[List[int]] = []
            for p, _ in live:
                if runs and p == runs[-1][0] + runs[-1][1]:
                    runs[-1][1] += 1
                else:
                    runs.append([p, 1])
            sm["live_runs"] = runs
            seg_dir.append(sm)

    meta["tier_layout"] = {
        "n_hot": int(n_hot),
        "hot_chunk_rows": HOT_CHUNK_ROWS,
        "hot_chunks": len(chunks) if n_hot else 0,
        "segments": seg_dir,
    }

    _add("tier:meta",
         lambda m=meta: json.dumps(m, sort_keys=True).encode(),
         kind="tier_meta")
    _add("tier:hotids", lambda a=hot_ids: a.tobytes())
    if n_hot:
        for k, (lo, hi) in enumerate(chunks):
            _add(f"tier:hot:{k}",
                 lambda lo=lo, hi=hi: np.ascontiguousarray(
                     tbl._data[hot_slots[lo:hi]]).tobytes())
    for sm in seg_dir:
        sid = sm["sid"]

        def _read_seg(sid=sid, crc=sm["crc"]):
            record, seg = tier.store.read_record(sid)
            return record

        sections[f"tier:seg:{sid}"] = {
            "kind": "tier", "meta": {},
            "size": int(sm["nbytes"]), "crc32": int(sm["crc"]),
            "read": _read_seg}

    def _read_state():
        sc_ids, sc_vals, fq_ids, fq_cnt = tbl._export_gate_state()
        return _pack_arrays(sc_ids, sc_vals, fq_ids, fq_cnt)

    _add("tier:state", _read_state)
    return sections


def build_table_from_sections(meta: Dict[str, Any],
                              section_bytes: Callable[[str], bytes],
                              spill_path: Optional[str] = None):
    """Rebuild a table from a ``table_sections`` stream. ``meta`` is the
    decoded ``tier:meta`` json; ``section_bytes(name)`` returns one
    section's payload (from staged files, a checkpoint stream, ...) —
    called one section at a time, so peak RSS is one bounded section
    plus the hot slab. ``spill_path`` overrides where the rebuilt
    table's spill log lives (required when the meta says tiered)."""
    from .core import LazyEmbeddingTable
    layout = meta["tier_layout"]
    tier = meta.get("tier") or {}
    kw = {}
    if tier:
        if tier.get("spilled") and not spill_path:
            # never reuse the SOURCE's log path (both processes may
            # share the box): configured spill dir, else a fresh
            # tempdir; a process-monotonic counter keeps concurrent
            # rebuilds in one dir from colliding
            import tempfile
            sdir = str(core.globals_["FLAGS_ps_slab_spill_dir"] or "") \
                or tempfile.mkdtemp(prefix="pt-slab-")
            spill_path = os.path.join(
                sdir,
                f"rebuild-{os.getpid()}-{next(_REBUILD_SEQ)}.slab")
        kw = dict(spill_path=spill_path if tier.get("spilled") else None,
                  hot_rows=int(tier.get("hot_rows", 0)),
                  at_rest_quant=tier.get("quant", ""),
                  entry_threshold=int(tier.get("entry_threshold", 0)),
                  spill_seg_rows=int(tier.get("seg_rows", 0)),
                  track_scores=tier.get("track_scores"))
    tbl = LazyEmbeddingTable(
        height=int(meta["height"]), dim=int(meta["dim"]),
        seed=int(meta["seed"]), scale=float(meta["scale"]),
        max_rows=meta.get("max_rows"), dtype=np.dtype(meta["dtype"]),
        **kw)
    try:
        tbl.evictions = int(meta.get("evictions", 0))

        n_hot = int(layout["n_hot"])
        hot_ids = np.frombuffer(section_bytes("tier:hotids"), np.int64)
        if len(hot_ids) != n_hot:
            raise core.SpillCorruptionError(
                f"slab stream: hot id section holds {len(hot_ids)} "
                f"ids, meta says {n_hot}")
        # hot slab, chunk at a time, LRU order preserved
        filled = 0
        for k in range(int(layout.get("hot_chunks", 0))):
            rows = np.frombuffer(section_bytes(f"tier:hot:{k}"),
                                 tbl.dtype).reshape(-1, tbl.dim)
            tbl._install_hot_rows(hot_ids[filled:filled + len(rows)],
                                  rows)
            filled += len(rows)
        if filled != n_hot:
            raise core.SpillCorruptionError(
                f"slab stream: hot chunks supplied {filled} rows, "
                f"meta says {n_hot}")
        # spilled segments, verbatim records
        for sm in layout.get("segments", []):
            record = section_bytes(f"tier:seg:{sm['sid']}")
            tbl._install_spilled_segment(record, sm)
        sc_ids, sc_vals, fq_ids, fq_cnt = _unpack_arrays(
            section_bytes("tier:state"),
            (np.int64, np.float32, np.int64, np.int64))
        tbl._import_gate_state(sc_ids, sc_vals, fq_ids, fq_cnt)
    except BaseException:
        # a rejected (torn/short) stream must not leak the partially
        # built table's fresh spill log — rejection is a tested,
        # RETRIED path
        tbl.close_spill(unlink=True)
        raise
    return tbl


# ---------------------------------------------------------------------------
# one-file section-stream container (io.save_checkpoint / save_persistables
# of a slab table): MAGIC, then per section u32 name_len | name |
# u64 payload_len | payload, in table_sections order. Self-delimiting;
# whole-file integrity rides the checkpoint manifest's crc32/size like any
# other tensor blob.
# ---------------------------------------------------------------------------
SLAB_STREAM_MAGIC = b"PTSLAB01"


def write_section_stream(fobj, sections) -> Tuple[int, int]:
    """Stream ``table_sections`` output into ``fobj`` one section at a
    time. Returns (crc32, size) of everything written — computed
    incrementally, so a spilled table checkpoints at O(one section)
    peak RSS."""
    import struct
    crc = zlib.crc32(SLAB_STREAM_MAGIC)
    size = len(SLAB_STREAM_MAGIC)
    fobj.write(SLAB_STREAM_MAGIC)
    for name, sec in sections.items():
        payload = sec["read"]()
        nm = name.encode()
        head = struct.pack("<I", len(nm)) + nm + \
            struct.pack("<Q", len(payload))
        fobj.write(head)
        fobj.write(payload)
        crc = zlib.crc32(head, crc)
        crc = zlib.crc32(payload, crc)
        size += len(head) + len(payload)
    return crc & 0xFFFFFFFF, size


def scan_section_headers(fobj) -> Iterable[Tuple[str, int, int]]:
    """Yield (name, payload_offset, payload_len) from a
    ``write_section_stream`` file, SEEKING past payloads — the one
    framing parser both the streaming iterator and the on-demand
    loader build on. Torn framing surfaces as the typed
    ``core.SpillCorruptionError`` (the corruption contract), never a
    bare struct/decode error."""
    import struct
    magic = fobj.read(len(SLAB_STREAM_MAGIC))
    if magic != SLAB_STREAM_MAGIC:
        raise core.SpillCorruptionError(
            "slab stream: bad magic — not a slab-table section stream")
    while True:
        head = fobj.read(4)
        if not head:
            return
        try:
            (nlen,) = struct.unpack("<I", head)
            if nlen > 4096:
                # section names are tens of bytes; a huge length is a
                # corrupt header — reading it would slurp the file
                raise core.SpillCorruptionError(
                    f"slab stream: absurd section-name length {nlen} "
                    f"(corrupt header)")
            name = fobj.read(nlen).decode()
            (plen,) = struct.unpack("<Q", fobj.read(8))
        except (struct.error, UnicodeDecodeError) as e:
            raise core.SpillCorruptionError(
                f"slab stream: torn section header ({e})") from e
        off = fobj.tell()
        fobj.seek(0, os.SEEK_END)
        end = fobj.tell()
        if off + plen > end:
            raise core.SpillCorruptionError(
                f"slab stream: section {name!r} truncated "
                f"({end - off}/{plen} bytes)")
        yield name, off, plen
        fobj.seek(off + plen)


def iter_section_stream(fobj) -> Iterable[Tuple[str, bytes]]:
    """Yield (name, payload) from a ``write_section_stream`` file, one
    section in RAM at a time."""
    for name, off, plen in scan_section_headers(fobj):
        fobj.seek(off)
        payload = fobj.read(plen)
        fobj.seek(off + plen)
        yield name, payload
