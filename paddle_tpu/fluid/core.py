"""Runtime core for paddle_tpu — the TPU-native equivalent of the reference's
pybind ``core`` extension module (reference: paddle/fluid/pybind/pybind.cc).

Where the reference exposes C++ Tensor/Scope/Executor objects backed by CUDA
allocations, this module backs the same API surface with ``jax.Array`` device
buffers managed by the XLA runtime: allocation, layout, and device transfer
are the compiler/runtime's job (reference memory/allocation/* is absorbed by
XLA — see SURVEY.md §2.1 "TPU mapping notes").

Contents:
  * VarDesc.VarType dtype enum (wire values match framework.proto:104).
  * Places: CPUPlace / TPUPlace (+ CUDAPlace compat alias → TPU).
  * LoDTensor / SelectedRows / LoDTensorArray runtime containers
    (reference: framework/lod_tensor.h:104, selected_rows.h:32).
  * Variable / Scope (reference: framework/variable.h:26, scope.h:46).
  * global flag registry (reference: platform/flags.cc ``FLAGS_*``).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from .proto import framework_pb2

__all__ = [
    "VarDesc", "CPUPlace", "TPUPlace", "CUDAPlace", "CUDAPinnedPlace",
    "Place", "LoDTensor", "Tensor", "SelectedRows", "LoDTensorArray",
    "LazyEmbeddingTable",
    "Variable", "Scope", "globals_", "get_flag", "set_flag",
    "dtype_to_np", "np_to_dtype", "dtype_to_jnp", "is_float_dtype",
    "is_compiled_with_tpu", "EOFException", "WorkerDeadError",
    "RpcProtocolError", "CheckpointError", "NumericFaultError",
    "StaleClusterViewError",
]


class EOFException(Exception):
    """Raised by non-iterable DataLoader/PyReader ``next()`` when the
    underlying generator is drained (reference: the C++ reader's
    EnforceNotMet-EOF that ``exe.run`` surfaces in the py_reader loop;
    the user catches it, calls ``reader.reset()`` and starts the next
    epoch)."""


class WorkerDeadError(RuntimeError):
    """A collective operation (barrier / reduce) released because a
    participant was declared dead by the pserver's HeartBeatMonitor —
    survivors get this promptly (≈ the heartbeat timeout) instead of
    blocking for the full barrier deadline. The message names the dead
    worker id(s) so launchers can act (docs/FAULT_TOLERANCE.md)."""


class RpcProtocolError(ConnectionError):
    """The RPC wire framing is invalid — e.g. a length prefix beyond
    FLAGS_rpc_max_message_size (garbage or malicious peer). Never
    retried: retry applies to transient transport failures, not to a
    corrupted protocol stream."""


class CheckpointError(RuntimeError):
    """A checkpoint directory failed validation (missing manifest,
    missing files, size/CRC mismatches) or load_vars found missing
    files. The message aggregates EVERY bad file, not just the first."""


class StaleClusterViewError(RuntimeError):
    """A PS data RPC reached a server that no longer owns the shard —
    the pserver drained/handed its state off (or is a standby that has
    not been promoted), and the client's ClusterView is stale. Carries
    the server's current view as a plain dict in ``view_dict`` (None
    when the server itself has no newer view, e.g. an unpromoted
    standby); the RPC client installs it and replays the SAME encoded
    frame — same dedup token — against the new owner, so exactly-once
    application survives the re-route (docs/FAULT_TOLERANCE.md
    "Elastic membership")."""

    def __init__(self, msg: str, view=None):
        super().__init__(msg)
        self.view_dict = view


class NumericFaultError(FloatingPointError):
    """The numeric fault plane (FLAGS_check_nan_inf +
    FLAGS_nan_inf_action — docs/FAULT_TOLERANCE.md "Numeric faults")
    could not contain a NaN/Inf: rollback retries exhausted, no intact
    checkpoint to roll back to, or a tripped step the raise-mode
    localizer could not reproduce. Subclasses FloatingPointError so
    pre-existing FLAGS_check_nan_inf handlers keep catching it."""


class DeadlineExceededError(TimeoutError):
    """A request's propagated deadline expired before the work finished
    (docs/SERVING.md "Ingress & overload"): the serving ingress stamps
    each request with a budget, and queue wait, bucket dispatch, and PS
    row fetches (``ps_rpc.call_budget``) all check the remaining budget
    — an expired request surfaces this typed error (HTTP 504) instead
    of holding a worker or an RPC channel. Subclasses TimeoutError so
    pre-existing timeout handling keeps catching it. ``queue_wait_s``
    carries the time the request sat admitted-but-undispatched when the
    expiry happened in the queue."""

    def __init__(self, msg: str, queue_wait_s: float = None):
        super().__init__(msg)
        self.queue_wait_s = queue_wait_s


class OverloadedError(RuntimeError):
    """The serving admission plane shed this request (HTTP 429): the
    bounded admission queue is full, the token-bucket rate gate refused
    it, or the CoDel-style oldest-drop evicted it to keep accepted-
    request p99 bounded under sustained overload. ``retry_after_s`` is
    the server's drain-time estimate from its rolling QPS/latency stats
    — monotone in queue depth, so a well-behaved client backs off
    harder the deeper the overload (docs/SERVING.md)."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class CircuitOpenError(ConnectionError):
    """A per-endpoint circuit breaker (fluid/ps_rpc.py, enabled by
    FLAGS_rpc_circuit_breaker) is OPEN for this pserver endpoint:
    recent calls died with transport/typed worker-dead errors, so new
    calls fail fast instead of burning their deadline against a dead
    server. Serving's sparse path catches it (with the other transport
    errors) and flips into serve-stale degraded mode; the breaker
    half-opens after FLAGS_rpc_breaker_reset_s and one probe call
    closes it again (docs/SERVING.md "Ingress & overload")."""


# --------------------------------------------------------------------------
# dtypes
# --------------------------------------------------------------------------
class _VarTypeEnum:
    """Mirror of framework.proto VarType.Type values (framework.proto:104)."""
    BOOL = framework_pb2.VarType.BOOL
    INT16 = framework_pb2.VarType.INT16
    INT32 = framework_pb2.VarType.INT32
    INT64 = framework_pb2.VarType.INT64
    FP16 = framework_pb2.VarType.FP16
    FP32 = framework_pb2.VarType.FP32
    FP64 = framework_pb2.VarType.FP64
    SIZE_T = framework_pb2.VarType.SIZE_T
    UINT8 = framework_pb2.VarType.UINT8
    INT8 = framework_pb2.VarType.INT8
    BF16 = framework_pb2.VarType.BF16

    LOD_TENSOR = framework_pb2.VarType.LOD_TENSOR
    SELECTED_ROWS = framework_pb2.VarType.SELECTED_ROWS
    FEED_MINIBATCH = framework_pb2.VarType.FEED_MINIBATCH
    FETCH_LIST = framework_pb2.VarType.FETCH_LIST
    STEP_SCOPES = framework_pb2.VarType.STEP_SCOPES
    LOD_RANK_TABLE = framework_pb2.VarType.LOD_RANK_TABLE
    LOD_TENSOR_ARRAY = framework_pb2.VarType.LOD_TENSOR_ARRAY
    PLACE_LIST = framework_pb2.VarType.PLACE_LIST
    READER = framework_pb2.VarType.READER
    RAW = framework_pb2.VarType.RAW
    TUPLE = framework_pb2.VarType.TUPLE


class VarDesc:
    VarType = _VarTypeEnum


_DTYPE_TO_NP = {
    _VarTypeEnum.BOOL: np.bool_,
    _VarTypeEnum.INT16: np.int16,
    _VarTypeEnum.INT32: np.int32,
    _VarTypeEnum.INT64: np.int64,
    _VarTypeEnum.FP16: np.float16,
    _VarTypeEnum.FP32: np.float32,
    _VarTypeEnum.FP64: np.float64,
    _VarTypeEnum.UINT8: np.uint8,
    _VarTypeEnum.INT8: np.int8,
}

_NP_TO_DTYPE = {np.dtype(v): k for k, v in _DTYPE_TO_NP.items()}
_NP_TO_DTYPE[np.dtype("bfloat16") if hasattr(np, "bfloat16") else jnp.bfloat16] = _VarTypeEnum.BF16

_STR_TO_DTYPE = {
    "bool": _VarTypeEnum.BOOL,
    "int16": _VarTypeEnum.INT16,
    "int32": _VarTypeEnum.INT32,
    "int64": _VarTypeEnum.INT64,
    "float16": _VarTypeEnum.FP16,
    "bfloat16": _VarTypeEnum.BF16,
    "float32": _VarTypeEnum.FP32,
    "float64": _VarTypeEnum.FP64,
    "uint8": _VarTypeEnum.UINT8,
    "int8": _VarTypeEnum.INT8,
}


def convert_np_dtype_to_dtype_(np_dtype) -> int:
    if isinstance(np_dtype, int):
        return np_dtype
    if isinstance(np_dtype, str):
        return _STR_TO_DTYPE[np_dtype]
    d = np.dtype(np_dtype) if not isinstance(np_dtype, np.dtype) else np_dtype
    if d in _NP_TO_DTYPE:
        return _NP_TO_DTYPE[d]
    if str(d) == "bfloat16":
        return _VarTypeEnum.BF16
    raise ValueError(f"unsupported numpy dtype {np_dtype}")


def np_to_dtype(np_dtype) -> int:
    return convert_np_dtype_to_dtype_(np_dtype)


def dtype_to_np(dtype: int):
    if dtype == _VarTypeEnum.BF16:
        return jnp.bfloat16
    return _DTYPE_TO_NP[dtype]


def dtype_to_jnp(dtype: int):
    """Device-side dtype. TPU-native narrowing: INT64→int32, FP64→float32
    (XLA on TPU has no fast 64-bit path; host serialization via dtype_to_np
    keeps the declared width)."""
    if dtype == _VarTypeEnum.BF16:
        return jnp.bfloat16
    if dtype == _VarTypeEnum.INT64:
        return jnp.int32
    if dtype == _VarTypeEnum.FP64:
        return jnp.float32
    return jnp.dtype(_DTYPE_TO_NP[dtype])


def is_float_dtype(dtype: int) -> bool:
    return dtype in (_VarTypeEnum.FP16, _VarTypeEnum.BF16, _VarTypeEnum.FP32,
                     _VarTypeEnum.FP64)


# --------------------------------------------------------------------------
# Places — device abstraction (reference: platform/place.h:26-79)
# --------------------------------------------------------------------------
class Place:
    """Base place."""
    def __eq__(self, other):
        return type(self) is type(other) and getattr(self, "_device_id", 0) == \
            getattr(other, "_device_id", 0)

    def __hash__(self):
        return hash((type(self).__name__, getattr(self, "_device_id", 0)))


class CPUPlace(Place):
    def __repr__(self):
        return "CPUPlace"

    def jax_device(self):
        # local_devices, not devices: in multi-process mode the global
        # list starts with process 0's devices — placing host data there
        # from another rank would create a non-addressable array
        try:
            return jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            return jax.local_devices()[0]


class TPUPlace(Place):
    """The accelerator place. On a CPU-only host (tests) it degrades to the
    default jax device, so programs written against TPUPlace run anywhere."""
    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def __repr__(self):
        return f"TPUPlace({self._device_id})"

    def get_device_id(self):
        return self._device_id

    def jax_device(self):
        devs = jax.local_devices()
        return devs[self._device_id % len(devs)]


# Compatibility alias: reference scripts say CUDAPlace; on this framework that
# means "the accelerator", i.e. the TPU chip of that ordinal.
CUDAPlace = TPUPlace


class CUDAPinnedPlace(CPUPlace):
    def __repr__(self):
        return "CUDAPinnedPlace"


def is_compiled_with_tpu() -> bool:
    """Accelerator probe. Exception-safe: a broken TPU backend (dead
    tunnel plugin raising at init) reports False instead of propagating,
    so `import paddle_tpu` and CPU-path scripts survive a bad backend
    (round-1 BENCH failure mode)."""
    try:
        return any(d.platform != "cpu" for d in jax.devices())
    except Exception:
        return False


def is_compiled_with_cuda() -> bool:
    # CUDA never exists here; scripts gating on this will take the CPU path,
    # so report accelerator presence instead for behavioural parity.
    return is_compiled_with_tpu()


def start_forked_quietly(procs):
    """Start fork-context worker processes with the fork-under-threads
    warnings suppressed: fork is deliberate at these call sites (reader
    closures can't be pickled for spawn) and the children never touch
    JAX, so an inherited JAX-internal lock can't deadlock them."""
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        warnings.simplefilter("ignore", RuntimeWarning)
        for p in procs:
            p.start()


def _as_place(place) -> Place:
    if place is None:
        return TPUPlace(0) if is_compiled_with_tpu() else CPUPlace()
    return place


# --------------------------------------------------------------------------
# Tensors
# --------------------------------------------------------------------------
def _to_device_array(data, place: Optional[Place] = None, dtype=None):
    if isinstance(data, jax.Array) and dtype is None:
        return data
    arr = np.asarray(data, dtype=dtype)
    # Device integer policy: 32-bit. TPU has no native int64 ALU path and
    # jax runs x64-off, so 64-bit feeds are cast explicitly here (instead
    # of leaking a per-call truncation warning from jax); the executor's
    # fetch boundary restores the program-declared int64 dtype, so user
    # code still sees the reference's int64 contracts (e.g. sequence_pad
    # Length — reference sequence_pad_op.cc).
    if not jax.config.jax_enable_x64 and arr.dtype in (np.int64, np.uint64):
        tgt = np.int32 if arr.dtype == np.int64 else np.uint32
        info = np.iinfo(tgt)
        if arr.size and (int(arr.min()) < info.min
                         or int(arr.max()) > info.max):
            # astype would WRAP (e.g. a 64-bit hashed CTR feature id
            # becoming a negative row index) — that corruption is silent
            # and unrecoverable at the fetch boundary, so refuse. Feeds
            # carrying genuine 64-bit ids belong on the host-side PS
            # lookup path (distributed_lookup_table), not on-device.
            raise ValueError(
                f"int64/uint64 feed value out of {np.dtype(tgt).name} "
                f"range (min={arr.min()}, max={arr.max()}): the device "
                "integer width is 32-bit (TPU has no native int64 path). "
                "Route >32-bit ids through the parameter-server lookup "
                "(distributed_lookup_table) or pre-hash them below 2^31.")
        arr = arr.astype(tgt)
    if place is None:
        return jnp.asarray(arr)
    return jax.device_put(arr, _as_place(place).jax_device())


class LoDTensor:
    """Dense tensor + level-of-detail offsets for ragged sequence batches
    (reference: framework/lod_tensor.h:104). The buffer is a jax.Array; LoD is
    host-side metadata (TPU kernels consume padded/packed forms, the LoD
    records the ragged structure)."""

    __slots__ = ("_array", "_lod")

    def __init__(self, array=None, lod: Optional[List[List[int]]] = None):
        self._array = array
        self._lod = [list(l) for l in lod] if lod else []

    # -- reference API surface -------------------------------------------
    def set(self, np_array, place=None):
        self._array = _to_device_array(np_array, place)

    def set_lod(self, lod):
        self._lod = [list(l) for l in lod]

    def lod(self):
        return [list(l) for l in self._lod]

    def set_recursive_sequence_lengths(self, seq_lens):
        # lengths [[2,3]] -> offsets [[0,2,5]]
        lod = []
        for lens in seq_lens:
            offs = [0]
            for ln in lens:
                offs.append(offs[-1] + int(ln))
            lod.append(offs)
        self._lod = lod

    def recursive_sequence_lengths(self):
        out = []
        for offs in self._lod:
            out.append([offs[i + 1] - offs[i] for i in range(len(offs) - 1)])
        return out

    def has_valid_recursive_sequence_lengths(self):
        if not self._lod:
            return True
        n = self._array.shape[0] if self._array is not None else 0
        return self._lod[-1][-1] == n

    def shape(self):
        return list(self._array.shape) if self._array is not None else []

    def _dtype(self):
        return self._array.dtype if self._array is not None else None

    def __array__(self, dtype=None):
        a = np.asarray(self._array)
        return a.astype(dtype) if dtype is not None else a

    def numpy(self):
        return np.asarray(self._array)

    @property
    def array(self):
        return self._array

    def __len__(self):
        return int(self._array.shape[0]) if self._array is not None else 0

    def __repr__(self):
        return f"LoDTensor(shape={self.shape()}, lod={self._lod})"


Tensor = LoDTensor


class SelectedRows:
    """Sparse row-set tensor: a value tensor whose i-th row corresponds to
    logical row ``rows[i]`` of a [height, ...] dense tensor (reference:
    framework/selected_rows.h:32). Used for embedding gradients and the
    sparse parameter-server path."""

    __slots__ = ("_rows", "_height", "_value")

    def __init__(self, rows=None, height: int = 0):
        self._rows = list(rows) if rows is not None else []
        self._height = int(height)
        self._value = LoDTensor()

    def rows(self):
        return self._rows

    def set_rows(self, rows):
        self._rows = [int(r) for r in rows]

    def height(self):
        return self._height

    def set_height(self, h):
        self._height = int(h)

    def get_tensor(self) -> LoDTensor:
        return self._value

    def sync_index(self):
        pass

    def to_dense(self) -> jnp.ndarray:
        val = self._value.array
        dense = jnp.zeros((self._height,) + tuple(val.shape[1:]), val.dtype)
        return dense.at[jnp.asarray(self._rows, jnp.int32)].add(val)

    def __repr__(self):
        return f"SelectedRows(height={self._height}, nrows={len(self._rows)})"


class LoDTensorArray(list):
    """reference: framework/lod_tensor_array.h — a std::vector<LoDTensor>."""
    pass


class LazyEmbeddingTable:
    """Beyond-HBM host-RAM embedding table for the sparse PS path
    (reference: framework/fleet/fleet_wrapper.h:86-190 — DownpourSparseTable
    pull creates features on first touch; memory is bounded by feature
    count, not by the logical [height, dim] shape, and features can be
    evicted/shrunk).

    Rows materialize on first access with a deterministic per-row init, so
    a 1e9-parameter logical table costs only O(touched rows) memory; an
    optional LRU bound evicts least-recently-used rows (an evicted, later
    re-touched row re-initializes — the reference's shrink() makes the
    same trade).

    Storage is a CONTIGUOUS slab (``_data``) plus an id→slot index, so
    the PS-plane hot paths are vectorized: ``get_rows`` is one
    fancy-index gather and ``apply_grad`` one ``np.subtract.at`` scatter
    — per-id python work is a single dict lookup, not a per-row
    stack/astype (the pserver applies thousands of rows per step on the
    wide_deep lanes; docs/PS_DATA_PLANE.md)."""

    __slots__ = ("height", "dim", "dtype", "seed", "scale", "max_rows",
                 "_index", "_data", "_free", "evictions")

    def __init__(self, height: int, dim: int, seed: int = 0,
                 scale: Optional[float] = None, max_rows: Optional[int] = None,
                 dtype=np.float32):
        from collections import OrderedDict
        self.height = int(height)
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.seed = int(seed)
        self.scale = float(scale) if scale is not None \
            else 1.0 / float(np.sqrt(dim))
        self.max_rows = int(max_rows) if max_rows else None
        # id -> slot in _data; insertion order doubles as LRU order when
        # max_rows bounds the table
        self._index: "OrderedDict[int, int]" = OrderedDict()
        self._data = np.empty((0, self.dim), self.dtype)
        self._free: list = []  # recycled slots of evicted rows
        self.evictions = 0

    def _init_row(self, r: int) -> np.ndarray:
        rs = np.random.RandomState((self.seed * 1000003 + int(r))
                                   % (2 ** 31 - 1))
        return rs.uniform(-self.scale, self.scale,
                          self.dim).astype(self.dtype)

    def _alloc(self, r: int) -> int:
        """Materialize row ``r``: claim a slot (recycled or new, growing
        the slab by doubling), init deterministically, LRU-evict."""
        n_alloc = len(self._index) + len(self._free)
        s = self._free.pop() if self._free else n_alloc
        if s >= len(self._data):
            cap = max(1024, 2 * len(self._data))
            grown = np.empty((cap, self.dim), self.dtype)
            grown[:len(self._data)] = self._data
            self._data = grown
        self._data[s] = self._init_row(r)
        self._index[r] = s
        if self.max_rows is not None and len(self._index) > self.max_rows:
            _evicted, old_slot = self._index.popitem(last=False)  # LRU out
            self._free.append(old_slot)
            self.evictions += 1
        return s

    def _slots_of(self, ids: np.ndarray) -> list:
        """Slot per id, materializing misses (UNBOUNDED tables only —
        slots stay valid for the whole batch because nothing evicts).
        One dict hit per id."""
        get = self._index.get
        alloc = self._alloc
        return [s if (s := get(r)) is not None else alloc(r)
                for r in ids.tolist()]

    def _slot_of_bounded(self, r: int) -> int:
        s = self._index.get(r)
        if s is None:
            return self._alloc(r)
        self._index.move_to_end(r)
        return s

    def get_rows(self, ids) -> np.ndarray:
        ids = np.asarray(ids).reshape(-1)
        if not len(ids):
            return np.zeros((0, self.dim), self.dtype)
        if self.max_rows is None:
            slots = self._slots_of(ids)  # FIRST: may grow/replace _data
            return self._data[slots]
        # bounded table: an eviction later in THIS batch may recycle an
        # earlier id's slot — copy each row at touch time (the dict
        # implementation's semantics) instead of batch-gathering stale
        # slot numbers
        out = np.empty((len(ids), self.dim), self.dtype)
        for i, r in enumerate(ids.tolist()):
            s = self._slot_of_bounded(r)  # FIRST: may grow/replace _data
            out[i] = self._data[s]
        return out

    def apply_grad(self, ids, grads, lr: float) -> None:
        """Row-wise SGD: rows[id] -= lr * grad (duplicate ids accumulate,
        in id order — one vectorized scatter for unbounded tables)."""
        ids = np.asarray(ids).reshape(-1)
        if not len(ids):
            return
        grads = np.asarray(grads).reshape(len(ids), self.dim)
        step = (lr * grads).astype(self.dtype, copy=False)
        if self.max_rows is None:
            slots = np.asarray(self._slots_of(ids), np.int64)
            np.subtract.at(self._data, slots, step)
            return
        # bounded: apply at touch time so a later in-batch eviction
        # can't scatter into a recycled slot
        for i, r in enumerate(ids.tolist()):
            s = self._slot_of_bounded(r)  # FIRST: may grow/replace _data
            self._data[s] -= step[i]

    # -- handoff (elastic membership, docs/FAULT_TOLERANCE.md) ------------
    def export_state(self):
        """Snapshot for a CRC-manifested shard handoff: (meta, ids,
        rows). ``ids`` lists materialized row ids in LRU order (oldest
        first — OrderedDict insertion order IS the eviction order) and
        ``rows`` their current values, so ``import_state`` on the
        destination rebuilds a bit-identical table INCLUDING future
        eviction decisions. Never-touched rows don't ship: they
        re-materialize from the same deterministic per-row init."""
        n = len(self._index)
        ids = np.fromiter(self._index.keys(), np.int64, n)
        slots = np.fromiter(self._index.values(), np.int64, n)
        rows = (self._data[slots] if n
                else np.empty((0, self.dim), self.dtype))
        meta = {"height": self.height, "dim": self.dim, "seed": self.seed,
                "scale": self.scale, "max_rows": self.max_rows,
                "dtype": self.dtype.str, "evictions": self.evictions}
        return meta, ids, np.ascontiguousarray(rows)

    @classmethod
    def from_state(cls, meta, ids, rows) -> "LazyEmbeddingTable":
        tbl = cls(height=int(meta["height"]), dim=int(meta["dim"]),
                  seed=int(meta["seed"]), scale=float(meta["scale"]),
                  max_rows=meta.get("max_rows"),
                  dtype=np.dtype(meta["dtype"]))
        tbl.import_state(ids, rows)
        tbl.evictions = int(meta.get("evictions", 0))
        return tbl

    def import_state(self, ids, rows) -> None:
        """Install a handoff snapshot wholesale (replaces any current
        content). Rows land compacted in the given order, which
        ``export_state`` guarantees is the source's LRU order."""
        from collections import OrderedDict
        ids = np.asarray(ids, np.int64).reshape(-1)
        rows = np.asarray(rows, self.dtype).reshape(len(ids), self.dim)
        self._index = OrderedDict(
            (int(r), i) for i, r in enumerate(ids.tolist()))
        self._data = np.array(rows, self.dtype, copy=True)
        self._free = []

    # -- introspection ----------------------------------------------------
    def touched_rows(self) -> int:
        return len(self._index)

    def nbytes(self) -> int:
        return len(self._index) * self.dim * self.dtype.itemsize

    def logical_params(self) -> int:
        return self.height * self.dim

    def __repr__(self):
        return (f"LazyEmbeddingTable(height={self.height}, dim={self.dim}, "
                f"touched={len(self._index)}, evictions={self.evictions})")


class LoDRankTable:
    """reference: framework/lod_rank_table.h — sequences of one LoD level
    sorted by length descending; items are (index, length)."""

    __slots__ = ("items", "level")

    def __init__(self, items=None, level=0):
        self.items = list(items or [])  # [(seq_index, length), ...]
        self.level = level

    def __repr__(self):
        return f"LoDRankTable({self.items})"


# --------------------------------------------------------------------------
# Variable / Scope (reference: framework/variable.h:26, scope.h:46)
# --------------------------------------------------------------------------
class Variable:
    """Any-container runtime variable."""

    __slots__ = ("_holder",)

    def __init__(self):
        self._holder = None

    def get_tensor(self) -> LoDTensor:
        if self._holder is None:
            self._holder = LoDTensor()
        if not isinstance(self._holder, LoDTensor):
            raise TypeError(f"variable holds {type(self._holder).__name__}")
        return self._holder

    def get_selected_rows(self) -> SelectedRows:
        if self._holder is None:
            self._holder = SelectedRows()
        return self._holder

    def get_lod_tensor_array(self) -> LoDTensorArray:
        if self._holder is None:
            self._holder = LoDTensorArray()
        return self._holder

    def get_lod_rank_table(self) -> "LoDRankTable":
        if self._holder is None:
            self._holder = LoDRankTable()
        return self._holder

    def set_value(self, v):
        self._holder = v

    def value(self):
        return self._holder

    def is_initialized(self):
        h = self._holder
        if h is None:
            return False
        if isinstance(h, LoDTensor):
            return h.array is not None
        return True


class Scope:
    """Hierarchical name → Variable map with child scopes."""

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, Variable] = {}
        self._parent = parent
        self._kids: List[Scope] = []
        self._lock = threading.Lock()

    def var(self, name: str) -> Variable:
        with self._lock:
            v = self._vars.get(name)
            if v is None:
                v = Variable()
                self._vars[name] = v
            return v

    def find_var(self, name: str) -> Optional[Variable]:
        s: Optional[Scope] = self
        while s is not None:
            v = s._vars.get(name)
            if v is not None:
                return v
            s = s._parent
        return None

    def erase(self, name: str):
        self._vars.pop(name, None)

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids.clear()

    def local_var_names(self):
        return list(self._vars.keys())

    def __contains__(self, name):
        return self.find_var(name) is not None


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def _switch_scope(scope: Scope) -> Scope:
    global _global_scope
    old = _global_scope
    _global_scope = scope
    return old


# --------------------------------------------------------------------------
# FLAGS — env-backed global config (reference: platform/flags.cc, the ~106
# gflags settable via FLAGS_* env and pybind global_value_getter_setter.cc)
# --------------------------------------------------------------------------
class _GlobalFlags:
    _DEFAULTS: Dict[str, Any] = {
        "FLAGS_check_nan_inf": False,
        # what the numeric fault plane DOES when FLAGS_check_nan_inf
        # finds a non-finite step (docs/FAULT_TOLERANCE.md "Numeric
        # faults"):
        #   raise    — localize the first bad op/var and raise
        #              FloatingPointError (the reference
        #              nan_inf_utils behavior)
        #   skip     — fused discard: params/optimizer state select
        #              back to their pre-step values ON DEVICE and
        #              training continues (zero host syncs on the
        #              happy path)
        #   rollback — skip + count consecutive bad steps; after
        #              FLAGS_nan_inf_tolerance of them restore the
        #              last intact PR-3 checkpoint (bit-exact, rng
        #              counters included), at most
        #              FLAGS_nan_inf_max_rollbacks times before a
        #              typed core.NumericFaultError
        "FLAGS_nan_inf_action": "raise",
        "FLAGS_nan_inf_tolerance": 3,
        "FLAGS_nan_inf_max_rollbacks": 2,
        # pserver-side guard (VarServer/listen_and_serv): what to do
        # with a non-finite sparse grad row or dense update —
        # "" (off, apply as-is) | "drop" (discard the bad rows/update,
        # count it) | "reject" (raise NumericFaultError back to the
        # sending trainer). Trip counters ride the built-in "stats"
        # RPC under the "health" key.
        "FLAGS_ps_reject_nonfinite": "",
        # elastic PS membership plane (docs/FAULT_TOLERANCE.md "Elastic
        # membership"): replica count per pserver slot — 2 means every
        # applied update chain-forwards to a warm standby that the
        # dead-primary listener promotes, so trainers fail over instead
        # of aborting with WorkerDeadError. 1 (default) = no replication.
        "FLAGS_ps_replicas": 1,
        # how long a client-side sender (Communicator requeue, failover
        # reconnects) keeps retrying toward a slot whose primary is
        # unreachable before giving up, in seconds — covers the
        # promotion window (~2× the heartbeat timeout) with slack
        "FLAGS_ps_failover_deadline": 60.0,
        # drain: how long the source pserver waits for the in-flight
        # sync round to quiesce (pending grads applied, barrier empty)
        # before aborting the drain with the source still serving
        "FLAGS_ps_drain_quiesce_deadline": 60.0,
        "FLAGS_cpu_deterministic": False,
        "FLAGS_benchmark": False,
        "FLAGS_eager_delete_tensor_gb": 0.0,
        "FLAGS_allocator_strategy": "xla",  # allocation is XLA's job on TPU
        "FLAGS_fraction_of_gpu_memory_to_use": 1.0,
        "FLAGS_paddle_num_threads": 1,
        "FLAGS_use_pinned_memory": True,
        # RPC fault tolerance (fluid/ps_rpc.py VarClient.call): per-call
        # deadline in MILLISECONDS (reference FLAGS_rpc_deadline), and how
        # many times a transient ConnectionError/OSError is retried with
        # exponential backoff + reconnect before surfacing
        "FLAGS_rpc_deadline": 180000,
        "FLAGS_rpc_retry_times": 3,
        # wire-framing guard: a length prefix beyond this raises
        # RpcProtocolError instead of attempting a giant allocation
        # (default 1 GiB — generous; real payloads are var-sized blobs).
        # Applies to BOTH frame parts of the binary wire (pickled header
        # and the declared raw-buffer total).
        "FLAGS_rpc_max_message_size": 1 << 30,
        # per-endpoint circuit breaker (serving ingress robustness,
        # docs/SERVING.md "Ingress & overload"): OFF by default — the
        # training planes rely on the PR 3 retry ladder + PR 6 failover
        # and must not fast-fail. Serving processes flip it on so a
        # dead pserver costs ONE deadline-bounded failure per endpoint
        # instead of every request's full retry ladder; while open,
        # calls raise CircuitOpenError immediately and the sparse path
        # serves stale cache rows flagged degraded.
        "FLAGS_rpc_circuit_breaker": False,
        # consecutive transport/worker-dead failures that trip an
        # endpoint's breaker OPEN
        "FLAGS_rpc_breaker_failures": 3,
        # how long an OPEN breaker waits before letting ONE half-open
        # probe call through (success closes it, failure re-opens)
        "FLAGS_rpc_breaker_reset_s": 5.0,
        # data-plane connection pool: how many sockets VarClient keeps
        # per endpoint so concurrent RPCs (sharded lookup fan-out,
        # communicator flushes) don't serialize on one connection
        # (reference: grpc_client.h FLAGS_rpc_client_threads /
        # channel-per-call overlap in parameter_prefetch.cc)
        "FLAGS_rpc_channels_per_endpoint": 2,
        # how long a pserver-side collective (sync barrier / reduce) waits
        # for stragglers before raising TimeoutError, in seconds; a DEAD
        # participant releases much earlier with WorkerDeadError
        "FLAGS_barrier_deadline": 300.0,
        # Communicator.stop(): how long to wait for each merge thread to
        # drain before logging a warning and moving on
        "FLAGS_communicator_join_timeout": 1.0,
        # async overlap plane (docs/PS_DATA_PLANE.md "Async overlap"):
        # how many UNACKNOWLEDGED sync rounds a trainer may keep in
        # flight while it computes ahead — the ps_round op submits the
        # round's push/barrier/pull to a background pipeline and
        # returns; a full pipe blocks the step. 0 (default) = fully
        # synchronous: the round runs inline and the trajectory is
        # bit-identical to the pre-overlap send/send_barrier/recv/
        # fetch_barrier sequence (the golden-oracle contract).
        "FLAGS_async_staleness": 0,
        # sparse prefetch under the overlap plane: while window i
        # computes, a background thread pulls window i+1's embedding
        # rows into a per-step buffer the lookup op consumes without an
        # RPC. Only active when FLAGS_async_staleness > 0 (prefetched
        # rows are up to one round stale by construction).
        "FLAGS_sparse_prefetch": True,
        # ---- compressed PS data plane (docs/PS_DATA_PLANE.md
        # "Compression") ----
        # wire v3 payload quantization: "" (off, exact frames) | "fp16"
        # (downcast) | "int8" (per-row absmax scale). Lossy and OPT-IN;
        # applies only to float32 data-plane payloads on connections
        # that negotiated wire v3 in the _hello handshake — old peers
        # on either side keep exchanging exact frames. Bytes-saved
        # evidence scrapes as ps_wire_bytes_{raw,sent}_total.
        "FLAGS_ps_wire_quant": "",
        # DGC deep gradient compression (reference WITH_DGC; Lin et
        # al., ICLR 2018): dense grads on the sync send / ps_round /
        # geo-delta paths sparsify to their top-k elements with local
        # error-feedback accumulation — unsent mass stays in the
        # trainer's residual and ships later, so convergence follows
        # the full gradient. OFF by default: bit-identical behavior.
        "FLAGS_dgc": False,
        # final sparsity: fraction of elements DROPPED per push (0.999
        # = ship the top 0.1%, the paper's steady-state setting)
        "FLAGS_dgc_sparsity": 0.999,
        # momentum correction factor for the compressor's local
        # velocity accumulation (u = m*u + g; 0 disables — pair with
        # a momentum-free server optimizer to keep semantics plain SGD)
        "FLAGS_dgc_momentum": 0.0,
        # warm-up: over the first N pushes per grad the sparsity ramps
        # exponentially from ~75% toward FLAGS_dgc_sparsity (the
        # paper's epoch ramp, per-push); 0 = no warm-up
        "FLAGS_dgc_warmup_steps": 0,
        # grads smaller than this many elements ship dense — top-k
        # bookkeeping on a bias vector costs more than it saves
        "FLAGS_dgc_min_elements": 512,
        "FLAGS_sync_nccl_allreduce": True,   # no-op: ICI collectives are compiled
        "FLAGS_executor_mode": "compiled",   # compiled | interpreted
        # segmented compilation: when a block fails the all-or-nothing
        # compiled check (a stateful/host op like auc/print/read among
        # pure ops), partition it into jitted segments around interpreted
        # islands instead of interpreting EVERYTHING (fluid/executor.py
        # _SegmentedBlock, fluid/ir.py analyze_block_segments). OFF means
        # such blocks take the pure interpreter (the correctness oracle).
        "FLAGS_executor_segmentation": True,
        # don't bother jitting segments for tiny blocks: below this many
        # compilable ops the per-segment dispatch + compile overhead
        # exceeds the interpreter's per-op cost
        "FLAGS_executor_seg_min_ops": 8,
        "FLAGS_seed": 0,
        # bf16 inputs on MXU matmuls/convs with f32 accumulate (params and
        # activations stay f32 outside the unit) — the TPU-native analogue
        # of the reference's TF32/fp16 math modes
        "FLAGS_use_bf16_matmul": False,
        # sparse tables with at least this many elements are hosted as
        # init-on-touch LazyEmbeddingTable on pservers (beyond-HBM scale)
        "FLAGS_lazy_sparse_table_threshold": 1 << 26,
        # reuse the device copy when the SAME ndarray object with the
        # SAME content fingerprint is fed again (skips the per-step
        # device_put — the dominant host cost of a small step); the
        # fingerprint makes this safe under in-place mutation, so it is
        # ON by default
        "FLAGS_feed_device_cache": True,
        # opt-in persistent XLA executable cache: non-empty -> every
        # Executor routes compiles through
        # jax_compilation_cache_dir=<dir> (inference.enable_compile_cache)
        # so a SECOND process running the same program loads the
        # executable from disk instead of recompiling
        "FLAGS_compilation_cache_dir": "",
        # multiprocess DataLoader liveness probe: how long the consumer
        # waits on the batch queue before checking whether the worker
        # process died (a killed worker surfaces RuntimeError instead of
        # hanging forever); per-loader kwarg worker_timeout overrides
        "FLAGS_dataloader_worker_timeout": 5.0,
        # how long to wait for the worker process to exit at iterator
        # teardown before it is killed
        "FLAGS_dataloader_join_timeout": 5.0,
        # ---- unified telemetry plane (docs/OBSERVABILITY.md) ----
        # non-empty: every process streams its profiler spans into a
        # bounded chrome-trace shard <dir>/trace-<pid>.json (raw
        # monotonic timestamps + clock-offset metadata from the ps_rpc
        # _hello handshake); tools/timeline.py merge aligns the shards
        # into ONE clock-corrected cluster timeline keyed by trace id.
        # Spans record even without start_profiler() while this is set.
        "FLAGS_trace_dir": "",
        # ring-buffer bound of one trace shard — oldest events drop
        # (counted in the shard metadata) so a long run's shard stays
        # O(bound), not O(steps)
        "FLAGS_trace_shard_max_events": 65536,
        # in-memory profiler event bound (ring semantics): beyond this
        # the OLDEST events drop and a dropped-events counter surfaces
        # in the summary/snapshot — a long profiled run can no longer
        # grow the host heap without bound. Applied at start_profiler/
        # reset_profiler time.
        "FLAGS_profiler_max_events": 1_000_000,
        # opt-in lightweight /metrics sidecar (Prometheus text format
        # over the telemetry registry): >0 binds 127.0.0.1:<port> at
        # pserver/ingress/executor startup so bench.py and the chaos/
        # loadgen tools scrape instead of poking process internals.
        # 0 (default) = off; the serving ingress additionally always
        # serves GET /metrics on its own port.
        "FLAGS_metrics_port": 0,
    }

    def __init__(self):
        self._values: Dict[str, Any] = {}
        for k, dv in self._DEFAULTS.items():
            env = os.environ.get(k)
            self._values[k] = self._parse(env, dv) if env is not None else dv

    @staticmethod
    def _parse(s: str, like: Any):
        if isinstance(like, bool):
            return s.lower() in ("1", "true", "yes")
        if isinstance(like, int):
            return int(s)
        if isinstance(like, float):
            return float(s)
        return s

    def __getitem__(self, key):
        return self._values[key]

    def __setitem__(self, key, value):
        self._values[key] = value

    def __contains__(self, key):
        return key in self._values

    def keys(self):
        return self._values.keys()


globals_ = _GlobalFlags()


def get_flag(name: str):
    return globals_[name]


def set_flag(name: str, value):
    globals_[name] = value


def set_flags(d: Dict[str, Any]):
    for k, v in d.items():
        globals_[k] = v
