"""Runtime core for paddle_tpu — the TPU-native equivalent of the reference's
pybind ``core`` extension module (reference: paddle/fluid/pybind/pybind.cc).

Where the reference exposes C++ Tensor/Scope/Executor objects backed by CUDA
allocations, this module backs the same API surface with ``jax.Array`` device
buffers managed by the XLA runtime: allocation, layout, and device transfer
are the compiler/runtime's job (reference memory/allocation/* is absorbed by
XLA — see SURVEY.md §2.1 "TPU mapping notes").

Contents:
  * VarDesc.VarType dtype enum (wire values match framework.proto:104).
  * Places: CPUPlace / TPUPlace (+ CUDAPlace compat alias → TPU).
  * LoDTensor / SelectedRows / LoDTensorArray runtime containers
    (reference: framework/lod_tensor.h:104, selected_rows.h:32).
  * Variable / Scope (reference: framework/variable.h:26, scope.h:46).
  * global flag registry (reference: platform/flags.cc ``FLAGS_*``).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from .proto import framework_pb2

__all__ = [
    "VarDesc", "CPUPlace", "TPUPlace", "CUDAPlace", "CUDAPinnedPlace",
    "Place", "LoDTensor", "Tensor", "SelectedRows", "LoDTensorArray",
    "LazyEmbeddingTable",
    "Variable", "Scope", "globals_", "get_flag", "set_flag",
    "dtype_to_np", "np_to_dtype", "dtype_to_jnp", "is_float_dtype",
    "is_compiled_with_tpu", "EOFException", "WorkerDeadError",
    "RpcProtocolError", "CheckpointError", "NumericFaultError",
    "StaleClusterViewError", "SpillCorruptionError",
]


class EOFException(Exception):
    """Raised by non-iterable DataLoader/PyReader ``next()`` when the
    underlying generator is drained (reference: the C++ reader's
    EnforceNotMet-EOF that ``exe.run`` surfaces in the py_reader loop;
    the user catches it, calls ``reader.reset()`` and starts the next
    epoch)."""


class WorkerDeadError(RuntimeError):
    """A collective operation (barrier / reduce) released because a
    participant was declared dead by the pserver's HeartBeatMonitor —
    survivors get this promptly (≈ the heartbeat timeout) instead of
    blocking for the full barrier deadline. The message names the dead
    worker id(s) so launchers can act (docs/FAULT_TOLERANCE.md)."""


class RpcProtocolError(ConnectionError):
    """The RPC wire framing is invalid — e.g. a length prefix beyond
    FLAGS_rpc_max_message_size (garbage or malicious peer). Never
    retried: retry applies to transient transport failures, not to a
    corrupted protocol stream."""


class CheckpointError(RuntimeError):
    """A checkpoint directory failed validation (missing manifest,
    missing files, size/CRC mismatches) or load_vars found missing
    files. The message aggregates EVERY bad file, not just the first."""


class SpillCorruptionError(CheckpointError):
    """A LazyEmbeddingTable spill-log segment (docs/PS_DATA_PLANE.md
    "Capacity tier") failed its CRC/size validation: the log was
    truncated, bit-flipped, or deleted out from under the table. The
    table REFUSES to serve the affected rows — same contract as a torn
    checkpoint (CheckpointError subclass, so existing rejection
    handlers keep working). Hot rows pinned in RAM keep serving."""


class StaleClusterViewError(RuntimeError):
    """A PS data RPC reached a server that no longer owns the shard —
    the pserver drained/handed its state off (or is a standby that has
    not been promoted), and the client's ClusterView is stale. Carries
    the server's current view as a plain dict in ``view_dict`` (None
    when the server itself has no newer view, e.g. an unpromoted
    standby); the RPC client installs it and replays the SAME encoded
    frame — same dedup token — against the new owner, so exactly-once
    application survives the re-route (docs/FAULT_TOLERANCE.md
    "Elastic membership")."""

    def __init__(self, msg: str, view=None):
        super().__init__(msg)
        self.view_dict = view


class NumericFaultError(FloatingPointError):
    """The numeric fault plane (FLAGS_check_nan_inf +
    FLAGS_nan_inf_action — docs/FAULT_TOLERANCE.md "Numeric faults")
    could not contain a NaN/Inf: rollback retries exhausted, no intact
    checkpoint to roll back to, or a tripped step the raise-mode
    localizer could not reproduce. Subclasses FloatingPointError so
    pre-existing FLAGS_check_nan_inf handlers keep catching it."""


class DeadlineExceededError(TimeoutError):
    """A request's propagated deadline expired before the work finished
    (docs/SERVING.md "Ingress & overload"): the serving ingress stamps
    each request with a budget, and queue wait, bucket dispatch, and PS
    row fetches (``ps_rpc.call_budget``) all check the remaining budget
    — an expired request surfaces this typed error (HTTP 504) instead
    of holding a worker or an RPC channel. Subclasses TimeoutError so
    pre-existing timeout handling keeps catching it. ``queue_wait_s``
    carries the time the request sat admitted-but-undispatched when the
    expiry happened in the queue."""

    def __init__(self, msg: str, queue_wait_s: float = None):
        super().__init__(msg)
        self.queue_wait_s = queue_wait_s


class OverloadedError(RuntimeError):
    """The serving admission plane shed this request (HTTP 429): the
    bounded admission queue is full, the token-bucket rate gate refused
    it, or the CoDel-style oldest-drop evicted it to keep accepted-
    request p99 bounded under sustained overload. ``retry_after_s`` is
    the server's drain-time estimate from its rolling QPS/latency stats
    — monotone in queue depth, so a well-behaved client backs off
    harder the deeper the overload (docs/SERVING.md)."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class CircuitOpenError(ConnectionError):
    """A per-endpoint circuit breaker (fluid/ps_rpc.py, enabled by
    FLAGS_rpc_circuit_breaker) is OPEN for this pserver endpoint:
    recent calls died with transport/typed worker-dead errors, so new
    calls fail fast instead of burning their deadline against a dead
    server. Serving's sparse path catches it (with the other transport
    errors) and flips into serve-stale degraded mode; the breaker
    half-opens after FLAGS_rpc_breaker_reset_s and one probe call
    closes it again (docs/SERVING.md "Ingress & overload")."""


# --------------------------------------------------------------------------
# dtypes
# --------------------------------------------------------------------------
class _VarTypeEnum:
    """Mirror of framework.proto VarType.Type values (framework.proto:104)."""
    BOOL = framework_pb2.VarType.BOOL
    INT16 = framework_pb2.VarType.INT16
    INT32 = framework_pb2.VarType.INT32
    INT64 = framework_pb2.VarType.INT64
    FP16 = framework_pb2.VarType.FP16
    FP32 = framework_pb2.VarType.FP32
    FP64 = framework_pb2.VarType.FP64
    SIZE_T = framework_pb2.VarType.SIZE_T
    UINT8 = framework_pb2.VarType.UINT8
    INT8 = framework_pb2.VarType.INT8
    BF16 = framework_pb2.VarType.BF16

    LOD_TENSOR = framework_pb2.VarType.LOD_TENSOR
    SELECTED_ROWS = framework_pb2.VarType.SELECTED_ROWS
    FEED_MINIBATCH = framework_pb2.VarType.FEED_MINIBATCH
    FETCH_LIST = framework_pb2.VarType.FETCH_LIST
    STEP_SCOPES = framework_pb2.VarType.STEP_SCOPES
    LOD_RANK_TABLE = framework_pb2.VarType.LOD_RANK_TABLE
    LOD_TENSOR_ARRAY = framework_pb2.VarType.LOD_TENSOR_ARRAY
    PLACE_LIST = framework_pb2.VarType.PLACE_LIST
    READER = framework_pb2.VarType.READER
    RAW = framework_pb2.VarType.RAW
    TUPLE = framework_pb2.VarType.TUPLE


class VarDesc:
    VarType = _VarTypeEnum


_DTYPE_TO_NP = {
    _VarTypeEnum.BOOL: np.bool_,
    _VarTypeEnum.INT16: np.int16,
    _VarTypeEnum.INT32: np.int32,
    _VarTypeEnum.INT64: np.int64,
    _VarTypeEnum.FP16: np.float16,
    _VarTypeEnum.FP32: np.float32,
    _VarTypeEnum.FP64: np.float64,
    _VarTypeEnum.UINT8: np.uint8,
    _VarTypeEnum.INT8: np.int8,
}

_NP_TO_DTYPE = {np.dtype(v): k for k, v in _DTYPE_TO_NP.items()}
_NP_TO_DTYPE[np.dtype("bfloat16") if hasattr(np, "bfloat16") else jnp.bfloat16] = _VarTypeEnum.BF16

_STR_TO_DTYPE = {
    "bool": _VarTypeEnum.BOOL,
    "int16": _VarTypeEnum.INT16,
    "int32": _VarTypeEnum.INT32,
    "int64": _VarTypeEnum.INT64,
    "float16": _VarTypeEnum.FP16,
    "bfloat16": _VarTypeEnum.BF16,
    "float32": _VarTypeEnum.FP32,
    "float64": _VarTypeEnum.FP64,
    "uint8": _VarTypeEnum.UINT8,
    "int8": _VarTypeEnum.INT8,
}


def convert_np_dtype_to_dtype_(np_dtype) -> int:
    if isinstance(np_dtype, int):
        return np_dtype
    if isinstance(np_dtype, str):
        return _STR_TO_DTYPE[np_dtype]
    d = np.dtype(np_dtype) if not isinstance(np_dtype, np.dtype) else np_dtype
    if d in _NP_TO_DTYPE:
        return _NP_TO_DTYPE[d]
    if str(d) == "bfloat16":
        return _VarTypeEnum.BF16
    raise ValueError(f"unsupported numpy dtype {np_dtype}")


def np_to_dtype(np_dtype) -> int:
    return convert_np_dtype_to_dtype_(np_dtype)


def dtype_to_np(dtype: int):
    if dtype == _VarTypeEnum.BF16:
        return jnp.bfloat16
    return _DTYPE_TO_NP[dtype]


def dtype_to_jnp(dtype: int):
    """Device-side dtype. TPU-native narrowing: INT64→int32, FP64→float32
    (XLA on TPU has no fast 64-bit path; host serialization via dtype_to_np
    keeps the declared width)."""
    if dtype == _VarTypeEnum.BF16:
        return jnp.bfloat16
    if dtype == _VarTypeEnum.INT64:
        return jnp.int32
    if dtype == _VarTypeEnum.FP64:
        return jnp.float32
    return jnp.dtype(_DTYPE_TO_NP[dtype])


def is_float_dtype(dtype: int) -> bool:
    return dtype in (_VarTypeEnum.FP16, _VarTypeEnum.BF16, _VarTypeEnum.FP32,
                     _VarTypeEnum.FP64)


# --------------------------------------------------------------------------
# Places — device abstraction (reference: platform/place.h:26-79)
# --------------------------------------------------------------------------
class Place:
    """Base place."""
    def __eq__(self, other):
        return type(self) is type(other) and getattr(self, "_device_id", 0) == \
            getattr(other, "_device_id", 0)

    def __hash__(self):
        return hash((type(self).__name__, getattr(self, "_device_id", 0)))


class CPUPlace(Place):
    def __repr__(self):
        return "CPUPlace"

    def jax_device(self):
        # local_devices, not devices: in multi-process mode the global
        # list starts with process 0's devices — placing host data there
        # from another rank would create a non-addressable array
        try:
            return jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            return jax.local_devices()[0]


class TPUPlace(Place):
    """The accelerator place. On a CPU-only host (tests) it degrades to the
    default jax device, so programs written against TPUPlace run anywhere."""
    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def __repr__(self):
        return f"TPUPlace({self._device_id})"

    def get_device_id(self):
        return self._device_id

    def jax_device(self):
        devs = jax.local_devices()
        return devs[self._device_id % len(devs)]


# Compatibility alias: reference scripts say CUDAPlace; on this framework that
# means "the accelerator", i.e. the TPU chip of that ordinal.
CUDAPlace = TPUPlace


class CUDAPinnedPlace(CPUPlace):
    def __repr__(self):
        return "CUDAPinnedPlace"


def is_compiled_with_tpu() -> bool:
    """Accelerator probe. Exception-safe: a broken TPU backend (dead
    tunnel plugin raising at init) reports False instead of propagating,
    so `import paddle_tpu` and CPU-path scripts survive a bad backend
    (round-1 BENCH failure mode)."""
    try:
        return any(d.platform != "cpu" for d in jax.devices())
    except Exception:
        return False


def is_compiled_with_cuda() -> bool:
    # CUDA never exists here; scripts gating on this will take the CPU path,
    # so report accelerator presence instead for behavioural parity.
    return is_compiled_with_tpu()


def start_forked_quietly(procs):
    """Start fork-context worker processes with the fork-under-threads
    warnings suppressed: fork is deliberate at these call sites (reader
    closures can't be pickled for spawn) and the children never touch
    JAX, so an inherited JAX-internal lock can't deadlock them."""
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        warnings.simplefilter("ignore", RuntimeWarning)
        for p in procs:
            p.start()


def _as_place(place) -> Place:
    if place is None:
        return TPUPlace(0) if is_compiled_with_tpu() else CPUPlace()
    return place


# --------------------------------------------------------------------------
# Tensors
# --------------------------------------------------------------------------
def _to_device_array(data, place: Optional[Place] = None, dtype=None):
    if isinstance(data, jax.Array) and dtype is None:
        return data
    arr = np.asarray(data, dtype=dtype)
    # Device integer policy: 32-bit. TPU has no native int64 ALU path and
    # jax runs x64-off, so 64-bit feeds are cast explicitly here (instead
    # of leaking a per-call truncation warning from jax); the executor's
    # fetch boundary restores the program-declared int64 dtype, so user
    # code still sees the reference's int64 contracts (e.g. sequence_pad
    # Length — reference sequence_pad_op.cc).
    if not jax.config.jax_enable_x64 and arr.dtype in (np.int64, np.uint64):
        tgt = np.int32 if arr.dtype == np.int64 else np.uint32
        info = np.iinfo(tgt)
        if arr.size and (int(arr.min()) < info.min
                         or int(arr.max()) > info.max):
            # astype would WRAP (e.g. a 64-bit hashed CTR feature id
            # becoming a negative row index) — that corruption is silent
            # and unrecoverable at the fetch boundary, so refuse. Feeds
            # carrying genuine 64-bit ids belong on the host-side PS
            # lookup path (distributed_lookup_table), not on-device.
            raise ValueError(
                f"int64/uint64 feed value out of {np.dtype(tgt).name} "
                f"range (min={arr.min()}, max={arr.max()}): the device "
                "integer width is 32-bit (TPU has no native int64 path). "
                "Route >32-bit ids through the parameter-server lookup "
                "(distributed_lookup_table) or pre-hash them below 2^31.")
        arr = arr.astype(tgt)
    if place is None:
        return jnp.asarray(arr)
    return jax.device_put(arr, _as_place(place).jax_device())


class LoDTensor:
    """Dense tensor + level-of-detail offsets for ragged sequence batches
    (reference: framework/lod_tensor.h:104). The buffer is a jax.Array; LoD is
    host-side metadata (TPU kernels consume padded/packed forms, the LoD
    records the ragged structure)."""

    __slots__ = ("_array", "_lod")

    def __init__(self, array=None, lod: Optional[List[List[int]]] = None):
        self._array = array
        self._lod = [list(l) for l in lod] if lod else []

    # -- reference API surface -------------------------------------------
    def set(self, np_array, place=None):
        self._array = _to_device_array(np_array, place)

    def set_lod(self, lod):
        self._lod = [list(l) for l in lod]

    def lod(self):
        return [list(l) for l in self._lod]

    def set_recursive_sequence_lengths(self, seq_lens):
        # lengths [[2,3]] -> offsets [[0,2,5]]
        lod = []
        for lens in seq_lens:
            offs = [0]
            for ln in lens:
                offs.append(offs[-1] + int(ln))
            lod.append(offs)
        self._lod = lod

    def recursive_sequence_lengths(self):
        out = []
        for offs in self._lod:
            out.append([offs[i + 1] - offs[i] for i in range(len(offs) - 1)])
        return out

    def has_valid_recursive_sequence_lengths(self):
        if not self._lod:
            return True
        n = self._array.shape[0] if self._array is not None else 0
        return self._lod[-1][-1] == n

    def shape(self):
        return list(self._array.shape) if self._array is not None else []

    def _dtype(self):
        return self._array.dtype if self._array is not None else None

    def __array__(self, dtype=None):
        a = np.asarray(self._array)
        return a.astype(dtype) if dtype is not None else a

    def numpy(self):
        return np.asarray(self._array)

    @property
    def array(self):
        return self._array

    def __len__(self):
        return int(self._array.shape[0]) if self._array is not None else 0

    def __repr__(self):
        return f"LoDTensor(shape={self.shape()}, lod={self._lod})"


Tensor = LoDTensor


class SelectedRows:
    """Sparse row-set tensor: a value tensor whose i-th row corresponds to
    logical row ``rows[i]`` of a [height, ...] dense tensor (reference:
    framework/selected_rows.h:32). Used for embedding gradients and the
    sparse parameter-server path."""

    __slots__ = ("_rows", "_height", "_value")

    def __init__(self, rows=None, height: int = 0):
        self._rows = list(rows) if rows is not None else []
        self._height = int(height)
        self._value = LoDTensor()

    def rows(self):
        return self._rows

    def set_rows(self, rows):
        self._rows = [int(r) for r in rows]

    def height(self):
        return self._height

    def set_height(self, h):
        self._height = int(h)

    def get_tensor(self) -> LoDTensor:
        return self._value

    def sync_index(self):
        pass

    def to_dense(self) -> jnp.ndarray:
        val = self._value.array
        dense = jnp.zeros((self._height,) + tuple(val.shape[1:]), val.dtype)
        return dense.at[jnp.asarray(self._rows, jnp.int32)].add(val)

    def __repr__(self):
        return f"SelectedRows(height={self._height}, nrows={len(self._rows)})"


class LoDTensorArray(list):
    """reference: framework/lod_tensor_array.h — a std::vector<LoDTensor>."""
    pass


class _SpillTier:
    """Tier state of one LazyEmbeddingTable (docs/PS_DATA_PLANE.md
    "Capacity tier"): the spill store + cold-row map, the entry-gate
    counters, decay-shrink scores, and the telemetry counters the
    pserver stats plane scrapes. ``store`` is None for an entry-gated
    but un-spilled table."""

    __slots__ = ("store", "spill_path", "hot_rows", "quant", "seg_rows",
                 "entry_threshold", "track_scores", "cold", "backing",
                 "seg_live", "seg_cold", "freq", "scores",
                 "hits", "misses", "promoted_rows", "spilled_rows_total",
                 "clean_evictions", "spill_batches", "entry_denied",
                 "grad_dropped_rows", "poison_dropped_rows",
                 "shrunk_rows", "shrink_runs")

    def __init__(self, spill_path, hot_rows, quant, seg_rows,
                 entry_threshold, dim, dtype, track_scores=None):
        self.spill_path = spill_path
        self.hot_rows = int(hot_rows)
        self.quant = quant
        self.seg_rows = int(seg_rows)
        self.entry_threshold = int(entry_threshold)
        # per-row touch scores feed shrink(); tracked when the entry
        # gate is on (or explicitly requested) — a plain spill tier
        # skips the per-touch dict update on its hot path
        self.track_scores = bool(entry_threshold > 0
                                 if track_scores is None
                                 else track_scores)
        self.store = None
        if spill_path:
            from . import slab_spill
            self.store = slab_spill.SpillStore(spill_path, dim, dtype)
        self.cold: Dict[int, tuple] = {}      # id -> (seg_id, row_pos)
        # CLEAN promoted rows keep their disk copy as backing: evicting
        # an unmodified row just flips it back to cold — zero write-back
        # (page-cache dirty-bit semantics). apply_grad dirties.
        self.backing: Dict[int, tuple] = {}   # hot id -> (seg, pos)
        self.seg_live: Dict[int, int] = {}    # seg -> cold+backing refs
        # seg -> COLD refs only, maintained incrementally wherever cold
        # refs move — tier_stats() reads it so a telemetry scrape under
        # the grad lock is O(segments), never O(spilled rows)
        self.seg_cold: Dict[int, int] = {}
        self.freq: Dict[int, int] = {}        # unentered id -> pull count
        self.scores: Dict[int, float] = {}    # materialized id -> score
        self.hits = 0
        self.misses = 0
        self.promoted_rows = 0
        self.spilled_rows_total = 0
        self.clean_evictions = 0
        self.spill_batches = 0
        self.entry_denied = 0
        self.grad_dropped_rows = 0
        self.poison_dropped_rows = 0
        self.shrunk_rows = 0
        self.shrink_runs = 0

    def deref_seg(self, sid) -> None:
        self.seg_live[sid] -= 1
        if self.seg_live[sid] == 0:
            self.seg_live.pop(sid)
            self.store.free(sid)


class LazyEmbeddingTable:
    """Beyond-HBM host-RAM embedding table for the sparse PS path
    (reference: framework/fleet/fleet_wrapper.h:86-190 — DownpourSparseTable
    pull creates features on first touch; memory is bounded by feature
    count, not by the logical [height, dim] shape, and features can be
    evicted/shrunk).

    Rows materialize on first access with a deterministic per-row init, so
    a 1e9-parameter logical table costs only O(touched rows) memory; an
    optional LRU bound evicts least-recently-used rows (an evicted, later
    re-touched row re-initializes — the reference's shrink() makes the
    same trade).

    Storage is a CONTIGUOUS slab (``_data``) plus an id→slot index, so
    the PS-plane hot paths are vectorized: ``get_rows`` is one
    fancy-index gather and ``apply_grad`` one ``np.subtract.at`` scatter
    — per-id python work is a single dict lookup, not a per-row
    stack/astype (the pserver applies thousands of rows per step on the
    wide_deep lanes; docs/PS_DATA_PLANE.md).

    CAPACITY TIER (docs/PS_DATA_PLANE.md "Capacity tier"): with
    ``spill_path`` + ``hot_rows`` the slab becomes the PINNED HOT SET of
    a two-tier table — LRU overflow writes back to an mmap-backed,
    CRC-stamped segment log (``fluid/slab_spill.SpillStore``), cold
    rows promote back into the slab on touch (one segment read per
    touched segment, not one seek per id), and ``at_rest_quant``
    ("fp16"/"int8") stores spilled rows through the PR 11 wire codec at
    2-3.8× density with dequant-on-touch feeding the
    FLAGS_ps_reject_nonfinite guard. ``entry_threshold`` > 1
    frequency-gates entry creation (an id must be PULLED that many
    times before it earns a slot — reference PSLib entry gating) and
    ``shrink()`` decays per-row touch scores and drops idle rows. All
    of it opt-in: an unconfigured table runs the exact pre-tier code
    paths."""

    __slots__ = ("height", "dim", "dtype", "seed", "scale", "max_rows",
                 "_index", "_data", "_free", "evictions", "_tier")

    def __init__(self, height: int, dim: int, seed: int = 0,
                 scale: Optional[float] = None, max_rows: Optional[int] = None,
                 dtype=np.float32, spill_path: Optional[str] = None,
                 hot_rows: Optional[int] = None, at_rest_quant: str = "",
                 entry_threshold: int = 0, spill_seg_rows: int = 0,
                 track_scores: Optional[bool] = None):
        from collections import OrderedDict
        self.height = int(height)
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.seed = int(seed)
        self.scale = float(scale) if scale is not None \
            else 1.0 / float(np.sqrt(dim))
        self.max_rows = int(max_rows) if max_rows else None
        # id -> slot in _data; insertion order doubles as LRU order when
        # max_rows bounds the table (and as the hot set's promotion/
        # eviction order when the spill tier bounds it)
        self._index: "OrderedDict[int, int]" = OrderedDict()
        self._data = np.empty((0, self.dim), self.dtype)
        self._free: list = []  # recycled slots of evicted rows
        self.evictions = 0
        self._tier = None
        tiered = bool(spill_path) and bool(hot_rows)
        if self.max_rows is not None and (
                tiered or int(entry_threshold) > 0 or track_scores):
            # the tiered code paths never run the max_rows eviction, so
            # accepting both would SILENTLY drop the RAM bound
            raise ValueError(
                "LazyEmbeddingTable: max_rows (evict-to-oblivion LRU) "
                "cannot combine with the capacity tier (spill/"
                "entry_threshold/track_scores) — the tier's hot_rows "
                "IS the RAM bound there")
        if at_rest_quant not in ("", "fp16", "int8"):
            raise ValueError(
                f"at_rest_quant={at_rest_quant!r} — expected '' | "
                f"'fp16' | 'int8'")
        if tiered or int(entry_threshold) > 0 or track_scores:
            self._tier = _SpillTier(
                spill_path=spill_path if tiered else None,
                hot_rows=int(hot_rows) if tiered else 0,
                quant=at_rest_quant,
                seg_rows=int(spill_seg_rows) or 4096,
                entry_threshold=int(entry_threshold),
                dim=self.dim, dtype=self.dtype,
                track_scores=track_scores)

    def _init_row(self, r: int) -> np.ndarray:
        rs = np.random.RandomState((self.seed * 1000003 + int(r))
                                   % (2 ** 31 - 1))
        return rs.uniform(-self.scale, self.scale,
                          self.dim).astype(self.dtype)

    def _grow_to(self, min_cap: int) -> None:
        """Grow the slab to at least ``min_cap`` rows by doubling —
        the ONE growth policy every claim/install path shares."""
        if min_cap <= len(self._data):
            return
        cap = max(1024, 2 * len(self._data), min_cap)
        grown = np.empty((cap, self.dim), self.dtype)
        grown[:len(self._data)] = self._data
        self._data = grown

    def _claim_slot(self) -> int:
        """Claim a slab slot (recycled or new, growing by doubling).
        The caller must insert the slot into ``_index`` before the next
        claim — fresh-slot numbering assumes every prior slot is either
        indexed or free (use ``_claim_slots`` for bulk claims)."""
        n_alloc = len(self._index) + len(self._free)
        s = self._free.pop() if self._free else n_alloc
        self._grow_to(s + 1)
        return s

    def _claim_slots(self, n: int) -> np.ndarray:
        """Claim ``n`` slots at once (recycled first, then a contiguous
        fresh run) WITHOUT requiring interleaved index insertions."""
        free = self._free
        out = [free.pop() for _ in range(min(n, len(free)))]
        m = n - len(out)
        if m:
            base = len(self._index) + len(free) + len(out)
            self._grow_to(base + m)
            out.extend(range(base, base + m))
        return np.asarray(out, np.int64)

    def _alloc(self, r: int) -> int:
        """Materialize row ``r``: claim a slot (recycled or new, growing
        the slab by doubling), init deterministically, LRU-evict."""
        s = self._claim_slot()
        self._data[s] = self._init_row(r)
        self._index[r] = s
        if self.max_rows is not None and len(self._index) > self.max_rows:
            _evicted, old_slot = self._index.popitem(last=False)  # LRU out
            self._free.append(old_slot)
            self.evictions += 1
        return s

    def _slots_of(self, ids: np.ndarray) -> list:
        """Slot per id, materializing misses (UNBOUNDED tables only —
        slots stay valid for the whole batch because nothing evicts).
        One dict hit per id."""
        get = self._index.get
        alloc = self._alloc
        return [s if (s := get(r)) is not None else alloc(r)
                for r in ids.tolist()]

    def _slot_of_bounded(self, r: int) -> int:
        s = self._index.get(r)
        if s is None:
            return self._alloc(r)
        self._index.move_to_end(r)
        return s

    def get_rows(self, ids) -> np.ndarray:
        ids = np.asarray(ids).reshape(-1)
        if not len(ids):
            return np.zeros((0, self.dim), self.dtype)
        if self._tier is not None:
            return self._get_rows_tiered(ids)
        if self.max_rows is None:
            slots = self._slots_of(ids)  # FIRST: may grow/replace _data
            return self._data[slots]
        # bounded table: an eviction later in THIS batch may recycle an
        # earlier id's slot — copy each row at touch time (the dict
        # implementation's semantics) instead of batch-gathering stale
        # slot numbers
        out = np.empty((len(ids), self.dim), self.dtype)
        for i, r in enumerate(ids.tolist()):
            s = self._slot_of_bounded(r)  # FIRST: may grow/replace _data
            out[i] = self._data[s]
        return out

    def apply_grad(self, ids, grads, lr: float) -> None:
        """Row-wise SGD: rows[id] -= lr * grad (duplicate ids accumulate,
        in id order — one vectorized scatter for unbounded tables)."""
        ids = np.asarray(ids).reshape(-1)
        if not len(ids):
            return
        grads = np.asarray(grads).reshape(len(ids), self.dim)
        step = (lr * grads).astype(self.dtype, copy=False)
        if self._tier is not None:
            self._apply_grad_tiered(ids, step)
            return
        if self.max_rows is None:
            slots = np.asarray(self._slots_of(ids), np.int64)
            np.subtract.at(self._data, slots, step)
            return
        # bounded: apply at touch time so a later in-batch eviction
        # can't scatter into a recycled slot
        for i, r in enumerate(ids.tolist()):
            s = self._slot_of_bounded(r)  # FIRST: may grow/replace _data
            self._data[s] -= step[i]

    # -- capacity tier (docs/PS_DATA_PLANE.md "Capacity tier") -------------
    def _promote_for(self, id_list, t) -> None:
        """Promote every cold id in ``id_list`` into the hot slab with
        ONE store read per touched segment (the batched I/O fan-in —
        never one seek per id). Counts hot hits / cold misses."""
        idx = self._index
        if t.store is None:
            t.hits += sum(1 for r in id_list if r in idx)
            return
        cold = t.cold
        by_seg: Dict[int, list] = {}
        queued = set()
        for r in id_list:
            if r in idx or r in queued:
                t.hits += 1
                continue
            cr = cold.get(r)
            if cr is not None:
                by_seg.setdefault(cr[0], []).append(r)
                queued.add(r)
        for sid in sorted(by_seg):
            self._promote_segment(sid, by_seg[sid], t)

    def _promote_segment(self, sid, rs, t) -> None:
        seg_ids, rows = t.store.read(sid)  # CRC-verified, dequantized
        n = len(rs)
        t.misses += n
        cold = t.cold
        pos = np.fromiter((cold[r][1] for r in rs), np.int64, n)
        rs_arr = np.asarray(rs, np.int64)
        if (seg_ids[pos] != rs_arr).any():
            bad = int(np.argmax(seg_ids[pos] != rs_arr))
            raise SpillCorruptionError(
                f"spill segment {sid}: row {int(pos[bad])} holds id "
                f"{int(seg_ids[pos[bad]])}, cold map expected "
                f"{rs[bad]} — log/directory desynchronized")
        take = rows[pos]
        # dequant-on-touch guard: a poisoned spilled row surfaces HERE,
        # exactly like a poisoned wire frame surfaces at decode
        # (FLAGS_ps_reject_nonfinite — docs/FAULT_TOLERANCE.md)
        mode = str(globals_["FLAGS_ps_reject_nonfinite"] or "") \
            if np.issubdtype(self.dtype, np.floating) else ""
        dropped = set()
        if mode:
            finite = np.isfinite(take).all(axis=1)
            if not finite.all():
                if mode == "reject":
                    bad = rs[int(np.argmin(finite))]
                    raise NumericFaultError(
                        f"spilled embedding row {bad} dequantized "
                        f"non-finite at touch "
                        f"(FLAGS_ps_reject_nonfinite=reject) — "
                        f"refusing to serve it")
                # drop: poisoned rows re-initialize deterministically
                # (the disk copy is poison — no clean backing for them)
                for i in np.flatnonzero(~finite):
                    take[i] = self._init_row(rs[int(i)])
                    dropped.add(rs[int(i)])
                    t.poison_dropped_rows += 1
        # bulk install: one fancy-index copy + one dict batch-update
        # (the promote loop is the cold-pull hot path — per-row python
        # here caps the spilled lane's throughput)
        slots = self._claim_slots(n)
        self._data[slots] = take
        self._index.update(zip((int(r) for r in rs), slots.tolist()))
        # NO score bump here: the caller's gather loop finds the id hot
        # now and bumps exactly once — a cold touch must not outscore a
        # hot touch
        # a CLEAN promote keeps its disk copy as backing — the segment
        # ref just moves cold→backing, and a later eviction of the
        # still-unmodified row is free (no re-encode, no write)
        backing = t.backing
        for r in rs:
            entry = cold.pop(r)
            if r in dropped:
                t.deref_seg(entry[0])
            else:
                backing[r] = entry
        t.seg_cold[sid] -= n
        if t.seg_cold[sid] <= 0:
            t.seg_cold.pop(sid)
        t.promoted_rows += n

    def _alloc_tiered(self, r: int) -> int:
        s = self._claim_slot()
        self._data[s] = self._init_row(r)
        self._index[r] = s
        t = self._tier
        if t.track_scores:
            t.scores[r] = t.scores.get(r, 0.0) + 1.0
        return s

    def _spill_overflow(self) -> None:
        """Write back the LRU overflow of the hot set as spill-log
        segments (batch-level granularity: eviction runs once per
        get_rows/apply_grad call, AFTER the whole batch touched, so an
        id can never lose its slot to a sibling id of the same batch
        mid-gather)."""
        t = self._tier
        if t.store is None:
            return
        n_over = len(self._index) - t.hot_rows
        if n_over <= 0:
            return
        backing, cold, free = t.backing, t.cold, self._free
        dirty_ids: list = []
        dirty_slots: list = []
        for _ in range(n_over):
            r, s = self._index.popitem(last=False)  # LRU out
            free.append(s)
            b = backing.pop(r, None)
            if b is not None:
                # CLEAN eviction: the disk copy is still the row's
                # value — flip back to cold, zero bytes written
                cold[r] = b
                t.seg_cold[b[0]] = t.seg_cold.get(b[0], 0) + 1
                t.clean_evictions += 1
            else:
                dirty_ids.append(r)
                dirty_slots.append(s)
        if dirty_ids:
            # slots were freed above but nothing claims between here
            # and the gather — the rows are intact
            rows = self._data[np.asarray(dirty_slots, np.int64)]
            ids_arr = np.asarray(dirty_ids, np.int64)
            for lo in range(0, len(dirty_ids), t.seg_rows):
                hi = min(lo + t.seg_rows, len(dirty_ids))
                sid = t.store.append(ids_arr[lo:hi], rows[lo:hi],
                                     quant=t.quant)
                t.seg_live[sid] = hi - lo
                t.seg_cold[sid] = hi - lo
                for j in range(lo, hi):
                    cold[int(ids_arr[j])] = (sid, j - lo)
                t.spill_batches += 1
        t.spilled_rows_total += n_over

    def _get_rows_tiered(self, ids: np.ndarray) -> np.ndarray:
        t = self._tier
        id_list = [int(r) for r in ids.tolist()]
        self._promote_for(id_list, t)
        idx = self._index
        thr = t.entry_threshold
        track = t.track_scores
        slots = np.empty(len(id_list), np.int64)
        gated: Dict[int, int] = {}  # out position -> id (no slot yet)
        for i, r in enumerate(id_list):
            s = idx.get(r)
            if s is None:
                if thr > 1:
                    c = t.freq.get(r, 0) + 1
                    if c < thr:
                        # below the entry gate: serve the deterministic
                        # init row WITHOUT materializing — a garbage id
                        # never earns a slot (reference PSLib entry
                        # frequency gating)
                        t.freq[r] = c
                        t.entry_denied += 1
                        gated[i] = r
                        slots[i] = -1
                        continue
                    t.freq.pop(r, None)
                s = self._alloc_tiered(r)
            else:
                idx.move_to_end(r)
                if track:
                    t.scores[r] = t.scores.get(r, 0.0) + 1.0
            slots[i] = s
        out = np.empty((len(id_list), self.dim), self.dtype)
        live = slots >= 0
        if live.all():
            out[:] = self._data[slots]
        elif live.any():
            out[live] = self._data[slots[live]]
        for i, r in gated.items():
            out[i] = self._init_row(r)
        self._spill_overflow()
        return out

    def _apply_grad_tiered(self, ids: np.ndarray, step: np.ndarray) -> None:
        t = self._tier
        id_list = [int(r) for r in ids.tolist()]
        self._promote_for(id_list, t)
        idx = self._index
        thr = t.entry_threshold
        track = t.track_scores
        backing = t.backing
        slots = np.empty(len(id_list), np.int64)
        keep = np.ones(len(id_list), bool)
        for i, r in enumerate(id_list):
            s = idx.get(r)
            if s is None:
                if thr > 1:
                    # entry creation is PULL-driven (reference PSLib):
                    # a grad for an id that never earned a slot is
                    # dropped, counted — garbage ids can't train
                    keep[i] = False
                    t.grad_dropped_rows += 1
                    continue
                s = self._alloc_tiered(r)
            else:
                idx.move_to_end(r)
                if track:
                    t.scores[r] = t.scores.get(r, 0.0) + 1.0
            # the update DIRTIES the row: its clean disk copy (if any)
            # is no longer its value — drop the backing ref
            if backing:
                b = backing.pop(r, None)
                if b is not None:
                    t.deref_seg(b[0])
            slots[i] = s
        if keep.all():
            np.subtract.at(self._data, slots, step)
        elif keep.any():
            np.subtract.at(self._data, slots[keep], step[keep])
        self._spill_overflow()

    def shrink(self, decay: float = 0.5, threshold: float = 0.5) -> int:
        """Decay-based shrink (reference PSLib table shrink / entry
        expiry): every materialized row's touch score multiplies by
        ``decay``; rows falling below ``threshold`` are DROPPED — hot
        slots freed, cold rows erased from the spill log's live set
        (fully-dead segments freed and eventually compacted away) — and
        so are below-threshold entry-gate counters. A dropped id that
        comes back re-initializes deterministically, the same trade the
        in-RAM LRU bound makes. Returns the number of rows dropped."""
        t = self._tier
        if t is None or not t.track_scores:
            raise RuntimeError(
                "shrink() needs touch-score tracking — construct the "
                "table with entry_threshold > 0 or track_scores=True "
                "(FLAGS_ps_entry_threshold / FLAGS_ps_slab_track_scores "
                "on a pserver)")
        decay = float(decay)
        dropped = 0
        new_scores: Dict[int, float] = {}
        for r, sc in t.scores.items():
            sc *= decay
            if sc >= threshold:
                new_scores[r] = sc
                continue
            s = self._index.pop(r, None)
            if s is not None:
                self._free.append(s)
                b = t.backing.pop(r, None)
                if b is not None:
                    t.deref_seg(b[0])
                dropped += 1
                continue
            cr = t.cold.pop(r, None)
            if cr is not None:
                t.seg_cold[cr[0]] -= 1
                if t.seg_cold[cr[0]] <= 0:
                    t.seg_cold.pop(cr[0])
                t.deref_seg(cr[0])
                dropped += 1
        t.scores = new_scores
        if t.freq:
            t.freq = {r: c for r, c in
                      ((r, int(c * decay)) for r, c in t.freq.items())
                      if c > 0}
        t.shrunk_rows += dropped
        t.shrink_runs += 1
        return dropped

    def tier_stats(self) -> Dict[str, Any]:
        """Telemetry gauges of the capacity tier (scraped through the
        pserver stats plane as ``ps_server_slab_*`` — docs/
        OBSERVABILITY.md). Empty dict for an untiered table."""
        t = self._tier
        if t is None:
            return {}
        cold_rows = len(t.cold)
        spilled_bytes = 0
        if t.store is not None and cold_rows:
            # bytes attributable to the COLD rows (backing copies of
            # clean hot rows are a write-elision byproduct, not spilled
            # capacity): the incrementally-maintained per-segment cold
            # counts keep this O(segments) — a stats scrape under the
            # grad lock must never walk every spilled row
            for sid, n_cold in t.seg_cold.items():
                sm = t.store.seg_meta(sid)
                if sm["n_rows"]:
                    spilled_bytes += int(
                        round(sm["row_bytes"] * n_cold / sm["n_rows"]))
        logical = cold_rows * self.dim * self.dtype.itemsize
        touches = t.hits + t.misses
        out = {
            "resident_rows": len(self._index),
            "spilled_rows": cold_rows,
            "resident_bytes": len(self._index) * self.dim
            * self.dtype.itemsize,
            "spilled_bytes": spilled_bytes,
            "logical_spilled_bytes": logical,
            "density_x": round(logical / spilled_bytes, 3)
            if spilled_bytes else 0.0,
            "hits": t.hits, "misses": t.misses,
            "hit_rate": round(t.hits / touches, 4) if touches else 0.0,
            "backing_rows": len(t.backing),
            "promoted_rows": t.promoted_rows,
            "spilled_rows_total": t.spilled_rows_total,
            "clean_evictions": t.clean_evictions,
            "spill_batches": t.spill_batches,
            "entry_denied": t.entry_denied,
            "grad_dropped_rows": t.grad_dropped_rows,
            "poison_dropped_rows": t.poison_dropped_rows,
            "shrunk_rows": t.shrunk_rows,
            "shrink_runs": t.shrink_runs,
            "gate_pending_ids": len(t.freq),
        }
        if t.store is not None:
            out.update({
                "spill_file_bytes": t.store.file_bytes(),
                "spill_live_bytes": t.store.live_bytes(),
                "store_reads": t.store.reads,
                "store_writes": t.store.writes,
                "compactions": t.store.compactions,
                "crc_failures": t.store.crc_failures,
            })
        return out

    def close_spill(self, unlink: bool = False) -> None:
        t = self._tier
        if t is not None and t.store is not None:
            (t.store.unlink if unlink else t.store.close)()

    # -- section-stream plumbing (slab_spill.table_sections /
    #    build_table_from_sections — the handoff + checkpoint legs) ------
    def export_meta(self) -> Dict[str, Any]:
        meta = {"height": self.height, "dim": self.dim,
                "seed": self.seed, "scale": self.scale,
                "max_rows": self.max_rows, "dtype": self.dtype.str,
                "evictions": self.evictions}
        t = self._tier
        if t is not None:
            meta["tier"] = {"hot_rows": t.hot_rows, "quant": t.quant,
                            "entry_threshold": t.entry_threshold,
                            "seg_rows": t.seg_rows,
                            "track_scores": t.track_scores,
                            "spilled": t.store is not None}
        return meta

    def _install_hot_rows(self, ids: np.ndarray, rows: np.ndarray) -> None:
        """Streaming rebuild: append one hot chunk to the slab in
        order (fresh table only — slots contiguous from 0)."""
        base = len(self._index)
        need = base + len(rows)
        # doubling growth (shared policy): per-chunk exact sizing would
        # re-copy the whole accumulated slab once per streamed chunk
        self._grow_to(need)
        self._data[base:need] = np.asarray(rows, self.dtype)
        for i, r in enumerate(ids.tolist()):
            self._index[int(r)] = base + i

    def _install_spilled_segment(self, record, sm) -> None:
        """Streaming rebuild: install one VERBATIM spill record plus
        its live map (bit-identical residency on the destination)."""
        t = self._tier
        if t is None or t.store is None:
            raise SpillCorruptionError(
                "slab stream carries spilled segments but the rebuilt "
                "table has no spill tier")
        sid = t.store.append_raw(record, int(sm["n_rows"]),
                                 sm.get("quant", ""),
                                 int(sm["row_bytes"]),
                                 expect_crc=sm.get("crc"))
        n_rows = int(sm["n_rows"])
        ids = np.frombuffer(record[:n_rows * 8], np.int64) \
            if not isinstance(record, np.ndarray) \
            else np.frombuffer(record.tobytes()[:n_rows * 8], np.int64)
        runs = sm.get("live_runs")
        if runs is None:
            live = sm.get("live_pos")
            live = range(n_rows) if live is None else live
        else:
            live = (p for start, n in runs
                    for p in range(int(start), int(start) + int(n)))
        n_live = 0
        for pos in live:
            t.cold[int(ids[int(pos)])] = (sid, int(pos))
            n_live += 1
        t.seg_live[sid] = n_live
        if n_live:
            t.seg_cold[sid] = n_live
        else:
            t.seg_live.pop(sid)
            t.store.free(sid)

    def _export_gate_state(self):
        t = self._tier
        empty = np.empty(0, np.int64)
        if t is None:
            return empty, np.empty(0, np.float32), empty, empty
        sc_ids = np.fromiter(t.scores.keys(), np.int64, len(t.scores))
        sc_vals = np.fromiter(t.scores.values(), np.float32,
                              len(t.scores))
        fq_ids = np.fromiter(t.freq.keys(), np.int64, len(t.freq))
        fq_cnt = np.fromiter(t.freq.values(), np.int64, len(t.freq))
        return sc_ids, sc_vals, fq_ids, fq_cnt

    def _import_gate_state(self, sc_ids, sc_vals, fq_ids, fq_cnt) -> None:
        t = self._tier
        if t is None:
            return
        t.scores = {int(r): float(v)
                    for r, v in zip(sc_ids.tolist(), sc_vals.tolist())}
        t.freq = {int(r): int(c)
                  for r, c in zip(fq_ids.tolist(), fq_cnt.tolist())}

    # -- handoff (elastic membership, docs/FAULT_TOLERANCE.md) ------------
    def export_state(self):
        """Snapshot for a CRC-manifested shard handoff: (meta, ids,
        rows). ``ids`` lists materialized row ids in LRU order (oldest
        first — OrderedDict insertion order IS the eviction order) and
        ``rows`` their current values, so ``import_state`` on the
        destination rebuilds a bit-identical table INCLUDING future
        eviction decisions. Never-touched rows don't ship: they
        re-materialize from the same deterministic per-row init.

        Tiered tables MATERIALIZE here (cold rows dequantized, listed
        oldest-first ahead of the hot LRU run — spill order IS the
        eviction order); the RSS-bounded path for big spilled tables is
        ``slab_spill.table_sections`` (what handoffs and checkpoints
        use). Gate/score state does not ride this materialized API."""
        n = len(self._index)
        ids = np.fromiter(self._index.keys(), np.int64, n)
        slots = np.fromiter(self._index.values(), np.int64, n)
        rows = (self._data[slots] if n
                else np.empty((0, self.dim), self.dtype))
        t = self._tier
        if t is not None and t.cold:
            cold = sorted(((sid, pos, r)
                           for r, (sid, pos) in t.cold.items()))
            cold_ids = np.asarray([r for _, _, r in cold], np.int64)
            cold_rows = np.empty((len(cold), self.dim), self.dtype)
            seg_cache_sid, seg_cache_rows = None, None
            for i, (sid, pos, _r) in enumerate(cold):
                if sid != seg_cache_sid:  # one read per segment
                    seg_cache_sid = sid
                    _sids, seg_cache_rows = t.store.read(sid)
                cold_rows[i] = seg_cache_rows[pos]
            ids = np.concatenate([cold_ids, ids])
            rows = np.concatenate(
                [cold_rows, np.asarray(rows, self.dtype)]) \
                if n else cold_rows
        return self.export_meta(), ids, np.ascontiguousarray(rows)

    @classmethod
    def from_state(cls, meta, ids, rows,
                   spill_path: Optional[str] = None) -> "LazyEmbeddingTable":
        tier = meta.get("tier") or {}
        kw = {}
        if tier and (tier.get("spilled") or spill_path):
            if spill_path is None:
                import tempfile
                spill_path = os.path.join(
                    tempfile.mkdtemp(prefix="pt-slab-"), "spill.log")
            kw = dict(spill_path=spill_path,
                      hot_rows=int(tier["hot_rows"]),
                      at_rest_quant=tier.get("quant", ""),
                      entry_threshold=int(tier.get("entry_threshold", 0)),
                      spill_seg_rows=int(tier.get("seg_rows", 0)),
                      track_scores=tier.get("track_scores"))
        elif tier:
            kw = dict(entry_threshold=int(tier.get("entry_threshold", 0)),
                      track_scores=tier.get("track_scores"))
        tbl = cls(height=int(meta["height"]), dim=int(meta["dim"]),
                  seed=int(meta["seed"]), scale=float(meta["scale"]),
                  max_rows=meta.get("max_rows"),
                  dtype=np.dtype(meta["dtype"]), **kw)
        tbl.import_state(ids, rows)
        tbl.evictions = int(meta.get("evictions", 0))
        return tbl

    def import_state(self, ids, rows) -> None:
        """Install a handoff snapshot wholesale (replaces any current
        content). Rows land compacted in the given order, which
        ``export_state`` guarantees is the source's LRU order. On a
        TIERED table the overflow beyond ``hot_rows`` — exactly the
        oldest prefix, i.e. the source's cold set — is written back to
        the spill tier (int8/fp16 re-encoding of already-dequantized
        values is exact, so residency round-trips are bit-identical)."""
        from collections import OrderedDict
        ids = np.asarray(ids, np.int64).reshape(-1)
        rows = np.asarray(rows, self.dtype).reshape(len(ids), self.dim)
        self._index = OrderedDict(
            (int(r), i) for i, r in enumerate(ids.tolist()))
        self._data = np.array(rows, self.dtype, copy=True)
        self._free = []
        t = self._tier
        if t is not None:
            if t.store is not None:  # wholesale replace: old log is dead
                t.store.clear()
            t.cold.clear()
            t.backing.clear()
            t.seg_live.clear()
            t.seg_cold.clear()
            t.freq.clear()
            t.scores = ({int(r): 1.0 for r in ids.tolist()}
                        if t.track_scores else {})
            self._spill_overflow()

    # -- introspection ----------------------------------------------------
    def touched_rows(self) -> int:
        """Materialized entries — hot slab rows plus spilled rows."""
        n = len(self._index)
        if self._tier is not None:
            n += len(self._tier.cold)
        return n

    def nbytes(self) -> int:
        """RESIDENT bytes (the hot slab); spilled bytes are on disk —
        see tier_stats()."""
        return len(self._index) * self.dim * self.dtype.itemsize

    def logical_params(self) -> int:
        return self.height * self.dim

    def __repr__(self):
        tier = ""
        if self._tier is not None:
            tier = (f", hot={len(self._index)}"
                    f", spilled={len(self._tier.cold)}")
        return (f"LazyEmbeddingTable(height={self.height}, dim={self.dim}, "
                f"touched={self.touched_rows()}, "
                f"evictions={self.evictions}{tier})")


class LoDRankTable:
    """reference: framework/lod_rank_table.h — sequences of one LoD level
    sorted by length descending; items are (index, length)."""

    __slots__ = ("items", "level")

    def __init__(self, items=None, level=0):
        self.items = list(items or [])  # [(seq_index, length), ...]
        self.level = level

    def __repr__(self):
        return f"LoDRankTable({self.items})"


# --------------------------------------------------------------------------
# Variable / Scope (reference: framework/variable.h:26, scope.h:46)
# --------------------------------------------------------------------------
class Variable:
    """Any-container runtime variable."""

    __slots__ = ("_holder",)

    def __init__(self):
        self._holder = None

    def get_tensor(self) -> LoDTensor:
        if self._holder is None:
            self._holder = LoDTensor()
        if not isinstance(self._holder, LoDTensor):
            raise TypeError(f"variable holds {type(self._holder).__name__}")
        return self._holder

    def get_selected_rows(self) -> SelectedRows:
        if self._holder is None:
            self._holder = SelectedRows()
        return self._holder

    def get_lod_tensor_array(self) -> LoDTensorArray:
        if self._holder is None:
            self._holder = LoDTensorArray()
        return self._holder

    def get_lod_rank_table(self) -> "LoDRankTable":
        if self._holder is None:
            self._holder = LoDRankTable()
        return self._holder

    def set_value(self, v):
        self._holder = v

    def value(self):
        return self._holder

    def is_initialized(self):
        h = self._holder
        if h is None:
            return False
        if isinstance(h, LoDTensor):
            return h.array is not None
        return True


class Scope:
    """Hierarchical name → Variable map with child scopes."""

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, Variable] = {}
        self._parent = parent
        self._kids: List[Scope] = []
        self._lock = threading.Lock()

    def var(self, name: str) -> Variable:
        with self._lock:
            v = self._vars.get(name)
            if v is None:
                v = Variable()
                self._vars[name] = v
            return v

    def find_var(self, name: str) -> Optional[Variable]:
        s: Optional[Scope] = self
        while s is not None:
            v = s._vars.get(name)
            if v is not None:
                return v
            s = s._parent
        return None

    def erase(self, name: str):
        self._vars.pop(name, None)

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids.clear()

    def local_var_names(self):
        return list(self._vars.keys())

    def __contains__(self, name):
        return self.find_var(name) is not None


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def _switch_scope(scope: Scope) -> Scope:
    global _global_scope
    old = _global_scope
    _global_scope = scope
    return old


# --------------------------------------------------------------------------
# FLAGS — env-backed global config (reference: platform/flags.cc, the ~106
# gflags settable via FLAGS_* env and pybind global_value_getter_setter.cc)
# --------------------------------------------------------------------------
class _GlobalFlags:
    _DEFAULTS: Dict[str, Any] = {
        "FLAGS_check_nan_inf": False,
        # what the numeric fault plane DOES when FLAGS_check_nan_inf
        # finds a non-finite step (docs/FAULT_TOLERANCE.md "Numeric
        # faults"):
        #   raise    — localize the first bad op/var and raise
        #              FloatingPointError (the reference
        #              nan_inf_utils behavior)
        #   skip     — fused discard: params/optimizer state select
        #              back to their pre-step values ON DEVICE and
        #              training continues (zero host syncs on the
        #              happy path)
        #   rollback — skip + count consecutive bad steps; after
        #              FLAGS_nan_inf_tolerance of them restore the
        #              last intact PR-3 checkpoint (bit-exact, rng
        #              counters included), at most
        #              FLAGS_nan_inf_max_rollbacks times before a
        #              typed core.NumericFaultError
        "FLAGS_nan_inf_action": "raise",
        "FLAGS_nan_inf_tolerance": 3,
        "FLAGS_nan_inf_max_rollbacks": 2,
        # pserver-side guard (VarServer/listen_and_serv): what to do
        # with a non-finite sparse grad row or dense update —
        # "" (off, apply as-is) | "drop" (discard the bad rows/update,
        # count it) | "reject" (raise NumericFaultError back to the
        # sending trainer). Trip counters ride the built-in "stats"
        # RPC under the "health" key.
        "FLAGS_ps_reject_nonfinite": "",
        # elastic PS membership plane (docs/FAULT_TOLERANCE.md "Elastic
        # membership"): replica count per pserver slot — 2 means every
        # applied update chain-forwards to a warm standby that the
        # dead-primary listener promotes, so trainers fail over instead
        # of aborting with WorkerDeadError. 1 (default) = no replication.
        "FLAGS_ps_replicas": 1,
        # how long a client-side sender (Communicator requeue, failover
        # reconnects) keeps retrying toward a slot whose primary is
        # unreachable before giving up, in seconds — covers the
        # promotion window (~2× the heartbeat timeout) with slack
        "FLAGS_ps_failover_deadline": 60.0,
        # drain: how long the source pserver waits for the in-flight
        # sync round to quiesce (pending grads applied, barrier empty)
        # before aborting the drain with the source still serving
        "FLAGS_ps_drain_quiesce_deadline": 60.0,
        "FLAGS_cpu_deterministic": False,
        "FLAGS_benchmark": False,
        "FLAGS_eager_delete_tensor_gb": 0.0,
        "FLAGS_allocator_strategy": "xla",  # allocation is XLA's job on TPU
        "FLAGS_fraction_of_gpu_memory_to_use": 1.0,
        "FLAGS_paddle_num_threads": 1,
        "FLAGS_use_pinned_memory": True,
        # RPC fault tolerance (fluid/ps_rpc.py VarClient.call): per-call
        # deadline in MILLISECONDS (reference FLAGS_rpc_deadline), and how
        # many times a transient ConnectionError/OSError is retried with
        # exponential backoff + reconnect before surfacing
        "FLAGS_rpc_deadline": 180000,
        "FLAGS_rpc_retry_times": 3,
        # wire-framing guard: a length prefix beyond this raises
        # RpcProtocolError instead of attempting a giant allocation
        # (default 1 GiB — generous; real payloads are var-sized blobs).
        # Applies to BOTH frame parts of the binary wire (pickled header
        # and the declared raw-buffer total).
        "FLAGS_rpc_max_message_size": 1 << 30,
        # per-endpoint circuit breaker (serving ingress robustness,
        # docs/SERVING.md "Ingress & overload"): OFF by default — the
        # training planes rely on the PR 3 retry ladder + PR 6 failover
        # and must not fast-fail. Serving processes flip it on so a
        # dead pserver costs ONE deadline-bounded failure per endpoint
        # instead of every request's full retry ladder; while open,
        # calls raise CircuitOpenError immediately and the sparse path
        # serves stale cache rows flagged degraded.
        "FLAGS_rpc_circuit_breaker": False,
        # consecutive transport/worker-dead failures that trip an
        # endpoint's breaker OPEN
        "FLAGS_rpc_breaker_failures": 3,
        # how long an OPEN breaker waits before letting ONE half-open
        # probe call through (success closes it, failure re-opens)
        "FLAGS_rpc_breaker_reset_s": 5.0,
        # data-plane connection pool: how many sockets VarClient keeps
        # per endpoint so concurrent RPCs (sharded lookup fan-out,
        # communicator flushes) don't serialize on one connection
        # (reference: grpc_client.h FLAGS_rpc_client_threads /
        # channel-per-call overlap in parameter_prefetch.cc)
        "FLAGS_rpc_channels_per_endpoint": 2,
        # how long a pserver-side collective (sync barrier / reduce) waits
        # for stragglers before raising TimeoutError, in seconds; a DEAD
        # participant releases much earlier with WorkerDeadError
        "FLAGS_barrier_deadline": 300.0,
        # Communicator.stop(): how long to wait for each merge thread to
        # drain before logging a warning and moving on
        "FLAGS_communicator_join_timeout": 1.0,
        # async overlap plane (docs/PS_DATA_PLANE.md "Async overlap"):
        # how many UNACKNOWLEDGED sync rounds a trainer may keep in
        # flight while it computes ahead — the ps_round op submits the
        # round's push/barrier/pull to a background pipeline and
        # returns; a full pipe blocks the step. 0 (default) = fully
        # synchronous: the round runs inline and the trajectory is
        # bit-identical to the pre-overlap send/send_barrier/recv/
        # fetch_barrier sequence (the golden-oracle contract).
        "FLAGS_async_staleness": 0,
        # sparse prefetch under the overlap plane: while window i
        # computes, a background thread pulls window i+1's embedding
        # rows into a per-step buffer the lookup op consumes without an
        # RPC. Only active when FLAGS_async_staleness > 0 (prefetched
        # rows are up to one round stale by construction).
        "FLAGS_sparse_prefetch": True,
        # ---- compressed PS data plane (docs/PS_DATA_PLANE.md
        # "Compression") ----
        # wire v3 payload quantization: "" (off, exact frames) | "fp16"
        # (downcast) | "int8" (per-row absmax scale). Lossy and OPT-IN;
        # applies only to float32 data-plane payloads on connections
        # that negotiated wire v3 in the _hello handshake — old peers
        # on either side keep exchanging exact frames. Bytes-saved
        # evidence scrapes as ps_wire_bytes_{raw,sent}_total.
        "FLAGS_ps_wire_quant": "",
        # DGC deep gradient compression (reference WITH_DGC; Lin et
        # al., ICLR 2018): dense grads on the sync send / ps_round /
        # geo-delta paths sparsify to their top-k elements with local
        # error-feedback accumulation — unsent mass stays in the
        # trainer's residual and ships later, so convergence follows
        # the full gradient. OFF by default: bit-identical behavior.
        "FLAGS_dgc": False,
        # final sparsity: fraction of elements DROPPED per push (0.999
        # = ship the top 0.1%, the paper's steady-state setting)
        "FLAGS_dgc_sparsity": 0.999,
        # momentum correction factor for the compressor's local
        # velocity accumulation (u = m*u + g; 0 disables — pair with
        # a momentum-free server optimizer to keep semantics plain SGD)
        "FLAGS_dgc_momentum": 0.0,
        # warm-up: over the first N pushes per grad the sparsity ramps
        # exponentially from ~75% toward FLAGS_dgc_sparsity (the
        # paper's epoch ramp, per-push); 0 = no warm-up
        "FLAGS_dgc_warmup_steps": 0,
        # grads smaller than this many elements ship dense — top-k
        # bookkeeping on a bias vector costs more than it saves
        "FLAGS_dgc_min_elements": 512,
        "FLAGS_sync_nccl_allreduce": True,   # no-op: ICI collectives are compiled
        # static-analysis plane (docs/ANALYSIS.md; fluid/analysis.py):
        # verify Programs at the choke points — Executor first compile of
        # a program version, the transpiler's own trainer-program output,
        # tools/verify_program.py. "" (off, default) | "warn" (log each
        # diagnostic + program_verify_diagnostics_total{rule,severity}
        # counters) | "error" (additionally raise ProgramVerifyError on
        # error-severity diagnostics). Runs ONCE per program version —
        # never per step, so warn mode adds no steady-state cost.
        "FLAGS_program_verify": "",
        "FLAGS_executor_mode": "compiled",   # compiled | interpreted
        # segmented compilation: when a block fails the all-or-nothing
        # compiled check (a stateful/host op like auc/print/read among
        # pure ops), partition it into jitted segments around interpreted
        # islands instead of interpreting EVERYTHING (fluid/executor.py
        # _SegmentedBlock, fluid/ir.py analyze_block_segments). OFF means
        # such blocks take the pure interpreter (the correctness oracle).
        "FLAGS_executor_segmentation": True,
        # don't bother jitting segments for tiny blocks: below this many
        # compilable ops the per-segment dispatch + compile overhead
        # exceeds the interpreter's per-op cost
        "FLAGS_executor_seg_min_ops": 8,
        "FLAGS_seed": 0,
        # bf16 inputs on MXU matmuls/convs with f32 accumulate (params and
        # activations stay f32 outside the unit) — the TPU-native analogue
        # of the reference's TF32/fp16 math modes
        "FLAGS_use_bf16_matmul": False,
        # sparse tables with at least this many elements are hosted as
        # init-on-touch LazyEmbeddingTable on pservers (beyond-HBM scale)
        "FLAGS_lazy_sparse_table_threshold": 1 << 26,
        # ---- capacity tier (docs/PS_DATA_PLANE.md "Capacity tier") ----
        # non-empty: pserver lazy tables grow a DISK tier — LRU overflow
        # of the hot set spills to an mmap-backed CRC-stamped segment
        # log under this directory and promotes back on touch. Empty
        # (default) = pure in-RAM slab, bit-identical to the pre-tier
        # behavior.
        "FLAGS_ps_slab_spill_dir": "",
        # rows pinned hot in RAM per table when the spill tier is on
        # (the table's entire RAM bound; must be > 0 with a spill dir)
        "FLAGS_ps_slab_hot_rows": 0,
        # at-rest row encoding in the spill log: "" (raw table dtype) |
        # "fp16" | "int8" (per-row absmax scales — the PR 11 wire codec
        # reused AT REST, ~3.6x row density at embedding widths; lossy,
        # error bound absmax_row/254 per element per spill cycle).
        # Segments holding non-finite rows store raw so
        # dequant-on-touch surfaces the poison to
        # FLAGS_ps_reject_nonfinite exactly.
        "FLAGS_ps_at_rest_quant": "",
        # frequency-gated entry creation (reference PSLib entry gating):
        # an id must be PULLED this many times before it materializes a
        # slot; grads for unentered ids are dropped+counted. 0/1 = off.
        "FLAGS_ps_entry_threshold": 0,
        # eviction write-back batch bound: one spill-log segment holds
        # at most this many rows (one segment read serves a whole cold
        # batch — the I/O fan-in unit)
        "FLAGS_ps_slab_seg_rows": 4096,
        # track per-row touch scores even without the entry gate or the
        # spill tier, so the table_shrink admin RPC works (costs one
        # dict update per touched row; gating implies it). On an
        # untiered, un-bounded table this is the ONLY cost of making it
        # shrinkable. Ignored for max_rows-bounded tables (LRU owns
        # their eviction).
        "FLAGS_ps_slab_track_scores": False,
        # trainer-driven shrink cron (reference PSLib save/shrink cron):
        # every N of trainer 0's sync rounds it fires ONE table_shrink
        # admin RPC per pserver (decay/threshold below), so idle rows
        # decay out of gated/tiered tables without an operator in the
        # loop; 0 = off. Counted server-side as slab "shrink_runs".
        "FLAGS_ps_shrink_every_steps": 0,
        "FLAGS_ps_shrink_decay": 0.98,
        "FLAGS_ps_shrink_threshold": 0.5,
        # reuse the device copy when the SAME ndarray object with the
        # SAME content fingerprint is fed again (skips the per-step
        # device_put — the dominant host cost of a small step); the
        # fingerprint makes this safe under in-place mutation, so it is
        # ON by default
        "FLAGS_feed_device_cache": True,
        # opt-in persistent XLA executable cache: non-empty -> every
        # Executor routes compiles through
        # jax_compilation_cache_dir=<dir> (inference.enable_compile_cache)
        # so a SECOND process running the same program loads the
        # executable from disk instead of recompiling
        "FLAGS_compilation_cache_dir": "",
        # multiprocess DataLoader liveness probe: how long the consumer
        # waits on the batch queue before checking whether the worker
        # process died (a killed worker surfaces RuntimeError instead of
        # hanging forever); per-loader kwarg worker_timeout overrides
        "FLAGS_dataloader_worker_timeout": 5.0,
        # how long to wait for the worker process to exit at iterator
        # teardown before it is killed
        "FLAGS_dataloader_join_timeout": 5.0,
        # ---- unified telemetry plane (docs/OBSERVABILITY.md) ----
        # non-empty: every process streams its profiler spans into a
        # bounded chrome-trace shard <dir>/trace-<pid>.json (raw
        # monotonic timestamps + clock-offset metadata from the ps_rpc
        # _hello handshake); tools/timeline.py merge aligns the shards
        # into ONE clock-corrected cluster timeline keyed by trace id.
        # Spans record even without start_profiler() while this is set.
        "FLAGS_trace_dir": "",
        # ring-buffer bound of one trace shard — oldest events drop
        # (counted in the shard metadata) so a long run's shard stays
        # O(bound), not O(steps)
        "FLAGS_trace_shard_max_events": 65536,
        # in-memory profiler event bound (ring semantics): beyond this
        # the OLDEST events drop and a dropped-events counter surfaces
        # in the summary/snapshot — a long profiled run can no longer
        # grow the host heap without bound. Applied at start_profiler/
        # reset_profiler time.
        "FLAGS_profiler_max_events": 1_000_000,
        # opt-in lightweight /metrics sidecar (Prometheus text format
        # over the telemetry registry): >0 binds 127.0.0.1:<port> at
        # pserver/ingress/executor startup so bench.py and the chaos/
        # loadgen tools scrape instead of poking process internals.
        # 0 (default) = off; the serving ingress additionally always
        # serves GET /metrics on its own port.
        "FLAGS_metrics_port": 0,
    }

    def __init__(self):
        self._values: Dict[str, Any] = {}
        for k, dv in self._DEFAULTS.items():
            env = os.environ.get(k)
            self._values[k] = self._parse(env, dv) if env is not None else dv

    @staticmethod
    def _parse(s: str, like: Any):
        if isinstance(like, bool):
            return s.lower() in ("1", "true", "yes")
        if isinstance(like, int):
            return int(s)
        if isinstance(like, float):
            return float(s)
        return s

    def __getitem__(self, key):
        return self._values[key]

    def __setitem__(self, key, value):
        self._values[key] = value

    def __contains__(self, key):
        return key in self._values

    def keys(self):
        return self._values.keys()


globals_ = _GlobalFlags()


def get_flag(name: str):
    return globals_[name]


def set_flag(name: str, value):
    globals_[name] = value


def set_flags(d: Dict[str, Any]):
    for k, v in d.items():
        globals_[k] = v
